#!/usr/bin/env python
"""CI fault-injection smoke: the degradation contract in one minute.

Runs a small checker workload clean, then re-runs it with a wedge, a
crash, and a flaky failure injected at the supervised dispatch sites
(CPU, interpret-safe), asserting every verdict is IDENTICAL to the
clean run — the acceptance bar of docs/resilience.md, at smoke scale.
`tools/ci.sh` invokes this right after the lint gate; exit 0 = the
degradation paths hold, 1 = a verdict flipped or a path crashed.

Deliberately tiny histories: this is a wiring check (every fault class
actually reaches a supervised site and degrades correctly), not a
stress test — tests/test_resilience.py carries the full matrix.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    from jepsen_tpu import resilience
    from jepsen_tpu.histories import corrupt_history, rand_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import engine

    m = CASRegister()
    hs = [rand_register_history(n_ops=24, n_processes=3, seed=s)
          for s in range(3)]
    hs[1] = corrupt_history(hs[1], seed=1, n_corruptions=2)

    clean = [engine.analysis(m, h)["valid?"] for h in hs]
    print(f"fault-smoke: clean verdicts {clean}")

    failures = 0
    for spec in ("wedge@dispatch:n=1,wedge@search:n=1",
                 "raise@dispatch,raise@search,raise@transfer",
                 "flaky@dispatch:n=1,flaky@search:n=1"):
        os.environ["JEPSEN_TPU_FAULTS"] = spec
        resilience.reset()
        try:
            got = [engine.analysis(m, h)["valid?"] for h in hs]
        except Exception as err:  # noqa: BLE001 — a crash IS the failure
            print(f"fault-smoke: {spec!r} CRASHED: {err!r}")
            failures += 1
            continue
        finally:
            del os.environ["JEPSEN_TPU_FAULTS"]
            resilience.reset()
        if got == clean:
            print(f"fault-smoke: {spec!r} -> verdicts preserved")
        else:
            print(f"fault-smoke: {spec!r} FLIPPED verdicts: "
                  f"{got} != {clean}")
            failures += 1

    if failures:
        print(f"fault-smoke: {failures} degradation path(s) broken")
        return 1
    print("fault-smoke: all degradation paths preserve verdicts")
    return 0


if __name__ == "__main__":
    sys.exit(main())
