#!/usr/bin/env python
"""Fleet chaos soak (ISSUE 13 acceptance): a Jepsen-style nemesis
schedule driven against a REAL multi-replica, multi-tenant serve
fleet — subprocess replicas, HTTP ingress, sync WAL-segment
replication, and the FleetSupervisor doing the healing, with every
claim verified from the parsed /metrics scrape.

The fleet: N replica subprocesses (each a CheckerService + HTTP
ingress + ops endpoint + sync SegmentReplicator shipping segments to
its ring successor), one flooding tenant and two quiet tenants
streaming real histories through parent-side routing
(FleetSupervisor.owner: ring + rehome pins). One replica spawns with
JEPSEN_TPU_FAULTS armed (wedge + flaky + slow at the device seams),
so degradation paths run under load.

The nemesis schedule (--smoke: one SIGKILL + one SIGSTOP cycle,
~15 s; full mode adds rolling kill/restart cycles until --secs):

  * SIGKILL a replica mid-stream AND delete its WAL dir — the
    supervisor must detect the death from /healthz misses and rehome
    its keys FROM THE REPLICATED SEGMENTS on the survivors;
  * SIGSTOP a replica (paused, not dead) — the supervisor declares
    it dead and rehomes; SIGCONT resumes it, and a delta posted
    straight to the resumed replica must get the structured epoch-
    fence refusal (the split-brain pin);
  * rolling restart (full mode): a killed replica respawns with the
    same identity + ports, recovers its WAL, finds its keys fenced,
    and rejoins the ring for new keys via the half-open probe.

Asserted (exit 1 on any failure):

  * ZERO verdict flips — a decided-invalid verdict never flips back,
    and every finalized key's verdict is bit-identical to a one-shot
    check of exactly the accepted ops;
  * ZERO lost keys — every key's final seq equals the count of
    acknowledged deltas, across kills, rehomes, and re-routes;
  * the epoch fence ENGAGED: the resumed replica answered
    {"fenced": true} and its scraped jepsen_serve_fenced_refusals
    moved;
  * quiet-tenant SLOs held: no quiet shed on any replica, ack p99
    within budget — from the scraped per-tenant histograms;
  * the supervisor's own trail: jepsen_fleet_deaths / rehomes (and
    rejoins, full mode) moved on the parent's registry.

docs/streaming.md "Fleet self-healing" is the runbook this script
rehearses; tools/ci.sh runs --smoke after soak --smoke.
"""

import argparse
import http.client
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

#: a replica dying mid-response surfaces as OSError OR an
#: http.client framing error — both mean "re-route and retry"
RETRY_ERRS = (OSError, http.client.HTTPException)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ACK_SLO_SECS = 10.0       # quiet-tenant ack p99 budget (CPU CI box,
# heartbeat pauses + re-routes included)
FAULT_SPEC = ("wedge@search:n=1,flaky@dispatch:n=2,"
              "slow@search:ms=5")
#: a SECOND replica runs with only a slow fault armed at the device
#: seams: the slow-delta probe posts it one key and asserts the
#: JEPSEN_TPU_SLOW_DELTA_SECS forensics record shows a device-
#: dominated stage breakdown (dispatch covers the bitdense seam,
#: search the serve/extend seam)
SLOW_SPEC = "slow@dispatch:ms=120,slow@search:ms=120"
SLOW_DELTA_SECS = "0.05"  # armed fleet-wide; every replica records
#: the flood tenant gets an explicit small pending-ops quota so the
#: fairness line trips deterministically against a HEALTHY worker
#: (the derived weight-share bound only bites when the queue backs up)
TENANTS = ("chaos-flood:token=tok-chaos-flood:weight=1:ops=24,"
           "chaos-q0:token=tok-chaos-q0:weight=2,"
           "chaos-q1:token=tok-chaos-q1:weight=2")

_CHILD = r"""
import faulthandler, json, os, signal, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# SIGUSR1 -> all-thread stack dump on stderr (lands in the replica's
# stderr log): the postmortem lever for a wedged worker
faulthandler.register(signal.SIGUSR1)
with open(sys.argv[1]) as fh:
    cfg = json.load(fh)
from jepsen_tpu.models import CASRegister
from jepsen_tpu.obs import httpd as ops_httpd
from jepsen_tpu.serve import CheckerService
from jepsen_tpu.serve import fleet as fleet_mod, ring as ring_mod
from jepsen_tpu.serve.ingress import DeltaIngress
from jepsen_tpu.serve.wal import DeltaWAL

name = cfg["name"]
wal_dir = cfg["wal_dirs"][name]
# static replication ring: every replica computes the same per-key
# successor the coordinator's rehome fallback will scan
ring = ring_mod.HashRing(sorted(cfg["wal_dirs"]))
repl = fleet_mod.SegmentReplicator(
    DeltaWAL(wal_dir),
    fleet_mod.ring_successor_dst(ring, cfg["wal_dirs"], name))
if repl.mode == "off":
    repl = None
svc = CheckerService(CASRegister(), wal_dir=wal_dir,
                     capacity=cfg.get("capacity", 256),
                     replicator=repl)
ing = DeltaIngress(svc, port=cfg["ingress_port"]).start()
ops = ops_httpd.start_ops_server(
    cfg["ops_port"], health_fn=svc.health, status_fn=svc.status,
    refresh_fn=svc.refresh_gauges, adopt_fn=svc.adopt_keys)
print(json.dumps({"ready": True, "ops": ops.port,
                  "ingress": ing.port}), flush=True)
while True:
    time.sleep(1)
"""


def _pick_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _post_lines(addr, reqs, token, timeout=30):
    body = "".join(json.dumps(r) + "\n" for r in reqs).encode()
    req = urllib.request.Request(
        f"http://{addr}/v1/deltas", data=body,
        headers={"Authorization": f"Bearer {token}"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return [json.loads(ln) for ln in
                resp.read().decode().splitlines()]


def _scrape(ops_addr, timeout=10):
    with urllib.request.urlopen(f"http://{ops_addr}/metrics",
                                timeout=timeout) as resp:
        return resp.read().decode()


class Fleet:
    """The subprocess replica set: spawn/kill/stop/respawn, with the
    parent-picked fixed ports that make restart-in-place possible."""

    def __init__(self, names, base_env, root):
        self.names = list(names)
        self.base_env = base_env
        self.root = root
        self.wal_dirs = {n: os.path.join(root, n) for n in names}
        self.ops_port = {n: _pick_port() for n in names}
        self.ing_port = {n: _pick_port() for n in names}
        self.procs = {}
        self.cfg_paths = {}
        script = os.path.join(root, "replica.py")
        with open(script, "w") as fh:
            fh.write(_CHILD)
        self.script = script
        for n in names:
            cfg = {"name": n, "wal_dirs": self.wal_dirs,
                   "ops_port": self.ops_port[n],
                   "ingress_port": self.ing_port[n]}
            path = os.path.join(root, f"{n}.json")
            with open(path, "w") as fh:
                json.dump(cfg, fh)
            self.cfg_paths[n] = path

    def ops_addr(self, n):
        return f"127.0.0.1:{self.ops_port[n]}"

    def ing_addr(self, n):
        return f"127.0.0.1:{self.ing_port[n]}"

    def spawn(self, name, extra_env=None):
        env = dict(self.base_env)
        if extra_env:
            env.update(extra_env)
        # replica stderr -> a per-replica log (append across
        # respawns): the postmortem evidence when an assertion fails
        errlog = open(os.path.join(self.root, f"{name}.stderr.log"),
                      "ab")
        proc = subprocess.Popen(
            [sys.executable, self.script, self.cfg_paths[name]],
            stdout=subprocess.PIPE, stderr=errlog,
            env=env)
        errlog.close()
        line = proc.stdout.readline().decode()
        if not line:
            raise RuntimeError(f"replica {name} produced no ready "
                               f"line (exit {proc.poll()})")
        doc = json.loads(line)
        assert doc.get("ready"), doc
        self.procs[name] = proc
        return proc

    def kill(self, name):
        self.procs[name].send_signal(signal.SIGKILL)
        self.procs[name].wait(timeout=30)

    def pause(self, name):
        self.procs[name].send_signal(signal.SIGSTOP)

    def resume(self, name):
        self.procs[name].send_signal(signal.SIGCONT)

    def close(self):
        for p in self.procs.values():
            if p.poll() is None:
                p.kill()


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--secs", type=float, default=60.0,
                   help="full-mode soak duration (rolling nemesis "
                        "cycles until the deadline)")
    p.add_argument("--smoke", action="store_true",
                   help="CI shape (~15 s): one SIGKILL(+WAL-dir "
                        "delete) cycle + one SIGSTOP/SIGCONT fence "
                        "cycle")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    from jepsen_tpu import obs
    from jepsen_tpu.histories import corrupt_history, \
        rand_register_history
    from jepsen_tpu.history import History
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.obs import httpd as ops_httpd
    from jepsen_tpu.parallel import encode as enc_mod, engine
    from jepsen_tpu.serve import fleet as fleet_mod

    failures = []

    def fail(msg):
        print(f"chaos: FAIL {msg}")
        failures.append(msg)

    t0 = time.monotonic()
    root = tempfile.mkdtemp(prefix="jepsen_chaos_")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env = {k: v for k, v in os.environ.items()
                if not k.startswith("JEPSEN_TPU_")}
    base_env.update(
        JAX_PLATFORMS="cpu",
        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH",
                                                      ""),
        JEPSEN_TPU_TENANTS=TENANTS,
        JEPSEN_TPU_SERVE_REPL="sync",
        # end-to-end delta tracing, fleet-wide: every replica keeps
        # its span buffer (fetched via GET /trace and merged below)
        # and records slow-delta forensics past the threshold
        JEPSEN_TPU_TRACE="1",
        JEPSEN_TPU_SLOW_DELTA_SECS=SLOW_DELTA_SECS)
    names = [f"r{i}" for i in range(max(2, args.replicas))]
    fleet = Fleet(names, base_env, root)
    # one replica runs with the device-fault matrix armed: wedge +
    # flaky + slow at the supervised dispatch seams, under real load;
    # a DIFFERENT replica runs slow-only (the slow-delta probe's
    # target — the wedge must not eat its first dispatch)
    fault_replica = names[-1]
    slow_replica = names[0]
    for n in names:
        extra = None
        if n == fault_replica:
            extra = {"JEPSEN_TPU_FAULTS": FAULT_SPEC}
        elif n == slow_replica:
            extra = {"JEPSEN_TPU_FAULTS": SLOW_SPEC}
        fleet.spawn(n, extra_env=extra)
    print(f"chaos: fleet up — {len(names)} replicas, faults armed "
          f"on {fault_replica} ({FAULT_SPEC}), slow armed on "
          f"{slow_replica} ({SLOW_SPEC})")

    rehome_events = []
    rehomed = threading.Event()

    def on_rehome(name, plan):
        rehome_events.append((name, {k: len(v)
                                     for k, v in plan.items()}))
        rehomed.set()

    sup = fleet_mod.FleetSupervisor(
        {n: fleet.ops_addr(n) for n in names}, fleet.wal_dirs,
        services={n: fleet_mod.HttpReplica(fleet.ops_addr(n))
                  for n in names},
        interval=0.25, threshold=2, fetch_timeout=1.0,
        on_rehome=on_rehome).start()

    # --- slow-delta forensics probe (before the nemesis: the slow
    # replica must be alive and undisturbed). One key posted straight
    # to the slow@dispatch replica; its /status must carry a
    # slow-delta record whose stage breakdown is device-dominated —
    # the PR-12 wedge diagnosis, now one structured read.
    slow_key = "chaos-slow-k"
    slow_piece = [dict(o) for o in rand_register_history(
        n_ops=8, n_processes=3, n_values=3, crash_p=0.0, seed=9000)]
    try:
        outs = _post_lines(fleet.ing_addr(slow_replica),
                           [{"key": slow_key, "ops": slow_piece,
                             "wait": True, "timeout": 90}],
                           "tok-chaos-q0", timeout=120)
        r = outs[0]
        if "valid?" not in r:
            fail(f"slow-delta probe got no verdict: {r}")
        if not r.get("delta_id"):
            fail(f"armed serve ack carried no delta_id: {r}")
        sdoc = ops_httpd.fetch_replica(fleet.ops_addr(slow_replica),
                                       timeout=10)
        slows = (sdoc.get("status") or {}).get("slow_deltas") or []
        mine = [s for s in slows if s.get("key") == slow_key]
        if not mine:
            fail(f"no slow-delta record for {slow_key} on "
                 f"{slow_replica}: {slows}")
        else:
            stages = mine[-1].get("stages") or {}
            if mine[-1].get("slowest_stage") != "device":
                fail(f"slow-delta breakdown not device-dominated: "
                     f"{mine[-1]}")
            print(f"chaos: slow-delta forensics OK on "
                  f"{slow_replica} — device stage "
                  f"{stages.get('device')}s of "
                  f"{mine[-1].get('total_secs')}s total "
                  f"(delta {mine[-1].get('delta_id')})")
    except RETRY_ERRS as err:
        fail(f"slow-delta probe could not reach {slow_replica}: "
             f"{err}")

    # --- tenants, keys, streams
    quiet = ["chaos-q0", "chaos-q1"]
    n_ops = 24 if args.smoke else 48
    cut = 6
    streams = {}
    for ti, tname in enumerate(quiet):
        h = rand_register_history(
            n_ops=n_ops, n_processes=4, n_values=3, crash_p=0.04,
            seed=args.seed + 10 * ti)
        if ti % 2:
            h = corrupt_history(h, seed=ti, n_corruptions=2)
        ops = list(h)
        streams[(tname, f"{tname}-k")] = [
            ops[i:i + cut] for i in range(0, len(ops), cut)]

    accepted = {k: [] for k in streams}
    finals = {}
    first_acked = {k: threading.Event() for k in streams}
    stop_flood = threading.Event()
    flip_stop = threading.Event()
    flips = []
    def route(key):
        return fleet.ing_addr(sup.owner(key))

    def submit_routed(tname, key, piece, seq, deadline):
        """Retry-until-landed: re-resolves the owner every attempt,
        so a rehome mid-stream re-routes the producer; an ack lost to
        a kill is resubmitted and dedupes by seq."""
        while time.monotonic() < deadline:
            try:
                outs = _post_lines(
                    route(key),
                    [{"key": key, "ops": [dict(o) for o in piece],
                      "seq": seq, "timeout": 10}],
                    f"tok-{tname}", timeout=20)
            except RETRY_ERRS:
                time.sleep(0.25)   # owner mid-death or mid-rehome
                continue
            r = outs[0]
            if r.get("accepted") or r.get("duplicate"):
                return r
            if r.get("fenced"):
                time.sleep(0.25)   # pins not updated yet — re-route
                continue
            if r.get("error", "").startswith("sequence gap"):
                time.sleep(0.25)   # adopter still replaying
                continue
            if r.get("shed"):
                fail(f"quiet tenant {tname} shed: {r}")
                return r
            fail(f"quiet tenant {tname} submit error: {r}")
            return r
        fail(f"quiet tenant {tname} timed out landing seq {seq} of "
             f"{key}")
        return None

    def producer(tname, key):
        pieces = streams[(tname, key)]
        deadline = time.monotonic() + (120 if args.smoke
                                       else args.secs + 120)
        for seq, piece in enumerate(pieces, start=1):
            r = submit_routed(tname, key, piece, seq, deadline)
            if r is None or r.get("shed") or "error" in r:
                return
            accepted[(tname, key)].append(piece)
            first_acked[(tname, key)].set()
        # finalize on the (current) owner, with re-route retries
        while time.monotonic() < deadline:
            try:
                outs = _post_lines(route(key),
                                   [{"op": "finalize", "key": key,
                                     "timeout": 60}],
                                   f"tok-{tname}", timeout=90)
            except RETRY_ERRS:
                time.sleep(0.25)
                continue
            if outs[0].get("fenced"):
                time.sleep(0.25)
                continue
            if "error" in outs[0]:
                own = sup.owner(key)
                print(f"chaos: DEBUG finalize {key} on {own}: "
                      f"{outs[0]}")
                try:
                    from jepsen_tpu.obs.httpd import fetch_replica
                    doc = fetch_replica(fleet.ops_addr(own),
                                        timeout=5)
                    st = (doc.get("status") or {})
                    print(f"chaos: DEBUG {own} worker_alive="
                          f"{st.get('worker_alive')} pending="
                          f"{st.get('pending_ops')} keys="
                          f"{ {k: (v.get('state'), v.get('seq'), v.get('pending_ops'), v.get('error')) for k, v in (st.get('keys') or {}).items()} }")
                except Exception as err:
                    print(f"chaos: DEBUG status fetch failed {err}")
                time.sleep(0.5)
                continue
            finals[(tname, key)] = outs[0]
            return
        fail(f"{key}: finalize never landed")

    def flood():
        # every piece is a SELF-CONTAINED complete history (every
        # call closes inside it, crash_p=0 so no call stays open as a
        # crashed wildcard), so the accepted subsequence — quota
        # sheds drop arbitrary pieces — still stitches into a stream
        # whose open-call/slot window stays ~n_processes wide.
        # Arbitrary h[lo:lo+k] slices here once stitched into an
        # 18-slot wildcard-riddled monster whose frontier search
        # wedged the adopter's worker for minutes — an accidental
        # adversarial-history DoS, not the fairness load this tenant
        # exists to apply.
        pieces = [list(rand_register_history(
            n_ops=8, n_processes=4, n_values=3, crash_p=0.0,
            seed=5000 + i))
            for i in range(8)]
        i = 0
        while not stop_flood.is_set():
            piece = pieces[i % len(pieces)]
            try:
                _post_lines(route("chaos-flood-k"),
                            [{"key": "chaos-flood-k",
                              "ops": [dict(o) for o in piece],
                              "timeout": 0.05}],
                            "tok-chaos-flood", timeout=8)
            except RETRY_ERRS:
                time.sleep(0.2)
            i += 1

    def flip_monitor():
        seen_invalid = set()
        while not flip_stop.is_set():
            for (tname, key) in streams:
                try:
                    outs = _post_lines(route(key),
                                       [{"op": "result", "key": key,
                                         "timeout": 0.05}],
                                       f"tok-{tname}", timeout=5)
                except RETRY_ERRS:
                    continue
                v = outs[0].get("valid?")
                if v is False:
                    seen_invalid.add(key)
                elif v is True and key in seen_invalid:
                    flips.append(key)
            time.sleep(0.25)

    threads = [threading.Thread(target=producer, args=k, daemon=True)
               for k in streams]
    fthread = threading.Thread(target=flood, daemon=True)
    mthread = threading.Thread(target=flip_monitor, daemon=True)
    mthread.start()
    fthread.start()
    for t in threads:
        t.start()

    # --- nemesis -----------------------------------------------------

    def await_rehome(what, timeout=30):
        if not rehomed.wait(timeout=timeout):
            fail(f"supervisor never rehomed after {what}")
            return False
        rehomed.clear()
        return True

    fence_engaged = False
    fenced_replica = None

    def sigkill_cycle():
        """SIGKILL + WAL-dir delete: rehome must come from the
        replicated segments."""
        for ev in first_acked.values():
            ev.wait(timeout=60)
        victim = sup.owner(next(iter(streams))[1])
        print(f"chaos: SIGKILL {victim} + deleting its WAL dir")
        fleet.kill(victim)
        shutil.rmtree(fleet.wal_dirs[victim], ignore_errors=True)
        return await_rehome(f"SIGKILL {victim}")

    def sigstop_cycle():
        """SIGSTOP -> rehome -> SIGCONT -> the resumed replica must
        answer the epoch-fence refusal to a directly-addressed
        delta."""
        nonlocal fence_engaged, fenced_replica
        live_keys = [k for (_t, k) in streams
                     if not sup._reps[sup.owner(k)].dead]
        if not live_keys:
            fail("no live key to SIGSTOP")
            return False
        key = live_keys[0]
        tname = next(t for (t, k) in streams if k == key)
        victim = sup.owner(key)
        print(f"chaos: SIGSTOP {victim} (owner of {key})")
        fleet.pause(victim)
        if not await_rehome(f"SIGSTOP {victim}"):
            fleet.resume(victim)
            return False
        print(f"chaos: SIGCONT {victim} — probing the fence")
        fleet.resume(victim)
        fenced_replica = victim
        # a stale producer that never heard about the rehome talks to
        # the resumed replica DIRECTLY: the epoch fence must refuse
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                outs = _post_lines(
                    fleet.ing_addr(victim),
                    [{"key": key, "ops": [], "seq": 999,
                      "timeout": 5}],
                    f"tok-{tname}", timeout=8)
            except RETRY_ERRS:
                time.sleep(0.25)   # still waking up
                continue
            r = outs[0]
            if r.get("fenced") is True:
                fence_engaged = True
                print(f"chaos: fence engaged on {victim}: "
                      f"epoch {r.get('epoch')} owner {r.get('owner')}")
                return True
            time.sleep(0.25)   # transport up but fence not yet
            # observed (shouldn't happen — the fence landed before
            # the rehome that gated this probe — but stay patient)
        fail(f"resumed replica {victim} never answered a fence "
             f"refusal")
        return False

    ok = sigkill_cycle()
    # the flood made its fairness point during the kill window; stop
    # it BEFORE the pause cycle so the SIGSTOP victim's WAL (which
    # the next rehome replays on the adopter) stays modest — an
    # unbounded flood backlog would turn adoption into minutes of
    # replay and read as a hang
    stop_flood.set()
    fthread.join(timeout=60)
    if ok:
        sigstop_cycle()

    # --- the scrape tells the story ----------------------------------
    # The quiet streams drain DURING the nemesis cycles, so the
    # fairness/SLO/fence evidence lives in the replicas that served
    # them — scrape it NOW, before full mode's rolling restarts
    # replace those processes (a respawned replica starts a fresh
    # in-process registry).
    for t in threads:
        t.join(timeout=300)
    live = [n for n in names if fleet.procs[n].poll() is None
            and not sup._reps[n].dead]
    # the resumed (fenced) replica answers /metrics even though the
    # supervisor may have re-admitted it — scrape it explicitly
    scrape_set = set(live)
    if fenced_replica is not None \
            and fleet.procs[fenced_replica].poll() is None:
        scrape_set.add(fenced_replica)
    parsed = {}
    for n in sorted(scrape_set):
        try:
            parsed[n] = ops_httpd.parse_prometheus(
                _scrape(fleet.ops_addr(n)))
        except OSError as err:
            fail(f"could not scrape {n}: {err}")

    # --- the merged fleet trace: one delta, one chain, two replicas.
    # The SIGSTOP victim admitted the rehomed key's deltas (its spans
    # carry their delta_ids) and survives resumed; the adopter
    # re-applied the same ids from the transferred WAL segments — the
    # merged Perfetto file must show at least one id on BOTH process
    # tracks (the readable-across-the-boundary acceptance).
    from jepsen_tpu.obs import trace_merge as tmerge
    tdocs, tnames = [], []
    for n in sorted(scrape_set):
        try:
            tdocs.append(tmerge.fetch_trace(fleet.ops_addr(n)))
            tnames.append(n)
        except (OSError, ValueError) as err:
            fail(f"could not fetch /trace from {n}: {err}")
    if tdocs:
        merged = tmerge.merge_traces(tdocs, tnames)
        terrs = tmerge.validate_trace(merged)
        if terrs:
            fail(f"merged fleet trace failed its schema: "
                 f"{terrs[:3]}")
        mpath = os.path.join(root, "fleet_trace.json")
        with open(mpath, "w") as fh:
            json.dump(merged, fh)
        cross = tmerge.cross_replica_ids(merged)
        if fence_engaged and not cross:
            fail("merged fleet trace shows no cross-replica delta "
                 "chain for the rehomed key")
        else:
            print(f"chaos: merged fleet trace ({len(tnames)} "
                  f"replicas) -> {mpath}: {len(cross)} "
                  f"cross-replica chain(s)")

    def total(metric, tenant=None):
        key = (obs.labeled(metric, tenant=tenant) if tenant
               else metric)
        return sum(p[key]["value"] for p in parsed.values()
                   if key in p)

    if not fence_engaged:
        fail("the epoch fence never engaged (no fenced response)")
    if total("jepsen_serve_fenced_refusals") < 1:
        fail("scrape shows no jepsen_serve_fenced_refusals anywhere")
    flood_sheds = int(total("jepsen_serve_sheds",
                            tenant="chaos-flood"))
    if flood_sheds < 1:
        fail("the flooding tenant never shed — the quota never bit")
    for tname in quiet:
        if total("jepsen_serve_sheds", tenant=tname) > 0:
            fail(f"quiet tenant {tname} was shed")
        # merged per-tenant ack histogram across the fleet
        merged = {"count": 0, "total": 0.0, "buckets": {},
                  "max": None, "min": None, "type": "histogram"}
        for p in parsed.values():
            h = p.get(obs.labeled("jepsen_serve_ack_secs",
                                  tenant=tname))
            if not h:
                continue
            merged["count"] += h["count"]
            merged["total"] += h["total"]
            for le, cum in h.get("buckets") or ():
                merged["buckets"][le] = merged["buckets"].get(
                    le, 0) + cum
            if h.get("max") is not None:
                merged["max"] = max(merged["max"] or 0.0, h["max"])
        merged["buckets"] = sorted(merged["buckets"].items())
        if not merged["count"]:
            fail(f"/metrics missing populated "
                 f"serve.ack_secs{{tenant={tname}}}")
            continue
        p99 = obs.hist_quantile(merged, 0.99)
        if p99 is None or p99 > ACK_SLO_SECS:
            fail(f"quiet tenant {tname} ack p99 {p99} past the "
                 f"{ACK_SLO_SECS}s SLO")

    if not args.smoke:
        # rolling restarts: respawn the killed replicas in place
        # (same identity + ports), let them rejoin via the half-open
        # probe, and keep the nemesis rolling until the deadline —
        # detect/rehome/rejoin under churn, with the flip monitor
        # still polling every key's verdict across each move
        deadline = t0 + args.secs
        cycle = 0
        while time.monotonic() < deadline:
            dead = [n for n in names if sup._reps[n].dead
                    and fleet.procs[n].poll() is not None]
            for n in dead:
                print(f"chaos: rolling restart of {n}")
                os.makedirs(fleet.wal_dirs[n], exist_ok=True)
                fleet.spawn(n, extra_env=(
                    {"JEPSEN_TPU_FAULTS": FAULT_SPEC}
                    if n == fault_replica else None))
            # wait for a rejoin before the next kill
            t_end = time.monotonic() + 20
            while time.monotonic() < t_end and any(
                    sup._reps[n].dead for n in dead):
                time.sleep(0.25)
            alive = [n for n in names if not sup._reps[n].dead]
            if len(alive) > 2 and time.monotonic() < deadline - 15:
                victim = alive[cycle % len(alive)]
                print(f"chaos: rolling SIGKILL {victim}")
                fleet.kill(victim)
                rehomed.clear()
                await_rehome(f"rolling kill {victim}")
            cycle += 1
            time.sleep(1)
        snap = obs.registry().snapshot()
        if (snap.get("fleet.rejoins") or {}).get("value", 0) < 1:
            fail("full mode: no replica ever rejoined through the "
                 "half-open probe")

    # --- drain + verify ---------------------------------------------
    flip_stop.set()
    mthread.join(timeout=30)

    if flips:
        fail(f"verdict flips observed on {sorted(set(flips))}")
    for (tname, key), pieces in accepted.items():
        f = finals.get((tname, key)) or {}
        ops = [op for piece in pieces for op in piece]
        if f.get("seq") != len(pieces):
            fail(f"{key}: final seq {f.get('seq')} != accepted "
                 f"{len(pieces)} — an admitted delta went missing "
                 f"(final answer: {f})")
        if not ops:
            continue
        ref = engine.check_encoded(
            enc_mod.encode(CASRegister(), History.wrap(ops)),
            capacity=256)
        pin = lambda r: {k: r.get(k) for k in  # noqa: E731
                         ("valid?", "op", "fail-event")}
        if pin(f) != pin(ref):
            fail(f"{key}: final verdict diverged from one-shot: "
                 f"{pin(f)} != {pin(ref)}")

    # the supervisor's own trail (parent-process registry)
    snap = obs.registry().snapshot()
    if (snap.get("fleet.deaths") or {}).get("value", 0) < 1:
        fail("fleet.deaths never moved — the supervisor missed the "
             "nemesis")
    if (snap.get("fleet.rehomes") or {}).get("value", 0) \
            < len(rehome_events):
        fail("fleet.rehomes under-counts the observed rehomes")
    # post-drain bounded state on the live replicas (recomputed —
    # full mode's rolling phase changed who is alive)
    live = [n for n in names if fleet.procs[n].poll() is None
            and not sup._reps[n].dead]
    for n in live:
        try:
            doc = ops_httpd.fetch_replica(fleet.ops_addr(n),
                                          timeout=10)
        except OSError:
            continue
        pend = (doc.get("status") or {}).get("pending_ops")
        if pend:
            fail(f"{n}: pending_ops {pend} after drain")

    sup.stop()
    fleet.close()
    dur = time.monotonic() - t0
    n_deltas = sum(len(p) for p in accepted.values())
    if failures:
        # keep the scratch dir: WAL segments + per-replica stderr
        # logs are the postmortem
        print(f"chaos: {len(failures)} failure(s) in {dur:.1f}s — "
              f"evidence kept in {root}")
        return 1
    shutil.rmtree(root, ignore_errors=True)
    print(f"chaos: OK in {dur:.1f}s — {n_deltas} quiet deltas / "
          f"{len(streams)} keys across {len(names)} replicas, "
          f"{len(rehome_events)} rehome(s) {rehome_events}, fence "
          f"engaged, flood shed {flood_sheds}x, zero flips, zero "
          f"lost keys")
    return 0


if __name__ == "__main__":
    sys.exit(main())
