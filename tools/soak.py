#!/usr/bin/env python
"""Multi-tenant serve soak harness (ISSUE 12 acceptance): sustained
load through the HTTP ingress with faults armed mid-soak, verified
against /metrics.

What it drives:

  * one multi-tenant CheckerService (WAL-backed, ops endpoint, HTTP
    ingress) — the full `jepsen serve --checker --ingress-port N`
    stack, in-process;
  * one FLOODING tenant hammering past its quota with tiny timeouts
    (it must shed, structurally, and hurt nobody else);
  * N quiet tenants streaming real histories as deltas over
    POST /v1/deltas, finalizing each key when its stream ends;
  * a mid-soak fault window arming JEPSEN_TPU_FAULTS with a wedge, a
    crash, a transient, AND the new deterministic latency fault
    (``slow@search``) — the degradation paths run under load, not in
    isolation.

What it asserts (each failure printed and counted; exit 1 on any):

  * ZERO verdict flips: a flip monitor polls every key's verdict
    through the soak — a decided-invalid verdict never flips back
    (prefix closure), and every finalized key's verdict+counterexample
    is bit-identical to a one-shot check of exactly the ops the
    service accepted;
  * bounded memory: pending ops never exceeded the global bound
    (max_pending_seen), and the drain ends at zero;
  * fairness: the flooding tenant shed (it outran its quota) while
    every quiet tenant shed NOTHING and acked within the SLO;
  * /metrics tells the story per tenant: the labeled
    ``serve.ack_secs``/``verdict_secs`` histograms are populated for
    every tenant, the flood tenant's labeled shed counter moved, and
    the quiet tenants' ack p99 (computed from the scraped exposition,
    not in-process state) is within the SLO;
  * bounded evidence: the decision ledger (armed with a tiny segment
    cap so rotation/retention fire under load) never outgrows
    ``segments x segment_bytes`` on disk, and every surviving record
    reads back clean.

``--smoke`` is the CI shape (~10 s; tools/ci.sh runs it after
serve_smoke); the default is a ~60 s soak and ``--secs`` scales it up
to the multi-hour shape the ROADMAP names.
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ACK_SLO_SECS = 5.0       # quiet-tenant ack p99 budget (CPU CI box)
FAULT_SPEC = ("wedge@search:n=1,flaky@dispatch:n=2,"
              "raise@pipeline:n=1,slow@search:ms=10")


def _post_lines(url, reqs, token, timeout=180):
    body = "".join(json.dumps(r) + "\n" for r in reqs).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Authorization": f"Bearer {token}"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return [json.loads(ln) for ln in
                resp.read().decode().splitlines()]


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--secs", type=float, default=60.0,
                   help="soak duration (the producers stop extending "
                        "at the deadline and finalize)")
    p.add_argument("--smoke", action="store_true",
                   help="CI shape: ~10 s, small histories")
    p.add_argument("--quiet-tenants", type=int, default=2)
    p.add_argument("--keys-per-tenant", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    if args.smoke:
        args.secs = min(args.secs, 10.0)

    # the decision ledger rides the soak with a TINY segment cap so
    # rotation + retention fire under sustained load — the post-drain
    # assert pins the disk bound evidence can never outgrow (ISSUE
    # 19; verdicts are flag-independent, parity-pinned)
    if "JEPSEN_TPU_LEDGER" not in os.environ:
        os.environ["JEPSEN_TPU_LEDGER"] = tempfile.mkdtemp(
            prefix="jepsen_soak_ledger_")
    if "JEPSEN_TPU_LEDGER_SEGMENT_BYTES" not in os.environ:
        os.environ["JEPSEN_TPU_LEDGER_SEGMENT_BYTES"] = "8192"
    if "JEPSEN_TPU_LEDGER_SEGMENTS" not in os.environ:
        os.environ["JEPSEN_TPU_LEDGER_SEGMENTS"] = "4"

    from jepsen_tpu import obs, resilience
    from jepsen_tpu.histories import corrupt_history, \
        rand_register_history
    from jepsen_tpu.history import History
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.obs import httpd as ops_httpd
    from jepsen_tpu.parallel import encode as enc_mod, engine
    from jepsen_tpu.serve import CheckerService, Tenant
    from jepsen_tpu.serve.ingress import DeltaIngress

    failures = []

    def fail(msg):
        print(f"soak: FAIL {msg}")
        failures.append(msg)

    # --- the fleet-shaped single instance
    quiet = [f"soak-q{i}" for i in range(args.quiet_tenants)]
    tenants = [Tenant("soak-flood", token="tok-flood", weight=1)] + [
        Tenant(name, token=f"tok-{name}", weight=2) for name in quiet]
    wal = tempfile.mkdtemp(prefix="jepsen_soak_wal_")
    svc = CheckerService(CASRegister(), wal_dir=wal, capacity=256,
                         tenants=tenants, global_bound=4096,
                         high_water=512)
    ops_srv = ops_httpd.start_ops_server(
        0, health_fn=svc.health, status_fn=svc.status,
        refresh_fn=svc.refresh_gauges)
    ing = DeltaIngress(svc, port=0).start()
    deltas_url = ing.url("/v1/deltas")

    # --- per-key histories, chopped into deltas
    n_ops = 48 if args.smoke else 96
    cut = 8
    streams = {}   # (tenant, key) -> [delta ops...]
    for ti, tname in enumerate(quiet):
        for ki in range(args.keys_per_tenant):
            h = rand_register_history(
                n_ops=n_ops, n_processes=4, n_values=3, crash_p=0.04,
                seed=args.seed + 100 * ti + ki)
            if ki % 2:
                h = corrupt_history(h, seed=ki, n_corruptions=2)
            ops = list(h)
            streams[(tname, f"{tname}-k{ki}")] = [
                ops[i:i + cut] for i in range(0, len(ops), cut)]

    accepted = {k: [] for k in streams}   # ops the service admitted
    finals = {}
    stop_flood = threading.Event()
    flip_stop = threading.Event()
    flips = []

    def flood():
        """The misbehaving tenant: floods until told to stop; its
        sheds are EXPECTED (and asserted)."""
        h = list(rand_register_history(n_ops=400, n_processes=4,
                                       n_values=3, seed=9999))
        i = 0
        while not stop_flood.is_set():
            lo = (i * 32) % (len(h) - 32)
            try:
                # no explicit seq: the service assigns enq_seq+1, so
                # a shed delta does not leave a gap behind it
                _post_lines(deltas_url,
                            [{"key": "flood-k", "ops":
                              [dict(o) for o in h[lo:lo + 32]],
                              "timeout": 0.05}],
                            "tok-flood", timeout=60)
            except OSError as err:
                fail(f"flood producer transport error: {err}")
                return
            i += 1

    def producer(tname, key):
        pieces = streams[(tname, key)]
        deadline = time.monotonic() + args.secs
        for seq, piece in enumerate(pieces, start=1):
            if time.monotonic() > deadline:
                break
            outs = _post_lines(
                deltas_url,
                [{"key": key, "ops": [dict(o) for o in piece],
                  "seq": seq, "timeout": 120}],
                f"tok-{tname}", timeout=180)
            r = outs[0]
            if r.get("shed"):
                fail(f"quiet tenant {tname} delta shed: {r}")
                break
            if not r.get("accepted"):
                fail(f"quiet tenant {tname} submit error: {r}")
                break
            accepted[(tname, key)].append(piece)
        outs = _post_lines(deltas_url,
                           [{"op": "finalize", "key": key,
                             "timeout": 180}],
                           f"tok-{tname}", timeout=240)
        finals[(tname, key)] = outs[0]

    def flip_monitor():
        """Polls every quiet key's verdict; a False that later reads
        True (at any seq) is a verdict flip — the one thing the whole
        stack promises can never happen."""
        seen_invalid = set()
        while not flip_stop.is_set():
            for (tname, key) in streams:
                r = svc.result(key, min_seq=0, timeout=0.01,
                               tenant=tname)
                v = r.get("valid?")
                if v is False:
                    seen_invalid.add(key)
                elif v is True and key in seen_invalid:
                    flips.append(key)
            time.sleep(0.25)

    threads = [threading.Thread(target=producer, args=k, daemon=True)
               for k in streams]
    fthread = threading.Thread(target=flood, daemon=True)
    mthread = threading.Thread(target=flip_monitor, daemon=True)
    t0 = time.monotonic()
    mthread.start()
    fthread.start()
    for t in threads:
        t.start()

    # --- the fault window: a third in, arm the full matrix; disarm
    # two thirds in — recovery has to finish under remaining load
    time.sleep(args.secs / 3)
    print(f"soak: arming faults ({FAULT_SPEC})")
    os.environ["JEPSEN_TPU_FAULTS"] = FAULT_SPEC
    resilience.reset()
    time.sleep(args.secs / 3)
    del os.environ["JEPSEN_TPU_FAULTS"]
    resilience.reset()
    print("soak: faults disarmed")

    for t in threads:
        t.join(timeout=600)
    stop_flood.set()
    fthread.join(timeout=120)
    if not svc.drain(timeout=300):
        fail("drain did not complete")
    flip_stop.set()
    mthread.join(timeout=30)

    # --- zero verdict flips + bit-identical finals
    if flips:
        fail(f"verdict flips observed on {sorted(set(flips))}")
    for (tname, key), pieces in accepted.items():
        f = finals.get((tname, key)) or {}
        ops = [op for piece in pieces for op in piece]
        if f.get("seq") != len(pieces):
            fail(f"{key}: final seq {f.get('seq')} != accepted "
                 f"{len(pieces)} — an admitted delta went missing")
        if not ops:
            continue
        ref = engine.check_encoded(
            enc_mod.encode(CASRegister(), History.wrap(ops)),
            capacity=256)
        pin = lambda r: {k: r.get(k) for k in  # noqa: E731
                         ("valid?", "op", "fail-event")}
        if pin(f) != pin(ref):
            fail(f"{key}: final verdict diverged from one-shot: "
                 f"{pin(f)} != {pin(ref)}")

    # --- bounded memory
    stats = svc.stats()
    if stats["max_pending_seen"] > 4096:
        fail(f"pending ops exceeded the global bound: {stats}")
    if stats["pending_ops"] != 0:
        fail(f"pending ops after drain: {stats}")

    # --- fairness + per-tenant SLO, verified from the SCRAPE
    status = svc.status()
    trows = status["tenants"]
    if trows["soak-flood"]["acct"]["sheds"] == 0:
        fail("the flooding tenant never shed — the quota never bit")
    for name in quiet:
        if trows[name]["acct"]["sheds"] != 0:
            fail(f"quiet tenant {name} was shed "
                 f"({trows[name]['acct']})")
    with urllib.request.urlopen(ops_srv.url("/metrics"),
                                timeout=30) as resp:
        exposition = resp.read().decode()
    parsed = ops_httpd.parse_prometheus(exposition)
    flood_sheds = parsed.get(
        obs.labeled("jepsen_serve_sheds", tenant="soak-flood"))
    if not flood_sheds or flood_sheds["value"] <= 0:
        fail("/metrics shows no labeled sheds for the flood tenant")
    for name in quiet:
        for which in ("ack", "verdict"):
            h = parsed.get(obs.labeled(
                f"jepsen_serve_{which}_secs", tenant=name))
            if not h or not h.get("count"):
                fail(f"/metrics missing populated "
                     f"serve.{which}_secs{{tenant={name}}}")
        h = parsed.get(obs.labeled("jepsen_serve_ack_secs",
                                   tenant=name))
        p99 = obs.hist_quantile(h, 0.99) if h else None
        if p99 is None or p99 > ACK_SLO_SECS:
            fail(f"quiet tenant {name} ack p99 {p99} past the "
                 f"{ACK_SLO_SECS}s SLO")

    # --- bounded evidence: the ledger rotated under load and its
    # on-disk footprint stayed inside retention × segment cap (plus
    # one record of overshoot per segment and the not-yet-rotated
    # active segment)
    from jepsen_tpu.obs import ledger as ledger_mod
    led = ledger_mod.active()
    if led is None:
        fail("decision ledger armed but not active")
    else:
        led.sync()
        size = ledger_mod.size_bytes(led.root)
        bound = (led.max_segments + 1) * (led.segment_bytes + 4096)
        if size > bound:
            fail(f"ledger outgrew its bound: {size} bytes > {bound} "
                 f"({led.max_segments} segments x "
                 f"{led.segment_bytes} bytes)")
        n_segments = len(ledger_mod.segment_paths(led.root))
        if n_segments > led.max_segments + 1:
            fail(f"ledger retention never bit: {n_segments} segments "
                 f"on disk > {led.max_segments} retained")
        recs, corrupt = ledger_mod.read_records(led.root)
        if corrupt:
            fail(f"ledger read back {corrupt} corrupt line(s)")
        if not recs:
            fail("soak minted no ledger records")

    ing.close()
    ops_srv.close()
    svc.close()
    dur = time.monotonic() - t0
    n_deltas = sum(len(p) for p in accepted.values())
    if failures:
        print(f"soak: {len(failures)} failure(s) in {dur:.1f}s")
        return 1
    print(f"soak: OK in {dur:.1f}s — {n_deltas} quiet deltas across "
          f"{len(streams)} keys / {len(quiet)} tenants, flood shed "
          f"{trows['soak-flood']['acct']['sheds']}x, faults armed "
          f"mid-soak, zero flips, bounded memory + bounded ledger, "
          f"per-tenant SLOs populated")
    return 0


if __name__ == "__main__":
    sys.exit(main())
