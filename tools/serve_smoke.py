#!/usr/bin/env python
"""CI streaming-checker smoke: the serve robustness contract in a few
seconds on CPU.

Starts the CheckerService in-process, streams two keys' histories as
deltas (one key with an injected wedge mid-stream via
JEPSEN_TPU_FAULTS), and asserts:

  * every delta verdict exists and the FINAL verdicts are identical
    (verdict + counterexample fields) to a one-shot batch check of the
    same histories — delta feeding never changes semantics;
  * the injected wedge degrades with a structured note instead of
    flipping a verdict or hanging the service;
  * graceful drain: zero pending ops at close, every admitted delta
    accounted for in the final seq;
  * the live ops surface answers while the service checks deltas: an
    ephemeral-port ops endpoint's /healthz is ready, /metrics parses
    as Prometheus text exposition (incl. the serve.* SLO histograms
    with buckets), and /status lists both smoke keys with their seqs
    (the ISSUE 9 acceptance wiring, end to end).

  * the HTTP ingress admits through the same tenant layer: a second
    service with two tenants (one FLOODING past its quota over
    POST /v1/deltas) still acks every quiet-tenant delta, sheds the
    flood with structured {shed, reason, tenant} answers, and shows
    both on the per-tenant /metrics labels (the ISSUE 12 fairness
    wiring, end to end).

  * the decision ledger records the run: with JEPSEN_TPU_LEDGER armed
    (a tempdir, set below) durable evidence records land on disk for
    the smoke's dispatches AND its publishes, /ledger answers the
    aggregated shape×strategy document while the service runs, and
    the strategy advisor (jepsen report --plan's engine) builds a
    deterministic plan from those live records (the ISSUE 19 wiring,
    end to end).

  * the self-tuning planner routes the smoke's dispatches
    (JEPSEN_TPU_AUTO=1, armed below): /status rows carry the "plan"
    provenance block, /plan answers the live decision table, the
    jepsen_engine_plan_* counters land on /metrics — and the streamed
    verdicts still pin against the static batch check (the ISSUE 20
    wiring, end to end).

`tools/ci.sh` runs this right after fault_smoke (and tools/soak.py
--smoke right after it). This is a wiring check; tests/test_serve.py
+ tests/test_ingress.py + tests/test_ring.py + tests/test_obs_httpd.py
carry the full matrix (families, evict/thaw, WAL replay, overload,
tenancy quotas, ring handoff, exposition format, healthz degradation,
flight recorder).
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _check_ops_surface(ops) -> int:
    """The ops-endpoint acceptance at smoke scale: ready /healthz,
    parseable Prometheus /metrics with the serve SLO histograms, both
    smoke keys in /status. Returns the failure count."""
    import json
    import re

    from jepsen_tpu.obs.httpd import _fetch as _http_get
    failures = 0
    code, body = _http_get(ops.url("/healthz"))
    health = json.loads(body)
    if code != 200 or not health.get("ok"):
        print(f"serve-smoke: /healthz not ready after a clean run: "
              f"{code} {health}")
        failures += 1
    code, body = _http_get(ops.url("/metrics"))
    sample = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+0-9.eE]+$')
    bad = [ln for ln in body.splitlines()
           if ln and not ln.startswith("#") and not sample.match(ln)]
    if code != 200 or bad:
        print(f"serve-smoke: /metrics not valid Prometheus text "
              f"(code {code}): {bad[:3]}")
        failures += 1
    for needed in ("jepsen_serve_ack_secs_bucket",
                   "jepsen_serve_verdict_secs_bucket",
                   "jepsen_serve_deltas",
                   # the smoke runs with JEPSEN_TPU_SEARCH_STATS=1, so
                   # the device-search telemetry series must be live
                   # on the ops surface (the ISSUE 10 wiring)
                   "jepsen_engine_search_events",
                   "jepsen_engine_search_frontier_peak",
                   # and with JEPSEN_TPU_COMPILE_CACHE armed, so the
                   # compile-economics histogram + registry ledger
                   # must be live too (docs/performance.md "Compile
                   # economics")
                   "jepsen_serve_compile_secs_bucket",
                   "jepsen_engine_programs_compiles",
                   # and with JEPSEN_TPU_AUTO armed, so the planner's
                   # decision counter must be live on the surface
                   "jepsen_engine_plan_decisions"):
        if needed not in body:
            print(f"serve-smoke: /metrics missing {needed}")
            failures += 1
    code, body = _http_get(ops.url("/status"))
    status = json.loads(body)
    keys = status.get("keys") or {}
    for k in ('"k1"', '"k2"'):
        row = keys.get(k)
        if row is None or row.get("seq") != 3:
            print(f"serve-smoke: /status missing key {k} at seq 3: "
                  f"{row}")
            failures += 1
        elif not (row.get("plan") or {}).get("vector"):
            # JEPSEN_TPU_AUTO is armed (main()): every key's last
            # result must carry the plan provenance block
            print(f"serve-smoke: /status row {k} missing the plan "
                  f"provenance block: {row.get('plan')}")
            failures += 1
    # JEPSEN_TPU_AUTO is armed: /plan must answer the live decision
    # table while the service runs
    code, body = _http_get(ops.url("/plan"))
    pdoc = json.loads(body)
    if code != 200 or not (pdoc.get("auto") or {}).get("enabled"):
        print(f"serve-smoke: /plan not serving the live auto table: "
              f"{code} {pdoc.get('auto')}")
        failures += 1
    # the decision ledger is armed (tempdir, main()): /ledger must
    # answer the aggregate with live cells while the service runs
    code, body = _http_get(ops.url("/ledger"))
    doc = json.loads(body)
    hdr = doc.get("ledger") or {}
    if code != 200 or not hdr.get("enabled") or not doc.get("cells"):
        print(f"serve-smoke: /ledger not serving live cells: "
              f"{code} {hdr}")
        failures += 1
    return failures


def _check_ledger_evidence() -> int:
    """The ISSUE 19 end-to-end: the smoke's records are durable on
    disk, carry both dispatch and publish evidence, and the advisor
    builds the same plan from them twice. Returns failures."""
    from jepsen_tpu.obs import advisor, ledger as ledger_mod

    failures = 0
    led = ledger_mod.active()
    if led is None:
        print("serve-smoke: ledger armed but not active")
        return 1
    led.sync()
    records, corrupt = ledger_mod.read_records(led.root)
    if corrupt:
        print(f"serve-smoke: ledger has {corrupt} corrupt line(s)")
        failures += 1
    kinds = {r.get("kind") for r in records}
    for needed in ("dispatch", "publish"):
        if needed not in kinds:
            print(f"serve-smoke: no {needed} records on disk "
                  f"(kinds={sorted(kinds)})")
            failures += 1
    engines = {r.get("engine") for r in records}
    if "serve" not in engines:
        print(f"serve-smoke: no serve-minted records "
              f"(engines={sorted(str(e) for e in engines)})")
        failures += 1
    plan = advisor.build_plan(records, [])
    if advisor.build_plan(records, []) != plan:
        print("serve-smoke: advisor plan not deterministic on the "
              "same records")
        failures += 1
    text = advisor.render_plan(plan)
    if not plan.get("shapes") or not text.strip():
        print(f"serve-smoke: advisor produced an empty plan from "
              f"{len(records)} live records")
        failures += 1
    if failures == 0:
        print(f"serve-smoke: ledger evidence OK — {len(records)} "
              f"records, {len(plan['shapes'])} shape group(s), "
              f"advisor plan renders")
    return failures


def _check_ingress_two_tenants() -> int:
    """The fairness wiring at smoke scale: over the HTTP ingress, one
    tenant floods past its quota (sheds, with tenant attribution)
    while the other tenant's deltas all ack. The worker starts
    STOPPED so 'flooding' is deterministic. Returns failures."""
    import json
    import urllib.request

    from jepsen_tpu import obs
    from jepsen_tpu.histories import rand_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.obs import httpd as ops_httpd
    from jepsen_tpu.serve import CheckerService, Tenant
    from jepsen_tpu.serve.ingress import DeltaIngress

    failures = 0
    h = list(rand_register_history(n_ops=80, n_processes=4,
                                   n_values=3, seed=77))
    svc = CheckerService(
        CASRegister(), capacity=128,
        tenants=[Tenant("smoke-flood", token="tf"),
                 Tenant("smoke-quiet", token="tq")],
        global_bound=400, high_water=100, start_worker=False)
    ing = DeltaIngress(svc, port=0).start()

    def post(reqs, token):
        body = "".join(json.dumps(r) + "\n" for r in reqs).encode()
        rq = urllib.request.Request(
            ing.url("/v1/deltas"), data=body,
            headers={"Authorization": f"Bearer {token}"})
        with urllib.request.urlopen(rq, timeout=60) as resp:
            return [json.loads(ln) for ln in
                    resp.read().decode().splitlines()]

    try:
        # flood: each tenant's derived bound is 50 ops; 20 deltas of
        # 8 ops = 160 ops attempted, so most MUST shed — immediately,
        # with the tenant named
        outs = post([{"key": "fk", "ops": [dict(o) for o in
                                           h[i:i + 8]],
                      "timeout": 0.05} for i in range(0, 160, 8)],
                    "tf")
        sheds = [o for o in outs if o.get("shed")]
        if not sheds or any(o.get("tenant") != "smoke-flood"
                            for o in sheds):
            print(f"serve-smoke: flood tenant never shed (or shed "
                  f"without tenant attribution): {outs[-1]}")
            failures += 1
        # quiet tenant: every delta acks despite the flood
        outs = post([{"key": "qk", "ops": [dict(o) for o in
                                           h[i:i + 8]],
                      "timeout": 5} for i in range(0, 40, 8)], "tq")
        if not all(o.get("accepted") for o in outs):
            print(f"serve-smoke: quiet tenant delta not acked under "
                  f"flood: {outs}")
            failures += 1
        st = svc.status()["tenants"]
        if st["smoke-quiet"]["acct"]["sheds"] != 0:
            print(f"serve-smoke: quiet tenant was shed: "
                  f"{st['smoke-quiet']}")
            failures += 1
        # the per-tenant series are on /metrics, labeled
        text = ops_httpd.render_prometheus()
        for needed in ('jepsen_serve_sheds{tenant="smoke-flood"}',
                       'jepsen_serve_ack_secs_bucket'
                       '{tenant="smoke-quiet"'):
            if needed not in text:
                print(f"serve-smoke: /metrics missing {needed}")
                failures += 1
        _ = obs  # imported for parity with the soak's checks
    finally:
        ing.close()
        svc.close(drain=False)   # the worker never ran, by design
    return failures


def main() -> int:
    # device-search telemetry on for the whole smoke: verdicts are
    # flag-independent (parity-pinned), and the ops-surface check
    # asserts the jepsen_engine_search_* series actually appear
    if "JEPSEN_TPU_SEARCH_STATS" not in os.environ:
        os.environ["JEPSEN_TPU_SEARCH_STATS"] = "1"
    # compile economics armed the same way: verdicts stay identical
    # (parity-pinned), and the ops-surface check asserts the
    # jepsen_serve_compile_secs histogram + program-registry counters
    # appear. An isolated tempdir, never a fixed path — the ci.sh
    # serve_smoke tempdir precedent.
    if "JEPSEN_TPU_COMPILE_CACHE" not in os.environ:
        import tempfile
        os.environ["JEPSEN_TPU_COMPILE_CACHE"] = tempfile.mkdtemp(
            prefix="jepsen_smoke_programs_")
    # the decision ledger armed the same way (verdicts are flag-
    # independent, parity-pinned): the ops-surface check asserts
    # /ledger serves live cells, and _check_ledger_evidence proves
    # records→disk→advisor end to end
    if "JEPSEN_TPU_LEDGER" not in os.environ:
        os.environ["JEPSEN_TPU_LEDGER"] = tempfile.mkdtemp(
            prefix="jepsen_smoke_ledger_")
    # the self-tuning planner armed the same way (verdicts are parity-
    # pinned across every strategy the planner routes between, so the
    # streamed-vs-batch pin below also proves AUTO changes nothing):
    # the ops-surface check asserts the "plan" provenance block on
    # /status rows, the jepsen_engine_plan_* series on /metrics, and
    # a live /plan document
    if "JEPSEN_TPU_AUTO" not in os.environ:
        os.environ["JEPSEN_TPU_AUTO"] = "1"

    from jepsen_tpu import resilience
    from jepsen_tpu.histories import corrupt_history, \
        rand_register_history
    from jepsen_tpu.history import History
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.obs import httpd as ops_httpd
    from jepsen_tpu.parallel import encode as enc_mod, engine
    from jepsen_tpu.serve import CheckerService

    m = CASRegister()
    h1 = list(rand_register_history(n_ops=24, n_processes=4,
                                    n_values=3, crash_p=0.05, seed=41))
    h2 = list(corrupt_history(
        rand_register_history(n_ops=24, n_processes=4, n_values=3,
                              crash_p=0.05, seed=42),
        seed=1, n_corruptions=2))
    refs = {k: engine.check_encoded(
        enc_mod.encode(m, History.wrap(h)), capacity=256,
        dedupe="sort") for k, h in (("k1", h1), ("k2", h2))}
    pin = lambda r: {k: r.get(k) for k in  # noqa: E731
                     ("valid?", "op", "fail-event", "max-frontier")}

    failures = 0
    wal = tempfile.mkdtemp(prefix="jepsen_serve_smoke_")
    svc = CheckerService(m, wal_dir=wal, capacity=256, dedupe="sort")
    ops = ops_httpd.start_ops_server(0, health_fn=svc.health,
                                     status_fn=svc.status,
                                     refresh_fn=svc.refresh_gauges)
    try:
        cuts = [(0, 16), (16, 32), (32, 48)]
        for i, (a, b) in enumerate(cuts):
            if i == 1:
                # a wedge mid-stream: the second delta's dispatch dies
                # and must degrade (checkpoint resume / host WGL), not
                # hang or flip
                os.environ["JEPSEN_TPU_FAULTS"] = "wedge@search:n=1"
                resilience.reset()
            try:
                for key, h in (("k1", h1), ("k2", h2)):
                    r = svc.submit(key, h[a:b], wait=True, timeout=120)
                    if "valid?" not in r:
                        print(f"serve-smoke: delta {i} on {key} got "
                              f"no verdict: {r}")
                        failures += 1
            finally:
                if i == 1:
                    del os.environ["JEPSEN_TPU_FAULTS"]
                    resilience.reset()
        finals = {k: svc.finalize(k, timeout=120) for k in refs}
        if not svc.drain(timeout=60):
            print("serve-smoke: drain did not complete")
            failures += 1
        stats = svc.stats()
        if stats["pending_ops"] != 0:
            print(f"serve-smoke: pending ops after drain: {stats}")
            failures += 1
        failures += _check_ops_surface(ops)
    finally:
        svc.close()
        ops.close()
    failures += _check_ledger_evidence()
    failures += _check_ingress_two_tenants()
    for k, ref in refs.items():
        if pin(finals[k]) != pin(ref):
            print(f"serve-smoke: {k} final verdict diverged from the "
                  f"one-shot check: {pin(finals[k])} != {pin(ref)}")
            failures += 1
        if finals[k]["seq"] != 3:   # 3 deltas accepted per key
            print(f"serve-smoke: {k} final seq {finals[k]['seq']} != 3 "
                  f"— an admitted delta went missing")
            failures += 1
    # with JEPSEN_TPU_TRACE=<path> (tools/ci.sh arms it), export the
    # smoke's span chain there — the trace-schema validator
    # (`python -m jepsen_tpu.obs.trace_merge --validate`) runs over
    # this file as the next CI stage
    from jepsen_tpu import obs
    tr = obs.tracer()
    if obs.enabled() and tr.path:
        out = obs.write_chrome_trace(tr.path)
        n_tagged = sum(1 for s in tr.spans()
                       if s.args.get("delta_id")
                       or s.args.get("delta_ids"))
        if not n_tagged:
            print("serve-smoke: traced run produced no "
                  "delta_id-tagged spans")
            failures += 1
        print(f"serve-smoke: trace exported to {out} "
              f"({n_tagged} delta-tagged spans)")
    if failures:
        print(f"serve-smoke: {failures} failure(s)")
        return 1
    print(f"serve-smoke: streamed verdicts identical to batch "
          f"(k1={finals['k1']['valid?']}, k2={finals['k2']['valid?']}), "
          f"wedge degraded cleanly, drain clean, ops endpoint "
          f"(/healthz /metrics /status /ledger /plan) live, decision "
          f"ledger durable + advisor plan built, auto planner "
          f"provenance on /status, two-tenant HTTP ingress fair "
          f"(flood shed, quiet acked)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
