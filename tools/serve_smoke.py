#!/usr/bin/env python
"""CI streaming-checker smoke: the serve robustness contract in a few
seconds on CPU.

Starts the CheckerService in-process, streams two keys' histories as
deltas (one key with an injected wedge mid-stream via
JEPSEN_TPU_FAULTS), and asserts:

  * every delta verdict exists and the FINAL verdicts are identical
    (verdict + counterexample fields) to a one-shot batch check of the
    same histories — delta feeding never changes semantics;
  * the injected wedge degrades with a structured note instead of
    flipping a verdict or hanging the service;
  * graceful drain: zero pending ops at close, every admitted delta
    accounted for in the final seq.

`tools/ci.sh` runs this right after fault_smoke. This is a wiring
check; tests/test_serve.py carries the full matrix (families,
evict/thaw, WAL replay, overload).
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    from jepsen_tpu import resilience
    from jepsen_tpu.histories import corrupt_history, \
        rand_register_history
    from jepsen_tpu.history import History
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import encode as enc_mod, engine
    from jepsen_tpu.serve import CheckerService

    m = CASRegister()
    h1 = list(rand_register_history(n_ops=24, n_processes=4,
                                    n_values=3, crash_p=0.05, seed=41))
    h2 = list(corrupt_history(
        rand_register_history(n_ops=24, n_processes=4, n_values=3,
                              crash_p=0.05, seed=42),
        seed=1, n_corruptions=2))
    refs = {k: engine.check_encoded(
        enc_mod.encode(m, History.wrap(h)), capacity=256,
        dedupe="sort") for k, h in (("k1", h1), ("k2", h2))}
    pin = lambda r: {k: r.get(k) for k in  # noqa: E731
                     ("valid?", "op", "fail-event", "max-frontier")}

    failures = 0
    wal = tempfile.mkdtemp(prefix="jepsen_serve_smoke_")
    svc = CheckerService(m, wal_dir=wal, capacity=256, dedupe="sort")
    try:
        cuts = [(0, 16), (16, 32), (32, 48)]
        for i, (a, b) in enumerate(cuts):
            if i == 1:
                # a wedge mid-stream: the second delta's dispatch dies
                # and must degrade (checkpoint resume / host WGL), not
                # hang or flip
                os.environ["JEPSEN_TPU_FAULTS"] = "wedge@search:n=1"
                resilience.reset()
            try:
                for key, h in (("k1", h1), ("k2", h2)):
                    r = svc.submit(key, h[a:b], wait=True, timeout=120)
                    if "valid?" not in r:
                        print(f"serve-smoke: delta {i} on {key} got "
                              f"no verdict: {r}")
                        failures += 1
            finally:
                if i == 1:
                    del os.environ["JEPSEN_TPU_FAULTS"]
                    resilience.reset()
        finals = {k: svc.finalize(k, timeout=120) for k in refs}
        if not svc.drain(timeout=60):
            print("serve-smoke: drain did not complete")
            failures += 1
        stats = svc.stats()
        if stats["pending_ops"] != 0:
            print(f"serve-smoke: pending ops after drain: {stats}")
            failures += 1
    finally:
        svc.close()
    for k, ref in refs.items():
        if pin(finals[k]) != pin(ref):
            print(f"serve-smoke: {k} final verdict diverged from the "
                  f"one-shot check: {pin(finals[k])} != {pin(ref)}")
            failures += 1
        if finals[k]["seq"] != 3:   # 3 deltas accepted per key
            print(f"serve-smoke: {k} final seq {finals[k]['seq']} != 3 "
                  f"— an admitted delta went missing")
            failures += 1
    if failures:
        print(f"serve-smoke: {failures} failure(s)")
        return 1
    print(f"serve-smoke: streamed verdicts identical to batch "
          f"(k1={finals['k1']['valid?']}, k2={finals['k2']['valid?']}), "
          f"wedge degraded cleanly, drain clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
