"""A/B/C perf harness: XLA while-closure vs XLA fori-closure vs the
VMEM-resident pallas kernel.

Decides (a) whether JEPSEN_TPU_PALLAS should default ON for the TPU
backend, and (b) whether the XLA closure's default loop shape should
flip to the fixed-trip fori variant (JEPSEN_TPU_CLOSURE=fori) — both
gated behind env flags until a hardware measurement exists ("flags do
not get to claim speedups", pallas_kernels.py docstring). Run on the
real chip:

    python tools/perf_ab.py              # full shapes
    BENCH_SMOKE=1 python tools/perf_ab.py  # tiny shapes (CI sanity)

Measures, per shape, steady-state wall time (cold run first to absorb
compiles; results fetched to host, so timings include the device sync),
and CORRECTNESS: each variant's result is compared against the while
baseline on every timed shape — a variant that ever disagrees is
vetoed from the verdict regardless of its speed (the on-chip gate the
pallas non-interpret lowering must pass before any default flip):

  single-key adversarial 1k / 10k   (the bench's headline shape)
  multi-key 84x120 batch            (the reference workload shape)

Prints one JSON line per measurement and a final verdict line with the
pallas:xla ratio per shape. The engine paths are driven through their
public entry points (check_encoded_bitdense / check_batch_bitdense)
with use_pallas explicitly set, so what is measured is exactly what the
flag would switch.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu import obs  # noqa: E402  (sys.path bootstrap above)

SMOKE = os.environ.get("BENCH_SMOKE") == "1"
REPEATS = 3

# PERF_AB_VARIANTS=while,pallas skips the others. Exists because a
# variant can crash the TPU *worker process* (observed r5: the fori
# closure on the 10k adversarial shape took down the worker), which
# poisons the whole PJRT client — no in-process try/except can recover
# it. A skipped variant keeps its prior verdict; 'while' (the baseline)
# is always measured.
_VARIANTS = {v.strip() for v in os.environ.get(
    "PERF_AB_VARIANTS", "while,fori,pallas").split(",") if v.strip()}
_UNKNOWN = _VARIANTS - {"while", "fori", "pallas"}
if _UNKNOWN:   # a typo must not silently skip a real variant
    raise SystemExit(f"PERF_AB_VARIANTS: unknown variant(s) "
                     f"{sorted(_UNKNOWN)}; valid: while,fori,pallas")
_VARIANTS.add("while")

# PERF_AB_DEDUPE=sort,hash,hash-pallas,hash-packed (default all four)
# selects the sparse-engine frontier-dedupe strategies the advisory
# A/B measures on the single-key adversarial shapes — the one-command
# measurement the JEPSEN_TPU_DEDUPE, JEPSEN_TPU_SPARSE_PALLAS, and
# JEPSEN_TPU_CONFIG_PACK flip-to-default decisions wait on
# ("hash-pallas" = the hash strategy through the VMEM frontier
# kernels, parallel.sparse_kernels — fused inside the width-aware
# gate, TILED past it, so no chip-matrix shape skips wholesale
# anymore; "hash-packed" = the hash strategy over the packed
# configuration word). Same skip-a-crashing-variant rationale as
# PERF_AB_VARIANTS; empty (PERF_AB_DEDUPE=) skips the block entirely.
# A typo raises with the valid set listed — an unknown name silently
# skipped would read as "measured and lost".
_DEDUPE_VALID = ("sort", "hash", "hash-pallas", "hash-packed")
_DEDUPE = [v.strip() for v in os.environ.get(
    "PERF_AB_DEDUPE",
    "sort,hash,hash-pallas,hash-packed").split(",") if v.strip()]
_UNKNOWN_D = set(_DEDUPE) - set(_DEDUPE_VALID)
if _UNKNOWN_D:
    raise SystemExit(f"PERF_AB_DEDUPE: unknown strategy(ies) "
                     f"{sorted(_UNKNOWN_D)}; valid: "
                     f"{','.join(_DEDUPE_VALID)}")

# PERF_AB_ELASTIC=steal,reshard (default both) selects the elastic-
# scheduling arms — the recorded A/B the JEPSEN_TPU_STEAL and
# JEPSEN_TPU_RESHARD flip decisions wait on: "steal" runs the pinned
# forced-skew multikey shape (parallel.elastic.forced_skew_histories)
# through the round executor with the scheduler off then on, plus an
# untimed stats-armed pass per arm whose search_stats record captures
# the BEFORE/AFTER per-device load-factor spread; "reshard" times the
# grow-the-table sharded ladder against the device-recruiting one on
# an escalating adversarial shape. Same validation posture as the
# other selector envs: a typo raises with the valid set listed.
_ELASTIC_VALID = ("steal", "reshard")
_ELASTIC = [v.strip() for v in os.environ.get(
    "PERF_AB_ELASTIC", "steal,reshard").split(",") if v.strip()]
_UNKNOWN_E = set(_ELASTIC) - set(_ELASTIC_VALID)
if _UNKNOWN_E:
    raise SystemExit(f"PERF_AB_ELASTIC: unknown arm(s) "
                     f"{sorted(_UNKNOWN_E)}; valid: "
                     f"{','.join(_ELASTIC_VALID)}")

# PERF_AB_COMPILE=0 skips the compile-economics record (default on) —
# cold-start vs warm-cache first-dispatch through a shared
# JEPSEN_TPU_COMPILE_CACHE dir, each arm its own subprocess so the
# in-process jit cache can't leak the cold arm's compile into the warm
# one. Same validation posture: an unrecognized value raises.
_COMPILE = os.environ.get("PERF_AB_COMPILE", "1")
if _COMPILE not in ("0", "1"):
    raise SystemExit(f"PERF_AB_COMPILE: {_COMPILE!r} invalid; "
                     f"valid: 0,1")

# PERF_AB_AUTO=1 adds the self-tuning planner arm (JEPSEN_TPU_AUTO,
# parallel.planner): the same adversarial sparse shape dispatched with
# every strategy axis left unset, so the online decision table routes
# it from the evidence the dispatches themselves mint. ADVISORY ONLY —
# the auto timings never feed a flip verdict (the planner only routes
# BETWEEN arms the static lines already measured); the line exists so
# the flag-flip campaign can see whether the table converges to the
# measured winner. Same validation posture: a typo raises.
_AUTO = os.environ.get("PERF_AB_AUTO", "0")
if _AUTO not in ("0", "1"):
    raise SystemExit(f"PERF_AB_AUTO: {_AUTO!r} invalid; valid: 0,1")


def _want(name: str) -> bool:
    return name in _VARIANTS


def emit(obj):
    print(json.dumps(obj), flush=True)


def _cost_priors(lower_one, pallas_ok: bool) -> dict:
    """Per-variant analytical prior from XLA's trace-time cost model
    (bitdense.cost_analysis_*): a ranking signal that exists even when
    no chip is reachable, and a cross-check on the measured ratios
    once one is. The while/fori rows are backend-independent; the
    pallas row is not (its 'program' field says what was costed).
    `lower_one(use_pallas, mode)` returns {"flops", "bytes_accessed",
    "program"}."""
    out = {}
    for name, (up, mode) in {"while": (False, "while"),
                             "fori": (False, "fori"),
                             "pallas": (True, "while")}.items():
        if name == "pallas" and not pallas_ok:
            out[name] = {"skipped": "unsupported shape"}
            continue
        try:
            out[name] = lower_one(up, mode)
        except Exception as err:  # noqa: BLE001 — the prior is
            out[name] = {"error": repr(err)}   # advisory, never fatal
    return out


def _cost_entry(lower_one, pallas_ok: bool, scan_events: int,
                C: int) -> dict:
    """One cost_table row: per-variant priors + the static trip counts
    (the cost model counts loop bodies once, so totals are modeled as
    body-cost x trips by the consumer)."""
    cost = _cost_priors(lower_one, pallas_ok)
    cost["trips"] = {"scan_events": scan_events,
                     "fori_closure": -(-C // 2)}
    return cost


def _steady(fn, shape: str = "", variant: str = ""):
    """Best-of-REPEATS steady wall time, measured through obs.timer so
    the recorded spans (with shape/variant attrs) and the emitted
    numbers are the same clock reads — run with JEPSEN_TPU_TRACE=1 and
    the measurement session itself opens in Perfetto. The best-of is
    also fed to the perf_ab.steady_secs registry histogram."""
    fn()                                    # cold: compile + warm cache
    best = float("inf")
    for _ in range(REPEATS):
        with obs.timer("perf_ab.run", shape=shape,
                       variant=variant) as tm:
            fn()
        best = min(best, tm.wall)
    obs.histogram("perf_ab.steady_secs").observe(best)
    return best


def _strip_closure(r):
    if isinstance(r, list):
        return [_strip_closure(x) for x in r]
    return {k: v for k, v in r.items() if k != "closure"}


PROFILE_DIR = os.environ.get("PERF_AB_PROFILE")


def _timed(res: dict, name: str, check, shape: str = "") -> float:
    """Time `check` via _steady, recording the result of EVERY
    execution (cold + each repeat) under res[name] — a
    nondeterministically-wrong kernel that happens to answer
    correctly on its last run must still flag.

    With PERF_AB_PROFILE=<dir>, one extra post-timing run per
    (shape, variant) is captured under jax.profiler.trace into its own
    subdirectory — the diagnosis artifact for WHERE the time goes
    (dispatch/sync vs compute; the r3 multikey regression suspicion),
    kept out of the timed runs so profiling overhead never skews the
    measured ratios."""
    def f():
        res.setdefault(name, []).append(check())
    t = _steady(f, shape=shape, variant=name)
    if PROFILE_DIR:
        try:
            import jax
            sub = os.path.join(
                PROFILE_DIR, _run_token(),
                f"{shape or 'shape'}-{name}".replace(" ", "_"))
            os.makedirs(sub, exist_ok=True)
            with jax.profiler.trace(sub):
                f()          # result feeds the correctness gate too
            emit({"profile": sub, "shape": shape, "variant": name})
        except Exception as err:  # noqa: BLE001 — the capture is
            # advisory, never fatal: timings and verdict already stand
            emit({"profile_error": repr(err), "shape": shape,
                  "variant": name})
    return t


_RUN_TOKEN = None


def _run_token() -> str:
    """One fresh subdirectory per harness invocation, so re-running
    into the same PERF_AB_PROFILE dir never mixes trace sessions."""
    global _RUN_TOKEN
    if _RUN_TOKEN is None:
        from datetime import datetime
        _RUN_TOKEN = (datetime.now().strftime("%Y%m%d-%H%M%S")
                      + f"-p{os.getpid()}")
    return _RUN_TOKEN


def _disagreeing(results: dict) -> set:
    """Correctness gate: every run of every measured variant must
    return the SAME result (verdict + counterexample fields; the
    closure label aside) as the while baseline's first run — a faster
    wrong kernel must never win. Returns the variant names with any
    disagreeing run (emitted; they veto the matching verdict below;
    'while' itself can flag, vetoing everything: it means the
    measurement is nondeterministic)."""
    vals = {k: [_strip_closure(r) for r in runs]
            for k, runs in results.items()}
    base = vals["while"][0]
    bad = {k for k, runs in vals.items()
           if any(r != base for r in runs)}
    if bad:
        emit({"correctness_mismatch":
              {k: vals[k] for k in sorted(bad | {"while"})}})
    return bad


def _probe_backend(timeout: float = 120.0):
    """Resolve the default backend in a THROWAWAY subprocess under a
    timeout: on this image a dead TPU tunnel blocks forever inside
    PJRT client creation with no Python-level signal delivery, so the
    probe — not this process — takes the hang. Returns the backend
    name, or None when the runtime is unreachable."""
    import subprocess
    code = ("import os, jax\n"
            "p = os.environ.get('JAX_PLATFORMS')\n"
            "if p: jax.config.update('jax_platforms', p)\n"
            "print(jax.default_backend())\n")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout)
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0:
        return None
    lines = out.stdout.strip().splitlines()
    return lines[-1] if lines else None


# One compile-record arm, run via `python -c` in a throwaway process:
# encode the adversarial shape, time the FIRST check_encoded dispatch
# (trace + compile or cache load + run, fetched to host), and report
# the program-registry ledger so the parent can tell a fresh compile
# (cold) from a deserialized executable (warm) without guessing.
_COMPILE_CHILD = """\
import json, os, sys, time
import jax
p = os.environ.get("JAX_PLATFORMS")
if p:
    jax.config.update("jax_platforms", p)
from jepsen_tpu.histories import adversarial_register_history
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import encode as enc_mod
from jepsen_tpu.parallel import engine as eng_mod
from jepsen_tpu.parallel import programs
L, k = int(sys.argv[1]), int(sys.argv[2])
e = enc_mod.encode(CASRegister(), adversarial_register_history(
    n_ops=L, k_crashed=k, seed=7))
cap = 1 << (k + 4)
t0 = time.perf_counter()
r = eng_mod.check_encoded(e, capacity=cap, max_capacity=cap * 4,
                          dedupe="hash")
secs = time.perf_counter() - t0
reg = programs.registry()
print(json.dumps({
    "first_dispatch_secs": secs,
    "stats": reg.stats() if reg is not None else None,
    "rows": int(e.slot_f.shape[0]),
    "pin": {k_: r.get(k_) for k_ in ("valid?", "op", "fail-event",
                                     "max-frontier",
                                     "configs-stepped")},
}))
"""


def compile_record(shapes, extra_rows=(), timeout=600.0):
    """The compile-economics record (docs/performance.md "Compile
    economics"): per chip-matrix shape, cold-start vs warm-cache
    first-dispatch seconds through one shared JEPSEN_TPU_COMPILE_CACHE
    dir — each arm a THROWAWAY subprocess (the _probe_backend isolation
    rationale: an in-process A/B would hand the warm arm the cold
    arm's live jit cache, timing nothing), so what is measured is
    exactly the restart a serve replica pays with and without a
    populated cache. Also emits the program-population arithmetic
    (distinct event-row shapes, exact vs canonicalized onto the
    EVENT_QUANTUM ladder) over every row count measured plus
    `extra_rows` — the JEPSEN_TPU_CANON_SHAPES sizing evidence,
    computable with no chip. Returns the per-shape records and the
    population dict; tests/test_perf_ab.py calls this directly on tiny
    shapes and asserts the warm arm is strictly faster with zero fresh
    compiles."""
    import shutil
    import subprocess
    import tempfile
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    records = []
    rows = [int(r) for r in extra_rows]
    for L, k in shapes:
        cache = tempfile.mkdtemp(prefix="jepsen_perf_ab_programs_")
        line = {"shape": f"compile-{L}@2^{k}"}
        arms = {}
        try:
            for arm in ("cold", "warm"):
                env = dict(os.environ,
                           JEPSEN_TPU_COMPILE_CACHE=cache,
                           JEPSEN_TPU_CANON_SHAPES="1",
                           JEPSEN_TPU_PRECOMPILE="0",
                           PYTHONPATH=os.pathsep.join(
                               [root,
                                os.environ.get("PYTHONPATH", "")]
                           ).rstrip(os.pathsep))
                try:
                    out = subprocess.run(
                        [sys.executable, "-c", _COMPILE_CHILD,
                         str(L), str(k)],
                        capture_output=True, text=True,
                        timeout=timeout, env=env)
                except subprocess.TimeoutExpired:
                    line[f"{arm}_error"] = f"timeout after {timeout}s"
                    break
                if out.returncode != 0:
                    line[f"{arm}_error"] = out.stderr.strip()[-300:]
                    break
                arms[arm] = json.loads(
                    out.stdout.strip().splitlines()[-1])
        finally:
            shutil.rmtree(cache, ignore_errors=True)
        if "cold" in arms and "warm" in arms:
            cold, warm = arms["cold"], arms["warm"]
            line.update(
                cold_first_dispatch_secs=round(
                    cold["first_dispatch_secs"], 3),
                warm_first_dispatch_secs=round(
                    warm["first_dispatch_secs"], 3),
                warm_speedup=round(
                    cold["first_dispatch_secs"]
                    / max(warm["first_dispatch_secs"], 1e-9), 2),
                cold_compiles=(cold["stats"] or {}).get("compiles"),
                warm_compiles=(warm["stats"] or {}).get("compiles"),
                warm_preloads=(warm["stats"] or {}).get("preloads"),
                warm_load_errors=(warm["stats"] or {}).get(
                    "load_errors"))
            # a cache-loaded program that answers differently is a
            # correctness failure, not a perf detail — flag it like
            # the variant mismatches above
            if warm["pin"] != cold["pin"]:
                line["pin_mismatch"] = True
            rows.append(int(cold["rows"]))
        emit(line)
        records.append(line)
    from jepsen_tpu.parallel import programs
    pop = programs.population_counts(rows) if rows else None
    emit({"compile_population": pop,
          "rows_measured": sorted(set(rows)),
          "note": "distinct event-row shapes a workload compiles, "
                  "exact vs canonicalized onto the EVENT_QUANTUM "
                  "ladder — the per-process program count "
                  "JEPSEN_TPU_CANON_SHAPES buys down; pure quantum "
                  "arithmetic, no chip needed"})
    return {"records": records, "population": pop}


def main():
    backend = _probe_backend()
    if backend is None:
        emit({"error": "device runtime unreachable — backend probe "
                       "hung or crashed (dead TPU tunnel?); set "
                       "JAX_PLATFORMS=cpu for an interpret-mode "
                       "sanity run"})
        sys.exit(1)

    import jax

    # honor JAX_PLATFORMS via jax.config too: on this image the axon
    # plugin initializes (and hangs on, when the tunnel is down) the
    # TPU client even under the env var alone — same pinning pattern
    # as tests/conftest.py and the dryrun hardening
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    from jepsen_tpu.histories import (
        adversarial_register_history, rand_register_history)
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import bitdense, encode as enc_mod
    from jepsen_tpu.parallel import pallas_kernels as pk

    # off-TPU runs are interpret-mode sanity checks whose timings the
    # verdict ignores — full shapes would grind for hours producing
    # discarded numbers, so force the tiny shapes. "axon" IS the real
    # chip (the PJRT plugin's backend name).
    smoke = SMOKE or not bitdense.is_tpu_platform(backend)
    if smoke and not SMOKE:
        emit({"note": f"non-tpu backend {backend!r}: forcing smoke "
                      f"shapes (interpret-mode timings, no verdict)"})
    model = CASRegister()
    ratios = {}
    fori_ratios = {}
    cost_table = {}
    bad_variants = set()       # variants that ever disagreed

    # ---- single-key adversarial ----
    adv_sizes = []           # the Ls measured — the dedupe A/B derates
    for L in ([200, 400] if smoke else [1000, 10000]):  # its own shapes
        # k=11 keeps the smoke shapes inside kernel support (C >= 12)
        k_crashed = 11 if smoke else 12
        h = adversarial_register_history(
            n_ops=L, k_crashed=k_crashed, seed=7)
        e = enc_mod.encode(model, h)
        adv_sizes.append(L)
        S, C = bitdense.n_states(e), max(5, e.n_slots)
        cost_table[f"single-{L}"] = _cost_entry(
            lambda up, mode: bitdense.cost_analysis_encoded(
                e, use_pallas=up, closure_mode=mode),
            pk.supported(S, C), e.n_returns, C)
        # while and fori are pure XLA: measured on EVERY shape — the
        # fori decision must never be settled by a pallas support skip.
        # Every execution's RESULT is captured for the correctness gate.
        res = {}

        def timed(name, **kw):
            return _timed(res, name,
                          lambda: bitdense.check_encoded_bitdense(
                              e, **kw),
                          shape=f"single-{L}")

        t_xla = timed("while", use_pallas=False, closure_mode="while")
        line = {"shape": f"single-key {L}-op adversarial", "S": S,
                "C": C,
                "xla_secs": round(t_xla, 3)}
        if _want("fori"):
            t_fori = timed("fori", use_pallas=False,
                           closure_mode="fori")
            fori_ratios[f"single-{L}"] = t_xla / t_fori
            line.update(fori_secs=round(t_fori, 3),
                        fori_speedup=round(t_xla / t_fori, 2))
        if _want("pallas") and pk.supported(S, C):
            # a variant that fails to COMPILE (e.g. a Mosaic lowering
            # gap only the real chip reveals — the r5 jnp.flip `rev`
            # find) must veto itself, not kill the while/fori
            # measurements the bench decision also needs
            try:
                t_pl = timed("pallas", use_pallas=True)
            except Exception as err:  # noqa: BLE001
                line["pallas_error"] = repr(err)[:300]
                bad_variants.add("pallas")
            else:
                ratios[f"single-{L}"] = t_xla / t_pl
                line.update(pallas_secs=round(t_pl, 3),
                            pallas_speedup=round(t_xla / t_pl, 2))
        elif _want("pallas"):
            line["pallas_skipped"] = f"unsupported S={S} C={C}"
        bad_variants |= _disagreeing(res)
        emit(line)

    # ---- sparse-engine frontier dedupe (advisory A/B) ----
    # sort (lexsort every closure iteration) vs hash (delta-frontier
    # closure over the device-resident visited set) on the SAME
    # adversarial shapes, through the public engine.check_encoded with
    # dedupe explicitly set — exactly what JEPSEN_TPU_DEDUPE would
    # switch. The configs-stepped counters are emitted alongside the
    # timings so the work reduction is visible even where the wall
    # times are noise (CPU). Verdict + localization + max-frontier must
    # agree between strategies (the counters differ by design); a
    # mismatch vetoes the dedupe verdict like any correctness failure.
    dedupe_ratios = {}
    sparse_pallas_ratios = {}
    config_pack_ratios = {}
    dedupe_bad = set()
    if _DEDUPE:
        from jepsen_tpu.parallel import engine as eng_mod
        from jepsen_tpu.parallel import sparse_kernels as sk
        # shape policy: the adversarial frontier peaks at ~10*2^k
        # configs, so full-k sparse runs cost minutes per strategy —
        # smoke (CPU) derates to k=6 (the delta asymptotics show at
        # any k; CI keeps the block exercised), the chip measures the
        # bench's real k at L=1000 (the representative sparse shape;
        # 10k at full k is tens of minutes per strategy and adds no
        # new information to the flip decision). The chip additionally
        # measures k=8 (capacity 4096) — the largest full-support
        # shape for the fused frontier kernel, whose VMEM gate excludes
        # the 2^16-capacity k=12 shape; the sparse-pallas flip decision
        # rides only shapes the kernel actually ran.
        if smoke:
            dedupe_shapes = [(L, 6) for L in adv_sizes]
        else:
            dedupe_shapes = [(1000, 12), (1000, 8)]
        for L, k_d in dedupe_shapes:
            e = enc_mod.encode(model, adversarial_register_history(
                n_ops=L, k_crashed=k_d, seed=7))
            cap = 1 << (k_d + 4)     # peak ~10*2^k configs, one tier
            shape_key = f"single-{L}@2^{k_d}"
            # the HOST-ONLY gate-coverage record (sparse_kernels.
            # gate_coverage): bytes/row, packed word width, and what
            # WOULD run (pallas / pallas-tiled / xla-hash) per layout
            # at this shape's capacity — computable with no chip, so
            # the flag-flip campaign inherits the sizing evidence
            # before a single on-chip measurement lands. Schema pinned
            # by tests/test_perf_ab.py.
            emit({"gate_coverage": sk.gate_coverage(
                      e.n_states, e.state_lo, e.slot_f.shape[1], cap),
                  "shape": shape_key})
            dres = {}
            dline = {"shape": f"single-key {L}-op adversarial "
                              f"sparse-dedupe (2^{k_d} open configs)"}
            for strat in _DEDUPE:
                if strat == "hash-pallas":
                    if not sk.supported(cap, e.slot_f.shape[1]) \
                            and sk.tiled_plan(
                                cap, e.slot_f.shape[1]) is None:
                        # only a shape even the TILED closure cannot
                        # cover skips — measuring the note-and-fallback
                        # path would time the XLA closure under the
                        # kernel's name. (The k=12 headline shape now
                        # runs: fused inside the gate, tiled past it.)
                        dline["hash-pallas_skipped"] = (
                            f"capacity {cap} past the kernel's VMEM "
                            f"gate even tiled")
                        continue
                    kw = {"dedupe": "hash", "sparse_pallas": True}
                elif strat == "hash-packed":
                    kw = {"dedupe": "hash", "config_pack": True}
                else:
                    kw = {"dedupe": strat}
                t = _timed(dres, strat,
                           lambda k=kw: eng_mod.check_encoded(
                               e, capacity=cap, max_capacity=cap * 4,
                               **k),
                           shape=f"dedupe-{L}-2^{k_d}")
                r0 = dres[strat][0]
                dline[f"{strat}_secs"] = round(t, 3)
                dline[f"{strat}_configs_stepped"] = \
                    r0.get("configs-stepped")
            pin = lambda r: {k_: r.get(k_) for k_ in  # noqa: E731
                             ("valid?", "op", "fail-event",
                              "max-frontier")}
            if dres:
                # dres can be empty: PERF_AB_DEDUPE=hash-pallas alone
                # on a shape past the kernel's VMEM gate skips the
                # only selected strategy — the line still emits (with
                # the skip note), the harness must not die on it
                base = pin(dres[next(iter(dres))][0])
                for strat, runs in dres.items():
                    if any(pin(r) != base for r in runs):
                        dline[f"{strat}_mismatch"] = True
                        dedupe_bad.add(strat)
            if "sort" in dres and "hash" in dres:
                dedupe_ratios[shape_key] = \
                    dline["sort_secs"] / max(dline["hash_secs"], 1e-9)
                dline["hash_speedup"] = round(
                    dedupe_ratios[shape_key], 2)
            if "hash" in dres and "hash-pallas" in dres:
                sparse_pallas_ratios[shape_key] = (
                    dline["hash_secs"]
                    / max(dline["hash-pallas_secs"], 1e-9))
                dline["hash_pallas_speedup"] = round(
                    sparse_pallas_ratios[shape_key], 2)
            if "hash" in dres and "hash-packed" in dres:
                config_pack_ratios[shape_key] = (
                    dline["hash_secs"]
                    / max(dline["hash-packed_secs"], 1e-9))
                dline["hash_packed_speedup"] = round(
                    config_pack_ratios[shape_key], 2)
            emit(dline)
            # the per-shape search-stats block (JEPSEN_TPU_SEARCH_
            # STATS machinery, forced on for this one untimed run so
            # the A/B JSONL ships probe/occupancy evidence alongside
            # the timings — ROADMAP items 2/5's sizing inputs): one
            # hash-dedupe run per shape, never timed, never part of
            # the flip decision
            if "hash" in dres:
                try:
                    rs = eng_mod.check_encoded(
                        e, capacity=cap, max_capacity=cap * 4,
                        dedupe="hash", search_stats=True)
                    st = dict(rs.get("stats") or {})
                    # trajectories are per-event lists — summarize for
                    # the JSONL record, the run dir keeps the full form
                    for key_ in ("frontier-width", "closure-iters",
                                 "configs-stepped-per-event",
                                 "closure-peak"):
                        st.pop(key_, None)
                    emit({"search_stats": st, "shape": shape_key})
                except Exception as err:  # noqa: BLE001 — advisory
                    # evidence must not kill the measurement run
                    emit({"search_stats_error": repr(err),
                          "shape": shape_key})

    # ---- auto planner arm (JEPSEN_TPU_AUTO — advisory only) ----
    # one adversarial sparse shape, every strategy axis left unset,
    # the planner routing from a throwaway ledger dir: measures the
    # cost of letting the online table pick vs. dispatching the static
    # default, and records the vector the table converged to. The
    # steady loop itself is the convergence driver — the cold run and
    # each repeat mint evidence, so by the best-of window the table
    # has samples past the floor. Never part of a flip verdict; the
    # same >=1.1x / never-disagreed reading is applied to the advisory
    # ratio so the JSONL is self-describing.
    auto_ratios = {}
    auto_bad = False
    auto_plan = None
    if _AUTO == "1":
        import shutil
        import tempfile
        from jepsen_tpu.obs import ledger as led_mod
        from jepsen_tpu.parallel import engine as eng_mod
        from jepsen_tpu.parallel import planner as pl_mod
        L_a, k_a = (adv_sizes[0], 6) if smoke else (1000, 8)
        e_a = enc_mod.encode(model, adversarial_register_history(
            n_ops=L_a, k_crashed=k_a, seed=7))
        cap_a = 1 << (k_a + 4)
        shape_key = f"auto-{L_a}@2^{k_a}"
        ares = {}
        t_static = _timed(ares, "static",
                          lambda: eng_mod.check_encoded(
                              e_a, capacity=cap_a,
                              max_capacity=cap_a * 4),
                          shape=shape_key)
        tmp = tempfile.mkdtemp(prefix="jepsen-perf-ab-auto-")
        saved = {k_: os.environ.get(k_)
                 for k_ in ("JEPSEN_TPU_AUTO", "JEPSEN_TPU_LEDGER")}
        os.environ["JEPSEN_TPU_AUTO"] = "1"
        os.environ["JEPSEN_TPU_LEDGER"] = tmp
        pl_mod.reset()
        led_mod.reset()
        try:
            t_auto = _timed(ares, "auto",
                            lambda: eng_mod.check_encoded(
                                e_a, capacity=cap_a,
                                max_capacity=cap_a * 4),
                            shape=shape_key)
        finally:
            # the arm must not leak AUTO routing (or the throwaway
            # table) into the elastic / batch blocks that follow
            for k_, v_ in saved.items():
                if v_ is None:
                    os.environ.pop(k_, None)
                else:
                    os.environ[k_] = v_
            pl_mod.reset()
            led_mod.reset()
            shutil.rmtree(tmp, ignore_errors=True)
        pin_a = lambda r: {k_: r.get(k_) for k_ in  # noqa: E731
                           ("valid?", "op", "fail-event",
                            "max-frontier")}
        base_a = pin_a(ares["static"][0])
        auto_bad = any(pin_a(r) != base_a
                       for r in ares["static"] + ares["auto"])
        auto_plan = ares["auto"][-1].get("plan")
        auto_ratios[shape_key] = t_static / max(t_auto, 1e-9)
        emit({"shape": f"single-key {L_a}-op adversarial auto-planner "
                       f"(2^{k_a} open configs)",
              "static_secs": round(t_static, 3),
              "auto_secs": round(t_auto, 3),
              "auto_speedup": round(auto_ratios[shape_key], 2),
              "auto_plan": auto_plan,
              "auto_mismatch": auto_bad})

    # ---- elastic scheduling (steal / reshard arms) ----
    steal_ratios = {}
    reshard_ratios = {}
    elastic_bad = set()
    if _ELASTIC:
        import numpy as _np
        from jax.sharding import Mesh as _Mesh
        from jepsen_tpu.parallel import elastic as el_mod
        mesh_all = _Mesh(_np.array(jax.devices()), ("key",))
        if "steal" in _ELASTIC:
            n_h, n_l = (4, 12) if smoke else (8, 40)
            model_sk, hs_sk = el_mod.forced_skew_histories(
                n_heavy=n_h, n_light=n_l)
            pre_sk = [enc_mod.encode(model_sk, h) for h in hs_sk]
            shape_key = f"multikey-skew-{n_h}h{n_l}l"
            try:
                ab = el_mod.steal_ab(model_sk, pre_sk, mesh_all)
            except AssertionError:
                # the A/B's own parity gate fired: scheduling changed
                # a result — vetoes the verdict like any mismatch
                elastic_bad.add("steal")
                emit({"steal_mismatch": True, "shape": shape_key})
            else:
                steal_ratios[shape_key] = ab["steal_speedup"]
                b_s, b_e = ab["static"][0], ab["steal"][0]
                emit({"shape": shape_key,
                      "static_secs": ab["static_secs"],
                      "steal_secs": ab["steal_secs"],
                      "steal_speedup": round(ab["steal_speedup"], 2),
                      "keys_stolen": b_e.get("steals"),
                      "busy_frac_static": b_s.get("busy_frac"),
                      "busy_frac_steal": b_e.get("busy_frac")})
                # the per-shape search_stats evidence record: one
                # UNTIMED stats-armed hash-dedupe pass per arm — the
                # before/after per-device load-factor spread the flag-
                # flip campaign reads; never part of the flip decision
                try:
                    ev = {}
                    for arm, name in ((False, "static"),
                                      (True, "steal")):
                        st = {}
                        el_mod.check_batch_stealing(
                            model_sk, pre_sk,
                            capacity=el_mod.SKEW_CAPACITY,
                            max_capacity=1 << 16, mesh=mesh_all,
                            steal=arm, dedupe="hash",
                            search_stats=True, stats=st)
                        b = st["buckets"][0]
                        ev[f"per_device_load_factor_{name}"] = \
                            b.get("per_device_load_factor_peak")
                        ev[f"load_factor_spread_{name}"] = \
                            b.get("load_factor_spread")
                        ev[f"per_device_cost_{name}"] = \
                            b.get("per_device_cost")
                    emit({"search_stats": ev, "shape": shape_key})
                except Exception as err:  # noqa: BLE001 — advisory
                    emit({"search_stats_error": repr(err),
                          "shape": shape_key})
        if "reshard" in _ELASTIC:
            from jepsen_tpu.parallel import sharded as sh_mod
            L_r, k_r = (200, 6) if smoke else (1000, 8)
            e_r = enc_mod.encode(model, adversarial_register_history(
                n_ops=L_r, k_crashed=k_r, seed=7))
            cap_r = 128    # well under the ~10*2^k peak: both arms
            # must climb their ladders — that climb IS the measurement
            shape_key = f"sharded-reshard-{L_r}@2^{k_r}"
            res_r = {}
            t_st = _timed(res_r, "static",
                          # reshard pinned OFF: an exported
                          # JEPSEN_TPU_RESHARD=1 must not delegate
                          # the static arm to the elastic ladder and
                          # A/B it against itself (the steal arm pins
                          # steal=arm the same way)
                          lambda: sh_mod.check_encoded_sharded(
                              e_r, mesh_all, capacity=cap_r,
                              max_capacity=1 << 16, reshard=False),
                          shape=shape_key)
            t_el = _timed(res_r, "reshard",
                          lambda: sh_mod.check_encoded_sharded_elastic(
                              e_r, mesh_all, capacity=cap_r,
                              max_capacity=1 << 16),
                          shape=shape_key)
            pin_r = lambda r: {k_: r.get(k_) for k_ in  # noqa: E731
                               ("valid?", "op", "fail-event",
                                "max-frontier")}
            base_r = pin_r(res_r["static"][0])
            if any(pin_r(r) != base_r for r in res_r["reshard"]):
                elastic_bad.add("reshard")
                emit({"reshard_mismatch": True, "shape": shape_key})
            reshard_ratios[shape_key] = t_st / max(t_el, 1e-9)
            emit({"shape": shape_key,
                  "static_secs": round(t_st, 3),
                  "reshard_secs": round(t_el, 3),
                  "reshard_speedup": round(
                      reshard_ratios[shape_key], 2),
                  "reshard_events": (res_r["reshard"][0].get("reshard")
                                     or {}).get("events"),
                  "devices_final": res_r["reshard"][0].get("devices")})

    # ---- multi-key batch ----
    n_keys, ops_per_key = (8, 40) if smoke else (84, 120)
    keys = [rand_register_history(
        n_ops=ops_per_key, n_processes=14, n_values=5, crash_p=0.005,
        fail_p=0.05, busy=0.8, seed=2024 + k) for k in range(n_keys)]
    encs = [enc_mod.encode(model, h) for h in keys]
    S = max(bitdense.n_states(e) for e in encs)
    C = max(5, max(e.n_slots for e in encs))
    cost_table["batch"] = _cost_entry(
        lambda up, mode: bitdense.cost_analysis_batch(
            encs, use_pallas=up, closure_mode=mode),
        pk.supported(S, C), max(e.n_returns for e in encs), C)
    res = {}

    def timed_batch(name, **kw):
        return _timed(res, name,
                      lambda: bitdense.check_batch_bitdense(encs, **kw),
                      shape="batch")

    t_xla = timed_batch("while", use_pallas=False, closure_mode="while")
    line = {"shape": f"batch {n_keys}x{ops_per_key}", "S": S, "C": C,
            "xla_secs": round(t_xla, 3)}
    if _want("fori"):
        t_fori = timed_batch("fori", use_pallas=False,
                             closure_mode="fori")
        fori_ratios["batch"] = t_xla / t_fori
        line.update(fori_secs=round(t_fori, 3),
                    fori_speedup=round(t_xla / t_fori, 2))
    if _want("pallas") and pk.supported(S, C):
        try:
            t_pl = timed_batch("pallas", use_pallas=True)
        except Exception as err:  # noqa: BLE001
            line["pallas_error"] = repr(err)[:300]
            bad_variants.add("pallas")
        else:
            ratios["batch"] = t_xla / t_pl
            line.update(pallas_secs=round(t_pl, 3),
                        pallas_speedup=round(t_xla / t_pl, 2))
    elif _want("pallas"):
        line["pallas_skipped"] = f"unsupported S={S} C={C}"
    bad_variants |= _disagreeing(res)
    emit(line)

    # ---- batch bucketing strategy (advisory measurement) ----
    # The bench's 84-key batch pads every key to the max slot count
    # (the r5 run: slots 11..15 -> one C=15 / W=1024 program; keys
    # needing W=64 pay 16x the word-work), because engine.check_batch's
    # power-of-two tiers put slots 9..16 in ONE tier. Exact-C grouping
    # trades ~2.9x less word-work against one compile + dispatch per
    # group. This measures that trade on the same encs; a measured win
    # here is the evidence for changing engine.check_batch's bucketing
    # (no default flips from this line — it's a strategy prior, and on
    # CPU it mostly measures compile count).
    from collections import defaultdict
    groups = defaultdict(list)           # C -> [(orig_idx, enc)]
    for i, e in enumerate(encs):
        groups[max(5, e.n_slots)].append((i, e))
    if len(groups) > 1:
        gres = {}

        def run_grouped(**kw):
            outs = [None] * len(encs)
            for cc in sorted(groups):
                idxs = [i for i, _ in groups[cc]]
                rs = bitdense.check_batch_bitdense(
                    [e for _, e in groups[cc]], **kw)
                for i, r in zip(idxs, rs):
                    outs[i] = r
            return outs

        def timed_grouped(name, **kw):
            return _timed(gres, name, lambda: run_grouped(**kw),
                          shape="batch-bucketed")

        t_gx = timed_grouped("while", use_pallas=False,
                             closure_mode="while")
        gline = {"shape": f"batch {n_keys}x{ops_per_key} exact-C "
                          f"bucketed ({len(groups)} groups)",
                 "groups": {str(cc): len(g)
                            for cc, g in sorted(groups.items())},
                 "xla_secs": round(t_gx, 3),
                 "xla_vs_padded": round(t_xla / t_gx, 2)}
        if _want("pallas"):
            # groups below the kernel floor (W < 128) downgrade to the
            # XLA closure inside _resolve_use_pallas — exactly what the
            # real-TPU default does per shape, so the mixed execution
            # IS the default path; the per-group closure labels say
            # which groups ran which
            try:
                t_gp = timed_grouped("pallas", use_pallas=True)
            except Exception as err:  # noqa: BLE001
                gline["pallas_error"] = repr(err)[:300]
            else:
                # label each group by the closure that actually RAN
                # (stamped on the result rows by the engine's own
                # resolve), not a harness-side re-derivation of the gate
                first_run = gres["pallas"][0]
                gline.update(
                    pallas_secs=round(t_gp, 3),
                    pallas_closures={
                        str(cc): first_run[g[0][0]]["closure"]
                        for cc, g in sorted(groups.items())})
                # ratio only against the PADDED BATCH's own pallas
                # timing ("pallas_secs" in line proves it completed);
                # res["pallas"] being non-empty is not enough — a
                # partial batch failure would leave t_pl holding the
                # single-key loop's value
                if "pallas_secs" in line:
                    gline["pallas_vs_padded"] = round(t_pl / t_gp, 2)
        # correctness: run_grouped restores original key order, so the
        # comparison against the padded batch's while baseline is exact
        base = _strip_closure(res["while"][0])
        for gname, gruns in gres.items():
            if any(_strip_closure(gr) != base for gr in gruns):
                gline[f"{gname}_mismatch"] = True
        emit(gline)

    # ---- compile economics (cold vs warm first-dispatch) ----
    # the JEPSEN_TPU_COMPILE_CACHE decision record: what a replica
    # restart costs with and without the populated AOT cache, on the
    # same chip-matrix shapes the sparse-dedupe A/B measures; the
    # batch encs' row counts feed the canonicalization population
    # arithmetic (84 keys of jittered lengths is where exact-shape
    # program count actually hurts)
    if _COMPILE == "1":
        compile_record(
            [(200, 8), (200, 6)] if smoke else [(1000, 12), (1000, 8)],
            extra_rows=[e.slot_f.shape[0] for e in encs])

    # analytical prior table: flops/bytes per (shape, variant) from
    # XLA's trace-time cost model — exists without any chip; once a
    # measurement lands, a large disagreement between the prior's
    # byte/flop ranking and the measured ratio flags dispatch/sync
    # overhead (not compute) as the bottleneck
    emit({"cost_table": cost_table,
          "note": "trace-time XLA cost_analysis (flops / bytes "
                  "accessed) per closure variant; advisory only — "
                  "defaults flip on MEASURED ratios, never on the "
                  "prior. Loop bodies are counted ONCE by the cost "
                  "model (trip counts are data-dependent): these rank "
                  "per-iteration variant cost; model totals via the "
                  "'trips' entry. The pallas row's 'program' field "
                  "says which program was costed (interpret emulation "
                  "off-TPU vs an uncountable kernel custom call on "
                  "it) — pallas priors are NOT comparable across "
                  "backends"})

    if not bitdense.is_tpu_platform(backend):
        # interpret-mode timings measure the interpreter, not the
        # kernel — never let them flip the default
        verdict = "no-verdict (non-tpu backend: interpret-mode timings)"
        fori_verdict = verdict
        dedupe_verdict = ("no-verdict (non-tpu backend: cpu timings "
                          "don't flip defaults; the configs_stepped "
                          "counters stand on any backend)")
        sparse_pallas_verdict = ("no-verdict (non-tpu backend: "
                                 "interpret-mode kernel timings "
                                 "measure the interpreter)")
        config_pack_verdict = ("no-verdict (non-tpu backend: cpu "
                               "timings don't flip defaults; the "
                               "gate_coverage records stand on any "
                               "backend)")
        steal_verdict = ("no-verdict (non-tpu backend: cpu timings "
                         "don't flip defaults; the forced-skew win "
                         "and the per-device spread records stand "
                         "on any backend)")
        reshard_verdict = steal_verdict
        auto_verdict = ("not-measured (PERF_AB_AUTO=0)"
                        if _AUTO != "1" else
                        "no-verdict (non-tpu backend; advisory either "
                        "way — JEPSEN_TPU_AUTO stays opt-in)")
    else:
        # a variant filtered out by PERF_AB_VARIANTS was not measured —
        # its verdict line must say so, never a definitive keep/flip
        # (the run's reader would otherwise revert a default that this
        # run produced no evidence against)
        if not _want("pallas"):
            verdict = "not-measured (pallas skipped by PERF_AB_VARIANTS)"
        else:
            verdict = ("default-on"
                       if ratios and min(ratios.values()) >= 1.1
                       else "keep-opt-in")
        if not _want("fori"):
            fori_verdict = ("not-measured (fori skipped by "
                            "PERF_AB_VARIANTS)")
        else:
            fori_verdict = ("default-fori"
                            if fori_ratios
                            and min(fori_ratios.values()) >= 1.1
                            else "keep-while")
        # correctness vetoes speed: a variant that EVER disagreed with
        # the while baseline cannot become the default, whatever it won
        if "pallas" in bad_variants or "while" in bad_variants:
            verdict = "keep-opt-in (VARIANT VETOED — see the " \
                      "correctness_mismatch / pallas_error lines)"
        if "fori" in bad_variants or "while" in bad_variants:
            fori_verdict = "keep-while (VARIANT VETOED — see the " \
                           "correctness_mismatch lines)"
        if not ({"sort", "hash"} <= set(_DEDUPE)):
            dedupe_verdict = ("not-measured (a strategy skipped by "
                              "PERF_AB_DEDUPE)")
        elif dedupe_bad:
            dedupe_verdict = ("keep-sort (STRATEGY VETOED — see the "
                              "*_mismatch keys on the sparse-dedupe "
                              "lines)")
        else:
            dedupe_verdict = ("default-hash"
                              if dedupe_ratios
                              and min(dedupe_ratios.values()) >= 1.1
                              else "keep-sort")
        if not ({"hash", "hash-pallas"} <= set(_DEDUPE)):
            sparse_pallas_verdict = ("not-measured (a strategy skipped "
                                     "by PERF_AB_DEDUPE)")
        elif dedupe_bad & {"hash", "hash-pallas"}:
            sparse_pallas_verdict = ("keep-opt-in (STRATEGY VETOED — "
                                     "see the *_mismatch keys on the "
                                     "sparse-dedupe lines)")
        else:
            sparse_pallas_verdict = (
                "default-on"
                if sparse_pallas_ratios
                and min(sparse_pallas_ratios.values()) >= 1.1
                else "keep-opt-in")
        if not ({"hash", "hash-packed"} <= set(_DEDUPE)):
            config_pack_verdict = ("not-measured (a strategy skipped "
                                   "by PERF_AB_DEDUPE)")
        elif dedupe_bad & {"hash", "hash-packed"}:
            config_pack_verdict = ("keep-opt-in (STRATEGY VETOED — "
                                   "see the *_mismatch keys on the "
                                   "sparse-dedupe lines)")
        else:
            config_pack_verdict = (
                "default-on"
                if config_pack_ratios
                and min(config_pack_ratios.values()) >= 1.1
                else "keep-opt-in")
        if "steal" not in _ELASTIC:
            steal_verdict = "not-measured (steal skipped by " \
                            "PERF_AB_ELASTIC)"
        elif "steal" in elastic_bad:
            steal_verdict = ("keep-opt-in (ARM VETOED — scheduling "
                             "changed a result; see steal_mismatch)")
        else:
            steal_verdict = ("default-on"
                             if steal_ratios
                             and min(steal_ratios.values()) >= 1.1
                             else "keep-opt-in")
        if "reshard" not in _ELASTIC:
            reshard_verdict = "not-measured (reshard skipped by " \
                              "PERF_AB_ELASTIC)"
        elif "reshard" in elastic_bad:
            reshard_verdict = ("keep-opt-in (ARM VETOED — see "
                               "reshard_mismatch)")
        else:
            reshard_verdict = ("default-on"
                               if reshard_ratios
                               and min(reshard_ratios.values()) >= 1.1
                               else "keep-opt-in")
        # the auto arm is ADVISORY on every backend: the planner only
        # routes between already-measured strategies, so its verdict
        # line reports convergence quality, never a default flip
        if _AUTO != "1":
            auto_verdict = "not-measured (PERF_AB_AUTO=0)"
        elif auto_bad:
            auto_verdict = ("advisory-veto (ARM DISAGREED — see "
                            "auto_mismatch; the planner routed to a "
                            "path whose results diverged)")
        else:
            auto_verdict = (
                "advisory-win (auto matched or beat static >=1.1x "
                "and never disagreed — JEPSEN_TPU_AUTO stays opt-in)"
                if auto_ratios and min(auto_ratios.values()) >= 1.1
                else "advisory-keep-static")
    emit({"backend": backend, "verdict": verdict,
          "fori_verdict": fori_verdict,
          "dedupe_verdict": dedupe_verdict,
          "sparse_pallas_verdict": sparse_pallas_verdict,
          "config_pack_verdict": config_pack_verdict,
          "steal_verdict": steal_verdict,
          "reshard_verdict": reshard_verdict,
          "auto_verdict": auto_verdict,
          "auto_measured": _AUTO == "1",
          "auto_ratios": {k: round(v, 2)
                          for k, v in auto_ratios.items()},
          "variants_measured": sorted(_VARIANTS),
          "dedupe_measured": sorted(_DEDUPE),
          "elastic_measured": sorted(_ELASTIC),
          "steal_ratios": {k: round(v, 2)
                           for k, v in steal_ratios.items()},
          "reshard_ratios": {k: round(v, 2)
                             for k, v in reshard_ratios.items()},
          "ratios": {k: round(v, 2) for k, v in ratios.items()},
          "dedupe_ratios": {k: round(v, 2)
                            for k, v in dedupe_ratios.items()},
          "sparse_pallas_ratios": {k: round(v, 2)
                                   for k, v in
                                   sparse_pallas_ratios.items()},
          "config_pack_ratios": {k: round(v, 2)
                                 for k, v in
                                 config_pack_ratios.items()},
          "fori_ratios": {k: round(v, 2) for k, v in fori_ratios.items()},
          "rule": "pallas default-on iff it wins >=1.1x on EVERY "
                  "measured shape on the tpu backend AND never "
                  "disagreed with the while baseline's results; fori "
                  "likewise vs the while closure (flip "
                  "bitdense._resolve_closure_mode). If both win, "
                  "pallas takes precedence (it replaces the XLA loop "
                  "entirely). dedupe=hash flips JEPSEN_TPU_DEDUPE's "
                  "default (engine._resolve_dedupe) under the same "
                  ">=1.1x-on-every-shape + never-disagreed rule, "
                  "measured on the sparse engine's sparse-dedupe "
                  "lines above; hash-pallas (the VMEM frontier "
                  "kernels vs the XLA hash strategy — fused inside "
                  "the width-aware gate, TILED past it, so every "
                  "chip-matrix shape measures) flips "
                  "JEPSEN_TPU_SPARSE_PALLAS's default "
                  "(engine._resolve_sparse_pallas) under the same "
                  "rule; hash-packed (the packed configuration word "
                  "vs the unpacked triple) flips "
                  "JEPSEN_TPU_CONFIG_PACK's default "
                  "(engine._resolve_config_pack) likewise — the "
                  "gate_coverage lines record, per shape and layout, "
                  "bytes/row and what would run, chip-free. steal "
                  "(the skew-driven key work-stealer vs the static "
                  "placement, same round executor) flips "
                  "JEPSEN_TPU_STEAL's default "
                  "(engine._resolve_steal) under the same "
                  ">=1.1x-on-every-shape + never-disagreed rule; "
                  "reshard (the device-recruiting sharded ladder vs "
                  "the grow-the-table one) flips JEPSEN_TPU_RESHARD "
                  "(engine._resolve_reshard) likewise — the "
                  "search_stats lines record the before/after "
                  "per-device load-factor spread per shape. The "
                  "PERF_AB_AUTO=1 arm (JEPSEN_TPU_AUTO planner "
                  "routing all axes) is ADVISORY under the same "
                  ">=1.1x / never-disagreed reading: it reports "
                  "whether the online table converged to the "
                  "measured winner, and flips nothing"})


if __name__ == "__main__":
    try:
        main()
    except Exception as err:  # noqa: BLE001
        import traceback
        traceback.print_exc()
        emit({"error": repr(err)})
        sys.exit(1)
