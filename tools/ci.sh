#!/usr/bin/env bash
# The repo's verification gate, in the order a reviewer runs it:
#
#   1. static analysis  — `python -m jepsen_tpu.analysis --check`
#      (tracing-safety / recompile / concurrency lint; pure AST, no
#      JAX init, exit 1 on any active finding — docs/linting.md)
#   1b. fault-injection smoke — tools/fault_smoke.py: a wedge, a
#      crash, and a flaky failure injected at the supervised dispatch
#      sites on CPU, verdicts asserted identical to the clean run
#      (the docs/resilience.md degradation contract, at smoke scale)
#   1c. streaming-checker smoke — tools/serve_smoke.py: the serve
#      service in-process, two keys' deltas (one with an injected
#      wedge), final verdicts asserted identical to the one-shot
#      batch check, clean drain, AND the live ops surface on an
#      ephemeral port: /healthz ready, /metrics valid Prometheus
#      text with the serve SLO histograms, /status listing both
#      keys (docs/streaming.md + docs/observability.md, smoke scale),
#      plus the two-tenant HTTP-ingress fairness wiring (flood shed
#      with tenant attribution, quiet tenant fully acked); runs with
#      JEPSEN_TPU_TRACE armed so the next stage can schema-validate
#      the delta-tagged span export, and with JEPSEN_TPU_COMPILE_CACHE
#      armed (isolated tempdir) so the /metrics check asserts the
#      compile-economics surface — jepsen_serve_compile_secs_bucket +
#      the jepsen_engine_programs_* registry ledger
#      (docs/performance.md "Compile economics"), and with
#      JEPSEN_TPU_LEDGER armed (isolated tempdir) so the decision-
#      ledger wiring is proven end to end: durable dispatch+publish
#      records on disk, /ledger serving live aggregate cells, and
#      the strategy advisor building a deterministic plan from them
#      (docs/observability.md "Decision ledger & strategy advisor")
#   1c'. trace-schema validator — `jepsen trace --validate` over the
#      smoke's Chrome-trace export (phase codes, pid/tid, span ids,
#      parent resolution — the docs/observability.md export contract)
#   1d. multi-tenant soak smoke — tools/soak.py --smoke (~10 s):
#      sustained multi-tenant load over the HTTP ingress with
#      JEPSEN_TPU_FAULTS armed mid-run (wedge/crash/flaky/slow);
#      asserts zero verdict flips, bounded memory, flood-tenant
#      sheds, quiet-tenant SLOs populated per tenant on /metrics,
#      and (with the decision ledger armed at a tiny segment cap)
#      that rotation + retention keep the evidence on disk inside
#      its documented bound
#   1e. fleet chaos smoke — tools/chaos.py --smoke (~15 s): a real
#      subprocess fleet under a nemesis schedule — one SIGKILL with
#      the victim's WAL dir deleted (rehome must come from the
#      replicated segments) and one SIGSTOP/SIGCONT cycle (the
#      resumed replica must answer the epoch-fence refusal);
#      asserts zero verdict flips, zero lost keys, fence engaged,
#      quiet-tenant SLOs from the parsed /metrics scrape
#      (docs/streaming.md "Fleet self-healing"); also arms
#      JEPSEN_TPU_TRACE + JEPSEN_TPU_SLOW_DELTA_SECS fleet-wide and
#      asserts a device-dominated slow-delta record on the slow@
#      replica and a cross-replica delta chain in the merged fleet
#      trace (docs/observability.md "End-to-end delta tracing")
#   2. tier-1 tests     — the ROADMAP.md invocation verbatim: the
#      full suite minus the slow tier on a virtual 8-device CPU mesh,
#      under the documented 870s budget (timeout -k 10 870). The
#      DOTS_PASSED line echoes the progress-dot count so a truncated
#      run is visible even when pytest's summary is lost.
#
# Exits nonzero when either stage fails. README "Verifying a change"
# points here; run from anywhere — the script cd's to the repo root.
set -u
cd "$(dirname "$0")/.." || exit 2

echo "== lint gate =="
python -m jepsen_tpu.analysis --check || exit 1

echo "== fault-injection smoke =="
env JAX_PLATFORMS=cpu python tools/fault_smoke.py || exit 1

echo "== streaming-checker smoke =="
# a mktemp path, not a fixed /tmp name: concurrent CI runs on one box
# must not clobber each other's export (or follow a pre-planted
# symlink at a predictable name)
SMOKE_TRACE="$(mktemp -t jepsen_smoke_trace.XXXXXX.json)" || exit 2
trap 'rm -f "$SMOKE_TRACE"' EXIT
env JAX_PLATFORMS=cpu JEPSEN_TPU_TRACE="$SMOKE_TRACE" \
    python tools/serve_smoke.py || exit 1

echo "== trace-schema validator (serve_smoke export) =="
env JAX_PLATFORMS=cpu python -m jepsen_tpu.obs.trace_merge \
    --validate "$SMOKE_TRACE" || exit 1

echo "== multi-tenant soak smoke =="
env JAX_PLATFORMS=cpu python tools/soak.py --smoke || exit 1

echo "== fleet chaos smoke =="
env JAX_PLATFORMS=cpu python tools/chaos.py --smoke || exit 1

echo "== tier-1 tests (870s budget) =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
    | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
