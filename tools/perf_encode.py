"""Microbench for the host encode fast path (the pipeline's stage 1).

The batched checker's end-to-end constant is the Python encode loop
(PERF_R05: device-only 9,189.9 ops/s vs 8,558.4 e2e — the gap is
encode + transfer, not search). PR 2 attacks it three ways; this tool
measures each in isolation with no device runtime — encode is pure
numpy (jax gets imported transitively but no backend is ever
initialized), so the numbers are portable and CI-safe:

  bulk         spec.encode_calls (one call per history, preallocated
               arrays) vs the row-wise spec.encode_call loop — same
               arrays bit for bit (asserted here via history_digest)
  stage split  prepare_encode (packing + slot walk) vs finish_encode
               (the [R, C] snapshot fill) — the fractions that decide
               how much of the encode the pipeline can overlap
  cache        EncodeCache miss vs hit vs store-dir (disk) hit

    python tools/perf_encode.py            # full shapes
    PERF_ENCODE_REPS=3 python tools/perf_encode.py

One JSON line per measurement, same consumption contract as bench.py
(machine-parsable, metric/value/unit keys).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from time import perf_counter

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPS = int(os.environ.get("PERF_ENCODE_REPS", "5"))


def emit(obj):
    print(json.dumps(obj), flush=True)


def _best(f, reps=REPS):
    """Best-of-N wall time (microbenches want the noise floor, not the
    scheduler's mood)."""
    best = float("inf")
    for _ in range(reps):
        t0 = perf_counter()
        f()
        best = min(best, perf_counter() - t0)
    return best


def _shapes():
    from jepsen_tpu.histories import (adversarial_register_history,
                                      rand_fifo_history,
                                      rand_gset_history,
                                      rand_queue_history,
                                      rand_register_history)
    from jepsen_tpu.models import (CASRegister, FIFOQueue, GSet,
                                   UnorderedQueue)
    yield ("cas-register 84x120 batch-key", CASRegister(),
           [rand_register_history(n_ops=120, n_processes=14, n_values=5,
                                  crash_p=0.005, fail_p=0.05, busy=0.8,
                                  seed=2024 + k) for k in range(84)])
    yield ("cas-register adversarial 10k", CASRegister(),
           [adversarial_register_history(n_ops=10_000, k_crashed=12,
                                         seed=7)])
    yield ("gset 500-op", GSet(),
           [rand_gset_history(n_ops=500, n_processes=6, n_elements=12,
                              crash_p=0.05, seed=3)])
    yield ("unordered-queue 500-op", UnorderedQueue(),
           [rand_queue_history(n_ops=500, n_processes=6, n_values=4,
                               crash_p=0.05, seed=4)])
    # fifo keys stay short: the packed depth bound (B*v <= 31 bits)
    # rejects long single-key fifo histories, so the realistic shape
    # is many short keys — same total ops
    yield ("fifo 40x40-op batch-key", FIFOQueue(),
           [rand_fifo_history(n_ops=40, n_processes=5, n_values=3,
                              crash_p=0.05, seed=500 + k)
            for k in range(40)])


def main():
    from jepsen_tpu.parallel import encode as enc_mod
    from jepsen_tpu.parallel import pipeline as pipe_mod
    from jepsen_tpu.parallel.engine import history_digest

    for name, model, hs in _shapes():
        n_ops = sum(len(h) for h in hs)

        # correctness first: bulk and row-wise paths must be
        # array-identical on every shape they are about to be timed on
        for h in hs:
            d_bulk = history_digest(enc_mod.encode(model, h))
            d_loop = history_digest(enc_mod.encode(model, h,
                                                   use_bulk=False))
            assert d_bulk == d_loop, (name, d_bulk, d_loop)

        bulk_secs = _best(lambda: [enc_mod.encode(model, h)
                                   for h in hs])
        # the bulk hook lives in stage 1 (prepare_encode) — compare
        # the stages head to head so the hook's effect is not diluted
        # by the (identical) snapshot fill
        prep_loop_secs = _best(
            lambda: [enc_mod.prepare_encode(model, h, use_bulk=False)
                     for h in hs])
        prep_secs = _best(lambda: [enc_mod.prepare_encode(model, h)
                                   for h in hs])
        preps = [enc_mod.prepare_encode(model, h) for h in hs]
        fill_secs = _best(lambda: [enc_mod.finish_encode(p)
                                   for p in preps])
        emit({"metric": f"encode {name}", "unit": "ops/sec",
              "value": round(n_ops / bulk_secs, 1),
              "n_keys": len(hs), "n_ops": n_ops,
              "encode_secs": round(bulk_secs, 4),
              "prepare_secs": round(prep_secs, 4),
              "prepare_loop_secs": round(prep_loop_secs, 4),
              "bulk_speedup": round(prep_loop_secs /
                                    max(prep_secs, 1e-9), 2),
              "fill_secs": round(fill_secs, 4),
              "overlappable_frac": round(fill_secs /
                                         max(bulk_secs, 1e-9), 3)})

    # cache: miss vs memory hit vs disk hit, on the bench batch shape
    name, model, hs = next(_shapes())
    with tempfile.TemporaryDirectory() as d:
        cache = pipe_mod.EncodeCache(max_entries=len(hs) + 1,
                                     store_dir=d)
        keys = [pipe_mod.encode_cache_key(model, h) for h in hs]

        def miss():
            for h, k in zip(hs, keys):
                e = cache.get(k, model) or enc_mod.encode(model, h)

        t_miss = _best(miss, reps=1)          # first pass: all misses
        for h, k in zip(hs, keys):
            cache.put(k, enc_mod.encode(model, h))
        t_hit = _best(lambda: [cache.get(k, model) for k in keys])
        disk = pipe_mod.EncodeCache(max_entries=len(hs) + 1,
                                    store_dir=d)
        t_disk = _best(
            lambda: [disk.get(k, model) for k in keys], reps=1)
        assert all(disk.get(k, model) is not None for k in keys)
        emit({"metric": f"encode cache, {name}", "unit": "x",
              "value": round(t_miss / max(t_hit, 1e-9), 1),
              "miss_secs": round(t_miss, 4),
              "memory_hit_secs": round(t_hit, 5),
              "disk_hit_secs": round(t_disk, 4),
              "note": "value = miss/memory-hit ratio; disk hit is a "
                      "fresh cache instance over the same store_dir"})


if __name__ == "__main__":
    main()
