"""Bounded-subprocess bisect of the frontier-sharded engine on a real
TPU.

Round-5 finding (PERF_R05.md): the sharded engine's first-ever
hardware contact crashed the TPU worker process
(`bench.py sec_sharded`, capacity 2^17, 10k adversarial history,
1-device mesh), and a follow-up in-process repro wedged the tunnel.
The engine is fully green on the 8-way CPU mesh (tests/test_sharded.py)
— whatever breaks is a TPU-runtime interaction no CPU test reaches.

Each probe below runs in its OWN subprocess under a hard timeout, so a
worker crash or a tunnel wedge costs one probe, never the session: the
parent never imports jax. Probes escalate from primitives to the full
engine:

  p1  shard_map + psum on the 1-device mesh        (collective floor)
  p2  lexsort at Nd=2^12 / 2^17                     (the dedupe's sort)
  p3  all_to_all on a 1-device axis                 (the exchange)
  p4  _check_sharded, 60-op history, cap 2^12       (tiny end-to-end)
  p5  _check_sharded, 1k history, cap 2^12
  p6  _check_sharded, 10k history, cap 2^12
  p7  _check_sharded, 10k history, cap 2^17         (the bench shape)

Run: python tools/bisect_sharded.py [--timeout 240]
One JSON line per probe: {"probe", "ok", "secs" | "error"/"hung"}.
A "hung"/crashed probe names the narrowest failing layer.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from time import perf_counter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PRELUDE = """
import os, numpy as np, jax
# honor JAX_PLATFORMS via jax.config too: on this image the axon
# plugin initializes (and hangs on, when the tunnel is down) the TPU
# client even under the env var alone — same pinning as perf_ab
_p = os.environ.get("JAX_PLATFORMS")
if _p:
    jax.config.update("jax_platforms", _p)
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()[:1]), ("frontier",))
"""

PROBES = {
    "p1-shardmap-psum": PRELUDE + """
f = jax.shard_map(lambda x: lax.psum(x, "frontier"), mesh=mesh,
                  in_specs=P(), out_specs=P(), check_vma=False)
out = f(jnp.ones((8,), jnp.int32))
assert int(out[0]) == 1
""",
    "p2-lexsort-2e12": PRELUDE + """
rng = np.random.default_rng(0)
M = 1 << 12
st = jnp.asarray(rng.integers(0, 40, M, dtype=np.int32))
ml = jnp.asarray(rng.integers(0, 2**32, M, dtype=np.uint32))
mh = jnp.asarray(rng.integers(0, 2**32, M, dtype=np.uint32))
live = jnp.asarray(rng.integers(0, 2, M).astype(bool))
o = jax.jit(lambda a, b, c, l: jnp.lexsort(
    (c, b, a, (~l).astype(jnp.int8))))(st, ml, mh, live)
o.block_until_ready()
""",
    "p2-lexsort-2e17": PRELUDE + """
rng = np.random.default_rng(0)
M = 1 << 17
st = jnp.asarray(rng.integers(0, 40, M, dtype=np.int32))
ml = jnp.asarray(rng.integers(0, 2**32, M, dtype=np.uint32))
mh = jnp.asarray(rng.integers(0, 2**32, M, dtype=np.uint32))
live = jnp.asarray(rng.integers(0, 2, M).astype(bool))
o = jax.jit(lambda a, b, c, l: jnp.lexsort(
    (c, b, a, (~l).astype(jnp.int8))))(st, ml, mh, live)
o.block_until_ready()
""",
    "p3-all-to-all-1dev": PRELUDE + """
def body(x):
    return lax.all_to_all(x, "frontier", split_axis=0, concat_axis=0,
                          tiled=True)
f = jax.shard_map(body, mesh=mesh, in_specs=P("frontier"),
                  out_specs=P("frontier"), check_vma=False)
out = f(jnp.arange(64, dtype=jnp.int32))
out.block_until_ready()
""",
}


def _engine_probe(n_ops: int, cap_log: int) -> str:
    return PRELUDE + f"""
from jepsen_tpu.histories import adversarial_register_history
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import sharded, encode as enc_mod
h = adversarial_register_history(n_ops={n_ops}, k_crashed=12, seed=7)
e = enc_mod.encode(CASRegister(), h)
r = sharded.check_encoded_sharded(e, mesh, capacity=1 << {cap_log},
                                  max_capacity=1 << 20)
print("RESULT", r.get("valid?"), r.get("capacity"), r.get("max-frontier"))
"""


PROBES["p4-engine-60op-cap12"] = _engine_probe(60, 12)
PROBES["p5-engine-1k-cap12"] = _engine_probe(1000, 12)
PROBES["p6-engine-10k-cap12"] = _engine_probe(10000, 12)
PROBES["p7-engine-10k-cap17"] = _engine_probe(10000, 17)


def run_probe(name: str, code: str, timeout: float) -> dict:
    t0 = perf_counter()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return {"probe": name, "ok": False, "hung": True,
                "timeout_secs": timeout}
    out = {"probe": name, "ok": p.returncode == 0,
           "secs": round(perf_counter() - t0, 1)}
    if p.returncode != 0:
        tail = (p.stderr or "").strip().splitlines()
        out["error"] = " | ".join(tail[-3:])[-400:]
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=float, default=240.0)
    ap.add_argument("--only", help="comma-separated probe-name filter")
    args = ap.parse_args()
    only = set(args.only.split(",")) if args.only else None
    for name, code in PROBES.items():
        if only and name not in only:
            continue
        res = run_probe(name, code, args.timeout)
        print(json.dumps(res), flush=True)
        if not res["ok"]:
            print(json.dumps(
                {"stop": f"first failure at {name} — layers above it "
                         f"are exonerated; this one owns the crash"}),
                flush=True)
            break


if __name__ == "__main__":
    main()
