#!/usr/bin/env python
"""`jepsen-tpu lint` runner — the tools/ entry for CI and hooks.

Thin wrapper over jepsen_tpu.analysis with the CLI exit-code contract:
0 = clean (every finding suppressed, each suppression naming its
rule), 1 = active findings, 2 = usage error. Pure AST work: CPU-only,
no JAX import, no device init — safe to run first in the tier-1 flow
and on machines with a wedged device runtime.

    python tools/lint.py --check          # the CI gate
    python tools/lint.py --json           # machine-readable report
    python tools/lint.py jepsen_tpu/parallel --show-suppressed

Equivalent entry points: `python -m jepsen_tpu.analysis` and the
`jepsen lint` CLI subcommand (jepsen_tpu.cli).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu import analysis  # noqa: E402

if __name__ == "__main__":
    sys.exit(analysis.main())
