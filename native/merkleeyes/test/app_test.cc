// In-process lifecycle test, mirroring the reference's
// merkleeyes/app_test.go:20-90: Info → CheckTx → BeginBlock →
// DeliverTx for every tx type → EndBlock → Commit, with hand-rolled tx
// encoders (app_test.go:92-171), plus tree/WAL/nonce coverage.
#include <unistd.h>

#include <cassert>
#include <cstdio>
#include <iostream>

#include "../src/app.h"

using namespace merkleeyes;

static int checks = 0;
#define CHECK(cond)                                              \
  do {                                                           \
    if (!(cond)) {                                               \
      std::cerr << "FAIL at " << __LINE__ << ": " #cond "\n";    \
      return 1;                                                  \
    }                                                            \
    checks++;                                                    \
  } while (0)

static bytes nonce(uint8_t seed) {
  bytes n(kNonceLength, 0);
  for (size_t i = 0; i < n.size(); i++) n[i] = uint8_t(seed + i);
  return n;
}

static bytes field(const std::string& s) {
  bytes out;
  put_uvarint(out, s.size());
  out.insert(out.end(), s.begin(), s.end());
  return out;
}

static bytes tx(uint8_t seed, uint8_t type, const bytes& args) {
  bytes t = nonce(seed);
  t.push_back(type);
  t.insert(t.end(), args.begin(), args.end());
  return t;
}

static bytes set_tx(uint8_t seed, const std::string& k,
                    const std::string& v) {
  bytes args = field(k);
  bytes vf = field(v);
  args.insert(args.end(), vf.begin(), vf.end());
  return tx(seed, 0x01, args);
}

static bytes cas_tx(uint8_t seed, const std::string& k,
                    const std::string& cmp, const std::string& v) {
  bytes args = field(k);
  bytes c = field(cmp), vf = field(v);
  args.insert(args.end(), c.begin(), c.end());
  args.insert(args.end(), vf.begin(), vf.end());
  return tx(seed, 0x04, args);
}

int tree_tests() {
  Tree t;
  CHECK(t.size() == 0);
  bytes k1{'a'}, k2{'b'}, k3{'c'}, v1{'1'}, v2{'2'};
  Tree t1 = t.set(k1, v1);
  Tree t2 = t1.set(k2, v2);
  CHECK(t.size() == 0);  // persistence: old snapshots untouched
  CHECK(t1.size() == 1 && t2.size() == 2);
  CHECK(t2.get(k1)->second == v1);
  CHECK(t2.get(k1)->first == 0);  // rank of 'a'
  CHECK(t2.get(k2)->first == 1);
  CHECK(!t2.get(k3));
  CHECK(t2.get_by_index(1)->first == k2);
  auto [t3, removed] = t2.remove(k1);
  CHECK(removed && t3.size() == 1 && t2.size() == 2);
  auto [t4, removed2] = t3.remove(k1);
  CHECK(!removed2);
  CHECK(t1.hash() != t2.hash());
  CHECK(t2.hash() == t.set(k2, v2).set(k1, v1).hash());  // order-free

  // balance under sequential inserts: height stays O(log n)
  Tree big;
  for (int i = 0; i < 1024; i++) {
    std::string key = "key" + std::to_string(1000000 + i);
    big = big.set(bytes(key.begin(), key.end()), v1);
  }
  CHECK(big.size() == 1024);
  for (int i = 0; i < 1024; i += 111) {
    std::string key = "key" + std::to_string(1000000 + i);
    CHECK(big.get(bytes(key.begin(), key.end())));
  }
  return 0;
}

int app_lifecycle() {
  App app;
  auto [h0, hash0] = app.info();
  CHECK(h0 == 0 && hash0.size() == 32);

  CHECK(app.check_tx(bytes{1, 2}).code == EncodingError);
  CHECK(app.check_tx(set_tx(1, "k", "v")).code == OK);

  app.begin_block();
  CHECK(app.deliver_tx(set_tx(1, "name", "satoshi")).code == OK);
  // duplicate nonce rejected (app.go:239-250)
  CHECK(app.deliver_tx(set_tx(1, "name", "mallory")).code == BadNonce);
  // Get on working tree sees the uncommitted write (app.go:291-306)
  TxResult g = app.deliver_tx(tx(2, 0x03, field("name")));
  CHECK(g.code == OK && std::string(g.data.begin(), g.data.end()) ==
                            "satoshi");
  // CAS success and failure (app.go:308-352)
  CHECK(app.deliver_tx(cas_tx(3, "name", "satoshi", "nakamoto")).code == OK);
  TxResult bad = app.deliver_tx(cas_tx(4, "name", "satoshi", "x"));
  CHECK(bad.code == ErrUnauthorized);
  // Rm (app.go:273-289)
  CHECK(app.deliver_tx(tx(5, 0x02, field("nope"))).code ==
        ErrBaseUnknownAddress);
  CHECK(app.deliver_tx(set_tx(6, "tmp", "x")).code == OK);
  CHECK(app.deliver_tx(tx(7, 0x02, field("tmp"))).code == OK);
  // unknown type byte
  CHECK(app.deliver_tx(tx(8, 0x99, {})).code == ErrUnknownRequest);

  // query before commit: committed tree is still empty (app.go:158-165)
  QueryResult q0 = app.query("/key", bytes{'n', 'a', 'm', 'e'});
  CHECK(q0.code == ErrBaseUnknownAddress);

  app.end_block();
  bytes apphash = app.commit();
  CHECK(apphash.size() == 32 && apphash != hash0);
  CHECK(app.height() == 1);

  QueryResult q1 = app.query("/key", bytes{'n', 'a', 'm', 'e'});
  CHECK(q1.code == OK);
  CHECK(std::string(q1.value.begin(), q1.value.end()) == "nakamoto");
  CHECK(q1.height == 1);

  // /size counts nonces too (everything lives in one tree, like the
  // reference's /nonce/ + /key/ prefixes)
  QueryResult qs = app.query("/size", {});
  CHECK(qs.code == OK);
  auto [size, c] = get_varint(qs.value.data(), qs.value.size());
  CHECK(c > 0 && size >= 2);

  QueryResult qi = app.query("/index", [] {
    bytes b;
    put_varint(b, 0);
    return b;
  }());
  CHECK(qi.code == OK && !qi.key.empty());

  QueryResult qbad = app.query("/bogus", {});
  CHECK(qbad.code == UnknownRequest);
  return 0;
}

int valset_tests() {
  App app;
  bytes pk(32, 0xaa);
  bytes args = field(std::string(32, char(0xaa)));
  put_u64be(args, 10);

  app.begin_block();
  CHECK(app.deliver_tx(tx(1, 0x05, args)).code == OK);
  auto updates = app.end_block();
  CHECK(updates.size() == 1 && updates.at(pk) == 10);
  CHECK(app.valset_version() == 1);

  // ValSetRead returns JSON with the validator
  app.begin_block();
  TxResult read = app.deliver_tx(tx(2, 0x06, {}));
  std::string json(read.data.begin(), read.data.end());
  CHECK(read.code == OK);
  CHECK(json.find("\"version\":1") != std::string::npos);
  CHECK(json.find("\"power\":10") != std::string::npos);

  // ValSetCAS with wrong version rejected (app.go:397-441)
  bytes cas_args;
  put_u64be(cas_args, 99);
  bytes pkf = field(std::string(32, char(0xbb)));
  cas_args.insert(cas_args.end(), pkf.begin(), pkf.end());
  put_u64be(cas_args, 5);
  CHECK(app.deliver_tx(tx(3, 0x07, cas_args)).code == ErrUnauthorized);
  // right version accepted
  bytes cas_ok;
  put_u64be(cas_ok, 1);
  cas_ok.insert(cas_ok.end(), pkf.begin(), pkf.end());
  put_u64be(cas_ok, 5);
  CHECK(app.deliver_tx(tx(4, 0x07, cas_ok)).code == OK);
  CHECK(app.end_block().size() == 1);
  CHECK(app.valset_version() == 2);

  // removing a non-existent validator fails (app.go:453-460)
  bytes rm;
  bytes pkf2 = field(std::string(32, char(0xcc)));
  rm.insert(rm.end(), pkf2.begin(), pkf2.end());
  put_u64be(rm, 0);
  app.begin_block();
  CHECK(app.deliver_tx(tx(5, 0x05, rm)).code == ErrUnauthorized);
  return 0;
}

int wal_tests() {
  std::string wal = "/tmp/merkleeyes_test_wal.bin";
  std::remove(wal.c_str());
  {
    App app(wal);
    app.begin_block();
    app.deliver_tx(set_tx(1, "k1", "v1"));
    app.commit();
    app.begin_block();
    app.deliver_tx(set_tx(2, "k2", "v2"));
    app.commit();
  }
  {
    App app(wal);  // replay
    CHECK(app.height() == 2);
    CHECK(app.query("/key", bytes{'k', '1'}).code == OK);
    CHECK(app.query("/key", bytes{'k', '2'}).code == OK);
    // replayed nonces stay burned
    CHECK(app.deliver_tx(set_tx(1, "k1", "evil")).code == BadNonce);
  }
  // truncation: chop the file mid-frame; replay keeps complete prefix
  FILE* f = std::fopen(wal.c_str(), "rb");
  std::fseek(f, 0, SEEK_END);
  long len = std::ftell(f);
  std::fclose(f);
  (void)!truncate(wal.c_str(), len - 3);
  {
    App app(wal);
    CHECK(app.height() == 1);  // second block lost, first intact
    CHECK(app.query("/key", bytes{'k', '1'}).code == OK);
    CHECK(app.query("/key", bytes{'k', '2'}).code ==
          ErrBaseUnknownAddress);
  }
  std::remove(wal.c_str());
  return 0;
}

int main() {
  if (tree_tests()) return 1;
  if (app_lifecycle()) return 1;
  if (valset_tests()) return 1;
  if (wal_tests()) return 1;
  std::cout << "OK: " << checks << " checks passed\n";
  return 0;
}
