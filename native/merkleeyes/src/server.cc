// merkleeyes server: serves the App over a unix or TCP socket.
//
// Two session protocols behind the same uvarint-length framing,
// selected by --proto:
//
//   --proto abci (DEFAULT) — the tendermint v0.34 ABCI socket
//     protocol (varint-delimited protobuf Request/Response, abci.h).
//     This is what a real tendermint binary speaks to its --proxy_app
//     (reference: merkleeyes/cmd/merkleeyes/main.go:26-57) and what
//     jepsen_tpu.tendermint.db deploys against.
//
//   --proto custom — this build's own compact protocol (kept for the
//     original test harness; documented in ../README.md):
//       request  = uvarint(len) ∥ msg-type ∥ body
//       response = uvarint(len) ∥ msg-type ∥ fields
//     msg types: 0x10 Info, 0x11 CheckTx, 0x12 DeliverTx,
//                0x13 BeginBlock, 0x14 EndBlock, 0x15 Commit,
//                0x16 Query, 0x17 Echo, 0x18 Flush
//
// One worker thread per connection; the App is serialized behind a
// mutex (tendermint drives ABCI from one connection, but the test
// harness may open several).
//
// Usage: merkleeyes --listen unix:/tmp/me.sock [--wal /path/wal]
//        merkleeyes --listen tcp:46658 [--proto abci|custom]

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <csignal>
#include <cstring>
#include <iostream>
#include <mutex>
#include <thread>

#include "abci.h"
#include "app.h"

namespace merkleeyes {

enum Msg : uint8_t {
  MsgInfo = 0x10,
  MsgCheckTx = 0x11,
  MsgDeliverTx = 0x12,
  MsgBeginBlock = 0x13,
  MsgEndBlock = 0x14,
  MsgCommit = 0x15,
  MsgQuery = 0x16,
  MsgEcho = 0x17,
  MsgFlush = 0x18,
};

struct Server {
  App app;
  std::mutex mu;
  bool abci_mode;

  explicit Server(const std::string& wal, bool abci = true)
      : app(wal), abci_mode(abci) {}

  bytes handle(const bytes& req) {
    std::lock_guard<std::mutex> lock(mu);
    if (abci_mode) return abci::handle(app, req);
    return handle_custom(req);
  }

  bytes handle_custom(const bytes& req) {
    bytes resp;
    if (req.empty()) {
      resp.push_back(0x00);
      put_uvarint(resp, EncodingError);
      return resp;
    }
    uint8_t type = req[0];
    const uint8_t* body = req.data() + 1;
    size_t n = req.size() - 1;
    resp.push_back(type);
    switch (type) {
      case MsgInfo: {
        auto [height, hash] = app.info();
        put_uvarint(resp, OK);
        put_varint(resp, height);
        put_bytes(resp, hash);
        break;
      }
      case MsgCheckTx: {
        TxResult r = app.check_tx(bytes(body, body + n));
        put_uvarint(resp, r.code);
        put_bytes(resp, r.data);
        put_str(resp, r.log);
        break;
      }
      case MsgDeliverTx: {
        TxResult r = app.deliver_tx(bytes(body, body + n));
        put_uvarint(resp, r.code);
        put_bytes(resp, r.data);
        put_str(resp, r.log);
        break;
      }
      case MsgBeginBlock:
        app.begin_block();
        put_uvarint(resp, OK);
        break;
      case MsgEndBlock: {
        auto updates = app.end_block();
        put_uvarint(resp, OK);
        put_uvarint(resp, updates.size());
        for (const auto& [pk, power] : updates) {
          put_bytes(resp, pk);
          put_varint(resp, power);
        }
        break;
      }
      case MsgCommit: {
        bytes hash = app.commit();
        put_uvarint(resp, OK);
        put_bytes(resp, hash);
        break;
      }
      case MsgQuery: {
        // body = uvarint(len path) ∥ path ∥ data
        auto [plen, c] = get_uvarint(body, n);
        if (c <= 0 || n - c < plen) {
          put_uvarint(resp, EncodingError);
          put_varint(resp, 0);
          put_varint(resp, -1);
          put_bytes(resp, {});
          put_bytes(resp, {});
          put_str(resp, "bad query frame");
          break;
        }
        std::string path(body + c, body + c + plen);
        bytes data(body + c + plen, body + n);
        QueryResult q = app.query(path, data);
        put_uvarint(resp, q.code);
        put_varint(resp, q.height);
        put_varint(resp, q.index);
        put_bytes(resp, q.key);
        put_bytes(resp, q.value);
        put_str(resp, q.log);
        break;
      }
      case MsgEcho:
        put_uvarint(resp, OK);
        resp.insert(resp.end(), body, body + n);
        break;
      case MsgFlush:
        put_uvarint(resp, OK);
        break;
      default:
        resp[0] = 0x00;
        put_uvarint(resp, UnknownRequest);
        put_str(resp, "unknown message type");
    }
    return resp;
  }
};

static bool read_exact(int fd, uint8_t* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::read(fd, buf + got, n - got);
    if (r <= 0) return false;
    got += size_t(r);
  }
  return true;
}

static bool write_all(int fd, const uint8_t* buf, size_t n) {
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::write(fd, buf + sent, n - sent);
    if (r <= 0) return false;
    sent += size_t(r);
  }
  return true;
}

// Reads one uvarint-framed message; false on EOF/error.
static bool read_frame(int fd, bytes& out) {
  uint64_t len = 0;
  int shift = 0;
  while (true) {
    uint8_t b;
    if (!read_exact(fd, &b, 1)) return false;
    len |= uint64_t(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
    if (shift > 63) return false;
  }
  if (len > (64u << 20)) return false;  // 64 MiB sanity cap
  out.resize(len);
  return len == 0 || read_exact(fd, out.data(), len);
}

static void serve_conn(Server* srv, int fd) {
  bytes req;
  while (read_frame(fd, req)) {
    bytes resp = srv->handle(req);
    bytes framed;
    put_uvarint(framed, resp.size());
    framed.insert(framed.end(), resp.begin(), resp.end());
    if (!write_all(fd, framed.data(), framed.size())) break;
  }
  ::close(fd);
}

static int listen_unix(const std::string& path) {
  ::unlink(path.c_str());
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  std::strncpy(addr.sun_path, path.c_str(), sizeof(addr.sun_path) - 1);
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 16) < 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

static int listen_tcp(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(uint16_t(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 16) < 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

}  // namespace merkleeyes

int main(int argc, char** argv) {
  using namespace merkleeyes;
  std::string listen_spec = "unix:/tmp/merkleeyes.sock";
  std::string wal;
  std::string proto = "abci";
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    if (a == "--listen" && i + 1 < argc) listen_spec = argv[++i];
    else if (a == "--wal" && i + 1 < argc) wal = argv[++i];
    else if (a == "--proto" && i + 1 < argc) proto = argv[++i];
    else if (a == "--help") {
      std::cout << "usage: merkleeyes --listen unix:PATH|tcp:PORT "
                   "[--wal FILE] [--proto abci|custom]\n";
      return 0;
    } else {
      std::cerr << "unknown flag: " << a << " (see --help)\n";
      return 1;
    }
  }
  ::signal(SIGPIPE, SIG_IGN);

  int lfd = -1;
  if (listen_spec.rfind("unix:", 0) == 0) {
    lfd = listen_unix(listen_spec.substr(5));
  } else if (listen_spec.rfind("tcp:", 0) == 0) {
    lfd = listen_tcp(std::stoi(listen_spec.substr(4)));
  } else {
    std::cerr << "bad --listen spec: " << listen_spec << "\n";
    return 1;
  }
  if (lfd < 0) {
    std::cerr << "cannot listen on " << listen_spec << ": "
              << std::strerror(errno) << "\n";
    return 1;
  }

  if (proto != "abci" && proto != "custom") {
    std::cerr << "bad --proto (want abci|custom): " << proto << "\n";
    return 1;
  }

  Server srv(wal, proto == "abci");
  std::cout << "merkleeyes listening on " << listen_spec << " (" << proto
            << ")" << std::endl;
  while (true) {
    int cfd = ::accept(lfd, nullptr, nullptr);
    if (cfd < 0) {
      if (errno == EINTR) continue;
      break;
    }
    std::thread(serve_conn, &srv, cfd).detach();
  }
  ::close(lfd);
  return 0;
}
