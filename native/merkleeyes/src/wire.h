// Wire primitives: unsigned varints (LEB128, as Go's binary.Uvarint),
// zigzag varints (Go's binary.Varint), big-endian u64, and
// length-prefixed frames.
//
// The *tx* format matches the reference exactly
// (merkleeyes/app.go:488-520 unmarshalBytes/decodeInt + the gowire
// encoding in tendermint/src/jepsen/tendermint/gowire.clj:5-109):
//   tx     = nonce[12] ∥ type-byte ∥ args
//   bytes  = uvarint(len) ∥ raw
//   power  = 8-byte big-endian
// The *session* framing (frame = uvarint(len) ∥ payload) is this
// build's own — the reference speaks protobuf ABCI to tendermint; this
// server speaks a minimal equivalent documented in ../README.md.
#pragma once

#include <cstdint>
#include <optional>
#include <string>
#include <vector>

namespace merkleeyes {

using bytes = std::vector<uint8_t>;

inline void put_uvarint(bytes& out, uint64_t v) {
  while (v >= 0x80) {
    out.push_back(uint8_t(v) | 0x80);
    v >>= 7;
  }
  out.push_back(uint8_t(v));
}

// Returns (value, bytes-consumed); consumed == 0 on truncation,
// negative on overflow — the binary.Uvarint contract.
inline std::pair<uint64_t, int> get_uvarint(const uint8_t* p, size_t n) {
  uint64_t v = 0;
  int shift = 0;
  for (size_t i = 0; i < n; i++) {
    uint8_t b = p[i];
    if (shift >= 64) return {0, -int(i + 1)};
    if (b < 0x80) {
      if (shift == 63 && b > 1) return {0, -int(i + 1)};
      return {v | (uint64_t(b) << shift), int(i + 1)};
    }
    v |= uint64_t(b & 0x7f) << shift;
    shift += 7;
  }
  return {0, 0};
}

// Signed varint, zigzag encoded (binary.PutVarint / binary.Varint).
inline void put_varint(bytes& out, int64_t v) {
  put_uvarint(out, (uint64_t(v) << 1) ^ uint64_t(v >> 63));
}

inline std::pair<int64_t, int> get_varint(const uint8_t* p, size_t n) {
  auto [uv, c] = get_uvarint(p, n);
  int64_t v = int64_t(uv >> 1);
  if (uv & 1) v = ~v;
  return {v, c};
}

inline void put_u64be(bytes& out, uint64_t v) {
  for (int i = 7; i >= 0; i--) out.push_back((v >> (8 * i)) & 0xff);
}

inline std::optional<uint64_t> get_u64be(const uint8_t* p, size_t n) {
  if (n < 8) return std::nullopt;
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

// CRC-32 (IEEE, the zlib/Go hash/crc32 polynomial) — WAL frame
// integrity. Table built on first use.
inline uint32_t crc32(const uint8_t* p, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

inline void put_bytes(bytes& out, const bytes& b) {
  put_uvarint(out, b.size());
  out.insert(out.end(), b.begin(), b.end());
}

inline void put_str(bytes& out, const std::string& s) {
  put_uvarint(out, s.size());
  out.insert(out.end(), s.begin(), s.end());
}

}  // namespace merkleeyes
