// Persistent (path-copying) AVL+ Merkle tree.
//
// Capability parallel of cosmos/iavl as used by the reference
// (merkleeyes/state.go:18-35): an ordered KV map whose every version is
// an immutable snapshot sharing structure with its predecessors, with a
// root hash covering keys and values. iavl's design — values at leaf
// nodes, inner nodes carrying the split key — is kept, because it makes
// leaf order (and so GetByIndex / key rank) the sort order of keys and
// keeps values out of inner-node hashes.
//
// Node hashing (domain-separated, à la iavl):
//   leaf:  H(0x00 ∥ uvarint(len k) ∥ k ∥ uvarint(len v) ∥ v)
//   inner: H(0x01 ∥ height ∥ uvarint(size) ∥ lhash ∥ rhash)
// The working tree (State.Working in state.go) is just "the latest
// root"; Commit publishes it as the committed root — structural
// sharing makes that free.
#pragma once

#include <memory>
#include <optional>

#include "sha256.h"
#include "wire.h"

namespace merkleeyes {

struct Node;
using NodeRef = std::shared_ptr<const Node>;

struct Node {
  bytes key;            // leaf: its key; inner: smallest key of right subtree
  bytes value;          // leaf only
  int height = 0;       // leaf = 0
  int64_t size = 1;     // number of leaves under this node
  NodeRef left, right;  // inner only
  mutable std::optional<std::array<uint8_t, 32>> hash_cache;

  bool leaf() const { return height == 0; }

  const std::array<uint8_t, 32>& hash() const {
    if (!hash_cache) {
      Sha256 s;
      bytes buf;
      if (leaf()) {
        buf.push_back(0x00);
        put_bytes(buf, key);
        put_bytes(buf, value);
        s.update(buf);
      } else {
        buf.push_back(0x01);
        put_uvarint(buf, uint64_t(height));
        put_uvarint(buf, uint64_t(size));
        s.update(buf);
        s.update(left->hash().data(), 32);
        s.update(right->hash().data(), 32);
      }
      hash_cache = s.digest();
    }
    return *hash_cache;
  }
};

inline NodeRef make_leaf(bytes key, bytes value) {
  auto n = std::make_shared<Node>();
  n->key = std::move(key);
  n->value = std::move(value);
  return n;
}

inline NodeRef make_inner(NodeRef l, NodeRef r) {
  auto n = std::make_shared<Node>();
  n->height = 1 + std::max(l->height, r->height);
  n->size = l->size + r->size;
  // split key: smallest key in the right subtree
  const Node* m = r.get();
  while (!m->leaf()) m = m->left.get();
  n->key = m->key;
  n->left = std::move(l);
  n->right = std::move(r);
  return n;
}

inline int balance_factor(const NodeRef& n) {
  return n->left->height - n->right->height;
}

inline NodeRef rotate_right(const NodeRef& n) {
  return make_inner(n->left->left, make_inner(n->left->right, n->right));
}

inline NodeRef rotate_left(const NodeRef& n) {
  return make_inner(make_inner(n->left, n->right->left), n->right->right);
}

inline NodeRef rebalance(NodeRef n) {
  int bf = balance_factor(n);
  if (bf > 1) {
    if (balance_factor(n->left) < 0)
      n = make_inner(rotate_left(n->left), n->right);
    return rotate_right(n);
  }
  if (bf < -1) {
    if (balance_factor(n->right) > 0)
      n = make_inner(n->left, rotate_right(n->right));
    return rotate_left(n);
  }
  return n;
}

// An immutable tree snapshot. All "mutators" return a new Tree.
class Tree {
 public:
  Tree() = default;
  explicit Tree(NodeRef root) : root_(std::move(root)) {}

  int64_t size() const { return root_ ? root_->size : 0; }

  std::array<uint8_t, 32> hash() const {
    if (!root_) return Sha256::hash({});  // empty-tree hash
    return root_->hash();
  }

  // (index, value) — index is the key's in-order rank; nullopt if absent.
  std::optional<std::pair<int64_t, bytes>> get(const bytes& key) const {
    const Node* n = root_.get();
    int64_t rank = 0;
    while (n) {
      if (n->leaf()) {
        if (n->key == key) return {{rank, n->value}};
        return std::nullopt;
      }
      if (key < n->key) {
        n = n->left.get();
      } else {
        rank += n->left->size;
        n = n->right.get();
      }
    }
    return std::nullopt;
  }

  // (key, value) at in-order index; nullopt out of range.
  std::optional<std::pair<bytes, bytes>> get_by_index(int64_t idx) const {
    if (!root_ || idx < 0 || idx >= root_->size) return std::nullopt;
    const Node* n = root_.get();
    while (!n->leaf()) {
      if (idx < n->left->size) {
        n = n->left.get();
      } else {
        idx -= n->left->size;
        n = n->right.get();
      }
    }
    return {{n->key, n->value}};
  }

  Tree set(const bytes& key, const bytes& value) const {
    return Tree(set_(root_, key, value));
  }

  // (tree', removed?)
  std::pair<Tree, bool> remove(const bytes& key) const {
    if (!root_) return {*this, false};
    auto [r, removed] = remove_(root_, key);
    if (!removed) return {*this, false};
    return {Tree(r), true};
  }

 private:
  static NodeRef set_(const NodeRef& n, const bytes& key,
                      const bytes& value) {
    if (!n) return make_leaf(key, value);
    if (n->leaf()) {
      if (n->key == key) return make_leaf(key, value);
      if (key < n->key)
        return make_inner(make_leaf(key, value), n);
      return make_inner(n, make_leaf(key, value));
    }
    if (key < n->key)
      return rebalance(make_inner(set_(n->left, key, value), n->right));
    return rebalance(make_inner(n->left, set_(n->right, key, value)));
  }

  // (subtree-or-null, removed?)
  static std::pair<NodeRef, bool> remove_(const NodeRef& n,
                                          const bytes& key) {
    if (n->leaf()) {
      if (n->key == key) return {nullptr, true};
      return {n, false};
    }
    if (key < n->key) {
      auto [l, removed] = remove_(n->left, key);
      if (!removed) return {n, false};
      if (!l) return {n->right, true};
      return {rebalance(make_inner(l, n->right)), true};
    }
    auto [r, removed] = remove_(n->right, key);
    if (!removed) return {n, false};
    if (!r) return {n->left, true};
    return {rebalance(make_inner(n->left, r)), true};
  }

  NodeRef root_;
};

}  // namespace merkleeyes
