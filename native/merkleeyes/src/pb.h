// Minimal protobuf wire-format primitives — just enough to speak the
// tendermint v0.34 ABCI socket protocol (abci.h). Hand-rolled instead
// of linking protoc output: the surface is ~15 message types with
// scalar/bytes/submessage fields only, and the framework must build
// with no vendored deps.
//
// Wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32.
// int64/uint64/uint32/bool/enum ride wire type 0 (two's-complement
// varint, NOT zigzag — zigzag is only sint64, which ABCI doesn't use).
#pragma once

#include "wire.h"

namespace merkleeyes {
namespace pb {

enum Wire : uint32_t {
  kVarint = 0,
  kFixed64 = 1,
  kLen = 2,
  kFixed32 = 5,
};

// ---- writing --------------------------------------------------------

inline void tag(bytes& out, uint32_t field, uint32_t wire) {
  put_uvarint(out, (uint64_t(field) << 3) | wire);
}

// Varint-typed field. proto3 omits zero-valued scalars; callers that
// must preserve an explicit 0 skip the helper and emit the tag
// themselves (ABCI never needs that).
inline void varint_field(bytes& out, uint32_t field, uint64_t v) {
  if (v == 0) return;
  tag(out, field, kVarint);
  put_uvarint(out, v);
}

inline void int64_field(bytes& out, uint32_t field, int64_t v) {
  varint_field(out, field, uint64_t(v));  // two's complement
}

inline void bytes_field(bytes& out, uint32_t field, const bytes& b) {
  if (b.empty()) return;
  tag(out, field, kLen);
  put_uvarint(out, b.size());
  out.insert(out.end(), b.begin(), b.end());
}

inline void string_field(bytes& out, uint32_t field, const std::string& s) {
  if (s.empty()) return;
  tag(out, field, kLen);
  put_uvarint(out, s.size());
  out.insert(out.end(), s.begin(), s.end());
}

// Submessages are emitted even when empty: a present-but-empty member
// is how a oneof arm (e.g. ResponseFlush) is distinguished from an
// absent one.
inline void msg_field(bytes& out, uint32_t field, const bytes& sub) {
  tag(out, field, kLen);
  put_uvarint(out, sub.size());
  out.insert(out.end(), sub.begin(), sub.end());
}

// ---- reading --------------------------------------------------------

struct Reader {
  const uint8_t* p;
  size_t n;
  size_t pos = 0;
  bool ok = true;

  Reader(const uint8_t* p_, size_t n_) : p(p_), n(n_) {}
  Reader(const bytes& b) : p(b.data()), n(b.size()) {}

  bool done() const { return !ok || pos >= n; }

  uint64_t varint() {
    auto [v, c] = get_uvarint(p + pos, n - pos);
    if (c <= 0) {
      ok = false;
      return 0;
    }
    pos += size_t(c);
    return v;
  }

  // Reads the next tag; false at end of buffer or on error.
  bool next(uint32_t& field, uint32_t& wire) {
    if (done()) return false;
    uint64_t t = varint();
    if (!ok) return false;
    field = uint32_t(t >> 3);
    wire = uint32_t(t & 7);
    return field != 0;
  }

  // Length-delimited payload as a sub-reader.
  Reader len_payload() {
    uint64_t len = varint();
    if (!ok || n - pos < len) {
      ok = false;
      return Reader(p, 0);
    }
    Reader sub(p + pos, size_t(len));
    pos += size_t(len);
    return sub;
  }

  bytes len_bytes() {
    Reader sub = len_payload();
    if (!ok) return {};
    return bytes(sub.p, sub.p + sub.n);
  }

  std::string len_string() {
    Reader sub = len_payload();
    if (!ok) return {};
    return std::string(sub.p, sub.p + sub.n);
  }

  void skip(uint32_t wire) {
    switch (wire) {
      case kVarint:
        varint();
        break;
      case kFixed64:
        if (n - pos < 8) ok = false;
        else pos += 8;
        break;
      case kLen:
        len_payload();
        break;
      case kFixed32:
        if (n - pos < 4) ok = false;
        else pos += 4;
        break;
      default:
        ok = false;
    }
  }
};

}  // namespace pb
}  // namespace merkleeyes
