// The merkleeyes application: an ABCI-style Merkle-KV state machine.
//
// Behavior parity with the reference Go app (merkleeyes/app.go):
//   tx = nonce[12] ∥ type ∥ args            (app.go:22-30,226-238)
//   types: Set 0x01, Rm 0x02, Get 0x03, CAS 0x04,
//          ValSetChange 0x05, ValSetRead 0x06, ValSetCAS 0x07
//   error codes (app.go:33-40): 0 ok, 2 unknown-request, 3 encoding,
//          4 bad-nonce, 5 unknown-tx-type, 6 internal,
//          7 base-unknown-address, 8 unauthorized
//   nonce dedupe in-tree under "/nonce/" (app.go:219-250)
//   user keys under "/key/" (app.go:223-226)
//   committed vs working tree; queries answer from committed only
//          (app.go:158-217, state.go:14-24)
//   valset changes collected per block, version bumped at EndBlock when
//          changes exist (app.go:134-146,451-485)
//
// Durability: an append-only WAL of committed tx blocks (frame =
// uvarint(len) ∥ txs), replayed at startup; a trailing partial frame is
// ignored — that is what the truncate nemesis produces. The reference
// delegates this to goleveldb; a WAL keeps the native component
// self-contained and gives file truncation well-defined semantics.
#pragma once

#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>

#include "tree.h"
#include "wire.h"

namespace merkleeyes {

// Error codes (app.go:33-40).
enum Code : uint32_t {
  OK = 0,
  UnknownRequest = 2,
  EncodingError = 3,
  BadNonce = 4,
  ErrUnknownRequest = 5,
  InternalError = 6,
  ErrBaseUnknownAddress = 7,
  ErrUnauthorized = 8,
};

constexpr size_t kNonceLength = 12;     // app.go:31
constexpr size_t kPubKeySize = 32;      // ed25519
constexpr size_t kMinTxLen = kNonceLength + 1;

struct TxResult {
  uint32_t code = OK;
  bytes data;
  std::string log;
};

struct QueryResult {
  uint32_t code = OK;
  int64_t height = 0;
  int64_t index = -1;
  bytes key;
  bytes value;
  std::string log;
};

inline bytes cat(const char* prefix, const bytes& b) {
  bytes out(prefix, prefix + std::strlen(prefix));
  out.insert(out.end(), b.begin(), b.end());
  return out;
}

class App {
 public:
  // wal_path empty = in-memory only.
  explicit App(std::string wal_path = "") : wal_path_(std::move(wal_path)) {
    if (!wal_path_.empty()) replay_wal();
  }

  // ---- ABCI surface -------------------------------------------------

  // Info (app.go:95-103): (height, committed app hash).
  std::pair<int64_t, bytes> info() const {
    auto h = committed_.hash();
    return {height_, bytes(h.begin(), h.end())};
  }

  TxResult check_tx(const bytes& tx) const {  // app.go:116-126
    if (tx.size() < kMinTxLen)
      return {EncodingError, {}, "Tx length must be at least 13"};
    return {OK, {}, ""};
  }

  TxResult deliver_tx(const bytes& tx) {  // app.go:129-131
    TxResult r = do_tx(tx);
    // Record every tx whose nonce was newly marked — do_tx marks the
    // nonce in the working tree before parsing args, so even txs that
    // then fail to parse (EncodingError / unknown type) mutate state
    // and must replay. Only the pre-nonce length check (too-short tx)
    // and BadNonce (nonce already present, no set) leave the tree
    // untouched.
    if (tx.size() >= kMinTxLen && r.code != BadNonce) {
      block_.insert(block_.end(), tx.begin(), tx.end());
      block_frames_.push_back(tx.size());
    }
    return r;
  }

  void begin_block() {  // app.go:134-139
    changes_.clear();
  }

  // InitChain (app.go:105-113): installs the genesis validator set,
  // returns the current app hash. pubkeys are raw ed25519. Persisted
  // as its own WAL frame — the reference keeps its valset in the
  // backing db (state.go aux state); without this a crash-restart
  // would silently drop every genesis validator.
  bytes init_chain(const std::map<bytes, int64_t>& validators) {
    for (const auto& [pk, power] : validators) validators_[pk] = power;
    append_init_chain_wal(validators);
    auto h = committed_.hash();
    return bytes(h.begin(), h.end());
  }

  // Returns the validator updates of this block (app.go:141-147).
  std::map<bytes, int64_t> end_block() {
    if (!changes_.empty()) valset_version_++;
    return changes_;
  }

  bytes commit() {  // app.go:149-156, state.go:66-90
    committed_ = working_;
    height_++;
    append_wal();
    block_.clear();
    block_frames_.clear();
    auto h = committed_.hash();
    return bytes(h.begin(), h.end());
  }

  QueryResult query(const std::string& path, const bytes& data,
                    int64_t req_height = 0,
                    bool prove = false) const {  // app.go:158-217
    QueryResult res;
    if (req_height != 0) {
      res.code = InternalError;
      res.log = "merkleeyes only supports queries on latest commit";
      return res;
    }
    if (prove) {  // app.go:174-176
      res.code = InternalError;
      res.log = "Query with proof is not supported";
      return res;
    }
    res.height = height_;
    if (path == "/store" || path == "/key") {
      res.key = data;
      auto got = committed_.get(cat("/key/", data));
      if (!got) {
        res.code = ErrBaseUnknownAddress;
        res.log = "not found";
        return res;
      }
      res.index = got->first;
      res.value = got->second;
    } else if (path == "/index") {
      auto [idx, n] = get_varint(data.data(), data.size());
      if (n != int(data.size())) {
        res.code = EncodingError;
        res.log = "Varint did not consume all of in";
        return res;
      }
      auto got = committed_.get_by_index(idx);
      if (!got) {
        res.code = ErrBaseUnknownAddress;
        res.log = "not found";
        return res;
      }
      res.key = got->first;
      res.index = idx;
      res.value = got->second;
    } else if (path == "/size") {
      bytes v;
      put_varint(v, committed_.size());
      res.value = v;
    } else {
      res.code = UnknownRequest;
      res.log = "Unexpected Query path: " + path;
    }
    return res;
  }

  int64_t height() const { return height_; }
  uint64_t valset_version() const { return valset_version_; }
  const std::map<bytes, int64_t>& validators() const { return validators_; }

  // JSON of the validator set (ValSetRead, app.go:383-395).
  std::string valset_json() const {
    std::string out = "{\"version\":" + std::to_string(valset_version_) +
                      ",\"validators\":[";
    bool first = true;
    for (const auto& [pk, power] : validators_) {
      if (!first) out += ",";
      first = false;
      out += "{\"pub_key\":\"" + to_hex(pk.data(), pk.size()) +
             "\",\"power\":" + std::to_string(power) + "}";
    }
    out += "]}";
    return out;
  }

 private:
  // ---- tx execution -------------------------------------------------

  // unmarshalBytes (app.go:488-520): uvarint length-prefixed field.
  static std::pair<bytes, TxResult> read_field(const bytes& buf, size_t& pos,
                                               const char* what,
                                               bool must_exhaust) {
    auto [len, n] = get_uvarint(buf.data() + pos, buf.size() - pos);
    if (n <= 0)
      return {{}, {EncodingError, {}, std::string("Buf too small ") + what}};
    if (len == 0)
      return {{}, {EncodingError, {},
                   std::string("Zero or negative length ") + what}};
    if (buf.size() - pos < size_t(n) + len)
      return {{}, {EncodingError, {},
                   std::string("Not enough bytes ") + what}};
    bytes field(buf.begin() + pos + n, buf.begin() + pos + n + len);
    pos += size_t(n) + len;
    if (must_exhaust && pos != buf.size())
      return {{}, {EncodingError, {}, "Got bytes left over"}};
    return {field, {OK, {}, ""}};
  }

  TxResult do_tx(const bytes& tx_full) {  // app.go:227-448
    if (tx_full.size() < kMinTxLen)
      return {EncodingError, {}, "Tx length must be at least 13"};
    bytes nonce(tx_full.begin(), tx_full.begin() + kNonceLength);

    // Nonce check + mark (app.go:239-250). Applied to the working tree
    // so a replayed nonce is rejected even before commit.
    bytes nkey = cat("/nonce/", nonce);
    if (working_.get(nkey)) {
      return {BadNonce,
              {},
              "Nonce " + to_hex(nonce.data(), nonce.size()) +
                  " already exists"};
    }
    working_ = working_.set(nkey, {0x01});

    uint8_t type = tx_full[kNonceLength];
    bytes tx(tx_full.begin() + kMinTxLen, tx_full.end());
    size_t pos = 0;

    switch (type) {
      case 0x01: {  // Set (app.go:257-271)
        auto [key, err1] = read_field(tx, pos, "key", false);
        if (err1.code != OK) return err1;
        auto [value, err2] = read_field(tx, pos, "value", true);
        if (err2.code != OK) return err2;
        working_ = working_.set(cat("/key/", key), value);
        return {OK, {}, ""};
      }
      case 0x02: {  // Rm (app.go:273-289)
        auto [key, err] = read_field(tx, pos, "key", true);
        if (err.code != OK) return err;
        auto [t2, removed] = working_.remove(cat("/key/", key));
        if (!removed)
          return {ErrBaseUnknownAddress, {},
                  "Failed to remove " + to_hex(key.data(), key.size())};
        working_ = t2;
        return {OK, {}, ""};
      }
      case 0x03: {  // Get (app.go:291-306)
        auto [key, err] = read_field(tx, pos, "key", true);
        if (err.code != OK) return err;
        auto got = working_.get(cat("/key/", key));
        if (!got)
          return {ErrBaseUnknownAddress, {},
                  "Cannot find key: " + to_hex(key.data(), key.size())};
        return {OK, got->second, ""};
      }
      case 0x04: {  // CompareAndSet (app.go:308-352)
        auto [key, err1] = read_field(tx, pos, "key", false);
        if (err1.code != OK) return err1;
        auto [cmp, err2] = read_field(tx, pos, "compareKey", false);
        if (err2.code != OK) return err2;
        auto [setv, err3] = read_field(tx, pos, "setValue", true);
        if (err3.code != OK) return err3;
        auto got = working_.get(cat("/key/", key));
        if (!got)
          return {ErrBaseUnknownAddress, {},
                  "Cannot find key: " + to_hex(key.data(), key.size())};
        if (got->second != cmp)
          return {ErrUnauthorized, {},
                  "Value was " + to_hex(got->second.data(),
                                        got->second.size()) +
                      ", not " + to_hex(cmp.data(), cmp.size())};
        working_ = working_.set(cat("/key/", key), setv);
        return {OK, {}, ""};
      }
      case 0x05: {  // ValSetChange (app.go:354-382)
        auto [pubkey, err] = read_field(tx, pos, "pubKey", false);
        if (err.code != OK) return err;
        if (pubkey.size() != kPubKeySize)
          return {EncodingError, {}, "PubKey must be 32 bytes"};
        auto power = get_u64be(tx.data() + pos, tx.size() - pos);
        if (!power)
          return {EncodingError, {}, "Can't decode power: not enough bytes"};
        return update_validator(pubkey, int64_t(*power));
      }
      case 0x06:  // ValSetRead (app.go:383-395)
        return {OK, [&] {
                  std::string j = valset_json();
                  return bytes(j.begin(), j.end());
                }(), ""};
      case 0x07: {  // ValSetCAS (app.go:397-441)
        auto version = get_u64be(tx.data(), tx.size());
        if (!version)
          return {EncodingError, {}, "Can't decode version: not enough bytes"};
        if (valset_version_ != *version)
          return {ErrUnauthorized, {},
                  "Version was " + std::to_string(valset_version_) +
                      ", not " + std::to_string(*version)};
        pos = 8;
        auto [pubkey, err] = read_field(tx, pos, "pubKey", false);
        if (err.code != OK) return err;
        if (pubkey.size() != kPubKeySize)
          return {EncodingError, {}, "PubKey must be 32 bytes"};
        auto power = get_u64be(tx.data() + pos, tx.size() - pos);
        if (!power)
          return {EncodingError, {}, "Can't decode power: not enough bytes"};
        return update_validator(pubkey, int64_t(*power));
      }
      default:
        return {ErrUnknownRequest, {}, "Unexpected tx type byte"};
    }
  }

  TxResult update_validator(const bytes& pubkey, int64_t power) {
    // app.go:451-485: power 0 removes (error if absent); else upsert.
    if (power == 0) {
      auto it = validators_.find(pubkey);
      if (it == validators_.end())
        return {ErrUnauthorized, {}, "Cannot remove non-existent validator"};
      validators_.erase(it);
    } else {
      validators_[pubkey] = power;
    }
    changes_[pubkey] = power;  // last change per pubkey wins in the block
    return {OK, {}, ""};
  }

  // ---- WAL ----------------------------------------------------------
  //
  // file    = "MEW1" ∥ frames
  // frame   = uvarint(len) ∥ payload ∥ crc32le(payload)
  // payload = tag ∥ rest, where
  //   tag 0x00 (block): rest = n × (uvarint(txlen) ∥ tx) — one frame
  //     per Commit, empty for empty blocks so replayed height matches;
  //   tag 0x01 (init-chain): rest = n × (uvarint(pklen) ∥ pk ∥
  //     varint(power)) — the genesis validator set from InitChain.
  //
  // Replay reproduces the exact pre-crash state: block frames re-run
  // every recorded tx and then apply EndBlock's valset-version bump
  // (otherwise a replayed ValSetCAS that succeeded pre-crash would be
  // rejected against a stale version).

  static constexpr uint8_t kWalBlock = 0x00;
  static constexpr uint8_t kWalInitChain = 0x01;
  // File magic: lets replay tell "not a MEW1 WAL" (refuse to run)
  // apart from "empty/new file" (start fresh) — without it a foreign
  // or corrupt file would be silently wiped. MEW1 is a BREAKING format
  // change from the headerless pre-release WAL: a legacy file hits
  // "bad magic" and the node refuses to start — move the file aside
  // (or delete it, losing replayed history) to upgrade in place.
  // Deliberate: the pre-release format carried no checksums, so
  // "convert on first boot" would launder torn writes into committed
  // history; refusing is the conservative arm of the same policy the
  // replay applies to interior corruption.
  static constexpr const char* kWalMagic = "MEW1";

  // frame on disk = uvarint(len(payload)) ∥ payload ∥ crc32le(payload)
  void write_wal_frame(const bytes& payload) {
    FILE* f = std::fopen(wal_path_.c_str(), "ab");
    if (!f) return;
    std::fseek(f, 0, SEEK_END);
    if (std::ftell(f) == 0) std::fwrite(kWalMagic, 1, 4, f);
    bytes frame;
    put_uvarint(frame, payload.size());
    frame.insert(frame.end(), payload.begin(), payload.end());
    uint32_t c = crc32(payload.data(), payload.size());
    for (int i = 0; i < 4; i++) frame.push_back((c >> (8 * i)) & 0xFF);
    std::fwrite(frame.data(), 1, frame.size(), f);
    std::fflush(f);
    std::fclose(f);
  }

  [[noreturn]] void wal_corrupt(const char* what) {
    std::fprintf(stderr,
                 "merkleeyes: WAL %s is corrupt (%s) — refusing to run; "
                 "move the file aside to start fresh\n",
                 wal_path_.c_str(), what);
    std::abort();
  }

  void append_wal() {
    if (wal_path_.empty()) return;
    bytes payload{kWalBlock};
    for (size_t i = 0, off = 0; i < block_frames_.size(); i++) {
      put_uvarint(payload, block_frames_[i]);
      payload.insert(payload.end(), block_.begin() + off,
                     block_.begin() + off + block_frames_[i]);
      off += block_frames_[i];
    }
    write_wal_frame(payload);
  }

  void append_init_chain_wal(const std::map<bytes, int64_t>& validators) {
    if (wal_path_.empty()) return;
    bytes payload{kWalInitChain};
    for (const auto& [pk, power] : validators) {
      put_uvarint(payload, pk.size());
      payload.insert(payload.end(), pk.begin(), pk.end());
      put_varint(payload, power);
    }
    write_wal_frame(payload);
  }

  // Replays the WAL. Two failure shapes are told apart:
  //   * a *partial final frame* — a length underrun at the tail, the
  //     exact shape `truncate -c -s -N` (the truncate nemesis) and
  //     crashes mid-append produce — is silently dropped: the file is
  //     truncated back to the last complete frame so later appends
  //     never land after garbage;
  //   * anything else (wrong magic, unknown frame tag, malformed frame
  //     interior) is corruption — refuse to run rather than silently
  //     discard committed history.
  void replay_wal() {
    FILE* f = std::fopen(wal_path_.c_str(), "rb");
    if (!f) return;
    bytes data;
    uint8_t buf[65536];
    size_t n;
    while ((n = std::fread(buf, 1, sizeof(buf), f)) > 0)
      data.insert(data.end(), buf, buf + n);
    std::fclose(f);

    if (data.empty()) return;
    if (data.size() < 4 ||
        std::memcmp(data.data(), kWalMagic, 4) != 0) {
      // A <4-byte prefix of the magic = crash during the very first
      // write; safe to start over. Anything else is not our WAL.
      if (data.size() < 4 &&
          std::memcmp(data.data(), kWalMagic, data.size()) == 0) {
        if (::truncate(wal_path_.c_str(), 0) != 0)
          wal_corrupt("cannot truncate partial magic");
        return;
      }
      wal_corrupt("bad magic");
    }

    size_t pos = 4;
    while (pos < data.size()) {
      auto [flen, c] = get_uvarint(data.data() + pos, data.size() - pos);
      // partial tail: length underrun (frame + its crc don't fit)
      if (c <= 0 || data.size() - pos - c < flen + 4) break;
      size_t p = pos + c, end = pos + c + flen;
      uint32_t want = 0;
      for (int i = 0; i < 4; i++)
        want |= uint32_t(data[end + i]) << (8 * i);
      if (crc32(data.data() + p, flen) != want) {
        // Bad checksum on the FINAL frame = torn write: drop it like a
        // partial frame. On an interior frame = real corruption.
        if (end + 4 == data.size()) break;
        wal_corrupt("frame checksum mismatch");
      }
      if (p == end) wal_corrupt("tagless empty frame");
      uint8_t frame_tag = data[p++];
      if (frame_tag == kWalInitChain) {
        while (p < end) {
          auto [klen, kc] = get_uvarint(data.data() + p, end - p);
          if (kc <= 0 || end - p - kc < klen)
            wal_corrupt("malformed init-chain frame");
          bytes pk(data.begin() + p + kc, data.begin() + p + kc + klen);
          p += kc + klen;
          auto [power, pc] = get_varint(data.data() + p, end - p);
          if (pc <= 0) wal_corrupt("malformed init-chain power");
          p += pc;
          validators_[pk] = power;
        }
      } else if (frame_tag == kWalBlock) {
        changes_.clear();  // BeginBlock
        while (p < end) {
          auto [tlen, tc] = get_uvarint(data.data() + p, end - p);
          if (tc <= 0 || end - p - tc < tlen)
            wal_corrupt("malformed block frame");
          bytes tx(data.begin() + p + tc, data.begin() + p + tc + tlen);
          do_tx(tx);  // replay against the working tree
          p += tc + tlen;
        }
        if (!changes_.empty()) valset_version_++;  // EndBlock
        committed_ = working_;
        height_++;
      } else {
        wal_corrupt("unknown frame tag");
      }
      pos = end + 4;  // skip the crc
    }
    if (pos < data.size()) {
      // Drop the partial final frame NOW: append_wal opens in "ab", so
      // without this the next commit's frame would land after the
      // partial bytes and a second restart would mis-parse the
      // boundary (partial frame borrowing the next frame's bytes).
      if (::truncate(wal_path_.c_str(), off_t(pos)) != 0)
        wal_corrupt("cannot truncate partial final frame");
    }
    changes_.clear();
    block_.clear();
    block_frames_.clear();
  }

  Tree working_, committed_;  // state.go:14-24
  int64_t height_ = 0;
  uint64_t valset_version_ = 0;
  std::map<bytes, int64_t> validators_;
  std::map<bytes, int64_t> changes_;  // this block's updates
  bytes block_;                       // txs accepted since last commit
  std::vector<size_t> block_frames_;
  std::string wal_path_;
};

}  // namespace merkleeyes
