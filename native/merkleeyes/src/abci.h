// The tendermint v0.34 ABCI socket protocol, server side.
//
// This is the wire protocol a real tendermint binary speaks to its
// --proxy_app (reference: merkleeyes/cmd/merkleeyes/main.go:26-57
// serves the app via tendermint's abci/server over a unix socket; the
// reference pins tendermint v0.34.1-dev1 in merkleeyes/go.mod). Framing
// is uvarint-length-delimited protobuf: each message is
//
//     uvarint(len(body)) ∥ body
//
// where body is a `tendermint.abci.Request` / `Response` — a oneof
// over the per-method messages. Field numbers below follow tendermint
// v0.34 proto/tendermint/abci/types.proto.
//
// The handler maps each request onto the App (app.h), whose tx/query
// semantics mirror the reference Go app (merkleeyes/app.go:95-217).
// Responses carry exactly the fields the reference app sets; oneof
// arms the app doesn't implement (set_option, snapshots) return the
// BaseApplication empty response, and an unparseable request returns
// ResponseException — the same contract tendermint's own server gives.
#pragma once

#include "app.h"
#include "pb.h"

namespace merkleeyes {
namespace abci {

// Request oneof field numbers (types.proto, tendermint v0.34).
enum Req : uint32_t {
  kReqEcho = 1,
  kReqFlush = 2,
  kReqInfo = 3,
  kReqSetOption = 4,
  kReqInitChain = 5,
  kReqQuery = 6,
  kReqBeginBlock = 7,
  kReqCheckTx = 8,
  kReqDeliverTx = 9,
  kReqEndBlock = 10,
  kReqCommit = 11,
  kReqListSnapshots = 12,
  kReqOfferSnapshot = 13,
  kReqLoadSnapshotChunk = 14,
  kReqApplySnapshotChunk = 15,
};

// Response oneof field numbers (exception first, then each method
// shifted by one relative to Request).
enum Resp : uint32_t {
  kRespException = 1,
  kRespEcho = 2,
  kRespFlush = 3,
  kRespInfo = 4,
  kRespSetOption = 5,
  kRespInitChain = 6,
  kRespQuery = 7,
  kRespBeginBlock = 8,
  kRespCheckTx = 9,
  kRespDeliverTx = 10,
  kRespEndBlock = 11,
  kRespCommit = 12,
  kRespListSnapshots = 13,
  kRespOfferSnapshot = 14,
  kRespLoadSnapshotChunk = 15,
  kRespApplySnapshotChunk = 16,
};

// version.ABCIVersion for tendermint v0.34.
constexpr const char* kABCIVersion = "0.17.0";

inline bytes wrap(uint32_t arm, const bytes& body) {
  bytes out;
  pb::msg_field(out, arm, body);
  return out;
}

inline bytes exception(const std::string& err) {
  bytes body;
  pb::string_field(body, 1, err);
  return wrap(kRespException, body);
}

// ResponseCheckTx / ResponseDeliverTx share the field layout
// {code:1, data:2, log:3, ...}.
inline bytes tx_response(uint32_t arm, const TxResult& r) {
  bytes body;
  pb::varint_field(body, 1, r.code);
  pb::bytes_field(body, 2, r.data);
  pb::string_field(body, 3, r.log);
  return wrap(arm, body);
}

// ValidatorUpdate{pub_key:1 = PublicKey{ed25519:1}, power:2}.
inline bytes validator_update(const bytes& pubkey, int64_t power) {
  bytes pk;
  pb::bytes_field(pk, 1, pubkey);  // PublicKey.ed25519
  bytes vu;
  pb::msg_field(vu, 1, pk);
  pb::int64_field(vu, 2, power);
  return vu;
}

// Parses one RequestInitChain's validators (field 4, repeated
// ValidatorUpdate) into (ed25519-pubkey -> power).
inline std::map<bytes, int64_t> parse_init_validators(pb::Reader req) {
  std::map<bytes, int64_t> out;
  uint32_t f, w;
  while (req.next(f, w)) {
    if (f != 4 || w != pb::kLen) {
      req.skip(w);
      continue;
    }
    pb::Reader vu = req.len_payload();
    bytes pubkey;
    int64_t power = 0;
    uint32_t vf, vw;
    while (vu.next(vf, vw)) {
      if (vf == 1 && vw == pb::kLen) {
        pb::Reader pk = vu.len_payload();
        uint32_t pf, pw;
        while (pk.next(pf, pw)) {
          if (pf == 1 && pw == pb::kLen) pubkey = pk.len_bytes();
          else pk.skip(pw);
        }
      } else if (vf == 2 && vw == pb::kVarint) {
        power = int64_t(vu.varint());
      } else {
        vu.skip(vw);
      }
    }
    if (!pubkey.empty()) out[pubkey] = power;
  }
  return out;
}

// Handles one Request frame body; returns the Response frame body.
inline bytes handle(App& app, const bytes& req_body) {
  pb::Reader outer(req_body);
  uint32_t arm, wire;
  if (!outer.next(arm, wire) || wire != pb::kLen)
    return exception("malformed Request: no oneof arm");
  pb::Reader req = outer.len_payload();
  if (!outer.ok) return exception("malformed Request: bad length");

  switch (arm) {
    case kReqEcho: {
      std::string msg;
      uint32_t f, w;
      while (req.next(f, w)) {
        if (f == 1 && w == pb::kLen) msg = req.len_string();
        else req.skip(w);
      }
      bytes body;
      pb::string_field(body, 1, msg);
      return wrap(kRespEcho, body);
    }

    case kReqFlush:
      return wrap(kRespFlush, {});

    case kReqInfo: {
      auto [height, hash] = app.info();
      bytes body;
      pb::string_field(body, 2, kABCIVersion);  // version
      pb::varint_field(body, 3, 1);             // app_version (app.go:97-102)
      pb::int64_field(body, 4, height);         // last_block_height
      pb::bytes_field(body, 5, hash);           // last_block_app_hash
      return wrap(kRespInfo, body);
    }

    case kReqSetOption:
      return wrap(kRespSetOption, {});

    case kReqInitChain: {
      bytes hash = app.init_chain(parse_init_validators(req));
      bytes body;
      pb::bytes_field(body, 3, hash);  // app_hash (app.go:105-113)
      return wrap(kRespInitChain, body);
    }

    case kReqQuery: {
      bytes data;
      std::string path;
      int64_t height = 0;
      bool prove = false;
      uint32_t f, w;
      while (req.next(f, w)) {
        if (f == 1 && w == pb::kLen) data = req.len_bytes();
        else if (f == 2 && w == pb::kLen) path = req.len_string();
        else if (f == 3 && w == pb::kVarint) height = int64_t(req.varint());
        else if (f == 4 && w == pb::kVarint) prove = req.varint() != 0;
        else req.skip(w);
      }
      QueryResult q = app.query(path, data, height, prove);
      bytes body;
      pb::varint_field(body, 1, q.code);
      pb::string_field(body, 3, q.log);
      // proto3 int64: unset and 0 coincide on the wire, so a >= 0
      // index always decodes faithfully; the -1 "no index" sentinel
      // stays off the wire (decodes as 0) — matching the custom
      // protocol's client, which clamps -1 to 0 on decode so both
      // protocols agree on QueryResult.index
      if (q.index >= 0) pb::int64_field(body, 5, q.index);
      pb::bytes_field(body, 6, q.key);
      pb::bytes_field(body, 7, q.value);
      pb::int64_field(body, 9, q.height);
      return wrap(kRespQuery, body);
    }

    case kReqBeginBlock:
      app.begin_block();
      return wrap(kRespBeginBlock, {});

    case kReqCheckTx: {
      bytes tx;
      uint32_t f, w;
      while (req.next(f, w)) {
        if (f == 1 && w == pb::kLen) tx = req.len_bytes();
        else req.skip(w);
      }
      return tx_response(kRespCheckTx, app.check_tx(tx));
    }

    case kReqDeliverTx: {
      bytes tx;
      uint32_t f, w;
      while (req.next(f, w)) {
        if (f == 1 && w == pb::kLen) tx = req.len_bytes();
        else req.skip(w);
      }
      return tx_response(kRespDeliverTx, app.deliver_tx(tx));
    }

    case kReqEndBlock: {
      bytes body;
      for (const auto& [pk, power] : app.end_block())
        pb::msg_field(body, 1, validator_update(pk, power));
      return wrap(kRespEndBlock, body);
    }

    case kReqCommit: {
      bytes body;
      pb::bytes_field(body, 2, app.commit());  // data
      return wrap(kRespCommit, body);
    }

    case kReqListSnapshots:
      return wrap(kRespListSnapshots, {});
    case kReqOfferSnapshot:
      return wrap(kRespOfferSnapshot, {});
    case kReqLoadSnapshotChunk:
      return wrap(kRespLoadSnapshotChunk, {});
    case kReqApplySnapshotChunk:
      return wrap(kRespApplySnapshotChunk, {});

    default:
      return exception("unknown Request arm " + std::to_string(arm));
  }
}

}  // namespace abci
}  // namespace merkleeyes
