"""Benchmark: linearizability-check throughput, device engines vs host.

The north-star metric (BASELINE.md): knossos ops/sec checked and max
history length verified @ 60s budget, target >= 100x a 32-core host on
adversarial histories. Emits one JSON line per sub-metric, HEADLINE
LAST (the driver parses `{"metric", "value", "unit", "vs_baseline"}`):

1. multi-key north-star shape — 84 keys x 120 ops (the reference's
   cas-register workload: 120-op keys via jepsen.independent,
   tendermint/src/jepsen/tendermint/core.clj:351-361), device
   end-to-end with the encode/device split reported, vs a measured
   host-engine baseline scaled to a MODELED 32-core box (ideal linear
   scaling — generous to the host; per-key checks parallelize
   perfectly, so 32x is the host's true ceiling).
2. adversarial single-key histories at 1k/5k/10k/50k ops
   (histories.adversarial_register_history: k crashed writes held open
   forever -> the host search carries 2^k configs through every event,
   the regime where knossos dies; SURVEY.md §2.10). Host runs under a
   cooperative deadline and reports real progress (events done), from
   which its full-run time is estimated. NOTE: a single key cannot be
   parallelized by knossos (linear/wgl are single-threaded per key),
   so no 32x scaling is applied to this baseline — stated in the
   methodology field.
3. frontier-sharded engine on the same 10k history over all local
   devices (1-device mesh on a single chip; the 8-device path is
   exercised by tests/test_sharded.py and the driver dryrun).
4. max history length verified within a 60s device budget
   (steady-state device time; compiles excluded and reported).

The host baseline is `checker.linear_packed` — the same
JIT-linearization algorithm knossos.linear runs (checker.clj:194-200)
over the same int encoding the device uses: our fastest fair CPU
implementation (4-6x the Model-object `checker.linear`; a slow
baseline would flatter the speedup). Caveat, stated rather than
fudged: a JVM knossos would run a Python baseline some constant factor
faster; the adversarial speedups measured here are orders of magnitude
above that factor.
"""

from __future__ import annotations

import json
import os
import sys
from time import monotonic, perf_counter

# -------- north-star multi-key shape (reference workload dimensions)
SMOKE = os.environ.get("BENCH_SMOKE") == "1"   # tiny shapes for CI/CPU
N_KEYS = 8 if SMOKE else 84
OPS_PER_KEY = 40 if SMOKE else 120
N_PROCESSES = 14
BUSY = 0.8
HOST_SAMPLE_KEYS = 2 if SMOKE else 4
SEED = 2024

# -------- adversarial single-key shape
ADV_K = 8 if SMOKE else int(os.environ.get("BENCH_ADV_K", "12"))
# ^ crashed writes held open: 2^k configs. Host cost scales ~4x per +2k;
#   the bit-packed device's scales ~4x per +2k only in W (memory), with
#   far smaller constants — raise k to widen the regime gap.
ADV_SIZES = [200, 400] if SMOKE else [1000, 5000, 10000, 50000]
HOST_DEADLINES = ({200: 10.0, 400: 5.0} if SMOKE
                  else {1000: 45.0, 5000: 20.0, 10000: 25.0, 50000: 15.0})
BUDGET_SECS = float(os.environ.get("BENCH_BUDGET_SECS", "900"))


def emit(obj):
    print(json.dumps(obj), flush=True)


def note(msg):
    print(f"# {msg}", file=sys.stderr, flush=True)


def main():
    from jepsen_tpu.histories import (
        adversarial_register_history, rand_register_history)
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.checker import linear_packed
    from jepsen_tpu.parallel import bitdense, encode as enc_mod

    model = CASRegister()
    t_start = monotonic()

    def left():
        return BUDGET_SECS - (monotonic() - t_start)

    # ---------------- 1. multi-key north-star shape --------------------
    keys = [rand_register_history(
        n_ops=OPS_PER_KEY, n_processes=N_PROCESSES, n_values=5,
        crash_p=0.005, fail_p=0.05, busy=BUSY, seed=SEED + k)
        for k in range(N_KEYS)]
    total_ops = N_KEYS * OPS_PER_KEY

    t0 = perf_counter()
    pre = [enc_mod.encode(model, h) for h in keys]
    encode_secs = perf_counter() - t0
    S_max = max(bitdense.n_states(e) for e in pre)
    C_max = max(e.n_slots for e in pre)
    assert bitdense.fits_bitdense(S_max, C_max), (S_max, C_max)
    bitdense.check_batch_bitdense(pre)          # warm up (jit compile)
    t0 = perf_counter()
    rs = bitdense.check_batch_bitdense(pre)
    device_secs = perf_counter() - t0
    assert all(r["valid?"] is True for r in rs), rs[:3]
    e2e_secs = encode_secs + device_secs
    dev_rate = total_ops / e2e_secs

    # Host baseline = checker.linear_packed: int-config frontier over
    # the SAME encoding the device uses — our fastest fair CPU
    # implementation of the search (4-6x the Model-object engine; a
    # slow baseline would flatter the speedup). Sequential single-core
    # measurement, then an EXPLICIT x32 ideal-scaling model. (A thread
    # pool would be GIL-bound here — pure-Python search threads
    # serialize — so measuring "parallel" wall time would just
    # re-measure one core and, on a many-core box, silently present a
    # single-core rate as the 32-core baseline.)
    t0 = perf_counter()
    for h in keys[:HOST_SAMPLE_KEYS]:
        rh = linear_packed.analysis(model, h, deadline=monotonic() + 60)
        assert rh["valid?"] is True, rh
    host_secs = perf_counter() - t0
    host_rate = HOST_SAMPLE_KEYS * OPS_PER_KEY / host_secs
    host32_rate = host_rate * 32

    emit({"metric": f"multi-key {N_KEYS}x{OPS_PER_KEY}-op cas-register "
                    f"(north-star shape), device end-to-end",
          "value": round(dev_rate, 1), "unit": "ops/sec",
          "vs_baseline": round(dev_rate / host32_rate, 2),
          "device_only_secs": round(device_secs, 3),
          "encode_secs": round(encode_secs, 3),
          "device_only_ops_per_sec": round(total_ops / device_secs, 1),
          "host_seq_ops_per_sec": round(host_rate, 1),
          "host_cpus": os.cpu_count() or 1,
          "baseline": "packed int-config host engine (our fastest CPU "
                      "implementation of the same search), single-core "
                      "measured sequentially, x32 ideal scaling modeled "
                      "(per-key checks parallelize perfectly, so 32x is "
                      "the host's true ceiling)"})

    # ---------------- 2. adversarial single-key ------------------------
    adv_results = {}
    adv_enc = {}     # L -> encoded history, reused by sections 3 and 4

    def adv_encoded(L):
        if L not in adv_enc:
            h = adversarial_register_history(n_ops=L, k_crashed=ADV_K,
                                             seed=7)
            adv_enc[L] = (h, enc_mod.encode(model, h))
        return adv_enc[L]

    for L in ADV_SIZES:
        if left() < 90:
            emit({"metric": f"adversarial single-key {L}-op", "value": None,
                  "unit": "ops/sec", "skipped": "bench budget exhausted"})
            continue
        h, e = adv_encoded(L)
        assert bitdense.fits_bitdense(bitdense.n_states(e), e.n_slots)
        t0 = perf_counter()
        r = bitdense.check_encoded_bitdense(e)      # cold (compile per R)
        warm_secs = perf_counter() - t0
        t0 = perf_counter()
        r = bitdense.check_encoded_bitdense(e)      # steady state
        dev_secs = perf_counter() - t0
        assert r["valid?"] is True, r
        R = e.n_returns

        host_info = {"deadline_secs": HOST_DEADLINES[L]}
        if left() > HOST_DEADLINES[L] + 30:
            t0 = perf_counter()
            rh = linear_packed.check_encoded(
                e, deadline=monotonic() + HOST_DEADLINES[L])
            host_wall = perf_counter() - t0
            if rh["valid?"] == "unknown":
                # deadline OR config-budget exhaustion: either way the
                # host's measured progress rate is the estimate
                done = max(1, rh.get("events-done", 1))
                host_est = host_wall * R / done
                host_info.update({"timeout": bool(rh.get("timeout")),
                                  "stopped": rh.get("error", "deadline"),
                                  "events_done": done, "of_events": R,
                                  "est_total_secs": round(host_est, 1)})
            else:
                assert rh["valid?"] is True, rh
                host_est = host_wall
                host_info.update({"timeout": False,
                                  "total_secs": round(host_wall, 1)})
        else:
            # out of budget: scale the previous size's measured rate
            idx = ADV_SIZES.index(L)
            prev = adv_results.get(ADV_SIZES[idx - 1]) if idx > 0 else None
            host_est = (prev["host_est"] * (L / prev["L"])
                        if prev and prev["host_est"] is not None else None)
            host_info.update({"skipped": "bench budget",
                              "est_total_secs": round(host_est, 1)
                              if host_est else None})

        speedup = round(host_est / dev_secs, 1) if host_est else None
        adv_results[L] = {"L": L, "dev_secs": dev_secs,
                          "host_est": host_est, "speedup": speedup}
        emit({"metric": f"adversarial single-key {L}-op cas-register "
                        f"(2^{ADV_K} open configs), device",
              "value": round(L / dev_secs, 1), "unit": "ops/sec",
              "vs_baseline": speedup,
              "device_secs": round(dev_secs, 2),
              "device_compile_secs": round(warm_secs - dev_secs, 2),
              "host": host_info,
              "baseline": "packed int-config host engine, single-"
                          "threaded — a single key cannot be "
                          "parallelized by knossos linear/wgl, so no "
                          "32x scaling applies"})

    # ---------------- 3. sharded engine on the local mesh --------------
    try:
        if 10000 in adv_results and left() > 120:
            import jax
            from jax.sharding import Mesh
            import numpy as np
            from jepsen_tpu.parallel import sharded
            _, e = adv_encoded(10000)
            mesh = Mesh(np.array(jax.devices()), ("frontier",))
            cap = 1 << 17
            t0 = perf_counter()
            r = sharded.check_encoded_sharded(e, mesh, capacity=cap,
                                              max_capacity=1 << 20)
            warm = perf_counter() - t0
            t0 = perf_counter()
            r = sharded.check_encoded_sharded(e, mesh,
                                              capacity=r.get("capacity", cap),
                                              max_capacity=1 << 20)
            dev_secs = perf_counter() - t0
            emit({"metric": "adversarial 10k-op via frontier-sharded engine",
                  "value": round(10000 / dev_secs, 1), "unit": "ops/sec",
                  "vs_baseline": round(adv_results[10000]["host_est"] / dev_secs,
                                       1) if adv_results[10000]["host_est"]
                  else None,
                  "devices": r.get("devices"), "valid": r.get("valid?"),
                  "device_secs": round(dev_secs, 2),
                  "device_compile_secs": round(warm - dev_secs, 2),
                  "note": "owner-routed all-to-all exchange; multi-device "
                          "behavior exercised on the 8-way CPU mesh in CI"})
    except Exception as err:  # noqa: BLE001 — a sharded-path failure
        # must not cost the bench its remaining sections or headline
        emit({"metric": "adversarial 10k-op via frontier-sharded engine",
              "value": None, "unit": "ops/sec", "error": repr(err)})

    # ---------------- 4. max length verified @ 60s ---------------------
    max_len = 0
    budget_per_run = 5 if SMOKE else 60
    L = 400 if SMOKE else 10000
    prev_dt = None
    while left() > 2.5 * budget_per_run:
        if prev_dt is not None and prev_dt * 2 > 1.5 * budget_per_run:
            break   # doubling would clearly blow the budget; stop early
        _, e = adv_encoded(L)
        bitdense.check_encoded_bitdense(e)          # compile, uncounted
        t0 = perf_counter()
        r = bitdense.check_encoded_bitdense(e)
        dt = perf_counter() - t0
        assert r["valid?"] is True, r
        note(f"max-length probe L={L}: {dt:.1f}s steady")
        if dt <= budget_per_run:
            max_len = L
            L *= 2
            prev_dt = dt
        else:
            break
    if max_len:
        emit({"metric": f"max adversarial (2^{ADV_K}-config) history "
                        f"length verified @ {budget_per_run}s device "
                        f"budget",
              "value": max_len, "unit": "ops",
              "vs_baseline": None,
              "note": "steady-state device time; per-shape compile "
                      "excluded (one-time, cached)"})

    # ---------------- HEADLINE (last line: the driver's record) --------
    # prefer 10k (the BASELINE.md config); else the largest that ran
    ten_k = adv_results.get(10000)
    if ten_k is None and adv_results:
        ten_k = adv_results[max(adv_results)]
    if ten_k is not None:
        emit({"metric": f"adversarial {ten_k['L']}-op single-key "
                        f"cas-register linearizability check "
                        f"(2^{ADV_K} open configs)",
              "value": round(ten_k["L"] / ten_k["dev_secs"], 1),
              "unit": "ops/sec",
              "vs_baseline": ten_k["speedup"],
              "methodology": "vs this repo's packed int-config host "
                             "engine (same algorithm and encoding as "
                             "the device; our fastest CPU "
                             "implementation) measured under a deadline "
                             "on the same history; single-key search "
                             "does not parallelize, so the single-core "
                             "host rate IS the 32-core rate"})
    else:
        # budget ran out before any adversarial size finished: fall back
        # to the multi-key line so the driver still records a headline
        emit({"metric": f"multi-key {N_KEYS}x{OPS_PER_KEY}-op "
                        f"cas-register, device end-to-end",
              "value": round(dev_rate, 1),
              "unit": "ops/sec",
              "vs_baseline": round(dev_rate / host32_rate, 2)})


if __name__ == "__main__":
    try:
        main()
    except Exception as err:  # noqa: BLE001
        # the driver parses JSON lines: a crash must still leave a
        # visible, machine-readable trace rather than bare stderr
        import traceback
        traceback.print_exc()
        emit({"metric": "bench crashed", "value": None, "unit": "ops/sec",
              "vs_baseline": None, "error": repr(err)})
        sys.exit(1)
