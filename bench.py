"""Benchmark: linearizability checking throughput, device engine vs host.

The north-star metric (BASELINE.md): ops/sec of linearizability checking
on a 10k-op Tendermint-shaped cas-register history. The reference's
cas-register workload rotates keys every 120 ops with 2n=10 worker
threads (tendermint/src/jepsen/tendermint/core.clj:351-361), so a 10k-op
history is ~84 independent per-key subhistories — exactly what
jepsen.independent feeds the checker per key. The CPU baseline is this
repo's host JIT-linearization engine (the same algorithm knossos.linear
runs), timed on a sample of keys; the device number is the batched dense
TPU engine checking all keys in one program (including host->device
encode time).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

N_KEYS = 84
OPS_PER_KEY = 120          # reference per-key cap
N_PROCESSES = 14           # concurrent workers per key
BUSY = 0.8                 # high overlap: realistic contention windows
HOST_SAMPLE_KEYS = 4
SEED = 2024


def main():
    from jepsen_tpu.histories import rand_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import engine
    from jepsen_tpu.checker import linear

    model = CASRegister()
    keys = [rand_register_history(
        n_ops=OPS_PER_KEY, n_processes=N_PROCESSES, n_values=5,
        crash_p=0.005, fail_p=0.05, busy=BUSY, seed=SEED + k)
        for k in range(N_KEYS)]
    total_ops = N_KEYS * OPS_PER_KEY

    # --- host baseline: same algorithm, per-key, sample + extrapolate
    t0 = time.perf_counter()
    for h in keys[:HOST_SAMPLE_KEYS]:
        rh = linear.analysis(model, h)
        assert rh["valid?"] is True, rh
    host_secs = time.perf_counter() - t0
    host_ops_per_sec = HOST_SAMPLE_KEYS * OPS_PER_KEY / host_secs

    # --- device engine: all keys in one batched program
    engine.check_batch(model, keys)  # warm-up: jit compile
    t0 = time.perf_counter()
    rs = engine.check_batch(model, keys)
    dev_secs = time.perf_counter() - t0
    assert all(r["valid?"] is True for r in rs), rs[:3]
    dev_ops_per_sec = total_ops / dev_secs

    print(json.dumps({
        "metric": "linearizability check throughput "
                  "(10k-op multi-key cas-register history)",
        "value": round(dev_ops_per_sec, 1),
        "unit": "ops/sec",
        "vs_baseline": round(dev_ops_per_sec / host_ops_per_sec, 2),
    }))
    print(f"# device: {dev_secs:.3f}s for {total_ops} ops across {N_KEYS} "
          f"keys (incl. encode); host: {host_secs:.3f}s for "
          f"{HOST_SAMPLE_KEYS * OPS_PER_KEY} ops "
          f"({host_ops_per_sec:.0f} ops/s)", file=sys.stderr)


if __name__ == "__main__":
    main()
