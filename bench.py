"""Benchmark: linearizability-check throughput, device engines vs host.

The north-star metric (BASELINE.md): knossos ops/sec checked and max
history length verified @ 60s budget, target >= 100x a 32-core host on
adversarial histories. Emits one JSON line per sub-metric, HEADLINE
LAST (the driver parses `{"metric", "value", "unit", "vs_baseline"}`):

1. multi-key north-star shape — 84 keys x 120 ops (the reference's
   cas-register workload: 120-op keys via jepsen.independent,
   tendermint/src/jepsen/tendermint/core.clj:351-361), device
   end-to-end with the encode/device split reported, vs a measured
   host-engine baseline scaled to a MODELED 32-core box (ideal linear
   scaling — generous to the host; per-key checks parallelize
   perfectly, so 32x is the host's true ceiling).
2. adversarial single-key histories at 1k/5k/10k/50k ops
   (histories.adversarial_register_history: k crashed writes held open
   forever -> the host search carries 2^k configs through every event,
   the regime where knossos dies; SURVEY.md §2.10). Host runs under a
   cooperative deadline and reports real progress (events done), from
   which its full-run time is estimated. NOTE: a single key cannot be
   parallelized by knossos (linear/wgl are single-threaded per key),
   so no 32x scaling is applied to this baseline — stated in the
   methodology field.
3. frontier-sharded engine on the same 10k history over all local
   devices (1-device mesh on a single chip; the 8-device path is
   exercised by tests/test_sharded.py and the driver dryrun).
4. max history length verified within a 60s device budget
   (steady-state device time; compiles excluded and reported).

The host baseline is `checker.linear_packed` — the same
JIT-linearization algorithm knossos.linear runs (checker.clj:194-200)
over the same int encoding the device uses: our fastest fair CPU
implementation (4-6x the Model-object `checker.linear`; a slow
baseline would flatter the speedup). Caveat, stated rather than
fudged: a JVM knossos would run a Python baseline some constant factor
faster; the adversarial speedups measured here are orders of magnitude
above that factor.

HANG ISOLATION. Every section runs in its OWN subprocess under a hard
wall-clock timeout (the parent process never imports jax). A wedged
device runtime — e.g. a TPU tunnel outage mid-call, observed in the
wild: the PJRT client blocks forever inside make_c_api_client / a
device sync with no Python-level signal delivery — therefore costs
exactly one section, not the bench: the parent kills the child, emits
a machine-readable `{"skipped": "timeout/hang"}` line, and moves on.
The headline is computed by the parent from whichever sections
completed, so the driver always records a result. Children re-emit
their JSON lines on stdout; the parent forwards them verbatim and
parses them to thread host-baseline estimates between sections.

A DEAD runtime is detected ONCE, up front: a bounded pre-probe child
touches the backend before any device section; if it hangs, all
device sections are skipped at once (per-section skip lines) and the
bench drops straight to the labeled CPU fallback, instead of paying
one full timeout per section (BENCH_r04 spent ~13 minutes of budget
rediscovering the same wedge four times).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
from time import monotonic, perf_counter

from jepsen_tpu import envflags
from jepsen_tpu import obs

# set in child_main when JEPSEN_TPU_TRACE is on: the repo-relative path
# this section's Chrome trace will be written to. emit() stamps it onto
# every JSON line the section produces ("trace": <relpath>) so the
# BENCH_* record points at the span evidence; with tracing off the key
# is absent and the line schema is byte-for-byte the historical one
# (pinned by tests/test_bench.py).
TRACE_REL = None

# -------- north-star multi-key shape (reference workload dimensions)
SMOKE = os.environ.get("BENCH_SMOKE") == "1"   # tiny shapes for CI/CPU
N_KEYS = 8 if SMOKE else 84
OPS_PER_KEY = 40 if SMOKE else 120
N_PROCESSES = 14
BUSY = 0.8
HOST_SAMPLE_KEYS = 2 if SMOKE else 4
SEED = 2024

# -------- adversarial single-key shape
ADV_K = 8 if SMOKE else int(os.environ.get("BENCH_ADV_K", "12"))
# ^ crashed writes held open: 2^k configs. Host cost scales ~4x per +2k;
#   the bit-packed device's scales ~4x per +2k only in W (memory), with
#   far smaller constants — raise k to widen the regime gap.
ADV_SIZES = [200, 400] if SMOKE else [1000, 5000, 10000, 50000]
HOST_DEADLINES = ({200: 10.0, 400: 5.0} if SMOKE
                  else {1000: 45.0, 5000: 20.0, 10000: 25.0, 50000: 15.0})
BUDGET_SECS = float(os.environ.get("BENCH_BUDGET_SECS", "900"))

# Per-section wall-clock timeouts (seconds). Generous against measured
# runtimes (compile + cold + steady + host deadline), tight against the
# global budget; tuned so a single hang leaves room for what follows.
TIMEOUT_SCALE = float(os.environ.get("BENCH_TIMEOUT_SCALE", "1"))
SEC_TIMEOUTS = {
    "multikey": 60 if SMOKE else 300,
    "adv": ({200: 60, 400: 60} if SMOKE
            else {1000: 180, 5000: 240, 10000: 300, 50000: 480}),
    "sharded": 90 if SMOKE else 300,
    "maxlen": 120 if SMOKE else 360,
    "stream": 90 if SMOKE else 240,
}


def sec_timeout(key: str, L: int | None = None) -> float:
    """Scaled per-section timeout. TIMEOUT_SCALE applies HERE — before
    the callers clamp by the remaining global budget — so a scale > 1
    can never push a section past BUDGET_SECS."""
    base = SEC_TIMEOUTS["adv"][L] if key == "adv" else SEC_TIMEOUTS[key]
    return base * TIMEOUT_SCALE


def emit(obj):
    if TRACE_REL is not None and "trace" not in obj:
        obj = {**obj, "trace": TRACE_REL}
    print(json.dumps(obj), flush=True)


def note(msg):
    print(f"# {msg}", file=sys.stderr, flush=True)


def emit_search_stats(section: str, results, extra=None):
    """The stats-gated occupancy/pad-waste advisory line: emitted ONLY
    under JEPSEN_TPU_SEARCH_STATS=1 (results then already carry the
    device-computed "stats" blocks — zero extra compute), so the
    default bench schema is byte-identical (gating pinned in
    test_bench.py). `results` is a result dict or a list of them."""
    if not envflags.env_bool("JEPSEN_TPU_SEARCH_STATS", default=False):
        return
    rs = results if isinstance(results, list) else [results]
    blocks = [r.get("stats") for r in rs if isinstance(r, dict)
              and r.get("stats")]
    if not blocks:
        return
    occ = [b["peak-occupancy"] for b in blocks
           if b.get("peak-occupancy") is not None]
    lf = [b["load-factor-peak"] for b in blocks
          if b.get("load-factor-peak") is not None]
    waste = [b["pad-waste"] for b in blocks
             if b.get("pad-waste") is not None]
    hist: dict = {}
    for b in blocks:
        for lab, n in (b.get("probe-hist") or {}).items():
            hist[lab] = hist.get(lab, 0) + int(n)
    line = {"metric": f"{section} search stats (advisory, "
                      f"JEPSEN_TPU_SEARCH_STATS)",
            "value": round(max(occ), 6) if occ else None,
            "unit": "peak-occupancy",
            "keys": len(blocks),
            "engine": blocks[0].get("engine"),
            "frontier_peak": max(b.get("frontier-peak") or 0
                                 for b in blocks),
            "load_factor_peak": round(max(lf), 6) if lf else None,
            "pad_waste_max": round(max(waste), 6) if waste else None,
            "probe_hist": hist or None,
            "escalated_keys": sum(1 for b in blocks
                                  if b.get("capacity-tier")),
            "note": "device-computed occupancy/probe evidence for "
                    "ROADMAP items 2/5 (docs/observability.md "
                    "'Search telemetry'); absent without the flag — "
                    "default schema unchanged"}
    if extra:
        line.update(extra)
    emit(line)


def emit_steal_advisory(section: str):
    """The flag-gated elastic-scheduling advisory line: emitted ONLY
    under JEPSEN_TPU_STEAL=1, so the default bench schema is
    byte-identical (gating pinned in test_bench.py). Runs the
    recorded forced-skew shape (parallel.elastic.forced_skew_histories
    — heavy ladder-climbing keys statically pinned onto the first
    devices) through the SAME round executor with stealing off then
    on, and reports the wall-clock win plus the per-device busy/idle
    accounting both arms observed — the chip-evidence row the
    JEPSEN_TPU_STEAL flag flip needs."""
    if not envflags.env_bool("JEPSEN_TPU_STEAL", default=False):
        return
    import jax
    import numpy as np
    from jax.sharding import Mesh
    from jepsen_tpu.parallel import elastic, encode as enc_mod
    model, hs = elastic.forced_skew_histories()
    pre = [enc_mod.encode(model, h) for h in hs]
    mesh = Mesh(np.array(jax.devices()), ("key",))
    with obs.timer("bench.steal_ab", keys=len(pre)):
        ab = elastic.steal_ab(model, pre, mesh)
    b_steal = ab["steal"][0]
    b_static = ab["static"][0]
    emit({"metric": f"{section} elastic steal A/B (advisory, "
                    f"JEPSEN_TPU_STEAL)",
          "value": ab["steal_speedup"], "unit": "x speedup",
          "static_secs": ab["static_secs"],
          "steal_secs": ab["steal_secs"],
          "verdicts_identical": ab["verdicts_identical"],
          "keys": len(pre), "rounds": b_steal.get("rounds"),
          "keys_stolen": b_steal.get("steals"),
          "busy_frac_static": b_static.get("busy_frac"),
          "busy_frac_steal": b_steal.get("busy_frac"),
          "per_device_busy_static": b_static.get("per_device_busy"),
          "per_device_busy_steal": b_steal.get("per_device_busy"),
          "note": "forced-skew shape: heavy capacity-ladder keys "
                  "pinned on the first devices by the static "
                  "placement; stealing migrates the pending backlog "
                  "wide (docs/performance.md 'Elastic scheduling'); "
                  "absent without the flag — default schema "
                  "unchanged"})


def emit_reshard_advisory(e, mesh, cap0: int, max_cap: int,
                          static_r: dict, static_secs: float):
    """The flag-gated re-shard ladder advisory (JEPSEN_TPU_RESHARD=1
    only — default schema byte-identical, pinned in test_bench.py):
    the sharded section's shape re-run through
    check_encoded_sharded_elastic, which answers capacity overflow by
    recruiting devices at flat per-device capacity instead of growing
    tables. Reports the rung trail plus per-device skew evidence from
    the static run's stats block when JEPSEN_TPU_SEARCH_STATS is also
    armed."""
    if not envflags.env_bool("JEPSEN_TPU_RESHARD", default=False):
        return
    from jepsen_tpu.parallel import sharded
    sharded.check_encoded_sharded_elastic(e, mesh, capacity=cap0,
                                          max_capacity=max_cap)  # warm
    with obs.timer("bench.sharded.reshard") as tm:
        r = sharded.check_encoded_sharded_elastic(
            e, mesh, capacity=cap0, max_capacity=max_cap)
    assert r["valid?"] == static_r["valid?"], (r, static_r)
    st = static_r.get("stats") or {}
    pd = (st.get("per-device") or {}).get("load-factor-peak")
    skew = None
    if pd and any(v is not None for v in pd):
        vals = [v for v in pd if v is not None]
        mean = sum(vals) / len(vals)
        skew = round(max(vals) / mean, 4) if mean else None
    emit({"metric": "sharded re-shard ladder (advisory, "
                    "JEPSEN_TPU_RESHARD)",
          "value": round(tm.wall, 3), "unit": "secs",
          "static_secs": round(static_secs, 3),
          "reshard_speedup": round(static_secs / max(tm.wall, 1e-9),
                                   2),
          "devices_final": r.get("devices"),
          "capacity_final": r.get("capacity"),
          "reshard_events": (r.get("reshard") or {}).get("events"),
          "per_device_load_factor_static": pd,
          "device_skew_static": skew,
          "verdict_match": r["valid?"] == static_r["valid?"],
          "note": "escalation recruits devices at flat per-device "
                  "capacity (1-D -> wider 1-D -> 2-D promotion) "
                  "before growing tables; absent without the flag — "
                  "default schema unchanged"})


def _enable_compile_cache():
    """Persistent compilation cache: lets a child reuse a sibling's
    compile for the same shape (e.g. maxlen re-probing the 10k shape).
    Best-effort — some backends (remote-compile tunnels) ignore it.

    The destination honors JEPSEN_TPU_COMPILE_CACHE when it names a
    directory (the serve fleet's program cache doubles as the bench
    cache) and otherwise lands under the run's own ``store/`` dir —
    never a fixed world-writable /tmp path, where a planted symlink
    or a concurrent run on a shared box could cross-wire caches (the
    same hazard class the ci.sh serve_smoke tempdir fix closed)."""
    from jepsen_tpu import envflags
    # read OUTSIDE the best-effort guard: a malformed flag value must
    # fail loudly (the envflags contract), not degrade to the default
    dest = envflags.env_path("JEPSEN_TPU_COMPILE_CACHE",
                             what="cache directory")
    cache_dir = dest or os.path.join("store", "bench_jax_cache")
    try:
        import jax
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001
        pass


def _adv_encoded(L):
    """(model, history, encoded, encode_secs) — encode timed so every
    device section can report its encode/transfer/device split."""
    from jepsen_tpu.histories import adversarial_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import encode as enc_mod
    model = CASRegister()
    h = adversarial_register_history(n_ops=L, k_crashed=ADV_K, seed=7)
    with obs.timer("bench.adv.encode", L=L) as tm:
        e = enc_mod.encode(model, h)
    return model, h, e, tm.wall


# ======================= child sections ============================

def sec_probe():
    """Minimal device touch: backend init + one tiny compiled op.

    Runs FIRST under its own short timeout so a wedged runtime (PJRT
    client creation blocking forever — the observed tunnel-outage
    failure mode) costs the bench ONE bounded probe instead of one
    full timeout per device section: BENCH_r04 burned ~13 minutes of
    budget rediscovering the same dead runtime four times, one 180s+
    timeout per section."""
    import jax
    import jax.numpy as jnp
    devs = jax.devices()
    x = jnp.ones((8, 8), jnp.float32)
    # one-shot jit in a probe subprocess that exits right after: there
    # is no second call for a module-level wrapper's cache to serve
    # jepsen-lint: disable=recompile-closure-capture
    jax.jit(lambda a: a @ a)(x).block_until_ready()
    emit({"metric": "device pre-probe", "value": 1.0, "unit": "ok",
          "platform": devs[0].platform, "n_devices": len(devs)})


def sec_multikey(label: str = None):
    from jepsen_tpu.histories import rand_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.checker import linear_packed
    from jepsen_tpu.parallel import bitdense, encode as enc_mod

    model = CASRegister()
    keys = [rand_register_history(
        n_ops=OPS_PER_KEY, n_processes=N_PROCESSES, n_values=5,
        crash_p=0.005, fail_p=0.05, busy=BUSY, seed=SEED + k)
        for k in range(N_KEYS)]
    total_ops = N_KEYS * OPS_PER_KEY

    # obs.timer: the recorded span and the emitted seconds are the
    # SAME clock reads — the split line and the trace cannot disagree
    with obs.timer("bench.multikey.encode", keys=N_KEYS) as tm:
        pre = [enc_mod.encode(model, h) for h in keys]
    encode_secs = tm.wall
    S_max = max(bitdense.n_states(e) for e in pre)
    C_max = max(e.n_slots for e in pre)
    assert bitdense.fits_bitdense(S_max, C_max), (S_max, C_max)
    bitdense.check_batch_bitdense(pre)          # warm up (jit compile)
    # measured via the dispatch/finalize split so the JSONL carries the
    # pad+place (transfer) vs search (device) separation; their sum is
    # the same wall the old single check_batch_bitdense call measured
    with obs.timer("bench.multikey.serial", keys=N_KEYS) as tm:
        pending = bitdense.dispatch_batch_bitdense(pre)
        rs = pending.finalize()
    batch_secs = tm.wall
    transfer_secs = pending.transfer_secs
    device_secs = batch_secs - transfer_secs
    assert all(r["valid?"] is True for r in rs), rs[:3]
    closure = rs[0].get("closure")
    e2e_secs = encode_secs + batch_secs
    dev_rate = total_ops / e2e_secs

    # Host baseline = checker.linear_packed: int-config frontier over
    # the SAME encoding the device uses — our fastest fair CPU
    # implementation of the search (4-6x the Model-object engine; a
    # slow baseline would flatter the speedup). Sequential single-core
    # measurement, then an EXPLICIT x32 ideal-scaling model. (A thread
    # pool would be GIL-bound here — pure-Python search threads
    # serialize — so measuring "parallel" wall time would just
    # re-measure one core and, on a many-core box, silently present a
    # single-core rate as the 32-core baseline.)
    with obs.timer("bench.multikey.host", keys=HOST_SAMPLE_KEYS) as tm:
        for h in keys[:HOST_SAMPLE_KEYS]:
            rh = linear_packed.analysis(model, h,
                                        deadline=monotonic() + 60)
            assert rh["valid?"] is True, rh
    host_secs = tm.wall
    host_rate = HOST_SAMPLE_KEYS * OPS_PER_KEY / host_secs
    host32_rate = host_rate * 32

    # a relabeled run (the CPU fallback) must not leave a line in the
    # record claiming a device number
    what = label or "device end-to-end"
    line_extra = {} if label is None else {"backend": label}
    emit({"metric": f"multi-key {N_KEYS}x{OPS_PER_KEY}-op cas-register "
                    f"(north-star shape), {what}",
          "value": round(dev_rate, 1), "unit": "ops/sec",
          "vs_baseline": round(dev_rate / host32_rate, 2),
          **line_extra,
          "closure": closure,
          # uniform dedupe keys (docs/performance.md "Dedup
          # strategies"): bitdense sections report "dense" (the
          # reachable-set tensor is a complete visited set; no sparse
          # counter exists) — real counters live on the sparse/sharded
          # lines and the adv section's dedupe A/B advisory
          "dedupe": rs[0].get("dedupe"),
          "configs_stepped": rs[0].get("configs-stepped"),
          "device_only_secs": round(batch_secs, 3),
          "encode_secs": round(encode_secs, 3),
          "transfer_secs": round(transfer_secs, 4),
          "device_secs": round(device_secs, 3),
          "device_only_ops_per_sec": round(total_ops / batch_secs, 1),
          "host_seq_ops_per_sec": round(host_rate, 1),
          "host_cpus": os.cpu_count() or 1,
          "baseline": "packed int-config host engine (our fastest CPU "
                      "implementation of the same search), single-core "
                      "measured sequentially, x32 ideal scaling modeled "
                      "(per-key checks parallelize perfectly, so 32x is "
                      "the host's true ceiling)"})
    emit_search_stats(f"multi-key {N_KEYS}x{OPS_PER_KEY}-op", rs)

    # -- pipelined e2e: the same batch through the pipelined executor
    # (encode/transfer overlapped with device work, parallel.pipeline),
    # with the encode/transfer/device split reported PER BUCKET. Run
    # once cache-less to warm the chunk-shape compiles, then measure a
    # steady cache-less pass (the overlap win) and a cache-hit pass
    # (the re-analysis win). Verdict parity with the serial line is
    # asserted — a pipelined speedup that changed answers would be a
    # bug report, not a result.
    from jepsen_tpu.parallel import engine, pipeline as pipe_mod
    engine.check_batch(model, keys, pipeline=True, cache=False)  # warm
    pstats = {}
    with obs.timer("bench.multikey.pipelined", keys=N_KEYS) as tm:
        rs_p = engine.check_batch(model, keys, pipeline=True,
                                  cache=False, pipeline_stats=pstats)
    pipe_secs = tm.wall
    assert [r["valid?"] for r in rs_p] == [r["valid?"] for r in rs]
    # explicit capacity: the cached pass must measure cache hits even
    # under JEPSEN_TPU_ENCODE_CACHE=0 in the ambient env (an explicit
    # arg overrides the flag, same contract as the other perf flags)
    cache = pipe_mod.EncodeCache(max_entries=N_KEYS + 8)
    engine.check_batch(model, keys, pipeline=True, cache=cache)  # fill
    cstats = {}
    with obs.timer("bench.multikey.cached", keys=N_KEYS) as tm:
        rs_c = engine.check_batch(model, keys, pipeline=True,
                                  cache=cache, pipeline_stats=cstats)
    cached_secs = tm.wall
    assert [r["valid?"] for r in rs_c] == [r["valid?"] for r in rs]
    assert cstats["cache"]["encodes"] == 0, cstats["cache"]
    emit({"metric": f"multi-key {N_KEYS}x{OPS_PER_KEY}-op cas-register "
                    f"(north-star shape), pipelined {what}",
          "value": round(total_ops / pipe_secs, 1), "unit": "ops/sec",
          "vs_baseline": round(total_ops / pipe_secs / host32_rate, 2),
          **line_extra,
          "closure": closure,
          "dedupe": cstats.get("dedupe"),
          "configs_stepped": None,   # bitdense buckets: see above
          "serial_e2e_secs": round(e2e_secs, 3),
          "pipelined_e2e_secs": round(pipe_secs, 3),
          "cached_e2e_secs": round(cached_secs, 3),
          "cache": cstats["cache"],
          "buckets": pstats["buckets"],
          "note": "pipelined = encode + transfer overlapped with "
                  "device search (JEPSEN_TPU_PIPELINE); cached = "
                  "second pass over the same histories, zero "
                  "re-encodes; buckets carry the per-bucket "
                  "encode/transfer/device split"})
    emit_steal_advisory(f"multi-key {N_KEYS}x{OPS_PER_KEY}-op")


def sec_adv(L: int, host_deadline: float, skip_host: bool,
            host_est_hint: float | None):
    from jepsen_tpu.checker import linear_packed
    from jepsen_tpu.parallel import bitdense

    _, _, e, encode_secs = _adv_encoded(L)
    assert bitdense.fits_bitdense(bitdense.n_states(e), e.n_slots)
    with obs.timer("bench.adv.cold", L=L) as tm:
        r = bitdense.check_encoded_bitdense(e)  # cold (compile per R)
    warm_secs = tm.wall
    tms = {}
    with obs.timer("bench.adv.steady", L=L) as tm:
        r = bitdense.check_encoded_bitdense(e, timings=tms)  # steady
    steady_secs = tm.wall
    # dev_secs keeps the HISTORICAL meaning (whole steady call — the
    # quantity the r5 artifacts recorded and the rate/speedup below
    # use); the split keys are uniform across sections: device_secs =
    # search only, transfer_secs reported separately
    dev_secs = steady_secs
    assert r["valid?"] is True, r
    closure = r.get("closure")
    R = e.n_returns

    host_info = {"deadline_secs": host_deadline}
    host_est = None
    if skip_host:
        # parent ran out of budget for a host run: it passes the
        # previous size's measured rate scaled by L as the estimate
        host_est = host_est_hint
        host_info.update({"skipped": "bench budget",
                          "est_total_secs": round(host_est, 1)
                          if host_est else None})
    else:
        t0 = perf_counter()
        rh = linear_packed.check_encoded(
            e, deadline=monotonic() + host_deadline)
        host_wall = perf_counter() - t0
        if rh["valid?"] == "unknown":
            # deadline OR config-budget exhaustion: either way the
            # host's measured progress rate is the estimate
            done = max(1, rh.get("events-done", 1))
            host_est = host_wall * R / done
            host_info.update({"timeout": bool(rh.get("timeout")),
                              "stopped": rh.get("error", "deadline"),
                              "events_done": done, "of_events": R,
                              "est_total_secs": round(host_est, 1)})
        else:
            assert rh["valid?"] is True, rh
            host_est = host_wall
            host_info.update({"timeout": False,
                              "total_secs": round(host_wall, 1)})

    speedup = round(host_est / dev_secs, 1) if host_est else None
    emit({"metric": f"adversarial single-key {L}-op cas-register "
                    f"(2^{ADV_K} open configs), device",
          "value": round(L / dev_secs, 1), "unit": "ops/sec",
          "vs_baseline": speedup,
          "L": L,
          "closure": closure,
          "dedupe": r.get("dedupe"),
          "configs_stepped": r.get("configs-stepped"),
          # split keys, uniform across sections: device_secs = search
          # only; steady_secs = the whole steady call (the r5
          # artifacts' old "device_secs"), which value/vs_baseline use
          "device_secs": round(tms["device_secs"], 3),
          "encode_secs": round(encode_secs, 3),
          "transfer_secs": round(tms["transfer_secs"], 4),
          "steady_secs": round(steady_secs, 3),
          "device_compile_secs": round(warm_secs - dev_secs, 2),
          "host_est_secs": round(host_est, 1) if host_est else None,
          "host": host_info,
          "baseline": "packed int-config host engine, single-"
                      "threaded — a single key cannot be "
                      "parallelized by knossos linear/wgl, so no "
                      "32x scaling applies"})
    emit_search_stats(f"adversarial single-key {L}-op", r, {"L": L})

    # -- sparse-engine dedupe A/B (advisory): the frontier engine's
    # sort vs hash strategies on the same encoded history, with the
    # configs-stepped counters that make the delta-frontier work
    # reduction visible even on CPU. Emitted AFTER the section's main
    # line (the parent harvests partial output, so a slow advisory can
    # never cost the headline) and bounded to L <= 1000 — the sparse
    # engine at 10k+ is the pre-bitdense cost profile, and the 1k
    # counters already show the asymptotics. Flip decisions belong to
    # tools/perf_ab.py's dedupe line; this records the counters in the
    # BENCH_* record.
    if L <= 1000:
        from jepsen_tpu.histories import adversarial_register_history
        from jepsen_tpu.models import CASRegister
        from jepsen_tpu.parallel import encode as enc_mod, engine
        # derated k: the full-k sparse frontier peaks at ~10*2^k
        # configs (k=12 -> capacity 2^16), minutes per strategy on a
        # CPU advisory run — the DELTA asymptotics show at any k, and
        # the full-k wall-clock decision belongs to tools/perf_ab.py
        # on a healthy chip
        k_ab = min(ADV_K, 6)
        e_ab = enc_mod.encode(CASRegister(), adversarial_register_history(
            n_ops=L, k_crashed=k_ab, seed=7))
        cap = 1 << (k_ab + 4)        # one tier: peak ~10*2^k configs
        ab = {}
        strategies = ["sort", "hash"]
        from jepsen_tpu import envflags
        if envflags.env_bool("JEPSEN_TPU_SPARSE_PALLAS", default=False):
            # the fused VMEM frontier kernel rides the A/B only when
            # its flag is on, so the default bench schema stays
            # byte-identical (the kernel is opt-in until the chip A/B;
            # tools/perf_ab.py's hash-pallas strategy owns the flip)
            strategies.append("hash-pallas")
        if envflags.env_bool("JEPSEN_TPU_CONFIG_PACK", default=False):
            # same opt-in gating for the packed configuration word:
            # flag off => schema byte-identical; tools/perf_ab.py's
            # hash-packed strategy owns the flip decision
            strategies.append("hash-packed")
        if envflags.env_bool("JEPSEN_TPU_AUTO", default=False):
            # the self-tuning planner rides the A/B the same opt-in
            # way: an "auto" arm with every strategy axis left unset,
            # so the live decision table routes it (docs/performance.md
            # "Auto planner") — flag off => schema byte-identical;
            # tools/perf_ab.py's PERF_AB_AUTO arm owns the advisory
            # convergence reading
            strategies.append("auto")
        for strat in strategies:
            kw = {"dedupe": strat}
            if strat == "hash-pallas":
                kw = {"dedupe": "hash", "sparse_pallas": True}
            elif strat == "hash-packed":
                kw = {"dedupe": "hash", "config_pack": True}
            elif strat == "auto":
                kw = {}
            engine.check_encoded(e_ab, capacity=cap,
                                 max_capacity=cap * 4, **kw)  # compile
            with obs.timer("bench.adv.dedupe_ab", L=L,
                           strategy=strat) as tm:
                ra = engine.check_encoded(e_ab, capacity=cap,
                                          max_capacity=cap * 4, **kw)
            ab[strat] = {"secs": round(tm.wall, 3),
                         "configs_stepped": ra.get("configs-stepped"),
                         "valid": ra.get("valid?")}
            if strat == "auto" and ra.get("plan"):
                # the provenance block says which vector the table
                # routed to, and from what evidence
                ab[strat]["plan"] = ra["plan"]
        assert all(v["valid"] is True for v in ab.values()), ab
        emit({"metric": f"adversarial single-key {L}-op sparse-engine "
                        f"dedupe A/B (advisory, 2^{k_ab} open configs)",
              "value": ab["hash"]["secs"], "unit": "secs",
              "vs_baseline": None, "L": L,
              "dedupe": ab,
              "hash_vs_sort_secs": round(
                  ab["sort"]["secs"] / max(ab["hash"]["secs"], 1e-9), 2),
              "note": "sparse frontier engine only (the bitdense line "
                      "above is the measured path); configs_stepped is "
                      "the closure work actually paid — hash steps the "
                      "delta, sort re-steps the whole frontier every "
                      "closure iteration. Flip decisions ride "
                      "tools/perf_ab.py's full-k sparse-dedupe lines"})


def sec_sharded(L: int, host_est: float | None,
                cap_log: int | None = None):
    import jax
    import numpy as np
    from jax.sharding import Mesh
    from jepsen_tpu.parallel import sharded

    _, _, e, encode_secs = _adv_encoded(L)
    mesh = Mesh(np.array(jax.devices()), ("frontier",))
    # H2D split: an explicit replicated placement of the event tables
    # onto the mesh, blocked on — the same arrays the engine ships
    # (its own internal placement is what the device_secs then pays)
    from jax.sharding import NamedSharding, PartitionSpec as P
    t0 = perf_counter()
    placed = jax.device_put(
        {"slot_f": e.slot_f, "slot_a0": e.slot_a0, "slot_a1": e.slot_a1,
         "slot_wild": e.slot_wild, "slot_occ": e.slot_occ,
         "ev_slot": e.ev_slot}, NamedSharding(mesh, P()))
    jax.block_until_ready(placed)
    transfer_secs = perf_counter() - t0
    del placed
    # cap_log is the parent's downshift lever: the r5 chip session saw
    # the 2^17-capacity program crash the TPU *worker process* on its
    # first hardware contact, so a crashed first attempt is retried in
    # a fresh child at a smaller tier — an overflowed "unknown" line
    # still beats no line. The downshift also caps GROWTH below the
    # known-fatal tier (2^17): overflow-doubling from 2^13 would
    # otherwise climb right back into it.
    if cap_log is not None:
        cap0, max_cap = 1 << cap_log, 1 << min(cap_log + 3, 16)
    else:
        cap0, max_cap = ((1 << 12) if SMOKE else (1 << 17)), 1 << 20
    t0 = perf_counter()
    # reshard pinned OFF: the section's main line measures the static
    # engine even when JEPSEN_TPU_RESHARD=1 arms the advisory below —
    # the A/B needs a static arm to compare against
    r = sharded.check_encoded_sharded(e, mesh, capacity=cap0,
                                      max_capacity=max_cap,
                                      reshard=False)
    warm = perf_counter() - t0
    cap = r.get("capacity", cap0)
    if cap != cap0:
        # capacity grew during the warm run: compile the final tier
        # before measuring, so the steady number holds no compile
        sharded.check_encoded_sharded(e, mesh, capacity=cap,
                                      max_capacity=max_cap,
                                      reshard=False)
    with obs.timer("bench.sharded.steady", L=L, capacity=cap) as tm:
        r = sharded.check_encoded_sharded(e, mesh, capacity=cap,
                                          max_capacity=max_cap,
                                          reshard=False)
    dev_secs = tm.wall
    line = {"metric": f"adversarial {L}-op via frontier-sharded engine",
            "value": round(L / dev_secs, 1), "unit": "ops/sec",
            "vs_baseline": round(host_est / dev_secs, 1)
            if host_est else None,
            "devices": r.get("devices"), "valid": r.get("valid?"),
            "dedupe": r.get("dedupe"),
            "configs_stepped": r.get("configs-stepped"),
            "device_secs": round(dev_secs, 2),
            "encode_secs": round(encode_secs, 3),
            "transfer_secs": round(transfer_secs, 4),
            "warm_secs": round(warm, 2),
            "note": "owner-routed all-to-all exchange; multi-device "
                    "behavior exercised on the 8-way CPU mesh in CI; "
                    "the sharded engine has no transfer/search seam, "
                    "so device_secs includes its internal placement "
                    "and transfer_secs is a separate explicit "
                    "measurement of the same arrays"}
    if cap == cap0:
        # warm and steady runs share one shape, so the difference IS
        # the compile; after tier growth it would also contain whole
        # searches at smaller capacities — omitted rather than fudged
        line["device_compile_secs"] = round(max(warm - dev_secs, 0.0), 2)
    else:
        line["capacity_grew_to"] = cap
    emit(line)
    emit_search_stats(f"sharded {L}-op", r, {"L": L})
    emit_reshard_advisory(e, mesh, cap0, max_cap, r, dev_secs)


MAXLEN_RUN_BUDGET = 5 if SMOKE else 60   # the metric's "@ 60s" budget


def sec_maxlen(budget_secs: float):
    """Max length verified @ 60s device budget, within budget_secs."""
    from jepsen_tpu.parallel import bitdense

    t_start = monotonic()

    def left():
        return budget_secs - (monotonic() - t_start)

    max_len = 0
    budget_per_run = MAXLEN_RUN_BUDGET
    L = 400 if SMOKE else 10000
    prev_dt = None
    split = {}   # encode/transfer/device of the last PASSING probe
    while left() > 2.5 * budget_per_run:
        if prev_dt is not None and prev_dt * 2 > 1.5 * budget_per_run:
            break   # doubling would clearly blow the budget; stop early
        _, _, e, encode_secs = _adv_encoded(L)
        bitdense.check_encoded_bitdense(e)          # compile, uncounted
        tms = {}
        with obs.timer("bench.maxlen.probe", L=L) as tm:
            r = bitdense.check_encoded_bitdense(e, timings=tms)
        dt = tm.wall
        assert r["valid?"] is True, r
        note(f"max-length probe L={L}: {dt:.1f}s steady")
        if dt <= budget_per_run:
            max_len = L
            L *= 2
            prev_dt = dt
            split = {"encode_secs": round(encode_secs, 3),
                     "transfer_secs": round(tms["transfer_secs"], 4),
                     "device_secs": round(tms["device_secs"], 3),
                     "dedupe": r.get("dedupe"),
                     "configs_stepped": r.get("configs-stepped")}
        else:
            break
    if max_len:
        emit({"metric": f"max adversarial (2^{ADV_K}-config) history "
                        f"length verified @ {budget_per_run}s device "
                        f"budget",
              "value": max_len, "unit": "ops",
              "vs_baseline": None,
              **split,
              "note": "steady-state device time; per-shape compile "
                      "excluded (one-time, cached); "
                      "encode/transfer/device split is the verified "
                      "(largest passing) length's"})


def sec_stream():
    """Advisory (BENCH_STREAM=1 only): incremental frontier extension
    (parallel.extend.HistorySession) vs a full re-encode + re-check of
    every prefix, on a growing history fed as deltas — the streaming
    checker's economics (docs/streaming.md). Emitted only when the
    flag is on, so the default bench schema stays byte-identical
    (pinned by tests/test_bench.py). `full_secs` includes each
    prefix's compile — that IS the cost full re-checking re-pays,
    while the incremental path reuses a handful of quantized chunk
    shapes."""
    from jepsen_tpu.histories import rand_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import encode as enc_mod, engine
    from jepsen_tpu.parallel import extend as ext
    from jepsen_tpu.history import History

    n_ops = int(os.environ.get("BENCH_STREAM_OPS",
                               "200" if SMOKE else "2000"))
    deltas = int(os.environ.get("BENCH_STREAM_DELTAS",
                                "8" if SMOKE else "20"))
    m = CASRegister()
    h = list(rand_register_history(n_ops=n_ops, n_processes=6,
                                   n_values=4, crash_p=0.03,
                                   fail_p=0.05, busy=0.7, seed=11))
    step = -(-len(h) // deltas)
    cuts = [min(len(h), (i + 1) * step) for i in range(deltas)]
    with obs.timer("bench.stream.incremental") as ti:
        s = ext.HistorySession(m, capacity=1024)
        lo = 0
        for cut in cuts:
            s.extend(h[lo:cut])
            lo = cut
            ri = s.check()
    with obs.timer("bench.stream.full") as tf:
        for cut in cuts:
            e = enc_mod.encode(m, History.wrap(h[:cut]))
            rf = engine.check_encoded(e, capacity=1024)
    emit({"metric": f"streaming incremental extension vs full "
                    f"re-check ({len(h)}-op history in {deltas} "
                    f"deltas) [advisory]",
          "value": round(len(h) / max(ti.wall, 1e-9), 1),
          "unit": "ops/sec", "vs_baseline": None,
          "stream": {"deltas": deltas, "ops": len(h),
                     "incremental_secs": round(ti.wall, 4),
                     "full_secs": round(tf.wall, 4),
                     "speedup": round(tf.wall / max(ti.wall, 1e-9), 2),
                     "verdicts_match": ri["valid?"] == rf["valid?"],
                     "final_resume_event":
                         ri["stream"]["resumed-from-event"]}})


# ======================= parent orchestrator =======================

def run_section(argv: list, timeout: float, env_extra: dict = None,
                trace_suffix: str = ""):
    """Spawn `python bench.py --section ...`; forward the child's
    stdout lines as they arrive, parse the JSON ones, kill on timeout.
    The ACTUAL timeout rides along as the final `--timeout` argv so
    the child can schedule its pre-kill stack dump just before it.
    `trace_suffix` joins the child's chrome-trace filename — retries
    MUST pass one, or the retry child would overwrite the file the
    first attempt's already-emitted lines point at.
    Returns (parsed JSON objects, status) — status in
    {"ok", "crash", "hung"}. parsed holds whatever JSON lines arrived
    BEFORE a kill — a child can emit its result line and then hang in
    a later phase (e.g. the host baseline), and callers rely on
    harvesting those partial results."""
    cmd = [sys.executable, os.path.abspath(__file__), "--section"] + \
        [str(a) for a in argv] + ["--timeout", f"{timeout:.0f}"]
    if trace_suffix:
        cmd += ["--trace-suffix", trace_suffix]
    env = None
    if env_extra:
        env = dict(os.environ)
        env.update(env_extra)
    parsed = []
    try:
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=sys.stderr, text=True, env=env)
    except OSError as err:
        emit({"metric": f"section {argv[0]}", "value": None,
              "unit": "ops/sec", "error": repr(err)})
        return parsed, "crash"

    def pump():
        for line in proc.stdout:
            line = line.rstrip("\n")
            if not line:
                continue
            print(line, flush=True)            # forward verbatim
            if line.lstrip().startswith("{"):
                try:
                    parsed.append(json.loads(line))
                except ValueError:
                    pass

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        rc = proc.wait(timeout=timeout)
        t.join(timeout=10)
        if rc != 0:
            emit({"metric": f"section {argv[0]}", "value": None,
                  "unit": "ops/sec",
                  "error": f"child exited rc={rc}"})
            return parsed, "crash"
        return parsed, "ok"
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        t.join(timeout=10)
        emit({"metric": f"section {argv[0]}", "value": None,
              "unit": "ops/sec",
              "skipped": f"timeout/hang after {timeout:.0f}s "
                         f"(section isolated in a subprocess; "
                         f"bench continues)"})
        return parsed, "hung"


def main():
    t_start = monotonic()

    def left():
        return BUDGET_SECS - (monotonic() - t_start)

    hung = []              # (kind, L) sections killed on timeout
    mk_line = None
    adv_results = {}       # L -> parsed line (with L, device_secs, host)

    # ---------------- 0. bounded device pre-probe ------------------
    # Fail a dead runtime ONCE: a single short child touches the
    # backend; if it hangs/crashes, every device section is skipped at
    # once (each with its own machine-readable skip line, so the
    # record stays per-section complete) and control drops straight to
    # the labeled CPU fallback below. A wedge that develops MID-bench
    # is still caught by the per-section isolation + retry.
    probe_to = min(max(1.0,
                       float(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
                       * TIMEOUT_SCALE), BUDGET_SECS)

    def probe_once(trace_suffix=""):
        parsed, st = run_section(["probe"], probe_to,
                                 trace_suffix=trace_suffix)
        probe_parsed[:] = parsed
        ok = (st == "ok"
              and any(p.get("metric") == "device pre-probe"
                      and p.get("value") for p in parsed))
        return ok, st

    def probe_platform():
        return next((p.get("platform") for p in probe_parsed
                     if p.get("metric") == "device pre-probe"), None)

    probe_parsed = []
    probe_ok, st = probe_once()
    if not probe_ok and left() > probe_to + 60:
        # one retry: a single probe hang/crash must not relabel a
        # healthy-chip round as cpu-fallback over a transient blip —
        # mid-bench hangs get a retry for the same reason
        note(f"device pre-probe failed ({st}) — retrying once")
        probe_ok, st = probe_once("retry")
    if not probe_ok:
        note(f"device pre-probe failed twice ({st}) — skipping ALL "
             f"device sections at once; straight to the labeled CPU "
             f"fallback")
        how = (f"hung past {probe_to:.0f}s" if st == "hung"
               else f"child {st}ed")
        skip = (f"device pre-probe {how} (twice) — runtime "
                f"unreachable; all device sections skipped at once")
        emit({"metric": f"multi-key {N_KEYS}x{OPS_PER_KEY}-op "
                        f"cas-register (north-star shape)",
              "value": None, "unit": "ops/sec", "skipped": skip})
        for L in ADV_SIZES:
            emit({"metric": f"adversarial single-key {L}-op",
                  "value": None, "unit": "ops/sec", "skipped": skip})
        emit({"metric": "adversarial via frontier-sharded engine",
              "value": None, "unit": "ops/sec", "skipped": skip})
        emit({"metric": f"max adversarial (2^{ADV_K}-config) history "
                        f"length verified @ {MAXLEN_RUN_BUDGET}s "
                        f"device budget",
              "value": None, "unit": "ops", "skipped": skip})

    # ---------------- 1. multi-key north-star shape ----------------
    if probe_ok:
        multikey, st = run_section(
            ["multikey"], min(sec_timeout("multikey"), BUDGET_SECS))
        mk_line = next((p for p in multikey if p.get("value")), None)
        if st == "hung":
            hung.append(("multikey", None))

    # ---------------- 1b. streaming advisory (flag-gated) ----------
    # BENCH_STREAM=1 only: an advisory incremental-extend vs full
    # re-check line — gated so the default bench schema (and its
    # budget) stays byte-identical when off
    if probe_ok and os.environ.get("BENCH_STREAM") == "1" \
            and left() > 90:
        run_section(["stream"], min(sec_timeout("stream"), left()))

    # ---------------- 2. adversarial single-key --------------------
    def run_adv(L, trace_suffix=""):
        deadline = HOST_DEADLINES[L]
        skip_host = left() < deadline + 90
        hint = ""
        if skip_host:
            # scale the largest completed size's host estimate
            prev = max((p for p in adv_results.values()
                        if p.get("host_est_secs")),
                       key=lambda p: p["L"], default=None)
            if prev:
                hint = prev["host_est_secs"] * (L / prev["L"])
        args = ["adv", L, deadline, int(skip_host), hint]
        parsed, st = run_section(
            args, min(sec_timeout("adv", L), max(left(), 60)),
            trace_suffix=trace_suffix)
        for p in parsed:
            if p.get("L") == L and p.get("value") is not None:
                adv_results[L] = p
        return st

    for L in (ADV_SIZES if probe_ok else []):
        if left() < min(90, sec_timeout("adv", L)):
            emit({"metric": f"adversarial single-key {L}-op",
                  "value": None,
                  "unit": "ops/sec", "skipped": "bench budget exhausted"})
            continue
        if run_adv(L) == "hung":
            hung.append(("adv", L))

    # ---------------- retry hung sections once ---------------------
    # a hang can be a transient device-runtime flake rather than a
    # hard outage; retry BEFORE sections 3-4 so a recovered result can
    # still feed the sharded section and the headline, and so maxlen
    # (which deliberately consumes the remaining budget) hasn't eaten
    # the retry's slot. Largest adversarial size first, then the
    # multi-key shape; a second hang just re-emits the skip line.
    for kind, L in sorted(hung, key=lambda k: -(k[1] or 0)):
        if kind == "adv":
            if L in adv_results or left() < 120:
                continue
            note(f"retrying hung adv L={L} (transient flake?)")
            run_adv(L, trace_suffix="retry")
        elif kind == "multikey" and mk_line is None and left() > 120:
            note("retrying hung multikey section (transient flake?)")
            parsed, _ = run_section(
                ["multikey"], min(sec_timeout("multikey"), left()),
                trace_suffix="retry")
            mk_line = next((p for p in parsed if p.get("value")), None)

    # ---------------- 3. sharded engine on the local mesh ----------
    pick = 10000 if not SMOKE else (400 if 400 in adv_results else None)
    if probe_ok and pick in adv_results and left() > 120:
        parsed, st = run_section(
            ["sharded", pick,
             adv_results[pick].get("host_est_secs") or ""],
            min(sec_timeout("sharded"), left()))
        if st != "ok" and not any(p.get("value") for p in parsed) \
                and not SMOKE and left() > 180:
            # r5 on-chip: the default 2^17-capacity program crashed
            # the TPU worker (child rc=1, PJRT client dead). A fresh
            # child at a smaller tier can still land a sharded line —
            # possibly an "unknown" overflow, which is honest evidence.
            # SMOKE already runs the smallest sensible tier (2^12), so
            # a downshift retry only exists for the production shape.
            # A HUNG child usually means the runtime wedged (a tunnel
            # outage survives worker restarts), where any retry just
            # burns another timeout — a crashed worker restarts, a
            # wedge doesn't, so gate the retry on a short re-probe.
            retry_ok = True
            if st == "hung":
                probe2, p2st = run_section(["probe"], 90,
                                           trace_suffix="sharded-gate")
                if p2st != "ok" or not any(
                        p.get("value") for p in probe2):
                    note("sharded section hung and the runtime no "
                         "longer answers a probe — skipping the "
                         "downshift retry (wedged, not crashed)")
                    retry_ok = False
            if retry_ok:
                note("sharded section crashed/hung; retrying in a "
                     "fresh child at capacity 2^13")
                run_section(
                    ["sharded", pick,
                     adv_results[pick].get("host_est_secs") or "",
                     "13"],
                    min(sec_timeout("sharded"), left()),
                    trace_suffix="retry13")

    # ---------------- 4. max length verified @ 60s -----------------
    # the child's own probe budget sits INSIDE the kill timeout, with
    # margin; only spawn when that budget clears the probe loop's own
    # floor (2.5x the per-run budget), so a child is never started
    # that could not run a single probe
    to = min(sec_timeout("maxlen"), left())
    if probe_ok and to - 30 > 2.5 * MAXLEN_RUN_BUDGET:
        run_section(["maxlen", to - 30], to)

    # ---------------- HEADLINE (last line: the driver's record) ----
    # prefer 10k (the BASELINE.md config); else the largest that ran
    ten_k = adv_results.get(10000)
    if ten_k is None and adv_results:
        ten_k = adv_results[max(adv_results)]
    if ten_k is not None:
        L = ten_k["L"]
        emit({"metric": f"adversarial {L}-op single-key "
                        f"cas-register linearizability check "
                        f"(2^{ADV_K} open configs)",
              "value": ten_k["value"],
              "unit": "ops/sec",
              "vs_baseline": ten_k.get("vs_baseline"),
              "backend": probe_platform(),
              "closure": ten_k.get("closure"),
              "methodology": "vs this repo's packed int-config host "
                             "engine (same algorithm and encoding as "
                             "the device; our fastest CPU "
                             "implementation) measured under a deadline "
                             "on the same history; single-key search "
                             "does not parallelize, so the single-core "
                             "host rate IS the 32-core rate"})
    elif mk_line is not None:
        # no adversarial size finished (budget/hang): fall back to the
        # multi-key line so the driver still records a headline
        emit({"metric": f"multi-key {N_KEYS}x{OPS_PER_KEY}-op "
                        f"cas-register, device end-to-end",
              "value": mk_line["value"],
              "unit": "ops/sec",
              "vs_baseline": mk_line.get("vs_baseline"),
              "backend": probe_platform(),
              "closure": mk_line.get("closure")})
    else:
        # EVERY device section hung or crashed — almost certainly a
        # dead TPU runtime (observed in the wild: the tunnel wedges
        # PJRT client creation). Record a clearly-labeled CPU-fallback
        # number rather than a null: it documents that the checker
        # machinery works and makes the outage legible in the record.
        # The fallback child is forced onto SMOKE shapes regardless of
        # the parent's: the full 84-key batch cannot finish on a host
        # CPU inside any reasonable window (BENCH_r03 recorded null for
        # exactly that reason), and the fallback's one job is to land a
        # labeled number. Its timeout is budget-independent (at least
        # sec_timeout("multikey"), at most 300s): this is the only
        # number the run will produce, and the driver's outer timeout
        # is the real bound. TIMEOUT_SCALE scales the floor as usual,
        # which is also how the error-headline path stays testable.
        note("all device sections failed — CPU-fallback multikey "
             "run on SMOKE shapes (labeled; not a TPU number)")
        parsed, _ = run_section(
            ["multikey", "cpu-fallback"],
            max(sec_timeout("multikey"), min(left(), 300)),
            env_extra={"JAX_PLATFORMS": "cpu", "BENCH_SMOKE": "1"})
        fb = next((p for p in parsed if p.get("value")), None)
        if fb is not None:
            line = {"metric": f"{fb['metric']} — CPU FALLBACK on SMOKE "
                              f"shapes (TPU runtime unreachable; NOT a "
                              f"device number)",
                    "value": fb["value"],
                    "unit": "ops/sec",
                    "vs_baseline": fb.get("vs_baseline"),
                    "backend": "cpu-fallback"}
            prior = _prior_onchip_headline()
            if prior:
                # a pointer, not a measurement: this run measured
                # nothing on a device — the reference says where a
                # real chip DID measure this bench, so a fallback
                # record never buries existing hardware evidence
                line["prior_onchip_headline"] = prior
            emit(line)
            return
        err_line = {"metric": "linearizability check throughput",
                    "value": None, "unit": "ops/sec",
                    "vs_baseline": None,
                    "error": "no section completed (device runtime "
                             "down?) — see the per-section lines above"}
        prior = _prior_onchip_headline()
        if prior:
            # the deadest-runtime record must point at the evidence too
            err_line["prior_onchip_headline"] = prior
        emit(err_line)


def _prior_onchip_headline():
    """Newest recorded on-chip headline from bench_results/*.jsonl
    (committed measurement artifacts — see PERF_R05.md), or None.
    "Newest" means the highest PARSED round number in
    `bench_r<N>_onchip.jsonl` — these are committed files, and git
    checkouts do not preserve mtime, so a fresh clone's mtimes are
    checkout order, not measurement order (plain filename sort is no
    better: it ranks r100 before r99). Files whose name carries no
    round number fall back to mtime and rank below any parsed round.
    Attached to fallback/error headlines as `prior_onchip_headline` so
    a dead-runtime round still points at the hardware evidence."""
    import glob
    import re
    base = os.path.dirname(os.path.abspath(__file__))
    paths = glob.glob(os.path.join(base, "bench_results",
                                   "bench_*_onchip.jsonl"))

    def order(p):
        m = re.match(r"bench_r(\d+)_onchip\.jsonl$", os.path.basename(p))
        if m:
            return (1, int(m.group(1)), 0.0)
        return (0, 0, os.path.getmtime(p))

    best = None
    for path in sorted(paths, key=order):
        lines = []
        try:
            with open(path) as f:
                for ln in f:
                    # these artifacts are written by runs that can be
                    # killed mid-write: one truncated line must not
                    # discard the file's valid headlines
                    try:
                        lines.append(json.loads(ln))
                    except ValueError:
                        continue
        except OSError:
            continue
        for p in reversed(lines):
            if isinstance(p, dict) and p.get("value") \
                    and p.get("backend") not in (None, "cpu-fallback"):
                best = {"file": os.path.relpath(path, base),
                        "metric": p.get("metric"),
                        "value": p.get("value"),
                        "vs_baseline": p.get("vs_baseline"),
                        "backend": p.get("backend"),
                        "note": "recorded artifact from a prior "
                                "healthy-chip run, NOT this run's "
                                "measurement"}
                break
    return best


def child_main(argv: list) -> None:
    # a child that hangs in device code cannot deliver Python signals;
    # dump a stack to stderr shortly before the parent's ACTUAL kill
    # time (threaded through as --timeout) so the hang site is
    # diagnosable from the bench log
    import faulthandler
    to = 300.0
    if "--timeout" in argv:
        i = argv.index("--timeout")
        to = float(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    trace_suffix = ""
    if "--trace-suffix" in argv:
        i = argv.index("--trace-suffix")
        trace_suffix = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    sec = argv[0]
    faulthandler.dump_traceback_later(max(20, to - 10), exit=False)
    from jepsen_tpu.resilience import faults as _faults
    _wedge = _faults.decide("child")
    if (_wedge is not None and _wedge.kind == "wedge"
            and os.environ.get("JAX_PLATFORMS") != "cpu"):
        # fault seam (resilience.faults, site "child"): simulate the
        # observed tunnel wedge (PJRT client creation blocking
        # forever, uninterruptible by Python signals) in every child
        # not pinned to cpu — mirroring production, where cpu-pinned
        # children survive an outage. JEPSEN_TPU_FAULTS=wedge@child
        # drives it; the legacy JEPSEN_TPU_TEST_WEDGE=1 maps onto the
        # same rule (faults.active_plan), so existing automation keeps
        # working.
        import time
        while True:
            time.sleep(3600)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        # env alone is not enough on this image — the TPU plugin's
        # backend hook ignores it; pin via config like conftest does
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:  # noqa: BLE001
            pass
    _enable_compile_cache()
    global TRACE_REL
    flusher = None
    if obs.enabled():
        # the pointer is computed BEFORE the section runs so every
        # line it emits carries it; the trace itself is written after
        # (and on a crash — partial spans still diagnose the hang).
        # The first section arg (adv/sharded L, multikey label) joins
        # the filename: four adv children must not overwrite each
        # other's evidence while their lines point at it; a parent
        # retry passes --trace-suffix for the same reason.
        tag = "_".join([sec] + [str(a) for a in argv[1:2] if a])
        if trace_suffix:
            tag += "_" + trace_suffix
        tag = "".join(ch if ch.isalnum() or ch in "-_" else "-"
                      for ch in tag)
        TRACE_REL = os.path.join("store", "bench_traces",
                                 f"bench_{tag}.trace.json")

        # A HUNG child never reaches the finally below — the parent's
        # proc.kill() is SIGKILL — so flush the partial trace shortly
        # before the kill time (alongside the faulthandler stack
        # dump): the spans recorded so far are exactly the evidence a
        # hang diagnosis needs, and the pointer the child already
        # stamped on its lines must not dangle. write_chrome_trace
        # reads a copy of the span buffer, so the normal end-of-
        # section write below simply supersedes this one.
        def _flush_partial():
            try:
                obs.write_chrome_trace(TRACE_REL)
            except Exception:  # noqa: BLE001 — best-effort, pre-kill
                pass
        flusher = threading.Timer(max(10.0, to - 10.0), _flush_partial)
        flusher.daemon = True
        flusher.start()
    try:
        if sec == "probe":
            sec_probe()
        elif sec == "multikey":
            sec_multikey(argv[1] if len(argv) > 1 else None)
        elif sec == "adv":
            L, deadline, skip_host = int(argv[1]), float(argv[2]), \
                bool(int(argv[3]))
            hint = float(argv[4]) if len(argv) > 4 and argv[4] else None
            sec_adv(L, deadline, skip_host, hint)
        elif sec == "sharded":
            L = int(argv[1])
            host_est = float(argv[2]) if len(argv) > 2 and argv[2] \
                else None
            cap_log = int(argv[3]) if len(argv) > 3 and argv[3] else None
            sec_sharded(L, host_est, cap_log)
        elif sec == "maxlen":
            sec_maxlen(float(argv[1]))
        elif sec == "stream":
            sec_stream()
        else:
            raise SystemExit(f"unknown section {sec!r}")
    finally:
        if flusher is not None:
            # cancel() is a no-op once the timer callback is already
            # executing — join so a section finishing right at the
            # flush deadline can't interleave two writers on one file
            flusher.cancel()
            flusher.join(timeout=30)
        if TRACE_REL is not None:
            obs.write_chrome_trace(TRACE_REL)


if __name__ == "__main__":
    try:
        if len(sys.argv) > 1 and sys.argv[1] == "--section":
            child_main(sys.argv[2:])
        else:
            main()
    except Exception as err:  # noqa: BLE001
        # JSON-line consumers must see a machine-readable trace of any
        # crash rather than bare stderr
        import traceback
        traceback.print_exc()
        emit({"metric": "bench crashed", "value": None, "unit": "ops/sec",
              "vs_baseline": None, "error": repr(err)})
        sys.exit(1)
