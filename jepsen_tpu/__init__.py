"""jepsen_tpu — a TPU-native distributed-systems testing framework.

A ground-up rebuild of the capabilities of the Jepsen framework
(reference: rachit77/jepsen — Clojure core `jepsen/src/jepsen/*.clj`,
Tendermint suite, merkleeyes Go ABCI app) designed TPU-first:

- Histories are columnar arrays (struct-of-arrays), not linked lists.
- Consistency models are jit'd pure functions over packed integer states.
- The linearizability search (knossos.linear / knossos.wgl equivalents)
  is a batched, device-sharded frontier expansion running under jax.jit
  over a `jax.sharding.Mesh` — millions of candidate configurations are
  vmap'd per chip, with visited-set dedupe riding ICI collectives.
- The host side (generators, clients, nemeses, cluster control, storage,
  CLI) is pure Python, mirroring the reference's layer map (SURVEY.md §1).

Package layout:
    history     op schema, EDN codec, canonicalisation, columnar encoding
    models      consistency models (register, cas-register, mutex, queues, set)
    checker/    Checker protocol + full checker suite incl. linearizability
    parallel/   the TPU search engine, mesh/sharding utilities
    ops/        low-level device kernels (dedupe, hashing, bitset ops)
    generator/  pure generator DSL + deterministic simulator + interpreter
    control/    remote-execution backends (ssh, docker, dummy)
    nemesis/    fault injection
    tests/      reusable workloads (linearizable register, bank, long-fork, ...)
    tendermint/ the bundled worked example: Tendermint BFT test suite
"""

__version__ = "0.1.0"

from jepsen_tpu.history import Op, History  # noqa: F401
