"""Operation histories: schema, canonicalisation, columnar encoding.

The history is the framework's central artifact: an ordered list of ops

    {:index i, :time nanos, :process p, :type t, :f f, :value v}

exactly the schema the reference produces (op contract documented at
jepsen/src/jepsen/generator.clj:371-380 and knossos.history, used by
jepsen/src/jepsen/core.clj:230 `history/index`). Types:

    invoke  a client begins an operation
    ok      it completed and took effect
    fail    it completed and did NOT take effect
    info    indeterminate (crashed) — may or may not have taken effect;
            the process is dead and its op stays concurrent with
            everything after it (knossos crash semantics)

This module provides:
  * `Op` — a dict with attribute access (op.type, op["type"] both work),
  * `History` — a list of ops + canonicalisation (index/pair/complete,
    the knossos.history equivalents) and EDN/JSONL IO,
  * `calls()` — invocation/completion pairing into `Call` records, the
    input to linearizability checking,
  * `Columns` — struct-of-arrays encoding with interning tables, the
    host↔device boundary: everything past this point is integer arrays.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

from jepsen_tpu import edn
from jepsen_tpu.edn import Keyword

TYPES = ("invoke", "ok", "fail", "info")
_TYPE_CODE = {t: i for i, t in enumerate(TYPES)}
NEMESIS = "nemesis"  # the nemesis pseudo-process
NEMESIS_CODE = -2  # integer encoding of :nemesis in columnar form


class Op(dict):
    """An operation: a dict with attribute sugar.

    Extra keys (:error, :debug, anything a client attaches) ride along,
    matching the reference's open-map ops.
    """

    __slots__ = ()

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            if k in ("index", "time", "process", "type", "f", "value", "error"):
                return None
            raise AttributeError(k)

    def __setattr__(self, k, v):
        self[k] = v

    # -- predicates (knossos.op equivalents: invoke?/ok?/fail?/info?,
    #    used pervasively e.g. jepsen/src/jepsen/checker.clj:154-156)
    @property
    def is_invoke(self):
        return self.get("type") == "invoke"

    @property
    def is_ok(self):
        return self.get("type") == "ok"

    @property
    def is_fail(self):
        return self.get("type") == "fail"

    @property
    def is_info(self):
        return self.get("type") == "info"

    def __repr__(self):
        core = {k: self.get(k) for k in ("index", "type", "process", "f", "value")
                if k in self}
        extra = {k: v for k, v in self.items() if k not in core}
        core.update(extra)
        inner = ", ".join(f"{k}={v!r}" for k, v in core.items())
        return f"Op({inner})"


def op(type=None, process=None, f=None, value=None, **kw) -> Op:
    """Construct an Op. `op('invoke', 0, 'read', None)`."""
    o = Op(kw)
    if type is not None:
        o["type"] = type
    if process is not None:
        o["process"] = process
    if f is not None:
        o["f"] = f
    o["value"] = value
    return o


# Test-fixture constructors (knossos.core/invoke-op, ok-op, fail-op —
# used by the reference's checker tests, jepsen/test/jepsen/checker_test.clj:7)
def invoke_op(process, f, value, **kw) -> Op:
    return op("invoke", process, f, value, **kw)


def ok_op(process, f, value, **kw) -> Op:
    return op("ok", process, f, value, **kw)


def fail_op(process, f, value, **kw) -> Op:
    return op("fail", process, f, value, **kw)


def info_op(process, f, value, **kw) -> Op:
    return op("info", process, f, value, **kw)


# --------------------------------------------------------------- conversion


def _from_edn(x: Any) -> Any:
    """EDN values -> plain Python. Keywords become strings."""
    if isinstance(x, Keyword):
        return x.name
    if isinstance(x, list):
        return [_from_edn(e) for e in x]
    if isinstance(x, tuple):
        return tuple(_from_edn(e) for e in x)
    if isinstance(x, dict):
        return {_from_edn(k): _from_edn(v) for k, v in x.items()}
    if isinstance(x, frozenset):
        return frozenset(_from_edn(e) for e in x)
    return x


def op_from_edn(form: dict) -> Op:
    return Op(_from_edn(form))


def _to_edn(x: Any) -> Any:
    if isinstance(x, str):
        return Keyword(x)
    return x


def op_to_edn_str(o: Op) -> str:
    """Render an op as the reference's EDN map (keyword keys; keyword-ish
    string values for :type/:f/:process where the reference uses keywords)."""
    parts = []
    for k, v in o.items():
        parts.append(":" + str(k))
        if k in ("type", "f") and isinstance(v, str):
            parts.append(":" + v)
        elif k == "process" and v == NEMESIS:
            parts.append(":nemesis")
        else:
            parts.append(edn.dumps(v))
    return "{" + ", ".join(
        f"{parts[i]} {parts[i+1]}" for i in range(0, len(parts), 2)
    ) + "}"


class History(list):
    """A list of `Op` with canonicalisation and IO helpers."""

    # ------------------------------------------------------------- creation
    @classmethod
    def wrap(cls, ops: Iterable) -> "History":
        h = cls()
        for o in ops:
            h.append(o if isinstance(o, Op) else Op(o))
        return h

    # ----------------------------------------------------------------- IO
    @classmethod
    def from_edn(cls, text: str) -> "History":
        """Parse a reference-format history.edn (one op map per line, as
        written by jepsen/src/jepsen/store.clj:351-362)."""
        return cls.wrap(op_from_edn(f) for f in edn.iter_forms(text))

    @classmethod
    def load(cls, path: str) -> "History":
        with open(path) as fh:
            text = fh.read()
        if path.endswith(".jsonl"):
            return cls.wrap(Op(json.loads(line)) for line in text.splitlines() if line.strip())
        return cls.from_edn(text)

    def to_edn(self) -> str:
        return "\n".join(op_to_edn_str(o) for o in self) + "\n"

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(o, default=_json_default) for o in self) + "\n"

    def save(self, path: str):
        with open(path, "w") as fh:
            fh.write(self.to_jsonl() if path.endswith(".jsonl") else self.to_edn())

    def save_npz(self, path: str) -> str:
        """Columnar binary sidecar — the Fressian-parity fast reload
        (the reference stores binary history for exactly this,
        jepsen/src/jepsen/store.clj:31-116; ours is struct-of-arrays,
        the layout the device engines consume). Exact by construction:
        the canonical columns are serialized, decoded back, and every
        op diffed against its reconstruction; any mismatch (op with
        extra keys, exotic process, lossy value round-trip) rides as a
        full EDN override line. Checker histories reconstruct fully, so
        reload is numpy-speed with zero EDN parsing."""
        if not path.endswith(".npz"):
            path = path + ".npz"
        cols = self.columns()
        f_ser = [edn.dumps(v) for v in cols.f_table._values]
        v_ser = [edn.dumps(v) for v in cols.value_table._values]
        f_dec = _decode_table(f_ser)
        v_dec = _decode_table(v_ser)
        ov_idx: list = []
        ov_edn: list = []
        for i, o in enumerate(self):
            recon = _op_from_columns(i, cols.index, cols.time,
                                     cols.process, cols.type, cols.f,
                                     cols.value, f_dec, v_dec)
            if dict(recon) != dict(o):
                ov_idx.append(i)
                ov_edn.append(op_to_edn_str(o))
        np.savez_compressed(
            path,
            version=np.int64(NPZ_VERSION),
            index=cols.index, time=cols.time, process=cols.process,
            type=cols.type, f=cols.f, value=cols.value,
            f_table=np.array(f_ser, dtype="U") if f_ser
            else np.zeros(0, "U1"),
            value_table=np.array(v_ser, dtype="U") if v_ser
            else np.zeros(0, "U1"),
            override_idx=np.array(ov_idx, np.int64),
            override_edn=np.array(ov_edn, dtype="U") if ov_edn
            else np.zeros(0, "U1"))
        return path

    @classmethod
    def load_npz(cls, path: str) -> "History":
        """Reload a save_npz sidecar. Exact: columnar reconstruction
        plus the stored EDN override lines."""
        z = np.load(path, allow_pickle=False)
        v = int(z["version"])
        if v > NPZ_VERSION:
            raise ValueError(f"history npz version {v} is newer than "
                             f"this reader ({NPZ_VERSION})")
        f_dec = _decode_table(z["f_table"])
        v_dec = _decode_table(z["value_table"])
        index, time, process = z["index"], z["time"], z["process"]
        type_, f, value = z["type"], z["f"], z["value"]
        ops = [_op_from_columns(i, index, time, process, type_, f,
                                value, f_dec, v_dec)
               for i in range(len(index))]
        for i, s in zip(z["override_idx"].tolist(), z["override_edn"]):
            ops[i] = op_from_edn(edn.loads(str(s)))
        return cls.wrap(ops)

    # --------------------------------------------------------- canonicalise
    def index(self) -> "History":
        """Assign :index 0..n-1 in order (knossos.history/index, called at
        jepsen/src/jepsen/core.clj:230 before any checker runs)."""
        for i, o in enumerate(self):
            o["index"] = i
        return self

    def pairs(self) -> "History":
        """Pair invocations with completions: each op gets a :pair-index
        pointing at its counterpart (completion of the same process), or -1
        for unpaired ops (knossos.history pairing semantics).

        A process executes at most one op at a time, so matching is by
        process: an invoke pairs with the next ok/fail/info of the same
        process. Nemesis ops pair the same way.
        """
        if any(o.get("index") is None for o in self):
            self.index()
        open_by_process: dict = {}
        for o in self:
            p = o.get("process")
            if o.is_invoke:
                o["pair-index"] = -1
                open_by_process[p] = o
            else:
                inv = open_by_process.pop(p, None)
                if inv is not None:
                    inv["pair-index"] = o["index"]
                    o["pair-index"] = inv["index"]
                else:
                    o["pair-index"] = -1
        return self

    def complete(self) -> "History":
        """knossos.history/complete semantics (used by the reference at
        jepsen/src/jepsen/checker.clj:756 and checker/timeline.clj:172):
        fill each invocation's :value from its ok completion when the
        invocation's value is nil (reads learn their value at completion).
        """
        self.pairs()
        by_index = {o["index"]: o for o in self if o.get("index") is not None}
        for o in self:
            if o.is_invoke and o.get("pair-index", -1) >= 0:
                comp = by_index[o["pair-index"]]
                if comp.is_ok and o.get("value") is None:
                    o["value"] = comp.get("value")
        return self

    # ------------------------------------------------------------- queries
    def invocations(self) -> Iterator[Op]:
        return (o for o in self if o.is_invoke)

    def completions(self) -> Iterator[Op]:
        return (o for o in self if not o.is_invoke)

    def oks(self) -> Iterator[Op]:
        return (o for o in self if o.is_ok)

    def client_ops(self) -> "History":
        return History.wrap(o for o in self if isinstance(o.get("process"), int))

    def processes(self) -> list:
        seen, out = set(), []
        for o in self:
            p = o.get("process")
            if p not in seen:
                seen.add(p)
                out.append(p)
        return out

    def filter_f(self, *fs) -> "History":
        fset = set(fs)
        return History.wrap(o for o in self if o.get("f") in fset)

    # ------------------------------------------------------------ columnar
    def columns(self, value_encoder: Optional[Callable] = None) -> "Columns":
        return Columns.from_history(self, value_encoder)


def _json_default(x):
    if isinstance(x, frozenset):
        return sorted(x, key=repr)
    return str(x)


# ------------------------------------------------------------------- Calls


@dataclass
class Call:
    """An invocation/completion pair — the unit of linearizability checking.

    crashed=True means the completion was :info (or missing): the op may or
    may not have taken effect and stays concurrent with the rest of the
    history (knossos crash semantics — SURVEY.md §7.3 hard part #2).
    """

    index: int          # dense call id, 0..m-1 in invocation order
    process: Any
    f: str
    value: Any          # invocation value (args)
    result: Any         # completion value (None if crashed)
    invoke_index: int   # position of invocation in the history
    complete_index: int # position of completion; crashed -> len(history)
    crashed: bool

    def __repr__(self):
        tail = " CRASHED" if self.crashed else f" -> {self.result!r}"
        return f"Call#{self.index}(p{self.process} {self.f} {self.value!r}{tail})"


def calls(history: History, drop_failed: bool = True) -> list:
    """Pair invocations with completions into Call records.

    With drop_failed (the default), failed ops are dropped — they did not
    take effect (knossos `without-failures` preprocessing); otherwise they
    are kept with failed=True. Nemesis and non-client ops are skipped.
    Crashed (:info) calls get complete_index = len(history).
    """
    n = len(history)
    open_by_process: dict = {}
    out: list = []
    failed: set = set()
    for i, o in enumerate(history):
        p = o.get("process")
        if not isinstance(p, int):
            continue
        if o.is_invoke:
            c = Call(
                index=-1, process=p, f=o.get("f"), value=o.get("value"),
                result=None, invoke_index=i, complete_index=n, crashed=True,
            )
            open_by_process[p] = c
            out.append(c)
        else:
            c = open_by_process.pop(p, None)
            if c is None:
                continue
            if o.is_ok:
                c.result = o.get("value")
                c.complete_index = i
                c.crashed = False
            elif o.is_fail:
                c.complete_index = i
                c.crashed = False
                failed.add(id(c))
    if drop_failed:
        out = [c for c in out if id(c) not in failed]
    for j, c in enumerate(out):
        c.index = j
    return out


def prune_wildcard_calls(cs: list) -> list:
    """Drop calls that cannot constrain a linearizability search: crashed
    reads. A crashed read's value is unknown, so its model step is the
    identity and always succeeds — it may be linearized at any point or
    never, and removing it is sound. This avoids exponential blowup from
    forever-open crashed calls (each open crashed call doubles the
    frontier's mask space; cf. the reference's tractability caps,
    jepsen/src/jepsen/tests/linearizable_register.clj:30-32). Crashed
    mutating ops (writes, cas, acquire/release, dequeue) must stay — even
    value-less ones mutate state. Re-numbers the surviving dense indices."""
    out = [c for c in cs if not (c.crashed and c.f == "read")]
    for j, c in enumerate(out):
        c.index = j
    return out


# ---------------------------------------------------------------- Columns


class Intern:
    """Bidirectional value <-> int table. nil is always code -1."""

    def __init__(self):
        self._to_code: dict = {}
        self._values: list = []

    def code(self, v) -> int:
        if v is None:
            return -1
        key = _hashable(v)
        c = self._to_code.get(key)
        if c is None:
            c = len(self._values)
            self._to_code[key] = c
            self._values.append(v)
        return c

    def value(self, code: int):
        return None if code < 0 else self._values[code]

    def __len__(self):
        return len(self._values)


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(e) for e in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, set):
        return frozenset(_hashable(e) for e in v)
    return v


@dataclass
class Columns:
    """Struct-of-arrays history encoding — the host↔device boundary.

    Every field is a dense numpy array over ops, with interning tables
    mapping :f and :value back to Python objects. This is what ships to
    the TPU engine (jepsen_tpu.parallel.engine); nothing past this point
    touches Python objects. Replaces the reference's per-op persistent
    maps with a layout XLA can tile.
    """

    index: np.ndarray      # i64
    time: np.ndarray       # i64 nanos (-1 if absent)
    process: np.ndarray    # i32; :nemesis -> -2, other non-ints -> -3
    type: np.ndarray       # u8, code into TYPES
    f: np.ndarray          # i32 into f_table
    value: np.ndarray      # i32 into value_table (-1 = nil / unencodable)
    f_table: Intern = field(default_factory=Intern)
    value_table: Intern = field(default_factory=Intern)

    @classmethod
    def from_history(cls, h: History, value_encoder: Optional[Callable] = None):
        n = len(h)
        idx = np.empty(n, np.int64)
        tim = np.empty(n, np.int64)
        proc = np.empty(n, np.int32)
        typ = np.empty(n, np.uint8)
        fcol = np.empty(n, np.int32)
        val = np.empty(n, np.int32)
        ftab, vtab = Intern(), Intern()
        enc = value_encoder or (lambda v: vtab.code(v))
        for i, o in enumerate(h):
            idx[i] = o.get("index", i)
            tim[i] = o.get("time", -1) if o.get("time") is not None else -1
            p = o.get("process")
            proc[i] = p if isinstance(p, int) else (NEMESIS_CODE if p == NEMESIS else -3)
            typ[i] = _TYPE_CODE.get(o.get("type"), 255)
            fcol[i] = ftab.code(o.get("f"))
            val[i] = enc(o.get("value"))
        return cls(idx, tim, proc, typ, fcol, val, ftab, vtab)

    def __len__(self):
        return len(self.index)


# -------------------------------------------------- columnar npz sidecar

NPZ_VERSION = 1


def _op_from_columns(i: int, index, time, process, type_, f, value,
                     f_vals: list, v_vals: list) -> Op:
    """Reconstruct op i from columnar arrays + decoded intern tables.
    The single source of truth for the npz round-trip: save() diffs
    this reconstruction against the original op and stores an EDN
    override line when they differ, so load() is exact regardless of
    what the columns can or cannot express."""
    o: dict = {"index": int(index[i])}
    t = int(time[i])
    if t != -1:
        o["time"] = t
    p = int(process[i])
    if p >= 0:
        o["process"] = p
    elif p == NEMESIS_CODE:
        o["process"] = NEMESIS
    tc = int(type_[i])
    if tc < len(TYPES):
        o["type"] = TYPES[tc]
    fv = f_vals[int(f[i])] if int(f[i]) >= 0 else None
    if fv is not None:
        o["f"] = fv
    vc = int(value[i])
    o["value"] = v_vals[vc] if vc >= 0 else None
    return Op(o)


def _decode_table(serialized) -> list:
    return [_from_edn(edn.loads(str(s))) for s in serialized]
