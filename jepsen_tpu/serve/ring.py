"""Replica scale-out for the streaming checker: a consistent-hash
ring over serve replicas, and key migration built from the primitives
PR 7 already cut — ``CheckpointStore`` freeze/thaw as the frontier
handoff and WAL **segment transfer** as the durable-op handoff.

The fleet model is shared-nothing: each replica is one
``CheckerService`` (own WAL dir, own device, own ops endpoint), and
:class:`HashRing` assigns every key an owner by hashing its EDN text
onto a vnode ring — adding or removing a replica moves only the keys
that hash into the changed arcs, never reshuffles the fleet.

Migration is recovery, deliberately. A key is re-homed by copying its
WAL segments (``DeltaWAL.segments``) and its frozen checkpoint pair
into the new owner's WAL dir, then calling
:meth:`CheckerService.adopt_keys` — the same deterministic replay a
restart runs, so a migrated key's verdict is **bit-identical** to an
unmigrated one-shot check (the PR 7 recovery contract, now
cross-process; pinned by tests/test_ring.py incl. a real kill -9).
Two flavors share the code path:

* **crash re-home** (:func:`rehome_dead_replica`): the dead replica
  can't flush anything — survivors take whatever its WAL fsynced
  (exactly the set of acknowledged deltas; unacknowledged ones were
  never promised) plus any checkpoint eviction already froze.
* **graceful drain** (:meth:`Router.migrate_key`): the source
  freezes the key's live frontier first (``session.freeze`` via the
  checkpoint store), so the new owner thaws instead of re-scanning.

``jepsen status --addr host:port`` (repeatable) renders the fleet
view — one table per replica plus a summary line (``obs.httpd``).

Import-safe: no JAX at module scope (routing and file transfer must
work from a coordinator that never touches a device).
"""

from __future__ import annotations

import bisect
import hashlib
import logging
import os
import shutil
from typing import Dict, List, Optional

from jepsen_tpu import edn, obs
from jepsen_tpu.serve.wal import DeltaWAL, _safe_name

_log = logging.getLogger(__name__)

DEFAULT_VNODES = 64


def _point(s: str) -> int:
    return int.from_bytes(hashlib.sha1(s.encode()).digest()[:8],
                          "big")


class HashRing:
    """Consistent hashing over replica names: each node owns
    ``vnodes`` points on a 64-bit ring; a key belongs to the first
    point clockwise of its own hash. Deterministic across processes
    (sha1 of strings — no Python hash randomization), so a router, a
    survivor, and a test all compute the same owner."""

    def __init__(self, nodes: Optional[List[str]] = None,
                 vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = vnodes
        self._points: List[int] = []
        self._owners: Dict[int, str] = {}
        self._nodes: set = set()
        for n in nodes or ():
            self.add(n)

    @property
    def nodes(self) -> List[str]:
        return sorted(self._nodes)

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for i in range(self.vnodes):
            p = _point(f"{node}#{i}")
            if p in self._owners:
                # a 64-bit collision between two nodes' vnodes: skip
                # the later point (the earlier owner keeps the arc)
                continue
            bisect.insort(self._points, p)
            self._owners[p] = node

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._points = [p for p in self._points
                        if self._owners[p] != node]
        self._owners = {p: o for p, o in self._owners.items()
                        if o != node}

    def owner(self, key) -> str:
        """The replica that owns ``key`` (hashed by its EDN text, the
        same identity the WAL files use)."""
        if not self._points:
            raise ValueError("ring has no nodes")
        p = _point(edn.dumps(key))
        i = bisect.bisect_right(self._points, p)
        if i == len(self._points):
            i = 0
        return self._owners[self._points[i]]

    def assignments(self, keys) -> Dict[str, list]:
        """node -> [key, ...] for a key set (the rebalance plan)."""
        out: Dict[str, list] = {}
        for k in keys:
            out.setdefault(self.owner(k), []).append(k)
        return out


# ------------------------------------------------------ file handoff


def transfer_key(src_wal_dir: str, dst_wal_dir: str, key) -> dict:
    """Copy one key's durable state — WAL segments + frozen
    checkpoint pair — from a (dead or draining) replica's WAL dir into
    the new owner's. Pure file copy: the source is never mutated (a
    crashed replica's dir is evidence; the operator removes it after
    the fleet is green), and the destination files land under the
    same deterministic names ``adopt_keys``'s recovery scan reads.
    Returns ``{"segments": n, "checkpoint": bool}``."""
    os.makedirs(dst_wal_dir, exist_ok=True)
    segs = DeltaWAL(src_wal_dir).segments(key)
    for path in segs:
        shutil.copy2(path, os.path.join(dst_wal_dir,
                                        os.path.basename(path)))
    stem = _safe_name(key)
    has_cp = False
    src_cps = os.path.join(src_wal_dir, "checkpoints")
    for ext in (".json", ".npz"):
        p = os.path.join(src_cps, stem + ext)
        if os.path.exists(p):
            dst_cps = os.path.join(dst_wal_dir, "checkpoints")
            os.makedirs(dst_cps, exist_ok=True)
            shutil.copy2(p, os.path.join(dst_cps, stem + ext))
            has_cp = True
    obs.counter("serve.ring.keys_transferred").inc()
    return {"segments": len(segs), "checkpoint": has_cp}


def rehome_dead_replica(dead_wal_dir: str, ring: HashRing,
                        dead_node: str,
                        wal_dirs: Dict[str, str],
                        services: Optional[Dict[str, object]] = None) \
        -> Dict[str, list]:
    """Re-home every key a dead replica's WAL holds onto the
    survivors: drop the node from the ring, transfer each key's
    segments + checkpoint to its new owner's WAL dir, and (when the
    survivor services are in hand) ``adopt_keys`` so they go live
    immediately. Returns the new node -> [key, ...] assignment.

    The WAL is the ground truth by construction: everything the dead
    replica ever ACKNOWLEDGED is in it (WAL-before-ack), so the
    survivors' replay reaches exactly the acknowledged stream — a
    kill -9 loses only never-promised work, and re-submitted
    in-flight deltas dedupe by seq."""
    ring.remove(dead_node)
    keys = DeltaWAL(dead_wal_dir).keys()
    plan = ring.assignments(keys)
    for node, node_keys in plan.items():
        dst = wal_dirs[node]
        for key in node_keys:
            transfer_key(dead_wal_dir, dst, key)
        _log.info("rehome: %d key(s) from dead %r -> %r",
                  len(node_keys), dead_node, node)
    if services:
        for node in plan:
            svc = services.get(node)
            if svc is not None:
                svc.adopt_keys()
    obs.counter("serve.ring.rehomes").inc()
    return plan


# ------------------------------------------------------------ router


class Router:
    """A thin fleet front for in-process replica sets (the soak
    harness and tests; a network deployment routes in the client or a
    proxy with the same :class:`HashRing` math): submit/result/
    finalize forward to the owning replica, ``kill`` + ``rehome``
    replay a crash, ``migrate_key`` is the graceful freeze-first
    move."""

    def __init__(self, services: Dict[str, object],
                 wal_dirs: Dict[str, str],
                 vnodes: int = DEFAULT_VNODES):
        if set(services) != set(wal_dirs):
            raise ValueError("services and wal_dirs must name the "
                             "same replicas")
        self.services = dict(services)
        self.wal_dirs = dict(wal_dirs)
        self.ring = HashRing(sorted(services), vnodes=vnodes)

    def owner(self, key) -> str:
        return self.ring.owner(key)

    def submit(self, key, ops, **kw):
        return self.services[self.ring.owner(key)].submit(key, ops,
                                                          **kw)

    def result(self, key, **kw):
        return self.services[self.ring.owner(key)].result(key, **kw)

    def finalize(self, key, **kw):
        return self.services[self.ring.owner(key)].finalize(key, **kw)

    def rehome(self, dead_node: str) -> Dict[str, list]:
        """Crash path: the node is gone (already killed/closed);
        survivors adopt its WAL."""
        dead_dir = self.wal_dirs.pop(dead_node)
        self.services.pop(dead_node, None)
        return rehome_dead_replica(dead_dir, self.ring, dead_node,
                                   self.wal_dirs, self.services)

    def migrate_key(self, key, dst_node: str) -> dict:
        """Graceful path: freeze the key's live frontier on its
        current owner (drain first — the source must not be applying),
        transfer, adopt on the destination. The ring is NOT changed —
        this is an operator move (drain-for-maintenance), and the
        caller re-points producers."""
        src_node = self.ring.owner(key)
        if src_node == dst_node:
            return {"noop": True, "node": src_node}
        src = self.services[src_node]
        src.drain(timeout=60)
        src.freeze_key(key)
        r = transfer_key(self.wal_dirs[src_node],
                         self.wal_dirs[dst_node], key)
        self.services[dst_node].adopt_keys()
        r["from"], r["to"] = src_node, dst_node
        return r
