"""Replica scale-out for the streaming checker: a consistent-hash
ring over serve replicas, and key migration built from the primitives
PR 7 already cut — ``CheckpointStore`` freeze/thaw as the frontier
handoff and WAL **segment transfer** as the durable-op handoff.

The fleet model is shared-nothing: each replica is one
``CheckerService`` (own WAL dir, own device, own ops endpoint), and
:class:`HashRing` assigns every key an owner by hashing its EDN text
onto a vnode ring — adding or removing a replica moves only the keys
that hash into the changed arcs, never reshuffles the fleet.

Migration is recovery, deliberately. A key is re-homed by copying its
WAL segments (``DeltaWAL.segments``) and its frozen checkpoint pair
into the new owner's WAL dir, then calling
:meth:`CheckerService.adopt_keys` — the same deterministic replay a
restart runs, so a migrated key's verdict is **bit-identical** to an
unmigrated one-shot check (the PR 7 recovery contract, now
cross-process; pinned by tests/test_ring.py incl. a real kill -9).
Two flavors share the code path:

* **crash re-home** (:func:`rehome_dead_replica`): the dead replica
  can't flush anything — survivors take whatever its WAL fsynced
  (exactly the set of acknowledged deltas; unacknowledged ones were
  never promised) plus any checkpoint eviction already froze.
* **graceful drain** (:meth:`Router.migrate_key`): the source
  freezes the key's live frontier first (``session.freeze`` via the
  checkpoint store), so the new owner thaws instead of re-scanning.

Both flavors FENCE the old owner (``DeltaWAL.write_fence`` before the
transfer, epoch bumped by ``adopt_keys``) so a paused-not-dead
replica that resurfaces is refused instead of becoming a second
writer, and both PIN the moved keys to their adopter in the routing
layer (``Router.pins`` / ``FleetSupervisor.pins``). When the dead
node's disk is gone, ``rehome_dead_replica`` reads the survivors'
``repl/`` segment mirrors instead (``serve.fleet.SegmentReplicator``,
``JEPSEN_TPU_SERVE_REPL``) — docs/streaming.md "Fleet self-healing".

``jepsen status --addr host:port`` (repeatable) renders the fleet
view — one table per replica plus a summary line (``obs.httpd``).

Import-safe: no JAX at module scope (routing and file transfer must
work from a coordinator that never touches a device).
"""

from __future__ import annotations

import bisect
import hashlib
import logging
import os
import shutil
from typing import Dict, List, Optional

from jepsen_tpu import edn, obs
from jepsen_tpu.serve.wal import DeltaWAL, WALError, _safe_name

_log = logging.getLogger(__name__)

DEFAULT_VNODES = 64

#: where replicated WAL segments land under a successor's WAL dir
#: (``serve.fleet.SegmentReplicator``) — the rehome fallback source
#: when the dead replica's own disk is gone
REPL_SUBDIR = "repl"


def _point(s: str) -> int:
    return int.from_bytes(hashlib.sha1(s.encode()).digest()[:8],
                          "big")


class HashRing:
    """Consistent hashing over replica names: each node owns
    ``vnodes`` points on a 64-bit ring; a key belongs to the first
    point clockwise of its own hash. Deterministic across processes
    (sha1 of strings — no Python hash randomization), so a router, a
    survivor, and a test all compute the same owner."""

    def __init__(self, nodes: Optional[List[str]] = None,
                 vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = vnodes
        self._points: List[int] = []
        self._owners: Dict[int, str] = {}
        self._nodes: set = set()
        for n in nodes or ():
            self.add(n)

    @property
    def nodes(self) -> List[str]:
        return sorted(self._nodes)

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for i in range(self.vnodes):
            p = _point(f"{node}#{i}")
            if p in self._owners:
                # a 64-bit collision between two nodes' vnodes: skip
                # the later point (the earlier owner keeps the arc)
                continue
            bisect.insort(self._points, p)
            self._owners[p] = node

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._points = [p for p in self._points
                        if self._owners[p] != node]
        self._owners = {p: o for p, o in self._owners.items()
                        if o != node}

    def owner(self, key) -> str:
        """The replica that owns ``key`` (hashed by its EDN text, the
        same identity the WAL files use)."""
        if not self._points:
            raise ValueError("ring has no nodes")
        p = _point(edn.dumps(key))
        i = bisect.bisect_right(self._points, p)
        if i == len(self._points):
            i = 0
        return self._owners[self._points[i]]

    def successor(self, key) -> Optional[str]:
        """The first DISTINCT node clockwise after the key's owner —
        the WAL-segment replication target (``serve.fleet``). None on
        a ring with fewer than two nodes. Deterministic like
        ``owner``, so the replica that ships and the coordinator that
        rehomes compute the same successor."""
        if len(self._nodes) < 2:
            return None
        p = _point(edn.dumps(key))
        i = bisect.bisect_right(self._points, p)
        owner = None
        for k in range(len(self._points)):
            node = self._owners[self._points[(i + k)
                                             % len(self._points)]]
            if owner is None:
                owner = node
            elif node != owner:
                return node
        return None

    def assignments(self, keys) -> Dict[str, list]:
        """node -> [key, ...] for a key set (the rebalance plan)."""
        out: Dict[str, list] = {}
        for k in keys:
            out.setdefault(self.owner(k), []).append(k)
        return out


# ------------------------------------------------------ file handoff


def transfer_key(src_wal_dir: str, dst_wal_dir: str, key) -> dict:
    """Copy one key's durable state — WAL segments + frozen
    checkpoint pair — from a (dead or draining) replica's WAL dir into
    the new owner's. Pure file copy: the source is never mutated (a
    crashed replica's dir is evidence; the operator removes it after
    the fleet is green), and the destination files land under the
    same deterministic names ``adopt_keys``'s recovery scan reads.
    Returns ``{"segments": n, "checkpoint": bool, "manifest": bool}``.
    The copied segments carry any per-delta trace ids the old owner
    stamped (``DeltaWAL.append(delta_id=...)``) — which is how a
    migrated delta's causal chain survives the replica boundary: the
    adopter's thaw/apply spans re-tag the same ids, and the merged
    fleet trace (``jepsen trace``) reads one chain across both process
    tracks. The ``.programs.json`` manifest rides along when the old
    owner wrote one (JEPSEN_TPU_COMPILE_CACHE armed): it names the
    compiled-program population ``adopt_keys`` pre-warms BEFORE
    replaying, so the adopter's first post-adoption delta dispatches
    without paying first-touch compile (docs/streaming.md
    "warm-handoff contract")."""
    os.makedirs(dst_wal_dir, exist_ok=True)
    with obs.span("serve.ring.transfer", key=str(key)):
        segs = DeltaWAL(src_wal_dir).segments(key)
        for path in segs:
            shutil.copy2(path, os.path.join(dst_wal_dir,
                                            os.path.basename(path)))
        stem = _safe_name(key)
        has_cp = False
        has_manifest = False
        src_cps = os.path.join(src_wal_dir, "checkpoints")
        for ext in (".json", ".npz", ".programs.json"):
            p = os.path.join(src_cps, stem + ext)
            if os.path.exists(p):
                dst_cps = os.path.join(dst_wal_dir, "checkpoints")
                os.makedirs(dst_cps, exist_ok=True)
                shutil.copy2(p, os.path.join(dst_cps, stem + ext))
                if ext == ".programs.json":
                    has_manifest = True
                else:
                    has_cp = True
    obs.counter("serve.ring.keys_transferred").inc()
    return {"segments": len(segs), "checkpoint": has_cp,
            "manifest": has_manifest}


def _key_sources(dead_wal_dir: str,
                 wal_dirs: Dict[str, str]) -> Dict[object, str]:
    """key -> source dir to transfer from. The dead replica's own WAL
    dir when it is still readable (it holds everything acknowledged);
    otherwise — the disk went with the node — every survivor's
    ``repl/`` mirror (``serve.fleet.SegmentReplicator`` ships segments
    there), preferring the copy with the most bytes when a key appears
    in several mirrors (ring changes can leave older copies behind)."""
    out: Dict[object, str] = {}
    if os.path.isdir(dead_wal_dir):
        try:
            for key in DeltaWAL(dead_wal_dir).keys():
                out[key] = dead_wal_dir
        except (OSError, WALError) as err:
            _log.warning("rehome: dead WAL dir %s unreadable (%r) — "
                         "falling back to replicated segments",
                         dead_wal_dir, err)
            out.clear()
    if out:
        return out
    # the mirrors hold EVERY replica's shipped keys, not just the
    # dead one's — a key a survivor holds in its OWN WAL dir is live
    # there and must not be "rehomed" (the transfer would overwrite a
    # live replica's segments with a possibly-lagging mirror copy)
    held_live: set = set()
    for d in wal_dirs.values():
        if os.path.isdir(d):
            try:
                held_live.update(DeltaWAL(d).keys())
            except (OSError, WALError):
                pass   # an unreadable survivor claims nothing; its
                # keys then transfer from the freshest mirror, which
                # is the best copy left
    best_bytes: Dict[object, int] = {}
    for d in wal_dirs.values():
        rd = os.path.join(d, REPL_SUBDIR)
        if not os.path.isdir(rd):
            continue
        rwal = DeltaWAL(rd)
        for key in rwal.keys():
            if key in held_live:
                continue
            n = rwal.size_bytes(key)
            if key not in out or n > best_bytes[key]:
                out[key] = rd
                best_bytes[key] = n
    if out:
        obs.counter("serve.ring.rehomes_from_replica").inc()
    return out


def rehome_dead_replica(dead_wal_dir: str, ring: HashRing,
                        dead_node: str,
                        wal_dirs: Dict[str, str],
                        services: Optional[Dict[str, object]] = None) \
        -> Dict[str, list]:
    """Re-home every key a dead replica's WAL holds onto the
    survivors: drop the node from the ring, FENCE each key in the dead
    replica's WAL dir, transfer each key's segments + checkpoint to
    its new owner's WAL dir, and (when the survivor services are in
    hand) ``adopt_keys`` so they go live immediately. Returns the new
    node -> [key, ...] assignment.

    The WAL is the ground truth by construction: everything the dead
    replica ever ACKNOWLEDGED is in it (WAL-before-ack), so the
    survivors' replay reaches exactly the acknowledged stream — a
    kill -9 loses only never-promised work, and re-submitted
    in-flight deltas dedupe by seq. When the dead node's DISK is gone
    too, the replicated segment mirrors on the survivors
    (``JEPSEN_TPU_SERVE_REPL``) are the source instead — with
    ``sync`` replication that is still exactly the acknowledged
    stream; with ``async`` it may trail by the replication lag
    (docs/streaming.md spells out the contract).

    Fencing comes FIRST, deliberately: the fence marker lands in the
    dead dir before any segment is copied, so a paused-not-dead
    replica that wakes mid-rehome re-checks the fence after its fsync
    and refuses — it can never acknowledge a delta the transfer
    already missed (the split-brain ordering argument, pinned in
    tests/test_fleet.py)."""
    ring.remove(dead_node)
    sources = _key_sources(dead_wal_dir, wal_dirs)
    plan = ring.assignments(sources)
    # fence only where a stale writer could still live: a missing
    # dead dir (disk went with the node) has nobody left to fence,
    # and recreating it would manufacture a directory the operator
    # deleted
    can_fence = os.path.isdir(dead_wal_dir)
    for node, node_keys in plan.items():
        n_manifests = 0
        dst = wal_dirs[node]
        for key in node_keys:
            src = sources[key]
            if can_fence:
                # fence before transfer (see docstring); best-effort
                try:
                    new_epoch = DeltaWAL(src).epoch(key) + 1
                    DeltaWAL(dead_wal_dir).write_fence(
                        key, new_epoch, owner=node)
                except OSError as err:
                    _log.warning("rehome: could not fence key %r in "
                                 "%s (%r)", key, dead_wal_dir, err)
            info = transfer_key(src, dst, key)
            if info.get("manifest"):
                n_manifests += 1
        _log.info("rehome: %d key(s) from dead %r -> %r "
                  "(%d program manifest(s) for warm handoff)",
                  len(node_keys), dead_node, node, n_manifests)
    if services:
        for node in plan:
            svc = services.get(node)
            if svc is not None:
                svc.adopt_keys()
    obs.counter("serve.ring.rehomes").inc()
    return plan


# ------------------------------------------------------------ router


class Router:
    """A thin fleet front for in-process replica sets (the soak
    harness and tests; a network deployment routes in the client or a
    proxy with the same :class:`HashRing` math): submit/result/
    finalize forward to the owning replica, ``kill`` + ``rehome``
    replay a crash, ``migrate_key`` is the graceful freeze-first
    move."""

    def __init__(self, services: Dict[str, object],
                 wal_dirs: Dict[str, str],
                 vnodes: int = DEFAULT_VNODES):
        if set(services) != set(wal_dirs):
            raise ValueError("services and wal_dirs must name the "
                             "same replicas")
        self.services = dict(services)
        self.wal_dirs = dict(wal_dirs)
        self.ring = HashRing(sorted(services), vnodes=vnodes)
        # key -> node overrides: a rehomed or migrated key stays with
        # its adopter even if the hash arcs later say otherwise (a
        # rejoining node gets NEW keys back, never the ones it lost —
        # the epoch fence refuses it those anyway)
        self.pins: Dict[object, str] = {}

    def owner(self, key) -> str:
        pinned = self.pins.get(key)
        if pinned is not None and pinned in self.services:
            return pinned
        return self.ring.owner(key)

    def submit(self, key, ops, **kw):
        return self.services[self.owner(key)].submit(key, ops, **kw)

    def result(self, key, **kw):
        return self.services[self.owner(key)].result(key, **kw)

    def finalize(self, key, **kw):
        return self.services[self.owner(key)].finalize(key, **kw)

    def rehome(self, dead_node: str) -> Dict[str, list]:
        """Crash path: the node is gone (already killed/closed);
        survivors adopt its WAL, and the adopted keys PIN to their
        adopter so a later rejoin of the node (for new keys) cannot
        route the old keys back to a fenced owner."""
        dead_dir = self.wal_dirs.pop(dead_node)
        self.services.pop(dead_node, None)
        plan = rehome_dead_replica(dead_dir, self.ring, dead_node,
                                   self.wal_dirs, self.services)
        for node, node_keys in plan.items():
            for key in node_keys:
                self.pins[key] = node
        return plan

    def migrate_key(self, key, dst_node: str) -> dict:
        """Graceful path: freeze the key's live frontier on its
        current owner (drain first — the source must not be applying),
        transfer, adopt on the destination, fence + pin. The ring is
        NOT changed — this is an operator move (drain-for-
        maintenance); the pin re-points this router's producers, and
        the fence refuses any producer still talking to the source
        directly."""
        src_node = self.owner(key)
        if src_node == dst_node:
            return {"noop": True, "node": src_node}
        src = self.services[src_node]
        src.drain(timeout=60)
        src.freeze_key(key)
        r = transfer_key(self.wal_dirs[src_node],
                         self.wal_dirs[dst_node], key)
        self.services[dst_node].adopt_keys()
        src.fence_key_ownership(key, owner=dst_node)
        self.pins[key] = dst_node
        r["from"], r["to"] = src_node, dst_node
        return r
