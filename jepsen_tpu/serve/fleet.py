"""Self-healing for the serve fleet: failure detection, automatic
re-homing, ownership fencing, and WAL segment replication.

PAPER.md's own subject — Jepsen — exists to prove that systems
survive nemeses; PR 11's fleet only had *manual* recovery
(``ring.rehome_dead_replica`` invoked by an operator, ``transfer_key``
needing the dead disk to still be readable, and nothing stopping a
paused replica from waking up as a second writer). This module closes
that loop with three cooperating pieces (docs/streaming.md "Fleet
self-healing"):

:class:`FleetSupervisor`
    Polls every replica's ``/healthz`` (the same fetch path ``jepsen
    status --addr`` reads) and runs the PR-6 circuit-breaker state
    machine PER REPLICA: ``threshold`` consecutive misses open the
    breaker — the replica is declared dead and its keys are re-homed
    onto the survivors via :func:`serve.ring.rehome_dead_replica`
    with bounded retry/backoff, a ``fleet.*`` metric trail, and a
    flight-recorder dump per rehome. A dead replica that answers
    again is admitted back through the breaker's half-open probe
    (``fleet.rejoins``) — for NEW keys only; the keys it lost stay
    PINNED to their adopters (``pins``), and the epoch fence refuses
    it the old ones regardless. With JEPSEN_TPU_COMPILE_CACHE armed
    the rehome is additionally a WARM handoff: ``transfer_key`` ships
    the dead replica's compiled-program manifest beside the WAL
    segments and ``adopt_keys`` pre-warms it before replaying, so the
    adopter's first post-adoption delta never pays first-dispatch
    compile on the verdict SLO (docs/streaming.md, docs/performance.md
    "Compile economics").

:class:`SegmentReplicator`
    Ships a key's WAL segments to its ring successor's ``repl/``
    mirror on every durable append (and therefore across rotations —
    shipping is a size-compared re-copy, so a sealed segment ships
    once and the active one converges). ``JEPSEN_TPU_SERVE_REPL``
    picks the mode: ``sync`` acks only after the successor copy is
    durable (fsynced) — a dead node WITH a dead disk then loses
    nothing acknowledged; ``async`` ships from a background thread
    (``serve.repl_lag_keys`` is the lag gauge, and the documented
    loss window is exactly that lag). A mid-copy kill can leave a
    torn trailing line on the mirror — the WAL replay already
    tolerates one torn tail per segment, re-pinned on this path by
    tests/test_fleet.py.

Epoch fence (the split-brain guard, implemented across ``serve.wal``
and ``serve.service``; this module drives it): every WAL segment
header carries an ownership epoch; ``adopt_keys`` bumps it; the
rehome path writes a fence marker in the dead replica's dir BEFORE
copying segments. A SIGSTOP'd replica that resumes after its keys
were rehomed re-checks the fence after its fsync and answers a
structured refusal on submit/result/finalize instead of acking
deltas the new owner will never replay.

``tools/chaos.py`` drives all of this under a Jepsen-style nemesis
schedule (SIGKILL, SIGSTOP/SIGCONT, injected device faults, rolling
restarts) against a real multi-replica, multi-tenant ingress soak —
``--smoke`` rides tools/ci.sh.

Import-safe: no JAX at module scope — the supervisor is a
coordinator that must run (and rehome) while device runtimes are
wedged, which is precisely when it is needed.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
from typing import Callable, Dict, Optional

from jepsen_tpu import envflags, obs
from jepsen_tpu.resilience import breaker as breaker_mod
from jepsen_tpu.serve import ring as ring_mod
from jepsen_tpu.serve.wal import DeltaWAL

_log = logging.getLogger(__name__)

REPL_MODES = ("off", "async", "sync")

#: cap on the supervisor's per-attempt rehome backoff
REHOME_BACKOFF_CAP_SECS = 30.0


def resolve_repl_mode(v: Optional[str] = None) -> str:
    """The WAL segment replication mode: ``off`` (default) | ``async``
    | ``sync`` (JEPSEN_TPU_SERVE_REPL; strictly validated)."""
    if v is not None:
        if v not in REPL_MODES:
            raise envflags.EnvFlagError(
                f"replication mode {v!r}: expected one of "
                f"{REPL_MODES}")
        return v
    return envflags.env_choice("JEPSEN_TPU_SERVE_REPL", REPL_MODES,
                               default="off",
                               what="WAL replication mode")


def resolve_fleet_interval(v: Optional[float] = None) -> float:
    if v is not None:
        return float(v)
    return envflags.env_float("JEPSEN_TPU_FLEET_INTERVAL", default=2.0,
                              min_value=0.01,
                              what="fleet heartbeat interval")


def resolve_fleet_threshold(v: Optional[int] = None) -> int:
    if v is not None:
        return int(v)
    return envflags.env_int("JEPSEN_TPU_FLEET_THRESHOLD", default=3,
                            min_value=1,
                            what="fleet consecutive-miss threshold")


def resolve_rehome_retries(v: Optional[int] = None) -> int:
    if v is not None:
        return int(v)
    return envflags.env_int("JEPSEN_TPU_FLEET_REHOME_RETRIES",
                            default=3, min_value=1,
                            what="rehome retry budget")


# ------------------------------------------------ segment replication


def constant_dst(path: str) -> Callable:
    """A fixed replication destination (``jepsen serve --checker
    --repl-dir PATH`` — e.g. the successor's mounted ``repl/`` dir)."""
    return lambda _key: path


def ring_successor_dst(ring: ring_mod.HashRing,
                       wal_dirs: Dict[str, str],
                       self_node: str) -> Callable:
    """Per-key replication destination: the key's ring successor's
    ``repl/`` mirror — the dir :func:`serve.ring.rehome_dead_replica`
    falls back to when the dead node's own disk is gone."""
    def dst(key) -> Optional[str]:
        succ = ring.successor(key)
        if succ is None or succ == self_node:
            return None
        d = wal_dirs.get(succ)
        return (os.path.join(d, ring_mod.REPL_SUBDIR)
                if d is not None else None)
    return dst


class SegmentReplicator:
    """Ships one service's WAL segments to per-key destinations
    (module docstring). ``after_append(key)`` is the service hook:
    ``sync`` ships inline and returns False when the successor copy
    did not land (the ack then carries ``replicated: False``);
    ``async`` enqueues for the shipper thread and returns None;
    ``off`` is a no-op.

    Copies are size-compared and INCREMENTAL (append-only files: size
    IS the version, so the destination size is the resume offset): a
    first ship lands the whole file via tmp + ``os.replace`` (a
    reader never sees a partial first copy), and every later ship
    appends only the suffix — one delta's bytes per ack, not the
    whole segment re-copied (an unbounded active segment would
    otherwise make sync acks O(stream) each). A mid-append kill
    leaves at most a torn final line on the mirror — exactly the
    per-segment tail the WAL replay already tolerates. ``sync`` mode
    fsyncs the data AND (for new files) the mirror directory before
    acking — successor durability means surviving the successor's
    own power cut."""

    def __init__(self, wal: DeltaWAL, dst_for_key: Callable,
                 mode: Optional[str] = None):
        self.wal = wal
        self.dst_for_key = dst_for_key
        self.mode = resolve_repl_mode(v=mode)
        self._cond = threading.Condition()
        self._pending: Dict[object, bool] = {}   # insertion-ordered
        self._inflight = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # per-key ship serialization: two producers acking different
        # seqs of one key (the handoff releases seq N's writer before
        # N+1's replication hook runs) must not interleave suffix
        # appends into the same mirror file
        self._ship_locks: Dict[object, threading.Lock] = {}

    # -- the copy itself

    def _fsync_path(self, path: str) -> None:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def ship(self, key) -> int:
        """Copy the key's out-of-date segment bytes to its
        destination now; returns the number of files touched. Raises
        OSError on an unreachable destination (callers count +
        degrade)."""
        dst = self.dst_for_key(key)
        if dst is None:
            return 0
        with self._cond:
            lock = self._ship_locks.setdefault(key, threading.Lock())
        with lock:
            return self._ship_locked(key, dst)

    def _ship_locked(self, key, dst: str) -> int:
        # the replication leg of the causal chain: sync mode runs this
        # inside the producer's ack window (nested under serve.wal via
        # the contextvar), async mode on the shipper thread
        with obs.span("serve.repl_ship", key=str(key)):
            return self._ship_files(key, dst)

    def _ship_files(self, key, dst: str) -> int:
        os.makedirs(dst, exist_ok=True)
        shipped = 0
        for src in self.wal.segments(key):
            dpath = os.path.join(dst, os.path.basename(src))
            try:
                ssize = os.path.getsize(src)
            except OSError:
                continue   # rotated away mid-scan
            try:
                dsize = os.path.getsize(dpath)
            except OSError:
                dsize = -1
            if dsize == ssize:
                continue   # already current
            if 0 <= dsize < ssize:
                # incremental: append the suffix (the destination
                # size is the shipped offset)
                with open(src, "rb") as sf, open(dpath, "ab") as df:
                    sf.seek(dsize)
                    shutil.copyfileobj(sf, df)
                    df.flush()
                    if self.mode == "sync":
                        os.fsync(df.fileno())
                new_bytes = ssize - dsize
            else:
                # first copy (or a shrunk source — repair): land the
                # whole file atomically
                tmp = dpath + ".tmp"
                shutil.copyfile(src, tmp)
                if self.mode == "sync":
                    self._fsync_path(tmp)
                os.replace(tmp, dpath)
                if self.mode == "sync":
                    # the directory entry must survive the
                    # successor's power cut too
                    self._fsync_path(dst)
                new_bytes = ssize
            shipped += 1
            obs.counter("serve.repl_segments_shipped").inc()
            obs.counter("serve.repl_bytes").inc(new_bytes)
        return shipped

    # -- the service hook

    def after_append(self, key) -> Optional[bool]:
        if self.mode == "off":
            return None
        if self.mode == "sync":
            if self.dst_for_key(key) is None:
                # a sync ack must not imply successor durability when
                # there is no successor (single-node ring, every peer
                # dead): mark it primary-durable only
                obs.counter("serve.repl_no_destination").inc()
                return False
            try:
                self.ship(key)
                return True
            except OSError as err:
                obs.counter("serve.repl_errors").inc()
                _log.warning("sync replication of key %r failed (%r) "
                             "— ack is primary-durable only", key, err)
                return False
        self.notify(key)
        return None

    # -- the async shipper

    def notify(self, key) -> None:
        with self._cond:
            self._pending[key] = True
            obs.gauge("serve.repl_lag_keys").set(len(self._pending)
                                                 + self._inflight)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="jepsen-repl-shipper")
                self._thread.start()
            self._cond.notify_all()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stop:
                    self._cond.wait(timeout=0.5)
                if self._stop and not self._pending:
                    return
                key = next(iter(self._pending))
                del self._pending[key]
                self._inflight = 1
                obs.gauge("serve.repl_lag_keys").set(
                    len(self._pending) + self._inflight)
            try:
                self.ship(key)
            except Exception as err:  # noqa: BLE001 — the shipper
                # thread must survive a sick destination; the lag
                # gauge and error counter are the operator's signal
                obs.counter("serve.repl_errors").inc()
                _log.warning("async replication of key %r failed "
                             "(%r)", key, err)
            finally:
                with self._cond:
                    self._inflight = 0
                    obs.gauge("serve.repl_lag_keys").set(
                        len(self._pending))
                    self._cond.notify_all()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until the async queue is empty (True) or the timeout
        passes (False)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while self._pending or self._inflight:
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    return False
                self._cond.wait(timeout=0.5 if rem is None
                                else min(rem, 0.5))
            return True

    def close(self, drain: bool = True) -> None:
        if drain:
            self.drain(timeout=30)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


# --------------------------------------------------- remote adoption


class HttpReplica:
    """A survivor handle for a replica in another process: exposes
    the one method the rehome path needs (``adopt_keys``), served by
    the replica's ops endpoint (``POST /adopt``, ``obs.httpd``) — so
    a coordinator can drive live adoption without importing the
    engine or touching the survivor's device."""

    def __init__(self, addr: str, timeout: float = 60.0):
        self.addr = addr
        self.timeout = timeout

    def adopt_keys(self) -> list:
        import urllib.request
        req = urllib.request.Request(
            f"http://{self.addr}/adopt", data=b"", method="POST")
        with urllib.request.urlopen(req,
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read().decode()).get("adopted", [])


def _default_fetch(addr: str, timeout: float) -> bool:
    """Liveness via the ops endpoint — the SAME fetch path `jepsen
    status --addr` renders (``obs.httpd.fetch_replica``), so the
    supervisor's dead/alive verdict and the operator's table cannot
    disagree. ANY HTTP answer counts as alive (a "degraded" replica —
    breaker open, queue past high-water — still acks into its WAL, so
    rehoming it would fork the stream); only "unreachable" is a
    miss."""
    from jepsen_tpu.obs import httpd as ops_httpd
    return ops_httpd.fetch_replica(
        addr, timeout=timeout)["state"] != "unreachable"


class _Replica:
    __slots__ = ("name", "addr", "breaker", "dead", "rehomed")

    def __init__(self, name, addr, breaker):
        self.name = name
        self.addr = addr
        self.breaker = breaker
        self.dead = False
        self.rehomed = False


class FleetSupervisor:
    """Automatic failure detection + re-homing for a serve fleet
    (module docstring).

    ``replicas`` maps name -> ops-endpoint address (``host:port``) —
    or to None with an injected ``fetch`` (in-process tests).
    ``services`` maps name -> an object with ``adopt_keys()`` (a
    local :class:`CheckerService` or an :class:`HttpReplica`).
    ``wal_dirs`` maps name -> that replica's WAL dir (the transfer
    source/destination — a shared filesystem or local dirs).

    Drive it with ``start()`` (daemon loop every ``interval``
    seconds) or deterministic ``tick()`` calls (tests use an
    injected clock + fetch). All knobs fall back to the validated
    ``JEPSEN_TPU_FLEET_*`` flags."""

    def __init__(self, replicas: Dict[str, Optional[str]],
                 wal_dirs: Dict[str, str],
                 services: Optional[Dict[str, object]] = None,
                 interval: Optional[float] = None,
                 threshold: Optional[int] = None,
                 rehome_retries: Optional[int] = None,
                 fetch: Optional[Callable] = None,
                 fetch_timeout: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 vnodes: int = ring_mod.DEFAULT_VNODES,
                 on_rehome: Optional[Callable] = None,
                 on_rejoin: Optional[Callable] = None):
        if set(replicas) != set(wal_dirs):
            raise ValueError("replicas and wal_dirs must name the "
                             "same fleet")
        self.interval = resolve_fleet_interval(interval)
        self.threshold = resolve_fleet_threshold(threshold)
        self.rehome_retries = resolve_rehome_retries(rehome_retries)
        self.wal_dirs = dict(wal_dirs)
        self.services = dict(services or {})
        self.ring = ring_mod.HashRing(sorted(replicas), vnodes=vnodes)
        self.pins: Dict[object, str] = {}
        self._fetch = fetch if fetch is not None else _default_fetch
        self._fetch_timeout = fetch_timeout
        self._clock = clock
        self._sleep = sleep
        self._on_rehome = on_rehome
        self._on_rejoin = on_rejoin
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._reps: Dict[str, _Replica] = {}
        for name in sorted(replicas):
            # one PR-6 breaker per replica: consecutive-miss
            # threshold -> open (dead), half-open probe -> rejoin.
            # Standalone instances (NOT breaker_for) with
            # track_global=False: a PEER replica's health must not
            # show up in this process's own /healthz breaker check,
            # nor push its own device dispatches onto the slow
            # supervised path via the module _tripped fast-path set.
            br = breaker_mod.CircuitBreaker(
                f"fleet:{name}", threshold=self.threshold,
                backoff_base=max(self.interval, 0.05), clock=clock,
                probe=self._make_probe(name, replicas[name]),
                track_global=False)
            self._reps[name] = _Replica(name, replicas[name], br)
            obs.gauge(f"fleet.replica.{name}.alive").set(1)
        self._gauges()

    # -- health checks

    def _make_probe(self, name: str, addr: Optional[str]):
        def probe() -> bool:
            return self._alive(name, addr)
        return probe

    def _alive(self, name: str, addr: Optional[str]) -> bool:
        try:
            return bool(self._fetch(addr if addr is not None
                                    else name, self._fetch_timeout))
        except Exception:  # noqa: BLE001 — unreachable IS the signal
            return False

    # -- the heartbeat round

    def tick(self) -> None:
        """One supervision round: heartbeat the live replicas, drive
        the breakers, rehome the newly dead, re-admit the recovered.
        ``start()`` calls this every ``interval``; tests call it
        directly with a fake clock."""
        for r in list(self._reps.values()):
            if r.dead:
                if not r.rehomed:
                    # an earlier rehome attempt exhausted its budget
                    # (e.g. a survivor's disk hiccup): keep trying,
                    # one bounded burst per tick
                    self._try_rehome(r)
                ok, _why = r.breaker.allow()
                if ok:
                    self._rejoin(r)
                continue
            obs.counter("fleet.heartbeats").inc()
            if self._alive(r.name, r.addr):
                r.breaker.record_success()
            else:
                obs.counter("fleet.misses").inc()
                r.breaker.record_failure("healthz miss")
                if r.breaker.state == breaker_mod.OPEN:
                    self._declare_dead(r)
        self._gauges()

    def _declare_dead(self, r: _Replica) -> None:
        r.dead = True
        obs.counter("fleet.deaths").inc()
        obs.gauge(f"fleet.replica.{r.name}.alive").set(0)
        _log.warning("fleet: replica %r declared dead after %d "
                     "consecutive healthz misses — rehoming its keys",
                     r.name, self.threshold)
        self._try_rehome(r)

    def _survivors(self) -> Dict[str, str]:
        return {n: d for n, d in self.wal_dirs.items()
                if not self._reps[n].dead}

    def _try_rehome(self, r: _Replica) -> Optional[Dict[str, list]]:
        """Bounded-retry rehome with exponential backoff; on success
        pins the moved keys, counts ``fleet.rehomes``, and dumps the
        flight recorder (the postmortem moment an armed ring
        exists for)."""
        survivors = self._survivors()
        if not survivors:
            _log.error("fleet: no survivors to rehome %r onto",
                       r.name)
            obs.counter("fleet.rehome_failures").inc()
            return None
        for attempt in range(self.rehome_retries):
            if attempt:
                self._sleep(min(self.interval * (2 ** (attempt - 1)),
                                REHOME_BACKOFF_CAP_SECS))
            try:
                plan = ring_mod.rehome_dead_replica(
                    self.wal_dirs[r.name], self.ring, r.name,
                    survivors,
                    {n: s for n, s in self.services.items()
                     if n in survivors})
            except Exception as err:  # noqa: BLE001 — a failed
                # attempt is retried; a failed BUDGET stays pending
                # and retries next tick
                obs.counter("fleet.rehome_failures").inc()
                _log.warning("fleet: rehome of %r failed (attempt "
                             "%d/%d): %r", r.name, attempt + 1,
                             self.rehome_retries, err)
                continue
            with self._lock:
                for node, keys in plan.items():
                    for k in keys:
                        self.pins[k] = node
            r.rehomed = True
            obs.counter("fleet.rehomes").inc()
            obs.flight_dump(f"fleet-rehome-{r.name}", context={
                "replica": r.name,
                "plan": {n: [str(k) for k in ks]
                         for n, ks in plan.items()}})
            _log.info("fleet: rehomed %d key(s) from %r: %s",
                      sum(len(v) for v in plan.values()), r.name,
                      {n: len(v) for n, v in plan.items()})
            if self._on_rehome is not None:
                self._on_rehome(r.name, plan)
            return plan
        return None

    def _rejoin(self, r: _Replica) -> None:
        """A dead replica's half-open probe answered: admit it back
        for NEW keys. Its old keys stay pinned to their adopters —
        and the epoch fence refuses it those even if a stale producer
        asks it directly."""
        r.dead = False
        r.rehomed = False
        self.ring.add(r.name)
        obs.counter("fleet.rejoins").inc()
        obs.gauge(f"fleet.replica.{r.name}.alive").set(1)
        _log.info("fleet: replica %r rejoined (new keys only; old "
                  "keys stay with their adopters)", r.name)
        if self._on_rejoin is not None:
            self._on_rejoin(r.name)

    def _gauges(self) -> None:
        obs.gauge("fleet.replicas_alive").set(
            sum(1 for r in self._reps.values() if not r.dead))

    # -- routing + introspection

    def owner(self, key) -> str:
        """Where producers should send the key now: its pinned
        adopter after a rehome, else the ring owner."""
        with self._lock:
            pinned = self.pins.get(key)
        if pinned is not None:
            return pinned
        return self.ring.owner(key)

    def status(self) -> dict:
        return {"replicas": {r.name: {"dead": r.dead,
                                      "rehomed": r.rehomed,
                                      "addr": r.addr,
                                      "breaker":
                                          r.breaker.snapshot()}
                             for r in self._reps.values()},
                "pins": {str(k): v for k, v in self.pins.items()}}

    # -- the loop

    def start(self) -> "FleetSupervisor":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="jepsen-fleet-supervisor")
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the supervisor must
                # outlive one bad round; the next tick re-reads truth
                _log.exception("fleet: supervision tick failed")
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
