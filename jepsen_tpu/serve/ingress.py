"""HTTP delta ingress for the streaming checker service — the
network transport of ``jepsen serve --checker``.

An asyncio, stdlib-only (the ``obs.httpd`` zero-new-deps posture)
HTTP/1.1 server that wraps the same blocking
:meth:`~jepsen_tpu.serve.service.CheckerService.submit` the stdio
transport drives — the blocking call **is** the backpressure: each
request's submit runs on an executor thread via ``run_in_executor``,
so a producer past its queue blocks (then sheds) exactly like a local
caller while the event loop keeps serving every other connection.

Endpoints (all JSON; request bodies are **streamed JSONL** — one
request object per line, one response object per line, flushed as
chunked transfer as each submit lands, so a long stream acks
incrementally instead of buffering):

    POST /v1/deltas     body lines: {"key": K, "ops": [...],
                        "seq": N?, "timeout": S?, "wait": B?}
                        or {"op": "result"|"finalize", "key": K,
                        "timeout": S?} interleaved mid-stream
    GET  /v1/result?key=<json K>[&timeout=S]
    POST /v1/finalize   body: {"key": K, "timeout": S?}

Auth: with tenants configured (``serve.tenancy``), every request must
carry ``Authorization: Bearer <token>`` naming a tenant; an unknown
or missing token answers 401 before the service sees the request, and
the resolved tenant rides into ``submit`` so admission, quotas, and
the ``{shed, reason, tenant}`` answers are the service's own — one
admission layer for every transport. Without tenants, no auth (the
single-tenant PR 7 behavior).

The server runs its event loop on a daemon thread (same ergonomics
as ``obs.httpd.OpsServer``: construct binds, ``start()`` serves,
``close()`` stops, ``.port`` readable for port 0), so the synchronous
CLI and tests drive it without owning a loop.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from jepsen_tpu import envflags, obs
from jepsen_tpu.history import Op, _hashable
from jepsen_tpu.serve.stdio import jsonable as _jsonable
from jepsen_tpu.serve.stdio import wire_key as _key_of

_log = logging.getLogger(__name__)

#: request-line budget: one JSONL delta line must fit (64 ops of a
#: register history is ~4 KiB; 1 MiB leaves room for fat values)
MAX_LINE_BYTES = 1 << 20
#: executor threads = concurrently BLOCKED producers (backpressure
#: waits park here); past this, requests queue at the executor
INGRESS_WORKERS = 32

_JSONL_TYPE = "application/x-ndjson"


def resolve_ingress_port(cli_value: Optional[int] = None) \
        -> Optional[int]:
    """The delta-ingress port: ``--ingress-port`` wins, else
    ``JEPSEN_TPU_INGRESS_PORT`` (0 = ephemeral); None when neither is
    set (stdio stays the only transport — PR 7 behavior)."""
    if cli_value is not None:
        return int(cli_value)
    return envflags.env_int("JEPSEN_TPU_INGRESS_PORT", default=None,
                            min_value=0, what="delta ingress port")


class DeltaIngress:
    """The HTTP ingress as an object: construct (binds — port 0 gets
    an OS-assigned one, readable as ``.port``), ``start()`` the loop
    thread, ``close()`` to stop. ``tenants`` defaults to the
    service's own table so both layers answer identically."""

    def __init__(self, service, port: int = 0,
                 host: str = "127.0.0.1", tenants=None):
        self.service = service
        self.tenants = (tenants if tenants is not None
                        else getattr(service, "_tenants", None))
        self.host = host
        self.port = None
        self._req_port = port
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._startup_err = None
        self._pool = ThreadPoolExecutor(
            max_workers=INGRESS_WORKERS,
            thread_name_prefix="jepsen-ingress")

    # ------------------------------------------------ thread plumbing

    def start(self) -> "DeltaIngress":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="jepsen-ingress-loop")
        self._thread.start()
        self._ready.wait(timeout=30)
        if self._startup_err is not None:
            raise self._startup_err
        if self.port is None:
            raise RuntimeError("ingress event loop failed to start")
        return self

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            self._server = loop.run_until_complete(
                asyncio.start_server(self._handle, self.host,
                                     self._req_port,
                                     limit=MAX_LINE_BYTES))
            self.port = self._server.sockets[0].getsockname()[1]
        except Exception as err:  # noqa: BLE001 — surfaced to start()
            self._startup_err = err
            self._ready.set()
            return
        self._ready.set()
        try:
            loop.run_forever()
        finally:
            self._server.close()
            loop.run_until_complete(self._server.wait_closed())
            loop.close()

    def url(self, path: str = "") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        if self._loop is not None and self._loop.is_running():
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self._pool.shutdown(wait=False)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # --------------------------------------------------- HTTP plumbing

    async def _call(self, fn, *args, **kw):
        """The blocking service call on an executor thread — the
        backpressure parks HERE while the loop serves everyone else.

        Span-context propagation: executor threads do not inherit the
        request task's contextvars, so without the per-call
        ``Context.copy()`` (``obs.ctx_runner``, the same fix the
        pipeline pool uses) the service's spans would start an orphan
        chain instead of nesting under the ingress request span.
        ``ctx_runner`` is the identity wrap when tracing is off."""
        loop = asyncio.get_running_loop()
        wrap = obs.ctx_runner()
        return await loop.run_in_executor(
            self._pool, wrap(lambda: fn(*args, **kw)))

    @staticmethod
    def _response(writer, code: int, body: bytes,
                  ctype: str = "application/json",
                  chunked: bool = False) -> None:
        reason = {200: "OK", 400: "Bad Request", 401: "Unauthorized",
                  404: "Not Found", 405: "Method Not Allowed",
                  413: "Payload Too Large",
                  500: "Internal Server Error"}.get(code, "OK")
        head = [f"HTTP/1.1 {code} {reason}",
                f"Content-Type: {ctype}"]
        if chunked:
            head.append("Transfer-Encoding: chunked")
        else:
            head.append(f"Content-Length: {len(body)}")
        head.append("")
        head.append("")
        writer.write("\r\n".join(head).encode())
        if not chunked and body:
            writer.write(body)

    @staticmethod
    def _chunk(data: bytes) -> bytes:
        return f"{len(data):x}\r\n".encode() + data + b"\r\n"

    def _json_err(self, writer, code: int, msg: str) -> None:
        self._response(writer, code,
                       (json.dumps({"error": msg}) + "\n").encode())

    def _auth(self, headers) -> tuple:
        """(token, error message | None): with tenants configured a
        Bearer token is REQUIRED and must name a tenant; without, no
        auth (token passes through as None)."""
        auth = headers.get("authorization", "")
        token = None
        if auth.lower().startswith("bearer "):
            token = auth[7:].strip()
        if self.tenants is None:
            return None, None
        if not token:
            return None, ("unauthorized: Authorization: Bearer "
                          "<tenant token> required")
        if self.tenants.by_token(token) is None:
            return None, "unauthorized: unknown tenant token"
        return token, None

    async def _handle(self, reader, writer) -> None:
        try:
            while True:
                keep = await self._handle_one(reader, writer)
                await writer.drain()
                if not keep:
                    break
        except (asyncio.IncompleteReadError, ConnectionResetError,
                asyncio.LimitOverrunError):
            pass
        except Exception:  # noqa: BLE001 — one bad connection must
            # not kill the acceptor loop's handler task silently
            _log.exception("ingress: connection handler failed")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, OSError):
                pass

    async def _handle_one(self, reader, writer) -> bool:
        """One request/response exchange; returns False to close the
        connection (EOF, Connection: close, or a streamed body whose
        framing we did not fully consume)."""
        try:
            line = await reader.readline()
        except (asyncio.LimitOverrunError, ValueError):
            self._json_err(writer, 413, "request line too long")
            return False
        if not line:
            return False
        try:
            method, target, _version = line.decode().split()
        except ValueError:
            self._json_err(writer, 400, "malformed request line")
            return False
        headers = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            name, _, val = h.decode().partition(":")
            headers[name.strip().lower()] = val.strip()
        obs.counter("serve.ingress.requests").inc()
        token, auth_err = self._auth(headers)
        clen = int(headers.get("content-length", 0) or 0)
        if auth_err is not None:
            obs.counter("serve.ingress.unauthorized").inc()
            # drain the body so the connection stays framed
            if clen:
                await reader.readexactly(min(clen, MAX_LINE_BYTES))
            self._json_err(writer, 401, auth_err)
            return False
        path, _, query = target.partition("?")
        path = path.rstrip("/") or "/"
        keep = headers.get("connection", "").lower() != "close"
        try:
            if method == "POST" and path == "/v1/deltas":
                if clen <= 0:
                    # no Content-Length (e.g. a chunked request body,
                    # which this server does not frame): an empty
                    # 200 would silently ack nothing and the unread
                    # body would corrupt keep-alive framing
                    self._json_err(writer, 400,
                                   "Content-Length required (chunked "
                                   "request bodies unsupported)")
                    return False
                await self._deltas(reader, writer, token, clen)
                return keep
            if method == "GET" and path == "/v1/result":
                q = urllib.parse.parse_qs(query)
                try:
                    key = _hashable(json.loads(q.get("key", [""])[0]))
                except ValueError:
                    self._json_err(writer, 400,
                                   "key must be a JSON value")
                    return keep
                try:
                    timeout = (float(q["timeout"][0])
                               if "timeout" in q else None)
                except ValueError:
                    # a malformed query param is the client's bug and
                    # must answer 400, not drop the connection
                    self._json_err(writer, 400,
                                   "timeout must be a number")
                    return keep
                r = await self._call(self.service.result, key,
                                     timeout=timeout, token=token)
                self._response(writer, 200, (json.dumps(
                    _jsonable(r)) + "\n").encode())
                return keep
            if method == "POST" and path == "/v1/finalize":
                body = await reader.readexactly(clen)
                req = json.loads(body or b"{}")
                r = await self._call(self.service.finalize,
                                     _key_of(req),
                                     timeout=req.get("timeout"),
                                     token=token)
                self._response(writer, 200, (json.dumps(
                    _jsonable(r)) + "\n").encode())
                return keep
            if path == "/":
                self._response(writer, 200, (json.dumps(
                    {"endpoints": ["/v1/deltas", "/v1/result",
                                   "/v1/finalize"]}) + "\n").encode())
                return keep
            self._json_err(writer, 404 if method in ("GET", "POST")
                           else 405, f"unknown endpoint {method} "
                                     f"{path}")
            return keep
        except json.JSONDecodeError as err:
            self._json_err(writer, 400, f"bad request body: {err}")
            return keep

    async def _deltas(self, reader, writer, token, clen: int) -> None:
        """The streamed-JSONL delta endpoint: responses flush as
        chunked transfer per input line, in order, so a producer sees
        each ack (or shed) as its delta lands rather than after the
        whole body."""
        self._response(writer, 200, b"", ctype=_JSONL_TYPE,
                       chunked=True)
        remaining = clen
        while remaining > 0:
            line = await reader.readline()
            if not line:
                break
            remaining -= len(line)
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except ValueError as err:
                resp = {"error": f"bad request line: {err}"}
            else:
                resp = await self._one_delta(req, token)
            writer.write(self._chunk(
                (json.dumps(_jsonable(resp)) + "\n").encode()))
            await writer.drain()
        writer.write(b"0\r\n\r\n")

    async def _one_delta(self, req: dict, token) -> dict:
        op = req.get("op")
        if op == "result":
            return await self._call(self.service.result,
                                    _key_of(req),
                                    timeout=req.get("timeout"),
                                    token=token)
        if op == "finalize":
            return await self._call(self.service.finalize,
                                    _key_of(req),
                                    timeout=req.get("timeout"),
                                    token=token)
        if "ops" not in req:
            return {"error": f"unknown request {req!r}"}
        try:
            ops = [Op(o) for o in req["ops"]]
        except Exception as err:  # noqa: BLE001 — a malformed op map
            # is the producer's bug and must answer, not disconnect
            return {"error": f"bad ops: {type(err).__name__}: {err}"}
        # the ingress leg of the delta's causal chain: the service's
        # serve.admit/serve.wal spans parent under this one (the
        # Context.copy in _call carries it across the executor hop);
        # a producer-supplied "delta_id" rides through, otherwise the
        # service mints one at admission and the ack reports it
        with obs.span("serve.ingress.request",
                      key=str(req.get("key"))) as sp:
            r = await self._call(
                self.service.submit, _key_of(req), ops,
                seq=req.get("seq"), timeout=req.get("timeout"),
                wait=bool(req.get("wait")), token=token,
                delta_id=req.get("delta_id"))
            if isinstance(r, dict) and r.get("delta_id"):
                sp.set(delta_id=r["delta_id"], seq=r.get("seq"))
            return r


def start_ingress(service, port: int, host: str = "127.0.0.1",
                  **kw) -> DeltaIngress:
    """Bind + start in one call (the CLI's entry point)."""
    return DeltaIngress(service, port=port, host=host, **kw).start()
