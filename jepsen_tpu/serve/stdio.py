"""JSONL stdio front-end for the streaming checker service — the
``jepsen serve --checker`` transport.

One JSON request per input line, one JSON response per output line
(machine-first, like the bench's emit contract). Requests::

    {"key": K, "ops": [<op map>, ...], "seq": N?}   submit a delta
    {"op": "result",   "key": K}                    current verdict
    {"op": "finalize", "key": K}                    drain + final check
    {"op": "drain"}                                 apply everything
    {"op": "stop"}                                  graceful shutdown

Op maps are the history schema ({"type", "process", "f", "value",
...}); responses are the service's structured dicts (``accepted`` /
``shed`` / ``duplicate`` / verdicts) with non-JSON values stringified.
The HTTP ingress (``serve.ingress``) wraps the same
:class:`CheckerService` calls; this transport exists so the service
is drivable from CI and a shell with zero extra dependencies.

Multi-tenant mode sits BELOW the transport (the service's admission
layer), so stdio producers authenticate exactly like HTTP ones: each
submit/result/finalize line may carry ``"token": "<tenant token>"``
(forwarded verbatim to the service, which resolves and enforces it);
with tenants configured and no token, the request is refused with the
service's structured error — stdio is not a side door around
tenancy.
"""

from __future__ import annotations

import json
import sys

from jepsen_tpu import obs
from jepsen_tpu.history import Op, _hashable


def jsonable(obj):
    """A response dict with non-JSON values stringified — the wire
    form BOTH transports (stdio here, ``serve.ingress`` over HTTP)
    emit, shared so they cannot drift."""
    return json.loads(json.dumps(obj, default=str))


def wire_key(req):
    """A request's key, canonicalized: JSON list keys
    (jepsen.independent [k sub] tuples) arrive as lists — map to the
    hashable form the service keys on. Shared with the HTTP
    ingress."""
    return _hashable(req.get("key"))


# the transports' historical private spellings
_jsonable = jsonable
_key = wire_key


def run_stdio(service, lines_in=None, out=None) -> int:
    """Drive ``service`` from a JSONL stream; returns an exit code.
    The service is closed (with drain) on EOF or ``stop``."""
    lines_in = sys.stdin if lines_in is None else lines_in
    out = sys.stdout if out is None else out

    def emit(obj):
        out.write(json.dumps(_jsonable(obj)) + "\n")
        out.flush()

    try:
        for line in lines_in:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except ValueError as err:
                emit({"error": f"bad request line: {err}"})
                continue
            op = req.get("op")
            if op == "stop":
                emit({"stopped": True})
                break
            if op == "drain":
                emit({"drained": service.drain(
                    timeout=req.get("timeout"))})
            elif op == "result":
                emit(service.result(_key(req),
                                    timeout=req.get("timeout"),
                                    token=req.get("token")))
            elif op == "finalize":
                emit(service.finalize(_key(req),
                                      timeout=req.get("timeout"),
                                      token=req.get("token")))
            elif "ops" in req:
                # the stdio leg of the delta's causal chain — same
                # shape as the HTTP ingress span, so a trace reads
                # identically whichever transport carried the delta;
                # a line-supplied "delta_id" rides through, else the
                # service mints one at admission (armed only)
                with obs.span("serve.stdio.request",
                              key=str(req.get("key"))) as sp:
                    r = service.submit(_key(req),
                                       [Op(o) for o in req["ops"]],
                                       seq=req.get("seq"),
                                       timeout=req.get("timeout"),
                                       wait=bool(req.get("wait")),
                                       token=req.get("token"),
                                       delta_id=req.get("delta_id"))
                    if isinstance(r, dict) and r.get("delta_id"):
                        sp.set(delta_id=r["delta_id"],
                               seq=r.get("seq"))
                emit(r)
            else:
                emit({"error": f"unknown request {req!r}"})
    finally:
        service.close(drain=True)
    return 0
