"""Streaming checker service (docs/streaming.md): a long-lived,
crash-safe front over the incremental extension engine
(``jepsen_tpu.parallel.extend``) — per-key history deltas in, online
verdicts out, with backpressure, load shedding, idle-frontier
eviction, WAL replay, tenant-isolated weighted-fair admission
(``serve.tenancy``), an asyncio HTTP delta ingress
(``serve.ingress``), consistent-hash replica scale-out with
freeze/thaw + WAL-segment key migration (``serve.ring``), and a
self-healing fleet layer — failure detection + auto-rehome +
epoch-fenced ownership + WAL segment replication (``serve.fleet``).
``jepsen serve --checker`` drives the stdio transport
(``serve.stdio``) and, with ``--ingress-port``, the HTTP one."""

from jepsen_tpu.serve.fleet import (  # noqa: F401
    FleetSupervisor, HttpReplica, SegmentReplicator,
)
from jepsen_tpu.serve.service import (  # noqa: F401
    CheckerService, default_wal_dir,
)
from jepsen_tpu.serve.tenancy import (  # noqa: F401
    DEFAULT_TENANT, Tenant, TenantSpecError, TenantTable,
    parse_tenants, resolve_tenants,
)
from jepsen_tpu.serve.wal import (  # noqa: F401
    CheckpointStore, DeltaWAL, WALError,
)
