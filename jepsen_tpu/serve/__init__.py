"""Streaming checker service (docs/streaming.md): a long-lived,
crash-safe front over the incremental extension engine
(``jepsen_tpu.parallel.extend``) — per-key history deltas in, online
verdicts out, with backpressure, load shedding, idle-frontier
eviction, and WAL replay. ``jepsen serve --checker`` is the CLI
ingress (``serve.stdio``)."""

from jepsen_tpu.serve.service import (  # noqa: F401
    CheckerService, default_wal_dir,
)
from jepsen_tpu.serve.wal import (  # noqa: F401
    CheckpointStore, DeltaWAL, WALError,
)
