"""Per-key delta write-ahead log for the streaming checker service.

The robustness contract (docs/streaming.md): a delta the service has
ADMITTED is durable before the producer sees ``{"accepted": ...}`` —
a kill-and-restart replays the WAL through the deterministic encode +
scan and lands bit-identical verdicts. Format: one append-only JSONL
file per key under the WAL root,

    {"key": "<edn of the key>"}                 header, first line
    {"seq": 1, "ops": ["<edn op>", ...]}        one line per delta

Ops are EDN-serialized individually (``history.op_to_edn_str`` — the
store's exact round-trip format), so replay reconstructs the op
stream byte-for-byte. Sequence numbers are the idempotence key:
``replay`` drops duplicate/stale seqs, so re-submitting a delta after
a crash (the client can't know whether the pre-crash submit landed)
is a no-op, never a double-apply.

Crash tolerance: every append is flushed + fsynced before returning;
a torn final line (the process died mid-write — that delta was never
acknowledged) is detected on replay, logged, counted
(``serve.wal_torn``), and ignored. Undecodable lines BEFORE the tail
mean real corruption and raise :class:`WALError` rather than silently
replaying a hole in an acknowledged stream.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Dict, List, Optional, Tuple

from jepsen_tpu import edn, obs
from jepsen_tpu.history import _hashable, op_from_edn, op_to_edn_str

_log = logging.getLogger(__name__)


class WALError(RuntimeError):
    """An acknowledged region of a WAL file cannot be replayed."""


def _safe_name(key) -> str:
    """Filesystem-safe, collision-free file stem for an arbitrary EDN
    key: readable prefix + content digest (the digest is the identity;
    the prefix is for humans)."""
    s = edn.dumps(key)
    digest = hashlib.sha1(s.encode()).hexdigest()[:10]
    prefix = "".join(ch if ch.isalnum() or ch in "-_" else "-"
                     for ch in s)[:40]
    return f"{prefix or 'key'}_{digest}"


class DeltaWAL:
    """Append-only per-key delta log under ``root`` (module docstring).
    Thread-safe; the service appends from producer threads and replays
    from the worker."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()          # handle/lock creation
        self._files: Dict[str, object] = {}    # stem -> open handle
        # per-stem write locks: independent keys fsync CONCURRENTLY —
        # one global lock here would re-serialize exactly what the
        # service's seq-ordered handoff exists to avoid
        self._stem_locks: Dict[str, threading.Lock] = {}

    # -- write path

    @staticmethod
    def _repair_tail(path: str) -> None:
        """Truncate a torn (newline-less) trailing line before the
        first append of this process. The partial line is an
        UNACKNOWLEDGED mid-write kill — replay already ignores it, but
        appending after it would concatenate the next record onto the
        partial bytes, turning an acknowledged delta into an
        unparseable line on the following restart."""
        try:
            with open(path, "rb+") as fh:
                fh.seek(0, os.SEEK_END)
                if fh.tell() == 0:
                    return
                fh.seek(-1, os.SEEK_END)
                if fh.read(1) == b"\n":
                    return
                fh.seek(0)
                data = fh.read()
                cut = data.rfind(b"\n")
                fh.truncate(cut + 1 if cut >= 0 else 0)
            obs.counter("serve.wal_torn").inc()
            _log.warning("WAL %s: truncated a torn trailing line "
                         "before appending (the delta was never "
                         "acknowledged)", path)
        except OSError as err:
            _log.warning("WAL %s: could not repair tail (%r)", path,
                         err)

    def append(self, key, seq: int, ops) -> None:
        stem = _safe_name(key)
        line = json.dumps({"seq": int(seq),
                           "ops": [op_to_edn_str(o) for o in ops]})
        with self._lock:
            slock = self._stem_locks.setdefault(stem, threading.Lock())
        with slock:
            with self._lock:
                fh = self._files.get(stem)
            if fh is None:
                path = os.path.join(self.root, stem + ".wal")
                fresh = not os.path.exists(path)
                if not fresh:
                    self._repair_tail(path)
                fh = open(path, "a")
                if fresh:
                    fh.write(json.dumps({"key": edn.dumps(key)}) + "\n")
                with self._lock:
                    self._files[stem] = fh
            fh.write(line + "\n")
            fh.flush()
            os.fsync(fh.fileno())

    def close(self) -> None:
        with self._lock:
            for fh in self._files.values():
                try:
                    fh.close()
                except OSError:
                    pass
            self._files.clear()
            self._stem_locks.clear()

    # -- replay path

    def keys(self) -> list:
        """Every key with a WAL file (decoded from the headers)."""
        out = []
        for name in sorted(os.listdir(self.root)):
            if not name.endswith(".wal"):
                continue
            path = os.path.join(self.root, name)
            try:
                with open(path) as fh:
                    head = fh.readline()
                # EDN round-trips sequences as lists; the service keys
                # a dict on these, so canonicalize to the hashable
                # form (nested tuples) — same identity either way,
                # because _safe_name hashes the EDN text
                out.append(_hashable(edn.loads(json.loads(head)["key"])))
            except Exception as err:  # noqa: BLE001 — a header we
                # cannot read means the whole file is suspect; this is
                # acknowledged data, so it must be loud, not skipped
                raise WALError(
                    f"unreadable WAL header in {path}: {err!r}") from err
        return out

    def replay(self, key) -> List[Tuple[int, list]]:
        """The key's admitted deltas as ``[(seq, [Op, ...]), ...]`` in
        ascending seq order, duplicates dropped. Tolerates exactly one
        torn TRAILING line (an unacknowledged mid-write kill)."""
        path = os.path.join(self.root, _safe_name(key) + ".wal")
        if not os.path.exists(path):
            return []
        with open(path) as fh:
            lines = fh.read().splitlines()
        out: List[Tuple[int, list]] = []
        seen = set()
        for i, line in enumerate(lines[1:], start=2):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                seq = int(rec["seq"])
                ops = [op_from_edn(edn.loads(s)) for s in rec["ops"]]
            except Exception as err:  # noqa: BLE001 — decode failure
                if i == len(lines):
                    obs.counter("serve.wal_torn").inc()
                    _log.warning(
                        "WAL %s: torn trailing line ignored (the "
                        "delta was never acknowledged): %r", path, err)
                    break
                raise WALError(
                    f"corrupt WAL line {i} in {path} (not the tail — "
                    f"acknowledged data): {err!r}") from err
            if seq in seen:
                continue
            seen.add(seq)
            out.append((seq, ops))
        out.sort(key=lambda t: t[0])
        return out

    def last_seq(self, key) -> int:
        deltas = self.replay(key)
        return deltas[-1][0] if deltas else 0

    def size_bytes(self, key) -> int:
        """The key's WAL file size (0 when none) — the /status
        per-key durability column."""
        path = os.path.join(self.root, _safe_name(key) + ".wal")
        try:
            return os.path.getsize(path)
        except OSError:
            return 0


# -------------------------------------------------- checkpoint store


class CheckpointStore:
    """The eviction side-car: a frozen session's FrontierCheckpoint
    (.npz, via ``FrontierCheckpoint.save``) plus a small JSON meta
    record (applied seq, op count, digest) under ``root``. Thaw reads
    both; a missing/mismatched pair degrades to a from-scratch rescan
    of the WAL replay — slower, never wrong."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _paths(self, key) -> Tuple[str, str]:
        stem = os.path.join(self.root, _safe_name(key))
        return stem + ".npz", stem + ".json"

    def save(self, key, meta: dict) -> None:
        _npz, jpath = self._paths(key)
        tmp = jpath + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(meta, fh)
        os.replace(tmp, jpath)

    def checkpoint_path(self, key) -> str:
        return self._paths(key)[0]

    def load(self, key) -> Tuple[Optional[object], Optional[dict]]:
        """(FrontierCheckpoint | None, meta | None)."""
        npz, jpath = self._paths(key)
        if not os.path.exists(jpath):
            return None, None
        try:
            with open(jpath) as fh:
                meta = json.load(fh)
        except Exception as err:  # noqa: BLE001 — a checkpoint is an
            # optimization; unreadable meta degrades to WAL replay
            _log.warning("checkpoint meta %s unreadable (%r) — "
                         "thaw will rescan from the WAL", jpath, err)
            return None, None
        cp = None
        if meta.get("checkpoint") and os.path.exists(npz):
            try:
                from jepsen_tpu.parallel import engine
                cp = engine.FrontierCheckpoint.load(npz)
            except Exception as err:  # noqa: BLE001 — same posture
                _log.warning("checkpoint %s unreadable (%r) — thaw "
                             "will rescan from the WAL", npz, err)
                cp = None
        return cp, meta

    def drop(self, key) -> None:
        for p in self._paths(key):
            try:
                os.remove(p)
            except OSError:
                pass
