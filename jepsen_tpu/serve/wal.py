"""Per-key delta write-ahead log for the streaming checker service.

The robustness contract (docs/streaming.md): a delta the service has
ADMITTED is durable before the producer sees ``{"accepted": ...}`` —
a kill-and-restart replays the WAL through the deterministic encode +
scan and lands bit-identical verdicts. Format: append-only JSONL
**segments** per key under the WAL root,

    <stem>.wal          segment 0 (always first)
    <stem>.wal.1        segment 1 (after the first rotation)
    <stem>.wal.N        ...

    {"key": "<edn>", "segment": N, "tenant": "..."?,
     "epoch": E}                                       header, first
                                                       line of EVERY
                                                       segment
    {"seq": 1, "ops": ["<edn op>", ...]}               one per delta

Ops are EDN-serialized individually (``history.op_to_edn_str`` — the
store's exact round-trip format), so replay reconstructs the op
stream byte-for-byte. Sequence numbers are the idempotence key:
``replay`` drops duplicate/stale seqs, so re-submitting a delta after
a crash (the client can't know whether the pre-crash submit landed)
is a no-op, never a double-apply.

Segmentation exists for two consumers (neither changes replay
semantics): per-tenant WAL-bytes quotas meter ``size_bytes`` (the sum
over segments), and replica handoff (``serve.ring.transfer_key``)
ships a key as a list of sealed files instead of one unbounded one.
``rotate`` seals the active segment; ``JEPSEN_TPU_SERVE_WAL_SEGMENT_
BYTES`` (0 = off, the default) rotates automatically past a size.
Each segment repeats the header so a transferred file set is
self-describing.

Ownership epochs + fences (docs/streaming.md "Fleet self-healing"):
every segment header carries the key's ownership **epoch** — bumped by
:meth:`CheckerService.adopt_keys` when a survivor takes the key over,
so the WAL itself records who owned which stretch of the stream. A
**fence marker** (``<stem>.fence``, written atomically by
``serve.ring.rehome_dead_replica`` / ``CheckerService.fence_key``
BEFORE the segments are transferred) tells a stale replica that
resurfaces — the SIGSTOP/paused-not-dead case — that its epoch is
over: the service refuses its producers with a structured answer
instead of becoming a second writer. An unreadable fence file fails
SAFE (treated as fenced): for a split-brain guard, refusing work
beats serving it on corrupt evidence.

Crash tolerance: every append is flushed + fsynced before returning;
a torn final line (the process died mid-write — that delta was never
acknowledged) is detected on replay, logged, counted
(``serve.wal_torn``), and ignored. Because a torn line was the tail
of its file when written, the tolerance is per SEGMENT: one torn
trailing line in any segment is an unacknowledged kill (possibly
followed by a post-restart rotation), while an undecodable line
before a segment's tail means real corruption and raises
:class:`WALError` rather than silently replaying a hole in an
acknowledged stream.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
from typing import Dict, List, Optional, Tuple

from jepsen_tpu import edn, envflags, obs
from jepsen_tpu.history import _hashable, op_from_edn, op_to_edn_str

_log = logging.getLogger(__name__)

_SEG_RE = re.compile(r"\.wal(?:\.(\d+))?$")


class WALError(RuntimeError):
    """An acknowledged region of a WAL file cannot be replayed."""


def _safe_name(key) -> str:
    """Filesystem-safe, collision-free file stem for an arbitrary EDN
    key: readable prefix + content digest (the digest is the identity;
    the prefix is for humans)."""
    s = edn.dumps(key)
    digest = hashlib.sha1(s.encode()).hexdigest()[:10]
    prefix = "".join(ch if ch.isalnum() or ch in "-_" else "-"
                     for ch in s)[:40]
    return f"{prefix or 'key'}_{digest}"


def _resolve_segment_bytes(v: Optional[int]) -> int:
    if v is not None:
        return int(v)
    return envflags.env_int("JEPSEN_TPU_SERVE_WAL_SEGMENT_BYTES",
                            default=0, min_value=0,
                            what="WAL segment size (bytes)") or 0


class DeltaWAL:
    """Append-only per-key delta log under ``root`` (module docstring).
    Thread-safe; the service appends from producer threads and replays
    from the worker. ``segment_bytes`` (or the env flag) > 0 rotates
    the active segment automatically once it grows past that size."""

    def __init__(self, root: str, segment_bytes: Optional[int] = None):
        self.root = root
        self.segment_bytes = _resolve_segment_bytes(segment_bytes)
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()          # handle/lock creation
        self._files: Dict[str, object] = {}    # stem -> open handle
        self._seg: Dict[str, int] = {}         # stem -> active index
        self._epochs: Dict[str, int] = {}      # stem -> epoch to stamp
        # on newly-opened segment headers (set_epoch; default: inherit
        # from the newest existing segment, else 1)
        # per-stem write locks: independent keys fsync CONCURRENTLY —
        # one global lock here would re-serialize exactly what the
        # service's seq-ordered handoff exists to avoid
        self._stem_locks: Dict[str, threading.Lock] = {}

    # -- segment naming

    def _seg_path(self, stem: str, i: int) -> str:
        base = os.path.join(self.root, stem + ".wal")
        return base if i == 0 else f"{base}.{i}"

    def _segment_indices(self, stem: str) -> List[int]:
        """Existing segment indices for a stem, ascending."""
        out = []
        prefix = stem + ".wal"
        for name in os.listdir(self.root):
            if not name.startswith(prefix):
                continue
            rest = name[len(stem):]
            m = _SEG_RE.fullmatch(rest)
            if m:
                out.append(int(m.group(1)) if m.group(1) else 0)
        return sorted(out)

    def segments(self, key) -> List[str]:
        """The key's segment paths in replay order — the unit replica
        handoff copies (``serve.ring.transfer_key``)."""
        stem = _safe_name(key)
        return [self._seg_path(stem, i)
                for i in self._segment_indices(stem)]

    # -- write path

    @staticmethod
    def _repair_tail(path: str) -> None:
        """Truncate a torn (newline-less) trailing line before the
        first append of this process. The partial line is an
        UNACKNOWLEDGED mid-write kill — replay already ignores it, but
        appending after it would concatenate the next record onto the
        partial bytes, turning an acknowledged delta into an
        unparseable line on the following restart."""
        try:
            with open(path, "rb+") as fh:
                fh.seek(0, os.SEEK_END)
                if fh.tell() == 0:
                    return
                fh.seek(-1, os.SEEK_END)
                if fh.read(1) == b"\n":
                    return
                fh.seek(0)
                data = fh.read()
                cut = data.rfind(b"\n")
                fh.truncate(cut + 1 if cut >= 0 else 0)
            obs.counter("serve.wal_torn").inc()
            _log.warning("WAL %s: truncated a torn trailing line "
                         "before appending (the delta was never "
                         "acknowledged)", path)
        except OSError as err:
            _log.warning("WAL %s: could not repair tail (%r)", path,
                         err)

    # AUDITED I/O-under-lock: the open (+ header write on a fresh
    # segment) runs under the caller's per-key stem lock BY DESIGN —
    # the stem lock IS the durability handoff serialization point, and
    # only this key's writers wait behind it (the dict lock self._lock
    # is only ever taken in short bursts around map reads/writes).
    # jepsen-lint: disable=concurrency-blocking-under-lock
    def _open_active(self, stem: str, key, tenant: Optional[str]):
        """The active (highest-index) segment's handle, opened —
        with tail repair — on first touch; callers hold the stem
        lock."""
        with self._lock:
            fh = self._files.get(stem)
        if fh is not None:
            return fh
        idx = self._seg.get(stem)
        if idx is None:
            existing = self._segment_indices(stem)
            idx = existing[-1] if existing else 0
        path = self._seg_path(stem, idx)
        fresh = not os.path.exists(path)
        if not fresh:
            self._repair_tail(path)
        fh = open(path, "a")
        if fresh:
            with self._lock:
                ep = self._epochs.get(stem)
            if ep is None:
                # inherit from the newest lower segment so a rotation
                # never silently resets an ownership epoch
                ep = self._header_epoch(stem, below=idx)
            head = {"key": edn.dumps(key), "segment": idx, "epoch": ep}
            if tenant is not None:
                head["tenant"] = tenant
            fh.write(json.dumps(head) + "\n")
        with self._lock:
            self._files[stem] = fh
            self._seg[stem] = idx
        return fh

    # AUDITED I/O-under-lock: write+flush+fsync under the per-key stem
    # lock is the WAL's core contract — the ack only returns once the
    # bytes are on disk, and the stem lock is what keeps two appends
    # to the SAME key from interleaving records. Cross-key appends
    # never contend (each key has its own stem lock).
    # jepsen-lint: disable=concurrency-blocking-under-lock
    def append(self, key, seq: int, ops,
               tenant: Optional[str] = None,
               delta_id: Optional[str] = None) -> int:
        """Durably append one delta; returns the bytes written (the
        per-tenant WAL-quota meter). ``tenant`` stamps the segment
        header so recovery re-homes the key to its owner.

        ``delta_id`` (when the service has delta tracing armed) rides
        the record as ``"id"`` so the delta's trace identity survives
        recovery, replica handoff, and adoption — the id travels with
        the transferred segment files. None keeps the record bytes
        identical to the pre-tracing format (the default-off parity
        contract); ``replay`` ignores the field either way."""
        stem = _safe_name(key)
        rec = {"seq": int(seq),
               "ops": [op_to_edn_str(o) for o in ops]}
        if delta_id is not None:
            rec["id"] = str(delta_id)
        line = json.dumps(rec)
        with self._lock:
            slock = self._stem_locks.setdefault(stem, threading.Lock())
        with slock:
            fh = self._open_active(stem, key, tenant)
            fh.write(line + "\n")
            fh.flush()
            os.fsync(fh.fileno())
            n = len(line) + 1
            if self.segment_bytes and fh.tell() >= self.segment_bytes:
                self._rotate_locked(stem)
            return n

    def _rotate_locked(self, stem: str) -> None:
        """Seal the active segment (callers hold the stem lock); the
        next append opens ``<stem>.wal.<n+1>`` with a fresh header."""
        with self._lock:
            fh = self._files.pop(stem, None)
            idx = self._seg.get(stem)
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass
        if idx is None:
            existing = self._segment_indices(stem)
            if not existing:
                return   # nothing written yet: rotating would orphan
                # segment 0 (keys() reads its header) — a no-op is the
                # only sound answer
            idx = existing[-1]
        with self._lock:
            self._seg[stem] = idx + 1
        obs.counter("serve.wal_rotations").inc()

    def rotate(self, key) -> None:
        """Seal the key's active segment now (replica handoff wants
        sealed files; quota tests want deterministic boundaries)."""
        stem = _safe_name(key)
        with self._lock:
            slock = self._stem_locks.setdefault(stem, threading.Lock())
        with slock:
            self._rotate_locked(stem)

    # AUDITED I/O-under-lock: same contract as append — the fence
    # epoch must be durable (flushed + fsynced) before touch returns,
    # and the stem lock serializes it against this key's appends.
    # jepsen-lint: disable=concurrency-blocking-under-lock
    def touch(self, key, tenant: Optional[str] = None) -> None:
        """Open the key's active segment NOW, writing its header if
        the file is fresh — adoption calls set_epoch + rotate + touch
        so the bumped ownership epoch is durable immediately, not at
        the next append (a fence computed from this dir's headers
        must already out-rank the previous owner)."""
        stem = _safe_name(key)
        with self._lock:
            slock = self._stem_locks.setdefault(stem, threading.Lock())
        with slock:
            fh = self._open_active(stem, key, tenant)
            fh.flush()
            os.fsync(fh.fileno())

    def close(self) -> None:
        with self._lock:
            for fh in self._files.values():
                try:
                    fh.close()
                except OSError:
                    pass
            self._files.clear()
            self._seg.clear()
            self._stem_locks.clear()
            self._epochs.clear()

    # -- replay path

    def keys(self) -> list:
        """Every key with a WAL file (decoded from the segment-0
        headers; rotation never drops segment 0, so one row per key)."""
        out = []
        for name in sorted(os.listdir(self.root)):
            if not name.endswith(".wal"):
                continue
            path = os.path.join(self.root, name)
            try:
                with open(path) as fh:
                    head = fh.readline()
                # EDN round-trips sequences as lists; the service keys
                # a dict on these, so canonicalize to the hashable
                # form (nested tuples) — same identity either way,
                # because _safe_name hashes the EDN text
                out.append(_hashable(edn.loads(json.loads(head)["key"])))
            except Exception as err:  # noqa: BLE001 — a header we
                # cannot read means the whole file is suspect; this is
                # acknowledged data, so it must be loud, not skipped
                raise WALError(
                    f"unreadable WAL header in {path}: {err!r}") from err
        return out

    def header(self, key) -> Optional[dict]:
        """The key's segment-0 header record ({"key", "segment",
        "tenant"?}), or None when the key has no WAL — how recovery
        learns which tenant owns a replayed key."""
        segs = self.segments(key)
        if not segs:
            return None
        try:
            with open(segs[0]) as fh:
                return json.loads(fh.readline())
        except Exception as err:  # noqa: BLE001 — same posture as keys()
            raise WALError(
                f"unreadable WAL header in {segs[0]}: {err!r}") from err

    # -- ownership epoch + fence

    def _header_epoch(self, stem: str, below: Optional[int] = None) \
            -> int:
        """The newest existing segment header's epoch (optionally only
        segments with index < ``below``), default 1 — pre-epoch WAL
        files read as epoch 1, so old fleets replay unchanged."""
        indices = [i for i in self._segment_indices(stem)
                   if below is None or i < below]
        for i in reversed(indices):
            path = self._seg_path(stem, i)
            try:
                with open(path) as fh:
                    return int(json.loads(fh.readline()).get(
                        "epoch", 1))
            except Exception as err:  # noqa: BLE001 — same posture as
                # keys(): an unreadable header is acknowledged data
                raise WALError(
                    f"unreadable WAL header in {path}: {err!r}") \
                    from err
        return 1

    def epoch(self, key) -> int:
        """The key's current ownership epoch: the pending stamp when
        one was set this process, else the newest segment header's,
        else 1 (no WAL yet)."""
        stem = _safe_name(key)
        with self._lock:
            e = self._epochs.get(stem)
        if e is not None:
            return e
        return self._header_epoch(stem)

    def header_epoch(self, key) -> int:
        """The newest segment HEADER's epoch, ignoring any pending
        in-process stamp — the adoption base: a key transferred back
        into this dir carries its truth in the transferred headers,
        and a stamp left by an earlier ownership generation of this
        process must not shadow it."""
        return self._header_epoch(_safe_name(key))

    def set_epoch(self, key, epoch: int) -> None:
        """Stamp ``epoch`` on every segment header this process opens
        for the key from now on (``adopt_keys`` bumps + rotates, so
        the bump lands in the next segment's header durably)."""
        with self._lock:
            self._epochs[_safe_name(key)] = int(epoch)

    def _fence_path(self, stem: str) -> str:
        return os.path.join(self.root, stem + ".fence")

    def write_fence(self, key, epoch: int,
                    owner: Optional[str] = None) -> dict:
        """Atomically drop the key's fence marker: any service over
        this WAL root whose key epoch is below ``epoch`` must refuse
        producers (it is no longer the owner). Written BEFORE segment
        transfer by the rehome path, so a stale writer that re-checks
        the fence after its fsync can never hand out an ack the new
        owner will not replay."""
        doc = {"key": edn.dumps(key), "epoch": int(epoch)}
        if owner is not None:
            doc["owner"] = owner
        path = self._fence_path(_safe_name(key))
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(json.dumps(doc) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        obs.counter("serve.fences_written").inc()
        return doc

    def fence(self, key) -> Optional[dict]:
        """The key's fence marker, or None. An unreadable fence fails
        SAFE — it reads as a fence at an unbeatable epoch, because a
        split-brain guard must refuse on corrupt evidence, never
        write through it."""
        path = self._fence_path(_safe_name(key))
        try:
            with open(path) as fh:
                doc = json.loads(fh.read())
            doc["epoch"] = int(doc["epoch"])
            return doc
        except FileNotFoundError:
            return None
        except Exception as err:  # noqa: BLE001 — corrupt marker
            _log.warning("WAL fence %s unreadable (%r) — treating the "
                         "key as fenced (fail-safe)", path, err)
            return {"epoch": 1 << 62, "error": f"unreadable fence: "
                                               f"{err!r}"}

    def clear_fence(self, key) -> None:
        """Drop a stale fence marker (adoption clears one an earlier
        ownership generation left behind, once its own epoch
        out-ranks it)."""
        try:
            os.remove(self._fence_path(_safe_name(key)))
        except OSError:
            pass

    def replay(self, key) -> List[Tuple[int, list]]:
        """The key's admitted deltas as ``[(seq, [Op, ...]), ...]`` in
        ascending seq order, across every segment, duplicates dropped.
        Tolerates one torn TRAILING line per segment (an
        unacknowledged mid-write kill — it was the tail of its file
        when written, segment boundary or not)."""
        return self.replay_with_ids(key)[0]

    def replay_with_ids(self, key):
        """One-scan ``(replay(key), seq -> delta_id)`` — the
        recovery/adoption/re-thaw path needs both, and with delta
        tracing armed must not pay the segment read + json decode
        twice per key. Same torn-tail/corruption posture as
        ``replay``; ids synthesized like ``delta_ids`` for records
        written without one."""
        out: List[Tuple[int, list]] = []
        seen = set()
        ids: Dict[int, str] = {}
        digest = self._id_digest(key)
        for path in self.segments(key):
            with open(path) as fh:
                lines = fh.read().splitlines()
            for i, line in enumerate(lines[1:], start=2):
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                    seq = int(rec["seq"])
                    ops = [op_from_edn(edn.loads(s)) for s in rec["ops"]]
                except Exception as err:  # noqa: BLE001 — decode failure
                    if i == len(lines):
                        obs.counter("serve.wal_torn").inc()
                        _log.warning(
                            "WAL %s: torn trailing line ignored (the "
                            "delta was never acknowledged): %r", path,
                            err)
                        break
                    raise WALError(
                        f"corrupt WAL line {i} in {path} (not the "
                        f"tail — acknowledged data): {err!r}") from err
                if seq in seen:
                    continue
                seen.add(seq)
                out.append((seq, ops))
                ids[seq] = self._record_id(digest, rec, seq)
        out.sort(key=lambda t: t[0])
        return out, ids

    @staticmethod
    def _id_digest(key) -> str:
        return _safe_name(key).rsplit("_", 1)[-1]

    @staticmethod
    def _record_id(digest: str, rec: dict, seq: int) -> str:
        """One record's trace id: the stamped ``"id"``, or the
        SYNTHESIZED stable stand-in (``wal-<stem digest>-<seq>``) for
        records written before delta tracing existed (or unarmed) —
        deterministic per (key, seq), so the same synthetic id
        reappears on every replay/adoption of the same record. ONE
        definition, shared by the strict (``replay_with_ids``) and
        lenient (``delta_ids``) scans: the two paths must never mint
        different ids for the same bytes."""
        return str(rec.get("id") or f"wal-{digest}-{seq}")

    def delta_ids(self, key) -> Dict[int, str]:
        """seq -> trace ``delta_id`` for every replayable delta of the
        key (ids per ``_record_id`` — stamped or synthesized). Decode
        failures are skipped (the torn-tail / corruption posture
        belongs to ``replay``; this is a telemetry read and must
        never out-strict it)."""
        digest = self._id_digest(key)
        out: Dict[int, str] = {}
        for path in self.segments(key):
            try:
                with open(path) as fh:
                    lines = fh.read().splitlines()
            except OSError:
                continue
            for line in lines[1:]:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                    seq = int(rec["seq"])
                except Exception:  # noqa: BLE001 — torn tail etc.
                    continue
                if seq not in out:
                    out[seq] = self._record_id(digest, rec, seq)
        return out

    def last_seq(self, key) -> int:
        deltas = self.replay(key)
        return deltas[-1][0] if deltas else 0

    def size_bytes(self, key) -> int:
        """The key's WAL size summed across segments (0 when none) —
        the /status durability column and the tenant WAL-quota meter."""
        total = 0
        for path in self.segments(key):
            try:
                total += os.path.getsize(path)
            except OSError:
                pass
        return total


# -------------------------------------------------- checkpoint store


class CheckpointStore:
    """The eviction side-car: a frozen session's FrontierCheckpoint
    (.npz, via ``FrontierCheckpoint.save``) plus a small JSON meta
    record (applied seq, op count, digest) under ``root``. Thaw reads
    both; a missing/mismatched pair degrades to a from-scratch rescan
    of the WAL replay — slower, never wrong."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _paths(self, key) -> Tuple[str, str]:
        stem = os.path.join(self.root, _safe_name(key))
        return stem + ".npz", stem + ".json"

    def save(self, key, meta: dict) -> None:
        _npz, jpath = self._paths(key)
        tmp = jpath + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(meta, fh)
        os.replace(tmp, jpath)

    def checkpoint_path(self, key) -> str:
        return self._paths(key)[0]

    def manifest_path(self, key) -> str:
        """The key's compiled-program manifest (written at freeze when
        JEPSEN_TPU_COMPILE_CACHE is armed; shipped by
        ``serve.ring.transfer_key``; pre-warmed by ``adopt_keys``)."""
        return os.path.join(self.root,
                            _safe_name(key) + ".programs.json")

    def load(self, key) -> Tuple[Optional[object], Optional[dict]]:
        """(FrontierCheckpoint | None, meta | None)."""
        npz, jpath = self._paths(key)
        if not os.path.exists(jpath):
            return None, None
        try:
            with open(jpath) as fh:
                meta = json.load(fh)
        except Exception as err:  # noqa: BLE001 — a checkpoint is an
            # optimization; unreadable meta degrades to WAL replay
            _log.warning("checkpoint meta %s unreadable (%r) — "
                         "thaw will rescan from the WAL", jpath, err)
            return None, None
        cp = None
        if meta.get("checkpoint") and os.path.exists(npz):
            try:
                from jepsen_tpu.parallel import engine
                cp = engine.FrontierCheckpoint.load(npz)
            except Exception as err:  # noqa: BLE001 — same posture
                _log.warning("checkpoint %s unreadable (%r) — thaw "
                             "will rescan from the WAL", npz, err)
                cp = None
        return cp, meta

    def drop(self, key) -> None:
        for p in self._paths(key) + (self.manifest_path(key),):
            try:
                os.remove(p)
            except OSError:
                pass
