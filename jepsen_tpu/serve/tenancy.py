"""Tenant model for the multi-tenant streaming checker service.

PR 7/8 built a crash-safe single-process checker with one implicit
producer population: any client could fill the queue, the WAL, and the
device, so one greedy producer was a denial of service against every
other. This module is the isolation boundary the fleet-shaped serve
mode admits through:

* a **tenant** is a named principal with an auth token, a scheduling
  weight, and three quotas — pending ops, keys, WAL bytes — all
  declared in one validated ``JEPSEN_TPU_TENANTS`` spec (or passed
  programmatically);
* **admission** is weighted-fair: each tenant's pending-ops bound
  defaults to its weight share of the shed high-water, so a tenant
  flooding past its share is shed *immediately* with a structured
  ``{shed, reason, tenant}`` while every other tenant's deltas keep
  admitting and acking inside their SLO (the fairness pin in
  tests/test_serve.py);
* **service order** is deficit round-robin (``serve.service``): per
  worker cycle every backlogged tenant banks ``weight x quantum`` ops
  of deficit and the batch takes whole deltas against it, so the
  device serves tenants proportionally to weight, not arrival order.

Spec grammar (comma-separated tenants, colon-separated fields)::

    JEPSEN_TPU_TENANTS = <name>[:token=T][:weight=W][:ops=N]
                         [:keys=N][:wal=BYTES][,<tenant>...]

    name    [A-Za-z0-9_-]+ — the metric label and /status row key
    token   the ingress bearer token (required when the HTTP ingress
            authenticates; distinct per tenant)
    weight  integer >= 1 (default 1) — DRR share and the divisor for
            the derived pending-ops bound
    ops     pending-ops quota (default 0 = derive from weight share)
    keys    max concurrently admitted keys (default from
            JEPSEN_TPU_TENANT_KEYS; 0 = unlimited)
    wal     WAL-bytes quota across the tenant's keys (default from
            JEPSEN_TPU_TENANT_WAL_BYTES; 0 = unlimited)

Validation is strict (the ``JEPSEN_TPU_FAULTS`` posture): an unknown
field, a duplicate name or token, or a malformed number raises
:class:`TenantSpecError` (an ``envflags.EnvFlagError``) at the first
read — a typo'd tenant plan must never silently run un-isolated.

With no tenants configured the service runs exactly as PR 7/8 shipped
it: one implicit :data:`DEFAULT_TENANT` with unlimited quotas, no
auth, no per-tenant metric labels, FIFO take order — byte-identical
behavior and metrics.

Import-safe: no JAX, no engine imports (the ingress authenticates
against this module while the device runtime may be wedged).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional

from jepsen_tpu import envflags

#: the implicit single-tenant name when no tenant table is configured
DEFAULT_TENANT = "default"

_NAME_RE = re.compile(r"^[A-Za-z0-9_-]+$")

_FIELDS = ("token", "weight", "ops", "keys", "wal")


class TenantSpecError(envflags.EnvFlagError):
    """A JEPSEN_TPU_TENANTS spec outside the grammar above."""


@dataclass(frozen=True)
class Tenant:
    """One tenant's declared identity, weight, and quotas (0 for a
    quota means "unlimited" / "derive" per the module docstring)."""

    name: str
    token: Optional[str] = None
    weight: int = 1
    max_pending_ops: int = 0
    max_keys: int = 0
    max_wal_bytes: int = 0


def _default_quota(flag: str, what: str) -> int:
    return envflags.env_int(flag, default=0, min_value=0, what=what) or 0


def _parse_int(part: str, field: str, val: str,
               min_value: int = 0) -> int:
    try:
        v = int(val)
    except ValueError:
        raise TenantSpecError(
            f"JEPSEN_TPU_TENANTS tenant {part!r}: field {field}={val!r} "
            f"must be an integer")
    if v < min_value:
        raise TenantSpecError(
            f"JEPSEN_TPU_TENANTS tenant {part!r}: field {field}={val!r} "
            f"must be >= {min_value}")
    return v


def parse_tenants(raw: str) -> List[Tenant]:
    """Parse a JEPSEN_TPU_TENANTS value into tenants, strictly
    (module docstring grammar). Duplicate names or tokens raise — two
    tenants sharing a token would collapse the isolation boundary the
    table exists to draw."""
    default_keys = _default_quota("JEPSEN_TPU_TENANT_KEYS",
                                  "default per-tenant key quota")
    default_wal = _default_quota("JEPSEN_TPU_TENANT_WAL_BYTES",
                                 "default per-tenant WAL-bytes quota")
    default_ops = _default_quota("JEPSEN_TPU_TENANT_OPS",
                                 "default per-tenant pending-ops quota")
    tenants: List[Tenant] = []
    names, tokens = set(), set()
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        name = fields[0].strip()
        if not _NAME_RE.match(name):
            raise TenantSpecError(
                f"JEPSEN_TPU_TENANTS tenant {part!r}: name {name!r} "
                f"must match [A-Za-z0-9_-]+ (it becomes a metric "
                f"label and a /status row)")
        if name in names:
            raise TenantSpecError(
                f"JEPSEN_TPU_TENANTS: duplicate tenant name {name!r}")
        names.add(name)
        kw = {"token": None, "weight": 1, "ops": default_ops,
              "keys": default_keys, "wal": default_wal}
        for f in fields[1:]:
            key, eq, val = f.partition("=")
            key = key.strip()
            if not eq or key not in _FIELDS:
                raise TenantSpecError(
                    f"JEPSEN_TPU_TENANTS tenant {part!r}: unknown "
                    f"field {f!r} (expected one of "
                    f"{[k + '=' for k in _FIELDS]})")
            if key == "token":
                if not val:
                    raise TenantSpecError(
                        f"JEPSEN_TPU_TENANTS tenant {part!r}: empty "
                        f"token")
                kw["token"] = val
            elif key == "weight":
                kw["weight"] = _parse_int(part, key, val, min_value=1)
            else:
                kw[key] = _parse_int(part, key, val, min_value=0)
        if kw["token"] is not None:
            if kw["token"] in tokens:
                raise TenantSpecError(
                    f"JEPSEN_TPU_TENANTS: tenant {name!r} reuses "
                    f"another tenant's token — tokens must be "
                    f"distinct (they ARE the isolation boundary)")
            tokens.add(kw["token"])
        tenants.append(Tenant(name=name, token=kw["token"],
                              weight=kw["weight"],
                              max_pending_ops=kw["ops"],
                              max_keys=kw["keys"],
                              max_wal_bytes=kw["wal"]))
    return tenants


class TenantTable:
    """Immutable name -> :class:`Tenant` and token -> tenant lookups
    (shared by the service's admission layer and the HTTP ingress's
    auth check, so both answer identically)."""

    def __init__(self, tenants: List[Tenant]):
        if not tenants:
            raise TenantSpecError("a TenantTable needs >= 1 tenant")
        self._by_name: Dict[str, Tenant] = {}
        self._by_token: Dict[str, Tenant] = {}
        for t in tenants:
            if t.name in self._by_name:
                raise TenantSpecError(
                    f"duplicate tenant name {t.name!r}")
            self._by_name[t.name] = t
            if t.token is not None:
                if t.token in self._by_token:
                    raise TenantSpecError(
                        f"tenant {t.name!r} reuses another tenant's "
                        f"token")
                self._by_token[t.token] = t
        self.total_weight = sum(t.weight for t in tenants)

    def __len__(self) -> int:
        return len(self._by_name)

    def names(self) -> List[str]:
        return sorted(self._by_name)

    def get(self, name: str) -> Optional[Tenant]:
        return self._by_name.get(name)

    def by_token(self, token: str) -> Optional[Tenant]:
        return self._by_token.get(token)

    def pending_bound(self, name: str, budget: int) -> int:
        """The tenant's effective pending-ops bound: its explicit
        ``ops`` quota, else its weight share of ``budget`` (the shed
        high-water when shedding is on, else the global bound). The
        derived shares sum to <= budget, so no single tenant — nor all
        tenants flooding at once — can push the service past the
        global shed line: a quiet tenant's deltas are admitted by
        construction, not by luck."""
        t = self._by_name[name]
        if t.max_pending_ops:
            return t.max_pending_ops
        return max(1, (budget * t.weight) // max(1, self.total_weight))


def resolve_tenants() -> Optional[TenantTable]:
    """The process tenant table from ``JEPSEN_TPU_TENANTS``, or None
    when unset/empty (single-tenant mode — PR 7/8 behavior,
    byte-identical)."""
    raw = envflags.env_raw("JEPSEN_TPU_TENANTS")
    if raw is None or not raw.strip():
        return None
    tenants = parse_tenants(raw)
    return TenantTable(tenants) if tenants else None


def resolve_quantum() -> int:
    """``JEPSEN_TPU_TENANT_QUANTUM``: ops of deficit one weight unit
    banks per worker cycle (default 512, min 1)."""
    return envflags.env_int("JEPSEN_TPU_TENANT_QUANTUM", default=512,
                            min_value=1, what="DRR quantum (ops)")
