"""The streaming checker service: crash-safe incremental checking of
per-key history deltas, with backpressure, shedding, and eviction.

``jepsen.core/run!`` interleaves test execution with analysis
(PAPER.md L6/L7); this is that loop as a long-lived service over the
TPU engine. Producers submit per-key deltas; the service extends each
key's frontier incrementally (``parallel.extend.HistorySession``),
batches shape-compatible keys into one device program per scan leg
(``parallel.extend.advance_sessions``), and serves verdicts that are
bit-identical to a one-shot batch check of the same prefix.

Robustness is the headline property, by construction:

* **No admitted delta is ever silently dropped.** ``submit`` appends
  to the per-key WAL (``serve.wal.DeltaWAL``) BEFORE acknowledging;
  the final verdict's ``seq`` accounts for every accepted delta.
* **Bounded memory.** Per-key queues are bounded in deltas, the
  global backlog in ops; a slow producer BLOCKS (backpressure), and
  past the high-water mark new deltas are shed with a structured
  ``{"shed": True, "reason": ...}`` instead of buffering — the
  service degrades by refusing work, never by OOM.
* **Crash safety.** A kill-and-restart replays the WAL through the
  deterministic encode + scan: bit-identical verdicts, duplicate
  deltas detected by sequence number (idempotent replay).
* **Eviction.** Idle keys freeze their frontier to the checkpoint
  store and drop their in-memory state; the next delta thaws them
  transparently (digest-guarded — a mismatch rescans, never trusts a
  stale frontier).
* **Device failure.** Every scan runs through the PR-6 resilience
  seam: a wedge mid-dispatch resumes from the checkpoint, a dead or
  breaker-open backend degrades the remaining suffix to the host WGL
  engine with the structured ``resilience`` note — verdicts never
  flip (docs/resilience.md).

* **Tenant isolation** (``serve.tenancy``; off when no tenants are
  configured — then everything below is byte-identical to the
  single-tenant service). Every submit resolves to a tenant (token or
  name); keys are owned by the tenant that admitted them; per-tenant
  pending-ops / key-count / WAL-bytes quotas shed a flooding tenant
  *immediately* with ``{"shed": ..., "tenant": ...}`` while other
  tenants keep admitting; the worker drains tenants by deficit
  round-robin so device time follows weights, not arrival order; and
  the ``serve.ack_secs``/``verdict_secs`` SLO histograms grow
  per-tenant labeled twins so /metrics answers "which tenant is slow
  and who caused it".

Threading: producers call ``submit``/``result`` from any thread; one
worker thread owns every session and the device. ``asyncio`` fronts
wrap the blocking calls with ``run_in_executor`` (the bounded
``submit`` IS the backpressure; ``serve.ingress`` is that front —
see docs/streaming.md).
"""

from __future__ import annotations

import logging
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, Optional

from jepsen_tpu import edn, envflags, obs
from jepsen_tpu.history import TYPES
from jepsen_tpu.obs import ledger as _ledger
from jepsen_tpu.obs import slo as _slo
from jepsen_tpu.parallel import extend as ext
from jepsen_tpu.parallel import programs
from jepsen_tpu.serve import tenancy
from jepsen_tpu.serve.wal import CheckpointStore, DeltaWAL

_log = logging.getLogger(__name__)

DEFAULT_PER_KEY_QUEUE = 64       # pending deltas per key
DEFAULT_GLOBAL_BOUND = 65536     # pending ops across all keys
DEFAULT_EVICT_SECS = 300.0


def _resolve_per_key_queue(v: Optional[int]) -> int:
    if v is not None:
        return int(v)
    return envflags.env_int("JEPSEN_TPU_SERVE_QUEUE",
                            default=DEFAULT_PER_KEY_QUEUE, min_value=1,
                            what="per-key queue bound")


def _resolve_global_bound(v: Optional[int]) -> int:
    if v is not None:
        return int(v)
    return envflags.env_int("JEPSEN_TPU_SERVE_GLOBAL",
                            default=DEFAULT_GLOBAL_BOUND, min_value=1,
                            what="global pending-ops bound")


def _resolve_high_water(v: Optional[int], global_bound: int) -> int:
    if v is None:
        v = envflags.env_int("JEPSEN_TPU_SERVE_HIGH_WATER",
                             default=-1, min_value=0,
                             what="shed high-water")
        if v == -1:
            v = (global_bound * 3) // 4   # shed before the hard block
    return int(v)


def _resolve_evict_secs(v: Optional[float]) -> float:
    if v is not None:
        return float(v)
    return envflags.env_float("JEPSEN_TPU_SERVE_EVICT_SECS",
                              default=DEFAULT_EVICT_SECS, min_value=0.0,
                              what="eviction idle seconds")


def _resolve_slow_delta(v: Optional[float]) -> float:
    if v is not None:
        return float(v)
    return envflags.env_float("JEPSEN_TPU_SLOW_DELTA_SECS",
                              default=0.0, min_value=0.0,
                              what="slow-delta threshold seconds") \
        or 0.0


def _mint_delta_id() -> str:
    """A fleet-unique trace identity for one admitted delta — minted
    at admission (whichever transport carried it), persisted in the
    WAL record, and tagged on every span leg of the delta's causal
    chain (docs/observability.md "End-to-end delta tracing")."""
    return uuid.uuid4().hex[:16]


def default_wal_dir() -> Optional[str]:
    """The JEPSEN_TPU_SERVE_WAL flag: unset/0 -> no WAL (in-memory
    service), 1 -> ``store/serve_wal``, path -> that directory."""
    import os
    v = envflags.env_path("JEPSEN_TPU_SERVE_WAL", what="WAL directory")
    if v == "":
        return os.path.join("store", "serve_wal")
    return v


class _Key:
    """Per-key service state; every field is guarded by the service
    condition except ``session``, which only the worker touches."""

    __slots__ = ("key", "session", "pending", "enq_seq", "applied_seq",
                 "last_result", "last_activity", "finalized",
                 "finalize_requested", "needs_check", "pending_ops",
                 "wal_next", "broken", "wal_dead", "acct",
                 "pending_times", "tenant", "epoch", "fenced",
                 "delta_recs", "device")

    def __init__(self, key, tenant: str = tenancy.DEFAULT_TENANT):
        self.key = key
        self.tenant = tenant   # the admitting tenant owns the key for
        # life: cross-tenant submits are refused (isolation), and the
        # name is the WAL-header stamp recovery re-homes by
        self.session = None
        self.pending: deque = deque()     # (seq, [Op, ...])
        self.enq_seq = 0
        self.applied_seq = 0
        self.last_result: Optional[dict] = None
        self.last_activity = 0.0
        self.finalized = False
        self.finalize_requested = False
        self.needs_check = False
        self.pending_ops = 0
        # per-key accounting for /status: admitted deltas/ops, sheds
        # this key ate, WAL deltas replayed at recovery/thaw
        self.acct = {"deltas": 0, "ops": 0, "sheds": 0, "replays": 0}
        # (seq, t_submit) of admitted-but-unapplied deltas — drained
        # whenever applied_seq advances, feeding the ingest->verdict
        # SLO histogram; bounded by the per-key queue bound
        self.pending_times: deque = deque()
        # per-delta trace records (delta tracing armed only — empty
        # otherwise): {"id", "seq", "tenant", "ops", "t_in", ...stage
        # stamps...}, seq-ordered because admission is; popped by the
        # worker at take time, closed out at verdict publish (the
        # slow-delta breakdown). Bounded by the per-key queue bound.
        self.delta_recs: deque = deque()
        self.wal_next = 1   # next seq allowed to write the WAL (the
        # per-key seq-ordered handoff that keeps file order == seq
        # order without holding the service lock across an fsync)
        self.broken = False     # worker crash lost state and no WAL
        # can rebuild it: the key refuses further deltas instead of
        # silently restarting from a truncated history
        self.wal_dead = False   # a WAL append for this key stalled or
        # failed: later seqs must not write (no holes below an
        # acknowledged delta) — producers get durable=False answers
        self.epoch = 1      # ownership epoch, stamped into every WAL
        # segment header this service opens; bumped by adopt_keys so
        # the fence below can tell a stale owner from the current one
        self.fenced = None  # the key's fence marker once observed:
        # ownership moved to another replica (rehome/migration) —
        # submit/result/finalize answer a structured refusal instead
        # of letting this replica become a second writer
        self.device = None  # elastic device pin (steal_key): when
        # set, this key's session places its scans here instead of
        # the service-wide device — the in-process half of key
        # work-stealing (JEPSEN_TPU_STEAL)


class _TenantState:
    """Per-tenant admission accounting (multi-tenant mode only);
    every field is guarded by the service condition. ``bound`` is the
    tenant's effective pending-ops quota (0 = unlimited), ``deficit``
    its deficit-round-robin credit in ops — refilled ``weight x
    quantum`` per worker cycle with backlog, spent as the batch takes
    deltas (debt allowed so an oversized delta still drains), reset
    when the tenant's queues empty (no banking while idle)."""

    __slots__ = ("name", "weight", "bound", "max_keys",
                 "max_wal_bytes", "pending_ops", "keys", "wal_bytes",
                 "deficit", "acct")

    def __init__(self, tenant: tenancy.Tenant, bound: int):
        self.name = tenant.name
        self.weight = tenant.weight
        self.bound = bound
        self.max_keys = tenant.max_keys
        self.max_wal_bytes = tenant.max_wal_bytes
        self.pending_ops = 0
        self.keys = 0
        self.wal_bytes = 0
        self.deficit = 0
        self.acct = {"deltas": 0, "ops": 0, "sheds": 0}


class CheckerService:
    """The streaming checker (module docstring). Construct, submit
    deltas, read results; ``close(drain=True)`` is the graceful
    shutdown. Usable as a context manager.

    ``tenants`` opts into multi-tenant mode: a ``tenancy.TenantTable``,
    a list of ``tenancy.Tenant``, or None to read
    ``JEPSEN_TPU_TENANTS`` (unset = single-tenant, the historical
    behavior, byte-identical)."""

    def __init__(self, model, wal_dir: Optional[str] = None, *,
                 capacity: int = 1024, max_capacity: int = 1 << 20,
                 dedupe: Optional[str] = None, probe_limit: int = 0,
                 sparse_pallas: Optional[bool] = None, device=None,
                 bucket: Optional[str] = None,
                 per_key_queue: Optional[int] = None,
                 global_bound: Optional[int] = None,
                 high_water: Optional[int] = None,
                 evict_idle_secs: Optional[float] = None,
                 tenants=None, drr_quantum: Optional[int] = None,
                 replicator=None,
                 slow_delta_secs: Optional[float] = None,
                 recover: bool = True, start_worker: bool = True,
                 clock=time.monotonic):
        self.model = model
        self.capacity = capacity
        self.max_capacity = max_capacity
        self.dedupe = dedupe
        self.probe_limit = probe_limit
        self.sparse_pallas = sparse_pallas
        self.device = device
        self.bucket = bucket
        self.per_key_queue = _resolve_per_key_queue(per_key_queue)
        self.global_bound = _resolve_global_bound(global_bound)
        self.high_water = _resolve_high_water(high_water,
                                              self.global_bound)
        self.evict_idle_secs = _resolve_evict_secs(evict_idle_secs)
        self.slow_delta_secs = _resolve_slow_delta(slow_delta_secs)
        # delta trace identity armed? Tracing on, a flight ring
        # retaining spans, or the slow-delta threshold — each is a
        # consumer of per-delta ids/stage records. Unarmed (the
        # default) keeps acks, WAL bytes-on-disk, and the /status
        # schema byte-identical to the pre-tracing service (the PR-4/
        # 8/9 parity standard).
        self._delta_obs = bool(self.slow_delta_secs) \
            or obs.enabled() or obs.flight_active()
        # this service's identity in the process-global slow-delta
        # ring: two services in one process must not read each
        # other's forensics on /status or suppress each other's
        # worst-offender flight dumps (a sentinel, not self — ring
        # entries must not pin the service's sessions alive)
        self._slow_scope = object()
        if tenants is None:
            tenants = tenancy.resolve_tenants()
        elif isinstance(tenants, (list, tuple)):
            tenants = tenancy.TenantTable(list(tenants))
        self._tenants: Optional[tenancy.TenantTable] = tenants
        self._tstate: Dict[str, _TenantState] = {}
        self._drr_idx = 0
        self._drr_quantum = (int(drr_quantum) if drr_quantum
                             else tenancy.resolve_quantum()
                             if tenants is not None else 0)
        if tenants is not None:
            budget = self.high_water or self.global_bound
            for name in tenants.names():
                self._tstate[name] = _TenantState(
                    tenants.get(name),
                    tenants.pending_bound(name, budget))
        self._clock = clock
        self._wal = DeltaWAL(wal_dir) if wal_dir else None
        # WAL segment replication (docs/streaming.md "Fleet
        # self-healing"): a configured JEPSEN_TPU_SERVE_REPL with no
        # target to ship to is a fault-tolerance plan that silently
        # protects nothing — fail loudly at construction instead
        from jepsen_tpu.serve.fleet import resolve_repl_mode
        mode = resolve_repl_mode()
        if replicator is not None and getattr(replicator, "mode",
                                              None) == "off":
            replicator = None
        if mode != "off" and replicator is None:
            raise ValueError(
                f"JEPSEN_TPU_SERVE_REPL={mode!r} but no replication "
                f"target is wired — pass replicator= (a "
                f"serve.fleet.SegmentReplicator) or `jepsen serve "
                f"--checker --repl-dir PATH`, or unset the flag")
        if replicator is not None and self._wal is None:
            raise ValueError("WAL segment replication needs a "
                             "WAL-backed service (wal_dir)")
        self._repl = replicator
        self._cps = (CheckpointStore(wal_dir + "/checkpoints")
                     if wal_dir else None)
        if wal_dir and obs.flight_active():
            # postmortem dumps land next to the WAL they explain
            obs.set_flight_dir(os.path.join(wal_dir, "flight"))
        self._keys: Dict = {}
        # ack-latency SLO burn tracking (obs.slo): unarmed (the
        # default, JEPSEN_TPU_SLO_ACK_SECS unset) it mints nothing —
        # /metrics and /healthz stay byte-identical
        self._slo = _slo.BurnRateTracker(clock=clock)
        self._cond = threading.Condition()
        self._pending_ops = 0
        self._inflight = 0
        self._stop = False
        self.max_pending_seen = 0   # high-water mark, for bound tests
        if recover and self._wal is not None:
            self._recover()
        self._worker = None
        if start_worker:
            self.start_worker()

    def start_worker(self) -> None:
        """Spawn the worker thread (the constructor's default).
        ``start_worker=False`` + a later call makes producer-side
        behavior — admission, backpressure, shedding — exactly
        observable in tests: nothing drains until the worker runs."""
        if self._worker is not None:
            return
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="jepsen-serve-worker")
        self._worker.start()

    # ------------------------------------------------- producer API

    def _resolve_tenant(self, tenant: Optional[str],
                        token: Optional[str]):
        """(tenant name, None) or (None, error dict). Single-tenant
        mode maps everything onto the implicit default tenant; with a
        table configured a token wins over a name (the transports
        authenticate by token; a bare name is the trusted in-process
        path), and an unidentified producer is refused — tenancy on
        means auth on."""
        if self._tenants is None:
            return tenancy.DEFAULT_TENANT, None
        if token is not None:
            t = self._tenants.by_token(token)
            if t is None:
                return None, {"error": "unauthorized: unknown tenant "
                                       "token"}
            return t.name, None
        if tenant is not None:
            if self._tenants.get(tenant) is None \
                    and tenant not in self._tstate:
                return None, {"error": f"unknown tenant {tenant!r}"}
            return tenant, None
        return None, {"error": "tenant required: the service is "
                               "multi-tenant — authenticate with a "
                               "tenant token (or name, in-process)"}

    def _tenant_state_locked(self, name: str) -> \
            Optional[_TenantState]:
        """The tenant's admission state (multi-tenant mode), minted
        ad hoc for a recovered key whose tenant left today's table —
        acknowledged data is never orphaned by a config change."""
        if self._tenants is None:
            return None
        ts = self._tstate.get(name)
        if ts is None:
            budget = self.high_water or self.global_bound
            bound = max(1, budget
                        // max(1, self._tenants.total_weight + 1))
            ts = self._tstate[name] = _TenantState(
                tenancy.Tenant(name=name), bound)
        return ts

    def _shed_locked(self, ks: Optional["_Key"],
                     ts: Optional[_TenantState], reason: str,
                     key) -> dict:
        """Build one structured shed answer + its accounting (callers
        hold the service condition and return/dump outside it)."""
        obs.counter("serve.sheds").inc()
        if ks is not None:
            ks.acct["sheds"] += 1
        out = {"shed": True, "reason": reason, "key": key}
        if ts is not None:
            ts.acct["sheds"] += 1
            obs.counter(obs.labeled("serve.sheds",
                                    tenant=ts.name)).inc()
            out["tenant"] = ts.name
        return out

    # ------------------------------------------ epoch fence (serve
    # ring/fleet ownership: docs/streaming.md "Fleet self-healing")

    def _read_fence(self, key):
        """The key's on-disk fence marker (one stat; None when
        unfenced or WAL-less). Callers run this OUTSIDE the service
        condition — it is file I/O."""
        return self._wal.fence(key) if self._wal is not None else None

    def _fence_locked(self, key, ks, fence):
        """Fold a freshly-read fence marker into the key's state and
        return the active fence (callers hold the condition). A fence
        at OR ABOVE this replica's epoch wins: adoption persists its
        bump immediately (set_epoch + rotate + touch), but a fence
        computed against a header the bump has not reached yet can
        legitimately TIE the stale owner's in-memory epoch — and a tie
        still means someone else took the key (an owner's own WAL dir
        never carries a fence for a key it currently holds)."""
        if ks is not None and ks.fenced is not None:
            return ks.fenced
        if fence is not None \
                and (ks is None or fence.get("epoch", 0) >= ks.epoch):
            if ks is not None:
                ks.fenced = fence
            return fence
        return None

    def _fence_refusal(self, key, fence) -> dict:
        """The structured split-brain refusal: this replica's
        ownership epoch is over — the producer must re-route to the
        owner the fence names (``jepsen status --addr`` shows the
        fleet; the supervisor's pins already route new traffic
        there)."""
        obs.counter("serve.fenced_refusals").inc()
        return {"error": "key ownership fenced: this replica's epoch "
                         "is over (it was rehomed while this replica "
                         "was presumed dead) — re-route to the "
                         "current owner",
                "fenced": True, "epoch": fence.get("epoch"),
                "owner": fence.get("owner"), "key": key}

    def fence_key_ownership(self, key, owner: Optional[str] = None) \
            -> dict:
        """Fence THIS service's ownership of ``key`` now (the
        graceful-migration finisher — ``serve.ring.Router.
        migrate_key`` calls it after the destination adopts): writes
        the durable fence marker at epoch+1 and marks the in-memory
        key, so producers still pointed here get the structured
        refusal instead of a second writer."""
        if self._wal is None:
            raise RuntimeError("fencing needs a WAL-backed service")
        with self._cond:
            ks = self._keys.get(key)
            epoch = (ks.epoch if ks is not None
                     else self._wal.epoch(key)) + 1
        doc = self._wal.write_fence(key, epoch, owner=owner)
        with self._cond:
            if ks is not None:
                ks.fenced = doc
            self._cond.notify_all()
        return doc

    def submit(self, key, ops, seq: Optional[int] = None,
               timeout: Optional[float] = None,
               wait: bool = False, tenant: Optional[str] = None,
               token: Optional[str] = None,
               delta_id: Optional[str] = None) -> dict:
        """Admit one delta for ``key``. Returns one of::

            {"accepted": True, "seq": n, "key": k}
            {"duplicate": True, "seq": n, "key": k}   idempotent replay
            {"shed": True, "reason": ..., "key": k}   overload
            {"error": ..., "key": k}                  malformed request

        With delta tracing armed (``JEPSEN_TPU_TRACE`` /
        ``JEPSEN_TPU_FLIGHT_RECORDER`` / ``JEPSEN_TPU_SLOW_DELTA_
        SECS``), every admitted delta gets a trace identity —
        ``delta_id`` (caller-supplied or minted here), returned on the
        ack, stamped into the WAL record, and tagged on each span leg
        of the delta's causal chain. Unarmed, ``delta_id`` is ignored
        and every answer/byte is identical to the pre-tracing
        service.

        Blocks (backpressure) while the key's queue or the global
        backlog is full, up to ``timeout`` seconds (then sheds). With
        ``wait=True``, additionally blocks until this delta's verdict
        is computed and returns it (the smoke-test convenience).

        Multi-tenant mode (``tenants`` configured): ``token`` (or the
        in-process ``tenant`` name) identifies the producer; shed and
        accepted answers carry ``"tenant"``; a tenant past its
        pending-ops / key-count / WAL-bytes quota is shed IMMEDIATELY
        (no blocking — a flooding tenant must not camp on the queue
        other tenants feed), while the global-bound backpressure
        below still blocks fairly."""
        ops = list(ops)
        for o in ops:
            t = o.get("type") if hasattr(o, "get") else None
            if t not in TYPES:
                return {"error": f"delta op {o!r}: type must be one of "
                                 f"{TYPES}", "key": key}
        tname, auth_err = self._resolve_tenant(tenant, token)
        if auth_err is not None:
            obs.counter("serve.unauthorized").inc()
            return {**auth_err, "key": key}
        # epoch fence, first look (one stat, outside the lock): a
        # replica whose key was rehomed away while it was paused must
        # refuse — and must not even MINT the key fresh at epoch 1
        fence = self._read_fence(key)
        t_in = self._clock()
        deadline = None if timeout is None else t_in + timeout
        # the delta's trace identity (armed only): filled in and
        # queued on the key at admission, closed out by the worker at
        # verdict publish (the slow-delta stage breakdown)
        rec = ({"id": str(delta_id) if delta_id else _mint_delta_id()}
               if self._delta_obs else None)
        shed = None   # set instead of returning inside the lock: the
        # flight-recorder dump a shed triggers is file I/O and must
        # run AFTER the service lock is released (the same reason the
        # WAL fsync below runs outside it)
        with self._cond, \
                obs.span("serve.admit", key=str(key)) as adm_sp:
            ts = self._tenant_state_locked(tname)
            ks = self._keys.get(key)
            f = self._fence_locked(key, ks, fence)
            if f is not None:
                return self._fence_refusal(key, f)
            if ks is None:
                if ts is not None and ts.max_keys \
                        and ts.keys >= ts.max_keys:
                    # refused BEFORE minting the key: a quota'd tenant
                    # must not grow the key table it is over-budget on
                    shed = self._shed_locked(
                        None, ts,
                        f"tenant {tname!r} key quota ({ts.keys} >= "
                        f"{ts.max_keys})", key)
                else:
                    ks = self._keys[key] = _Key(key, tenant=tname)
                    if ts is not None:
                        ts.keys += 1
                    obs.counter("serve.keys_admitted").inc()
            if shed is None and ks.tenant != tname:
                # tenant isolation: a key belongs to the tenant that
                # admitted it — no cross-tenant appends, no
                # cross-tenant seq probing
                return {"error": f"key is owned by another tenant "
                                 f"(not {tname!r})", "key": key,
                        "tenant": tname}
            # validate-then-wait-then-REVALIDATE: every check runs
            # again after a cond.wait released the lock — a concurrent
            # producer may have taken the seq or finalized the key
            # while this one slept
            while shed is None:
                if ks.fenced is not None:
                    # a concurrent submit's post-fsync recheck (or an
                    # operator fence) landed while this one waited
                    return self._fence_refusal(key, ks.fenced)
                if ks.broken:
                    return {"error": "key state was lost to a worker "
                                     "crash and no WAL is configured "
                                     "to rebuild it — restart the "
                                     "stream under a new key",
                            "key": key}
                if ks.finalized or ks.finalize_requested:
                    return {"error": "key is finalized", "key": key}
                my_seq = int(seq) if seq is not None else ks.enq_seq + 1
                if my_seq <= ks.enq_seq:
                    obs.counter("serve.duplicate_deltas").inc()
                    return {"duplicate": True, "seq": my_seq,
                            "key": key}
                if my_seq != ks.enq_seq + 1:
                    return {"error": f"sequence gap: expected "
                                     f"{ks.enq_seq + 1}, got {my_seq}",
                            "key": key}
                if ts is not None and ts.max_wal_bytes \
                        and ts.wal_bytes > ts.max_wal_bytes:
                    # before shedding, re-sync the meter from disk:
                    # the in-memory count only ever grows, but the
                    # documented operator relief is archiving/deleting
                    # rotated segments — stat() the tenant's files so
                    # that relief actually lifts the quota without a
                    # process restart (one sweep per over-quota
                    # attempt, bounded by the tenant's key count)
                    if self._wal is not None:
                        ts.wal_bytes = sum(
                            self._wal.size_bytes(k.key)
                            for k in self._keys.values()
                            if k.tenant == tname)
                    if ts.wal_bytes > ts.max_wal_bytes:
                        shed = self._shed_locked(
                            ks, ts,
                            f"tenant {tname!r} WAL-bytes quota "
                            f"({ts.wal_bytes} > {ts.max_wal_bytes})",
                            key)
                        break
                if ts is not None and ts.bound \
                        and ts.pending_ops + len(ops) > ts.bound:
                    # the weighted-fair line: this tenant is past its
                    # share, so it sheds NOW — the global queue keeps
                    # room for every other tenant's deltas, which is
                    # exactly why the quiet tenant's ack SLO holds
                    # under someone else's flood
                    shed = self._shed_locked(
                        ks, ts,
                        f"tenant {tname!r} pending-ops quota "
                        f"({ts.pending_ops}+{len(ops)} > {ts.bound})",
                        key)
                    break
                if self.high_water \
                        and self._pending_ops + len(ops) \
                        > self.high_water:
                    shed = self._shed_locked(
                        ks, ts,
                        f"pending ops past high-water "
                        f"({self._pending_ops}+{len(ops)} > "
                        f"{self.high_water})", key)
                    break
                if len(ks.pending) < self.per_key_queue \
                        and self._pending_ops + len(ops) \
                        <= self.global_bound:
                    break   # admitted
                if self._stop:
                    shed = self._shed_locked(ks, ts,
                                             "service stopping", key)
                    break
                rem = (None if deadline is None
                       else deadline - self._clock())
                if rem is not None and rem <= 0:
                    shed = self._shed_locked(
                        ks, ts, "backpressure timeout (queue full)",
                        key)
                    break
                self._cond.wait(0.5 if rem is None else min(rem, 0.5))
            if shed is None:
                # reserve the seq + queue slot under the lock (pending
                # stays seq-ordered because reservations are), then
                # write the WAL OUTSIDE it — an fsync must not
                # serialize every other key's producers and the worker
                # on one lock
                ks.pending.append((my_seq, ops))
                ks.enq_seq = my_seq
                ks.pending_ops += len(ops)
                ks.acct["deltas"] += 1
                ks.acct["ops"] += len(ops)
                ks.pending_times.append((my_seq, t_in))
                if rec is not None:
                    rec.update(seq=my_seq, tenant=tname,
                               ops=len(ops), t_in=t_in,
                               t_admit=self._clock())
                    ks.delta_recs.append(rec)
                    adm_sp.set(delta_id=rec["id"], seq=my_seq,
                               tenant=tname)
                self._pending_ops += len(ops)
                self.max_pending_seen = max(self.max_pending_seen,
                                            self._pending_ops)
                obs.counter("serve.deltas").inc()
                obs.counter("serve.delta_ops").inc(len(ops))
                obs.gauge("serve.pending_ops").set(self._pending_ops)
                if ts is not None:
                    ts.pending_ops += len(ops)
                    ts.acct["deltas"] += 1
                    ts.acct["ops"] += len(ops)
                    obs.counter(obs.labeled(
                        "serve.deltas", tenant=tname)).inc()
                    obs.gauge(obs.labeled(
                        "serve.pending_ops",
                        tenant=tname)).set(ts.pending_ops)
                # Perfetto counter track: queue depth over time lines
                # up with the stream/dispatch spans (no-op untraced)
                obs.counter_sample("serve.pending_ops",
                                   self._pending_ops)
                self._cond.notify_all()
        if shed is not None:
            # overload IS the postmortem moment: an armed flight
            # recorder dumps here — outside the service lock, because
            # the dump is file I/O and a sick disk must not freeze
            # every producer and the ops surface (a None check when
            # off; the per-process cap bounds a shed storm). The
            # trigger context cross-references the shed answer.
            obs.flight_dump("serve-shed", context={
                "key": str(key), "reason": shed.get("reason"),
                "tenant": shed.get("tenant")})
            return shed
        durable = self._wal is not None
        durable_replica = None   # sync replication verdict (None =
        # not in sync mode / nothing shipped this ack)
        if self._wal is not None:
            # per-key seq-ordered handoff: seq N's bytes land before
            # N+1's, so a crash can truncate the WAL only at the tail,
            # never leave a hole below an acknowledged delta. The wait
            # honors the caller deadline and shutdown — one stalled
            # fsync (a sick disk) must not block later producers
            # forever; it instead marks the key's WAL dead so no later
            # seq writes (no holes), and answers carry durable=False.
            give_up = False
            with self._cond:
                while ks.wal_next != my_seq and not ks.wal_dead \
                        and ks.fenced is None:
                    if self._stop:
                        give_up = True
                        break
                    rem = (None if deadline is None
                           else deadline - self._clock())
                    if rem is not None and rem <= 0:
                        give_up = True
                        break
                    self._cond.wait(0.5 if rem is None
                                    else min(rem, 0.5))
                if ks.fenced is not None:
                    # fenced while parked in the handoff: nothing may
                    # write below a fence — refuse instead of ack
                    return self._fence_refusal(key, ks.fenced)
                if give_up or ks.wal_dead:
                    ks.wal_dead = True
                    durable = False
                    self._cond.notify_all()
                elif rec is not None:
                    # WAL stage start stamp. The stage is measured as
                    # a start/end DURATION, not a timeline split: the
                    # fsync below runs outside the lock, CONCURRENTLY
                    # with the queue/device stages — the worker may
                    # take (and even publish) this delta while its
                    # fsync is still in flight, so a t_take-relative
                    # split would mis-attribute a slow disk to the
                    # queue stage.
                    rec["t_wal_start"] = self._clock()
            if durable:
                try:
                    with obs.span("serve.wal", key=str(key),
                                  seq=my_seq,
                                  delta_id=(rec or {}).get("id")):
                        nbytes = self._wal.append(
                            key, my_seq, ops,
                            tenant=(tname if ts is not None
                                    else None),
                            delta_id=(rec or {}).get("id"))
                except Exception as err:  # noqa: BLE001 — a failed
                    # append must not hold the handoff or hide the
                    # durability loss from the producer
                    durable = False
                    obs.counter("serve.wal_errors").inc()
                    _log.warning("WAL append failed for key %r seq "
                                 "%d (%r) — delta applies in-process "
                                 "only", key, my_seq, err)
                    with self._cond:
                        ks.wal_dead = True
                        if rec is not None:
                            rec["t_wal_end"] = self._clock()
                        self._cond.notify_all()
                else:
                    with self._cond:
                        ks.wal_next = my_seq + 1
                        if rec is not None:
                            # the WAL-duration end stamp (under the
                            # condition — _finish_recs_locked holds it
                            # too, so the read/write pair cannot tear;
                            # a rec the worker ALREADY published keeps
                            # its in-flight attribution, see there)
                            rec["t_wal_end"] = self._clock()
                        if ts is not None:
                            # the WAL-bytes quota meter: the tenant
                            # pays for every byte its keys fsync
                            ts.wal_bytes += nbytes
                        self._cond.notify_all()
                    # fence recheck AFTER the fsync, before the ack:
                    # the rehome path writes its fence BEFORE copying
                    # segments, so either this delta's bytes made the
                    # transfer (consistent) or this stat sees the
                    # fence and the producer never gets the ack — a
                    # paused replica cannot acknowledge a delta the
                    # new owner will not replay (pinned in
                    # tests/test_fleet.py)
                    fence2 = self._read_fence(key)
                    if fence2 is not None \
                            and fence2.get("epoch", 0) >= ks.epoch:
                        with self._cond:
                            if ks.fenced is None:
                                ks.fenced = fence2
                            self._cond.notify_all()
                        return self._fence_refusal(key, ks.fenced)
                    if self._repl is not None:
                        # ship the key's segments to its ring
                        # successor; sync mode returns False when the
                        # successor copy did NOT land (the ack below
                        # then says so instead of implying fleet-wide
                        # durability)
                        durable_replica = \
                            self._repl.after_append(key) is not False
        # ingest->ack SLO: admission (incl. backpressure wait) through
        # WAL durability — the producer-visible accept latency
        ack = max(0.0, self._clock() - t_in)
        obs.histogram("serve.ack_secs").observe(ack)
        if ts is not None:
            # the per-tenant SLO twin (/metrics renders it as a real
            # {tenant="..."} label on the same histogram name)
            obs.histogram(obs.labeled("serve.ack_secs",
                                      tenant=tname)).observe(ack)
        if wait:
            rem = None if deadline is None else deadline - self._clock()
            r = self.result(key, min_seq=my_seq, timeout=rem,
                            tenant=tname)
            if rec is not None and isinstance(r, dict):
                r.setdefault("delta_id", rec["id"])
            if not durable and self._wal is not None:
                r["durable"] = False
            if durable_replica is False:
                r["replicated"] = False
            return r
        out = {"accepted": True, "seq": my_seq, "key": key}
        if rec is not None:
            # the producer learns its delta's trace identity: the
            # handle that cross-references spans, slow-delta records,
            # and flight dumps fleet-wide
            out["delta_id"] = rec["id"]
        if ts is not None:
            out["tenant"] = tname
        if not durable and self._wal is not None:
            obs.counter("serve.nondurable_acks").inc()
            out["durable"] = False
        if durable_replica is False:
            # sync-mode promise not met this ack: primary-durable
            # only (serve.repl_errors counted by the replicator)
            out["replicated"] = False
        return out

    def _own_key_locked(self, key, tenant: Optional[str],
                        token: Optional[str]):
        """(ks, None) or (None, error dict): lookup + tenant ownership
        for the read paths. With tenants configured EVERY caller must
        identify itself (token from the transports, tenant name from
        trusted in-process code) and only sees its own keys —
        result/finalize are not a side door around the auth submit
        enforces (a tokenless stdio line could otherwise read or SEAL
        another tenant's key). Single-tenant mode keeps the
        historical unauthenticated view."""
        ks = self._keys.get(key)
        if ks is None:
            return None, {"error": "unknown key", "key": key}
        if self._tenants is None:
            return ks, None
        tname, err = self._resolve_tenant(tenant, token)
        if err is not None:
            obs.counter("serve.unauthorized").inc()
            return None, {**err, "key": key}
        if ks.tenant != tname:
            return None, {"error": f"key is owned by another tenant "
                                   f"(not {tname!r})", "key": key,
                          "tenant": tname}
        return ks, None

    def result(self, key, min_seq: Optional[int] = None,
               timeout: Optional[float] = None,
               tenant: Optional[str] = None,
               token: Optional[str] = None) -> dict:
        """The verdict covering the key's applied deltas; blocks until
        at least ``min_seq`` (default: everything enqueued so far) has
        been applied."""
        deadline = None if timeout is None else self._clock() + timeout
        fence = self._read_fence(key)
        with self._cond:
            ks, err = self._own_key_locked(key, tenant, token)
            if err is not None:
                return err
            f = self._fence_locked(key, ks, fence)
            if f is not None:
                # the verdict moved with the ownership: the current
                # owner serves it (replayed from the transferred WAL)
                return self._fence_refusal(key, f)
            target = ks.enq_seq if min_seq is None else int(min_seq)
            while ks.applied_seq < target or ks.last_result is None \
                    or ks.needs_check:
                rem = (None if deadline is None
                       else deadline - self._clock())
                if rem is not None and rem <= 0:
                    return {"error": "timeout waiting for verdict",
                            "key": key, "applied-seq": ks.applied_seq}
                self._cond.wait(0.5 if rem is None else min(rem, 0.5))
            r = dict(ks.last_result)
            r["seq"] = ks.applied_seq
            r["key"] = key
            return r

    def finalize(self, key, timeout: Optional[float] = None,
                 tenant: Optional[str] = None,
                 token: Optional[str] = None) -> dict:
        """Drain the key's pending deltas, run the final check
        (counterexample extraction included), and seal the key —
        further deltas get ``{"error": "key is finalized"}``."""
        deadline = None if timeout is None else self._clock() + timeout
        fence = self._read_fence(key)
        with self._cond:
            ks, err = self._own_key_locked(key, tenant, token)
            if err is not None:
                return err
            f = self._fence_locked(key, ks, fence)
            if f is not None:
                # sealing is the owner's right; a fenced replica
                # sealing the key would shadow deltas the new owner
                # is still admitting
                return self._fence_refusal(key, f)
            ks.finalize_requested = True
            self._cond.notify_all()
            while not ks.finalized:
                rem = (None if deadline is None
                       else deadline - self._clock())
                if rem is not None and rem <= 0:
                    return {"error": "timeout waiting for finalize",
                            "key": key}
                self._cond.wait(0.5 if rem is None else min(rem, 0.5))
            r = dict(ks.last_result or {})
            r["seq"] = ks.applied_seq
            r["key"] = key
            return r

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every admitted delta has been applied (graceful
        shutdown's first half). True when drained."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while self._pending_ops > 0 or self._inflight > 0 \
                    or any(ks.needs_check
                           or (ks.finalize_requested
                               and not ks.finalized)
                           for ks in self._keys.values()):
                rem = (None if deadline is None
                       else deadline - self._clock())
                if rem is not None and rem <= 0:
                    return False
                self._cond.wait(0.5 if rem is None else min(rem, 0.5))
            return True

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Graceful shutdown: drain (unless told not to), stop the
        worker, close the WAL. Admitted-but-unapplied deltas survive
        in the WAL either way — the restart replays them."""
        if drain:
            self.drain(timeout=timeout)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=30)
        if self._repl is not None:
            # flush the async replication queue before the WAL goes
            # away — a graceful shutdown leaves the successor mirror
            # current (a kill, of course, does not: that lag is the
            # documented async-mode loss window)
            self._repl.close(drain=drain)
        if self._wal is not None:
            self._wal.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=not any(exc))
        return False

    def stats(self) -> dict:
        with self._cond:
            return {"keys": len(self._keys),
                    "keys_live": sum(1 for k in self._keys.values()
                                     if k.session is not None),
                    "pending_ops": self._pending_ops,
                    "max_pending_seen": self.max_pending_seen}

    # ----------------------------------------------- the ops surface

    def refresh_gauges(self) -> None:
        """Point-in-time refresh of the computed gauges (queue depth,
        live sessions, WAL lag) — the ops endpoint calls this before
        every render so a scrape reads current levels, not the levels
        as of the last submit/evict."""
        with self._cond:
            pending = self._pending_ops
            live = sum(1 for k in self._keys.values()
                       if k.session is not None)
            wal_lag = sum(ks.enq_seq - (ks.wal_next - 1)
                          for ks in self._keys.values()) \
                if self._wal is not None else 0
            tpending = {name: ts.pending_ops
                        for name, ts in self._tstate.items()} \
                if self._tenants is not None else {}
        obs.gauge("serve.pending_ops").set(pending)
        obs.gauge("serve.keys_live").set(live)
        for name, v in tpending.items():
            obs.gauge(obs.labeled("serve.pending_ops",
                                  tenant=name)).set(v)
        if self._wal is not None:
            # admitted deltas whose WAL bytes have not landed yet —
            # nonzero is producers outrunning fsync; growing is a
            # sick disk (the wal_dead path's precursor)
            obs.gauge("serve.wal_lag_deltas").set(wal_lag)
        # SLO burn rates ride the same refresh: every /metrics render
        # re-derives the two-window burn from the ack histogram (a
        # no-op returning None when the target flag is unset)
        self._slo.sample()

    def status(self) -> dict:
        """The /status document: one row per key (seq, pending,
        frontier live/evicted, last verdict, WAL bytes, resilience
        notes, per-key accounting) plus service totals — everything an
        operator needs before deciding whether to read the flight
        recorder or the WAL."""
        with self._cond:
            rows = []
            for ks in self._keys.values():
                r = ks.last_result or {}
                state = ("fenced" if ks.fenced is not None
                         else "poisoned" if ks.broken
                         else "live" if ks.session is not None
                         else "evicted" if ks.applied_seq
                         else "idle")   # admitted nothing yet (e.g.
                # every delta shed): no frontier was ever built, so
                # "evicted" would imply a checkpoint that isn't there
                row = {
                    "seq": ks.applied_seq,
                    "enq_seq": ks.enq_seq,
                    "pending_deltas": len(ks.pending),
                    "pending_ops": ks.pending_ops,
                    "state": state,
                    "finalized": ks.finalized,
                    "verdict": r.get("valid?"),
                    "error": r.get("error"),
                    "resilience": r.get("resilience"),
                    "wal_dead": ks.wal_dead,
                    "epoch": ks.epoch,
                    "fenced": ks.fenced,
                    "acct": dict(ks.acct),
                }
                if self._tenants is not None:
                    row["tenant"] = ks.tenant
                if r.get("stats"):
                    # JEPSEN_TPU_SEARCH_STATS: the key's lifetime
                    # search telemetry, trajectories summarized (the
                    # full per-event lists stay in the run-dir record
                    # — a /status scrape must stay small)
                    s = r["stats"]
                    row["stats"] = {
                        k: s.get(k) for k in
                        ("events", "frontier-peak", "peak-occupancy",
                         "capacity", "capacity-tier", "dedupe",
                         "delta-split-ratio", "load-factor-peak",
                         "probe-hist", "pad-waste")
                        if s.get(k) is not None}
                if r.get("plan"):
                    # JEPSEN_TPU_AUTO: which strategy vector the
                    # planner routed this key's scans through, and on
                    # what evidence — the per-key provenance twin of
                    # the /plan table view
                    row["plan"] = dict(r["plan"])
                rows.append((ks.key, row))
            doc = {"pending_ops": self._pending_ops,
                   "max_pending_seen": self.max_pending_seen,
                   "high_water": self.high_water,
                   "global_bound": self.global_bound,
                   "keys_live": sum(1 for k in self._keys.values()
                                    if k.session is not None),
                   "worker_alive": self._worker is not None
                   and self._worker.is_alive()}
            trows = {name: {"weight": ts.weight,
                            "pending_ops": ts.pending_ops,
                            "pending_bound": ts.bound,
                            "keys": ts.keys,
                            "max_keys": ts.max_keys,
                            "wal_bytes": ts.wal_bytes,
                            "max_wal_bytes": ts.max_wal_bytes,
                            "acct": dict(ts.acct)}
                     for name, ts in self._tstate.items()} \
                if self._tenants is not None else None
        # WAL sizes are filesystem reads — outside the service lock
        keys = {}
        for key, row in rows:
            if self._wal is not None:
                row["wal_bytes"] = self._wal.size_bytes(key)
            keys[edn.dumps(key)] = row
        doc["keys"] = keys
        if self.slow_delta_secs:
            # slow-delta forensics (armed only — the key is absent,
            # not empty, when the threshold is off: /status schema
            # parity): the retained ring, oldest first, each record a
            # stage breakdown + verdict/resilience/stats context
            doc["slow_delta_secs"] = self.slow_delta_secs
            doc["slow_deltas"] = obs.slow_delta_records(
                self._slow_scope)
        if trows is not None:
            # the per-tenant SLO answer, readable without a /metrics
            # scrape: quantiles straight from the labeled histograms
            snap = obs.registry().snapshot()
            for name, t in trows.items():
                for which in ("ack", "verdict"):
                    h = snap.get(obs.labeled(f"serve.{which}_secs",
                                             tenant=name))
                    t[f"{which}_p50"] = (obs.hist_quantile(h, 0.5)
                                         if h else None)
                    t[f"{which}_p99"] = (obs.hist_quantile(h, 0.99)
                                         if h else None)
            doc["tenants"] = trows
        return doc

    def health(self) -> dict:
        """The /healthz document: ``ok`` is READINESS (serve this
        instance traffic?), degraded by a dead worker, an unwritable/
        dead WAL, any non-closed circuit breaker, or the queue at/past
        the shed high-water. Liveness is the HTTP answer itself. The
        CLI additionally merges the continuous chip watch
        (``probe.ProbeWatch.status``) into ``checks``."""
        with self._cond:
            worker_ok = (self._worker is not None
                         and self._worker.is_alive()
                         and not self._stop)
            pending = self._pending_ops
            wal_dead = sum(1 for ks in self._keys.values()
                           if ks.wal_dead)
            poisoned = sum(1 for ks in self._keys.values()
                           if ks.broken)
            n_keys = len(self._keys)
        checks = {"worker": {"ok": worker_ok}}
        if self._wal is not None:
            writable = os.access(self._wal.root, os.W_OK)
            checks["wal"] = {"ok": writable and wal_dead == 0,
                             "dir": self._wal.root,
                             "writable": writable,
                             "dead_keys": wal_dead}
        queue_ok = not self.high_water or pending < self.high_water
        checks["queue"] = {"ok": queue_ok, "pending_ops": pending,
                           "high_water": self.high_water,
                           "global_bound": self.global_bound}
        # breaker states come from the resilience registry (imported
        # here, not at module scope: serve must not pull the breaker
        # machinery in for WAL-less in-memory embeddings)
        from jepsen_tpu.resilience import breaker as breaker_mod
        snaps = breaker_mod.snapshots()
        checks["breakers"] = {
            "ok": all(s["state"] == breaker_mod.CLOSED for s in snaps),
            "states": {s["backend"]: s["state"] for s in snaps}}
        checks["keys"] = {"ok": poisoned == 0, "total": n_keys,
                          "poisoned": poisoned}
        if self._slo.armed:
            # armed only — the check key is absent, not ok:true, when
            # JEPSEN_TPU_SLO_ACK_SECS is unset (/healthz schema
            # parity); with JEPSEN_TPU_SLO_BURN_MAX=0 the check is
            # informational and never degrades readiness
            checks["slo"] = self._slo.check()
        return {"ok": all(c["ok"] for c in checks.values()),
                "live": True, "checks": checks}

    # ------------------------------------------------------ recovery

    def _recover_key(self, key, bump_epoch: bool = False):
        """Build one key's state from its WAL segments + evicted
        checkpoint (no shared-state mutation — the caller installs
        under the condition). Returns (ks, wal_bytes) or None.

        ``bump_epoch`` is the ADOPTION path (``adopt_keys``): the new
        owner takes the key at epoch+1 and seals the transferred
        segments so its first append opens a fresh segment whose
        header carries the bump durably — the fence the rehome wrote
        in the old owner's dir names exactly this epoch. A plain
        restart keeps the stored epoch (same owner, same epoch)."""
        deltas, wal_ids = self._wal.replay_with_ids(key)
        if not deltas:
            return None
        head = self._wal.header(key) or {}
        tname = (head.get("tenant") or tenancy.DEFAULT_TENANT) \
            if self._tenants is not None else tenancy.DEFAULT_TENANT
        cp, meta = (self._cps.load(key) if self._cps is not None
                    else (None, None))
        applied = int(meta.get("applied_seq", 0)) if meta else 0
        base = [op for seq, ops in deltas if seq <= applied
                for op in ops]
        rest = [(seq, ops) for seq, ops in deltas if seq > applied]
        ks = _Key(key, tenant=tname)
        # adoption bases its bump on the transferred segment HEADERS
        # (header_epoch), never on a pending in-process stamp a
        # previous ownership generation of this key left behind — the
        # migrate-away-and-back case would otherwise tie its own
        # fence forever
        ks.epoch = self._wal.header_epoch(key) \
            + (1 if bump_epoch else 0)
        self._wal.set_epoch(key, ks.epoch)
        if bump_epoch:
            # persist the bump NOW (fresh segment + fsynced header):
            # a fence computed from this dir's headers must already
            # out-rank the previous owner, even if this adopter never
            # sees another append
            self._wal.rotate(key)
            self._wal.touch(key, tenant=(tname if self._tenants
                                         is not None else None))
        fence = self._wal.fence(key)
        if fence is not None and fence.get("epoch", 0) >= ks.epoch:
            # this key was rehomed away while the replica was down:
            # recover it for forensics, refuse its producers
            ks.fenced = fence
        elif fence is not None and bump_epoch:
            # a stale fence from an earlier ownership generation (the
            # key migrated back here): our bumped epoch out-ranks it,
            # so it no longer binds — drop it
            self._wal.clear_fence(key)
        # delta trace identity rides the transferred segments: the ids
        # the previous owner stamped (or synthesized stand-ins for
        # pre-tracing records) re-tag this replica's thaw/apply spans,
        # so a migrated delta's chain reads across the replica
        # boundary in the merged fleet trace. replay_with_ids above
        # collected them in the same segment scan — recovery must not
        # read + decode every segment twice.
        ids = wal_ids if self._delta_obs else {}
        sess = self._new_session(key)
        if base:
            sp_kw = {"key": str(key), "ops": len(base)}
            if ids:
                bids = [ids[seq] for seq, _ops in deltas
                        if seq <= applied and seq in ids]
                sp_kw["delta_ids"] = bids[-32:]
            with obs.span("serve.thaw", **sp_kw):
                sess.thaw(base, cp)
            ks.applied_seq = applied
            ks.needs_check = True
        ks.session = sess
        if meta and meta.get("finalized"):
            ks.finalize_requested = True
        ks.enq_seq = deltas[-1][0]
        ks.wal_next = deltas[-1][0] + 1
        ks.pending.extend(rest)
        if self._delta_obs:
            now = self._clock()
            for seq, dops in rest:
                ks.delta_recs.append(
                    {"id": ids.get(seq) or _mint_delta_id(),
                     "seq": seq, "tenant": tname, "ops": len(dops),
                     "t_in": now})
        ks.pending_ops = sum(len(ops) for _, ops in rest)
        ks.last_activity = self._clock()
        ks.acct["replays"] = len(deltas)
        return ks, self._wal.size_bytes(key)

    def _install_recovered_locked(self, ks: _Key,
                                  wal_bytes: int) -> None:
        """Admit a rebuilt key into the live tables (callers hold the
        condition, or run pre-worker where no one else can)."""
        self._keys[ks.key] = ks
        self._pending_ops += ks.pending_ops
        ts = self._tenant_state_locked(ks.tenant)
        if ts is not None:
            ts.keys += 1
            ts.pending_ops += ks.pending_ops
            ts.wal_bytes += wal_bytes
        obs.counter("serve.replayed_deltas").inc(ks.acct["replays"])

    def _recover(self) -> None:
        """Rebuild every key from its WAL (synchronously, before the
        worker starts): replay is deterministic, so the recomputed
        verdicts are bit-identical to the pre-crash ones. An evicted
        checkpoint, when present and digest-matched, spares the replay
        its device re-scan of the settled prefix."""
        for key in self._wal.keys():
            built = self._recover_key(key)
            if built is None:
                continue
            self._install_recovered_locked(*built)
        if self._keys:
            _log.info("serve: recovered %d key(s) from the WAL",
                      len(self._keys))

    def adopt_keys(self) -> list:
        """Recover any WAL keys not yet admitted, LIVE — the replica
        handoff entry point. ``serve.ring.transfer_key`` copies a dead
        (or draining) replica's WAL segments and frozen checkpoint
        pair into this service's wal_dir; this call replays them into
        running sessions exactly like a restart would, so the migrated
        keys' verdicts stay bit-identical to an unmigrated check
        (the PR 7 recovery contract, cross-process). Returns the
        adopted keys."""
        if self._wal is None:
            raise RuntimeError("adopt_keys needs a WAL-backed service")
        # warm handoff, ordered BEFORE replay (docs/streaming.md
        # contract): pre-warm every transferred program manifest so
        # the replay itself — and the first post-adoption delta —
        # dispatches compiled programs instead of paying first-touch
        # compile on the verdict SLO
        self._prewarm_programs()
        adopted = []

        def _replaceable(cur) -> bool:
            # two kinds of key object adoption may replace: an empty
            # SHELL a producer's early retry minted while the handoff
            # was in flight (nothing admitted, nothing applied — its
            # submits all answered "sequence gap"), and a FENCED key
            # whose local state is forensics-only — ownership
            # returning (migrate-away-and-back, on a LIVE service) is
            # exactly what adoption is. Real live state is an
            # unfenced key with admitted or applied deltas.
            if cur.fenced is not None:
                return True
            return not (cur.enq_seq or cur.applied_seq or cur.pending
                        or cur.needs_check)

        for key in self._wal.keys():
            with self._cond:
                cur = self._keys.get(key)
                if cur is not None and not _replaceable(cur):
                    continue
            built = self._recover_key(key, bump_epoch=True)   # heavy
            # (replay + thaw): outside the lock so live producers
            # keep admitting. The epoch bump is what the fence in the
            # dead replica's dir names — adoption IS the ownership
            # transition.
            if built is None:
                continue
            with self._cond:
                cur = self._keys.get(key)
                if cur is not None:
                    if not _replaceable(cur):
                        # a producer landed REAL deltas mid-replay —
                        # keep the live key; the operator re-runs
                        # adopt after quiescing that producer
                        _log.warning("adopt_keys: key %r gained live "
                                     "state during replay — keeping "
                                     "the live key", key)
                        continue
                    # replace the empty shell with the recovered
                    # state: release its quota slot, and fence the
                    # orphaned object so any waiter still holding it
                    # gets a structured answer that re-routes (its
                    # retry then finds the recovered key)
                    ts = self._tenant_state_locked(cur.tenant)
                    if ts is not None:
                        ts.keys -= 1
                    cur.fenced = {"epoch": built[0].epoch,
                                  "owner": None}
                self._install_recovered_locked(*built)
                self._cond.notify_all()
            adopted.append(key)
            obs.counter("serve.adopted_keys").inc()
        if adopted:
            _log.info("serve: adopted %d key(s) from transferred WAL "
                      "segments", len(adopted))
        return adopted

    def _prewarm_programs(self) -> None:
        """Compile (or cache-load) every program the transferred
        ``.programs.json`` manifests name. Runs lock-free on the
        adopter's calling thread; a no-op unless
        JEPSEN_TPU_COMPILE_CACHE armed the registry. Malformed
        manifests degrade to plain first-dispatch compile — warm
        handoff is an optimization, never a correctness gate."""
        reg = programs.registry()
        if reg is None or self._cps is None:
            return
        import glob

        from jepsen_tpu.parallel import engine
        entries = engine.program_entries()
        warmed = 0
        for path in sorted(glob.glob(os.path.join(
                self._cps.root, "*.programs.json"))):
            warmed += reg.warm_manifest(path, entries)
        if warmed:
            _log.info("serve: pre-warmed %d program(s) from "
                      "transferred manifests", warmed)

    # -------------------------------------------------- worker side

    def _new_session(self, key, device=None) -> ext.HistorySession:
        return ext.HistorySession(
            self.model, capacity=self.capacity,
            max_capacity=self.max_capacity, dedupe=self.dedupe,
            probe_limit=self.probe_limit,
            sparse_pallas=self.sparse_pallas,
            device=device if device is not None else self.device,
            key=key)

    def _session_for(self, ks: _Key) -> ext.HistorySession:
        if ks.session is not None:
            return ks.session
        # evicted: thaw transparently from checkpoint store + WAL —
        # onto the key's stolen device pin when one is set
        sess = self._new_session(ks.key, device=ks.device)
        cp, _meta = (self._cps.load(ks.key)
                     if self._cps is not None else (None, None))
        deltas, ids = (self._wal.replay_with_ids(ks.key)
                       if self._wal else ([], {}))
        applied = [(seq, dops) for seq, dops in deltas
                   if seq <= ks.applied_seq]
        ops = [op for _seq, dops in applied for op in dops]
        if ops:
            sp_kw = {"key": str(ks.key)}
            if self._delta_obs:
                sp_kw["delta_ids"] = [ids[seq] for seq, _d in applied
                                      if seq in ids][-32:]
            with obs.span("serve.thaw", **sp_kw):
                sess.thaw(ops, cp)
            obs.counter("serve.thaws").inc()
            ks.acct["replays"] += len(applied)
        ks.session = sess
        return sess

    def _work_available_locked(self) -> bool:
        return any(ks.pending or ks.needs_check
                   or (ks.finalize_requested and not ks.finalized)
                   for ks in self._keys.values())

    def _take_recs_locked(self, ks: _Key, last_seq) -> tuple:
        """Pop the per-delta trace records this batch covers (callers
        hold the condition) and stamp the queue->worker handoff time.
        Ownership moves with the batch: the records are closed out at
        verdict publish, whichever path publishes. Empty when delta
        tracing is unarmed (``delta_recs`` never fills)."""
        if last_seq is None or not ks.delta_recs:
            return ()
        now = self._clock()
        out = []
        while ks.delta_recs and ks.delta_recs[0]["seq"] <= last_seq:
            r = ks.delta_recs.popleft()
            r["t_take"] = now
            out.append(r)
        return tuple(out)

    def _take_work_locked(self) -> list:
        """Pop pending deltas (coalesced, seq order) and settle the
        backpressure accounting HERE — ops leave the queue exactly
        once, so no later error path can double-decrement. In-flight
        work is bounded by what the queue admitted.

        Single-tenant mode takes everything (the historical FIFO
        drain). Multi-tenant mode is deficit round-robin: every
        backlogged tenant banks ``weight x quantum`` ops of credit per
        cycle and the batch takes whole deltas while credit lasts
        (debt allowed so an oversized delta still drains — the tenant
        then skips cycles until refills repay it), so device time
        tracks weights even when one tenant's queues are always
        full."""
        if self._tenants is None:
            batch = []
            for ks in self._keys.values():
                if not (ks.pending or ks.needs_check
                        or (ks.finalize_requested
                            and not ks.finalized)):
                    continue
                ops = []
                last_seq = None
                while ks.pending:
                    seq, dops = ks.pending.popleft()
                    ops.extend(dops)
                    last_seq = seq
                ks.pending_ops -= len(ops)
                self._pending_ops -= len(ops)
                final = ks.finalize_requested and not ks.finalized
                batch.append((ks, ops, last_seq, final,
                              self._take_recs_locked(ks, last_seq)))
            if batch:
                obs.gauge("serve.pending_ops").set(self._pending_ops)
                obs.counter_sample("serve.pending_ops",
                                   self._pending_ops)
                self._cond.notify_all()   # queue space freed: release
                # blocked producers now, not after the device work
            return batch
        return self._take_drr_locked()

    def _take_drr_locked(self) -> list:
        batch = []
        by_tenant: Dict[str, list] = {}
        for ks in self._keys.values():
            if ks.pending or ks.needs_check \
                    or (ks.finalize_requested and not ks.finalized):
                by_tenant.setdefault(ks.tenant, []).append(ks)
        names = sorted(self._tstate)
        if names:
            # rotate the starting tenant each cycle so ties don't
            # always break for the alphabetically first name
            start = self._drr_idx % len(names)
            self._drr_idx += 1
            names = names[start:] + names[:start]
        took_ops = 0
        for tname in names:
            ts = self._tstate[tname]
            keys = by_tenant.get(tname, ())
            if not keys:
                ts.deficit = 0   # classic DRR: no banking while idle
                continue
            if any(ks.pending for ks in keys):
                ts.deficit += ts.weight * self._drr_quantum
            for ks in keys:
                ops = []
                last_seq = None
                while ks.pending and ts.deficit > 0:
                    seq, dops = ks.pending.popleft()
                    ops.extend(dops)
                    last_seq = seq
                    ts.deficit -= len(dops)
                # finalize only once the key's queue is EMPTY: a
                # deficit that ran out mid-drain must not seal the
                # key over acknowledged-but-unapplied deltas (the
                # final verdict is bit-identical to one-shot only if
                # it covers everything admitted) — the leftover
                # drains next cycle and the finalize fires then
                final = ks.finalize_requested and not ks.finalized \
                    and not ks.pending
                if not (ops or ks.needs_check or final):
                    continue
                if ops:
                    ks.pending_ops -= len(ops)
                    self._pending_ops -= len(ops)
                    ts.pending_ops -= len(ops)
                    took_ops += len(ops)
                    obs.gauge(obs.labeled(
                        "serve.pending_ops",
                        tenant=tname)).set(ts.pending_ops)
                batch.append((ks, ops, last_seq, final,
                              self._take_recs_locked(ks, last_seq)))
            if not any(ks.pending for ks in keys):
                ts.deficit = 0
        if took_ops:
            obs.gauge("serve.pending_ops").set(self._pending_ops)
            obs.counter_sample("serve.pending_ops", self._pending_ops)
        if batch:
            self._cond.notify_all()
        return batch

    def _observe_verdicts_locked(self, ks: _Key) -> None:
        """Drain the key's admitted-delta timestamps up to its applied
        seq into the ingest->verdict SLO histogram (callers hold the
        service condition)."""
        now = self._clock()
        h = obs.histogram("serve.verdict_secs")
        ht = (obs.histogram(obs.labeled("serve.verdict_secs",
                                        tenant=ks.tenant))
              if self._tenants is not None else None)
        while ks.pending_times and ks.pending_times[0][0] \
                <= ks.applied_seq:
            _seq, t_in = ks.pending_times.popleft()
            v = max(0.0, now - t_in)
            h.observe(v)
            if ht is not None:
                ht.observe(v)

    def _crashed_entry(self, ks: _Key, err) -> dict:
        """Per-entry failure isolation: a loud error verdict, and the
        in-memory session is DROPPED so the next delta thaw-replays
        the WAL instead of extending a session that may have missed
        acknowledged ops. Without a WAL there is nothing to replay —
        the key is POISONED (further deltas refused) rather than
        silently rebuilt from a truncated history."""
        obs.counter("serve.worker_errors").inc()
        _log.exception("serve worker: key %r failed", ks.key)
        # the crash's postmortem evidence, tracing on or off (a None
        # check when the flight recorder is unarmed); the trigger
        # context names the key so the dump cross-references the
        # error verdict and any slow-delta record
        obs.flight_dump("serve-worker-error", context={
            "key": str(ks.key), "tenant": ks.tenant,
            "error": f"{type(err).__name__}: {err}"})
        ks.session = None
        if self._wal is None:
            ks.broken = True
        return {"valid?": "unknown",
                "error": f"serve worker crashed on this key: "
                         f"{type(err).__name__}: {err}"}

    def _process(self, batch: list) -> None:
        # phase 1 (no lock): apply deltas; a crash costs ONE key
        entries = []
        for ks, ops, last_seq, final, recs in batch:
            sess = err_r = None
            if ks.broken:
                # poisoned (worker crash, no WAL): keep serving the
                # error verdict; never rebuild from a truncated stream
                entries.append((ks, None, last_seq, final,
                                dict(ks.last_result or {
                                    "valid?": "unknown",
                                    "error": "key poisoned"}), recs))
                continue
            try:
                sess = self._session_for(ks)
                if ops:
                    sp_kw = {"key": str(ks.key), "ops": len(ops)}
                    if recs:
                        # the delta ids this apply advances — the
                        # worker-side link of each delta's chain
                        sp_kw["delta_ids"] = [r["id"] for r in recs]
                        sp_kw["tenant"] = ks.tenant
                    with obs.span("serve.apply", **sp_kw):
                        sess.extend(ops)
            except Exception as err:  # noqa: BLE001 — isolate per key
                err_r = self._crashed_entry(ks, err)
            entries.append((ks, sess, last_seq, final, err_r, recs))
        # phase 2 (no lock): one batched advance over the live ones
        live = [e for e in entries if e[4] is None]
        try:
            with obs.span("serve.advance", keys=len(live)):
                rs = ext.advance_sessions([e[1] for e in live],
                                          bucket=self.bucket)
            results = dict(zip((id(e[0]) for e in live), rs))
        except Exception as err:  # noqa: BLE001 — advance_sessions
            # degrades internally; anything escaping is a bug, and it
            # must cost these keys a loud verdict, not the worker
            results = {id(e[0]): self._crashed_entry(e[0], err)
                       for e in live}
        # phase 3 (no lock): finalization — counterexample extraction
        # is a real device dispatch and must not stall every other
        # key's submit/result behind the service lock
        for ks, sess, _last_seq, final, err_r, _recs in entries:
            if final and err_r is None and id(ks) in results \
                    and sess is not None:
                try:
                    results[id(ks)] = sess.finalize()
                except Exception as err:  # noqa: BLE001
                    results[id(ks)] = self._crashed_entry(ks, err)
        # phase 4: publish under the lock. t_dev_end splits each
        # delta's device stage (apply/advance/finalize above) from its
        # publish stage (this lock acquisition + bookkeeping).
        t_dev_end = self._clock()
        dump_ctx = None
        with self._cond:
            with obs.span("serve.publish", keys=len(entries)):
                for ks, sess, last_seq, final, err_r, recs in entries:
                    ks.last_result = (err_r if err_r is not None
                                      else results[id(ks)])
                    ks.needs_check = False
                    if final:
                        ks.finalized = True
                    if last_seq is not None:
                        ks.applied_seq = last_seq
                    self._observe_verdicts_locked(ks)
                    ctx = self._finish_recs_locked(ks, recs,
                                                   t_dev_end)
                    if ctx is not None:
                        dump_ctx = ctx
                    ks.last_activity = self._clock()
            self._cond.notify_all()
        if dump_ctx is not None:
            # the worst slow delta so far gets the flight ring dumped
            # with it — outside the service lock (file I/O)
            obs.flight_dump("slow-delta", context=dump_ctx)
        led = _ledger.active()
        if led is not None:
            # one evidence record per key per publish, minted OUTSIDE
            # the service lock (ledger appends are file I/O); secs is
            # the batch's publish stage — the same t_dev_end split
            # _finish_recs_locked attributes
            t_pub = self._clock()
            for ks, _sess, _last_seq, final, err_r, recs in entries:
                led.record(
                    "publish", engine="serve", key=str(ks.key),
                    tenant=ks.tenant, deltas=len(recs or ()),
                    final=bool(final), batch=len(entries),
                    secs=round(max(0.0, t_pub - t_dev_end), 6),
                    outcome={"verdict": _ledger.verdict_class(
                                 ks.last_result or {}),
                             "crashed": err_r is not None})

    def _finish_recs_locked(self, ks: _Key, recs,
                            t_dev_end: float) -> Optional[dict]:
        """Close out a batch's per-delta trace records at verdict
        publish (callers hold the condition): compute each delta's
        stage breakdown, and when ``JEPSEN_TPU_SLOW_DELTA_SECS`` is
        armed and crossed, land the structured forensics record in the
        bounded newest-wins ring (``obs.record_slow_delta``). Returns
        the record to flight-dump when one is the new worst offender
        (the CALLER dumps, outside the lock — a dump is file I/O)."""
        if not recs:
            return None
        now = self._clock()
        worst_ctx = None
        r0 = ks.last_result or {}
        for r in recs:
            t_in = r["t_in"]
            t_admit = r.get("t_admit", t_in)
            t_take = r.get("t_take", t_admit)
            total = max(0.0, now - t_in)
            if not self.slow_delta_secs \
                    or total < self.slow_delta_secs:
                continue
            # the WAL stage is a measured fsync DURATION, concurrent
            # with queue/device (the worker takes a delta without
            # waiting for its fsync — the handoff only orders WRITES
            # per key), so queue is the full admission->take wait and
            # the stages need not sum to total. A verdict published
            # while the fsync is still in flight attributes the
            # elapsed window so far (end stamp missing) — the sick-
            # disk evidence must not read wal=0.
            ws = r.get("t_wal_start")
            we = r.get("t_wal_end")
            # None-checks, not truthiness: an injectable clock may
            # legally stamp 0.0 (the fake-clock test pattern)
            wal_secs = (max(0.0, we - ws)
                        if ws is not None and we is not None
                        else max(0.0, now - ws) if ws is not None
                        else 0.0)
            stages = {
                "backpressure": max(0.0, t_admit - t_in),
                "wal": wal_secs,
                "queue": max(0.0, t_take - t_admit),
                "device": max(0.0, t_dev_end - t_take),
                "publish": max(0.0, now - t_dev_end),
            }
            doc = {"delta_id": r["id"], "key": str(ks.key),
                   "tenant": r.get("tenant"), "seq": r.get("seq"),
                   "ops": r.get("ops"),
                   "total_secs": round(total, 6),
                   "stages": {k: round(v, 6)
                              for k, v in stages.items()},
                   "slowest_stage": max(stages, key=stages.get),
                   "verdict": r0.get("valid?")}
            if r0.get("error"):
                doc["error"] = r0["error"]
            if r0.get("resilience"):
                # the degradation notes: WHY the device stage was
                # slow reads straight off the record
                doc["resilience"] = r0["resilience"]
            if r0.get("stats"):
                # the JEPSEN_TPU_SEARCH_STATS block (armed only):
                # which device program the delta was running, sized
                s = r0["stats"]
                doc["stats"] = {k: s.get(k) for k in
                                ("events", "frontier-peak",
                                 "capacity", "capacity-tier",
                                 "dedupe", "load-factor-peak",
                                 "probe-hist", "pad-waste")
                                if s.get(k) is not None}
            if obs.record_slow_delta(doc, scope=self._slow_scope):
                worst_ctx = doc
        return worst_ctx

    def _freeze_session(self, ks: _Key, locked: bool = False) -> None:
        """Freeze one key's live frontier to the checkpoint store and
        drop the in-memory session. Eviction (worker thread — the
        only session toucher, so it freezes lock-free) and graceful
        migration (any thread — the caller HOLDS the condition for
        the whole freeze so the worker cannot pick the key up
        mid-write) share this."""
        with obs.span("serve.evict", key=str(ks.key)):
            meta = ks.session.freeze(
                self._cps.checkpoint_path(ks.key))
        meta["applied_seq"] = ks.applied_seq
        meta["finalized"] = ks.finalized
        self._cps.save(ks.key, meta)
        self._write_program_manifest(ks.key)
        if locked:
            ks.session = None
        else:
            with self._cond:
                ks.session = None
        obs.counter("serve.evictions").inc()

    def _write_program_manifest(self, key) -> None:
        """Beside the frozen checkpoint pair, record the process's
        compiled-program population (parallel.programs manifest) so
        ``serve.ring.transfer_key`` ships it and the adopter pre-warms
        before replaying — the warm-handoff half of the compile-
        economics contract (docs/streaming.md). A no-op unless
        JEPSEN_TPU_COMPILE_CACHE armed the registry; best-effort —
        the freeze that just landed must not fail over telemetry."""
        reg = programs.registry()
        if reg is None:
            return
        try:
            reg.write_manifest(self._cps.manifest_path(key))
        except Exception as err:  # noqa: BLE001 — advisory artifact
            _log.warning("program manifest write failed for key %r: "
                         "%s", key, err)

    def freeze_key(self, key) -> bool:
        """Freeze one key NOW (the graceful-migration primitive —
        ``serve.ring`` transfers the checkpoint pair + WAL segments
        and the new owner thaws instead of re-scanning). False when
        there is nothing to freeze: no checkpoint store, no live
        session, or the key still has unapplied work (drain first).
        The whole freeze runs UNDER the service condition: a producer
        racing the migration (not yet re-pointed) must not land a
        delta the worker extends the session with while ``freeze()``
        is serializing it — producers block for one checkpoint write,
        an explicit operator move's acceptable cost."""
        if self._cps is None:
            return False
        with self._cond:
            ks = self._keys.get(key)
            if ks is None or ks.session is None or ks.pending \
                    or ks.needs_check:
                return False
            self._freeze_session(ks, locked=True)
        return True

    def steal_key(self, key, device=None) -> bool:
        """Migrate a mid-stream key's device placement — the serve
        half of elastic key work-stealing (JEPSEN_TPU_STEAL /
        docs/performance.md "Elastic scheduling"): an external
        scheduler that sees one device running hot (the per-key
        ``engine.search.*`` stats / ``serve.apply`` spans are the
        signal) moves whole KEYS, never mid-search state. With a
        checkpoint store the live frontier freezes through the
        eviction path and the next delta thaws it onto ``device`` —
        the FrontierCheckpoint freeze/thaw IS the migration primitive,
        bit-identical resume guaranteed by the eviction contract.
        Without one, an idle live session re-places in memory
        (HistorySession.migrate — checkpoints are host-side numpy
        either way). False when the key does not exist or still has
        unapplied work (drain first — stealing is best-effort and
        never interrupts a running scan)."""
        with self._cond:
            ks = self._keys.get(key)
            if ks is None:
                return False
            if ks.pending or ks.needs_check:
                return False
            if ks.session is not None:
                if self._cps is not None:
                    self._freeze_session(ks, locked=True)
                else:
                    ks.session.migrate(device)
            ks.device = device
        obs.counter("serve.keys_stolen").inc()
        return True

    def _maybe_evict(self) -> None:
        if self._cps is None or self.evict_idle_secs <= 0:
            return
        now = self._clock()
        with self._cond:
            victims = [ks for ks in self._keys.values()
                       if ks.session is not None and not ks.pending
                       and not ks.needs_check
                       and not (ks.finalize_requested
                                and not ks.finalized)
                       and now - ks.last_activity
                       > self.evict_idle_secs]
        for ks in victims:
            self._freeze_session(ks)
        if victims:
            with self._cond:
                live = sum(1 for k in self._keys.values()
                           if k.session is not None)
            obs.gauge("serve.keys_live").set(live)

    def _run(self) -> None:
        poll = (min(0.25, max(0.01, self.evict_idle_secs / 4))
                if self._cps is not None and self.evict_idle_secs > 0
                else 0.5)
        while True:
            with self._cond:
                while not self._stop \
                        and not self._work_available_locked():
                    self._cond.wait(timeout=poll)
                    if self._cps is not None:
                        break   # wake to run the eviction sweep
                if self._stop and not self._work_available_locked():
                    return
                batch = self._take_work_locked()
                self._inflight = len(batch)
            try:
                if batch:
                    self._process(batch)
            except Exception as err:  # noqa: BLE001 — _process
                # isolates failures per key; anything reaching here is
                # a bug in the batching itself. The worker must
                # survive it: publish loud error verdicts (accounting
                # was settled at take time) and drop the sessions so
                # the WAL replay recovers the truth on the next delta.
                t_dev_end = self._clock()
                dump_ctx = None
                # per-key postmortems FIRST, outside the cond: each
                # _crashed_entry writes a flight dump (file I/O), and
                # the publish lock below must only cover bookkeeping —
                # same contract as _process's no-lock phases
                err_rs = {id(ks): self._crashed_entry(ks, err)
                          for ks, _ops, _seq, _final, _recs in batch}
                with self._cond:
                    for ks, _ops, last_seq, _final, recs in batch:
                        ks.last_result = err_rs[id(ks)]
                        ks.needs_check = False
                        if last_seq is not None:
                            ks.applied_seq = last_seq
                        self._observe_verdicts_locked(ks)
                        ctx = self._finish_recs_locked(ks, recs,
                                                       t_dev_end)
                        if ctx is not None:
                            dump_ctx = ctx
                    self._cond.notify_all()
                if dump_ctx is not None:
                    # same contract as the _process publish path: a
                    # crashed batch's worst offender still raised the
                    # ring's high-water, so dropping its dump here
                    # would suppress every later (smaller) offender's
                    # dump too. File I/O outside the lock.
                    obs.flight_dump("slow-delta", context=dump_ctx)
            finally:
                with self._cond:
                    self._inflight = 0
                    self._cond.notify_all()
            self._maybe_evict()
