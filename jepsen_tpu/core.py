"""Test orchestration: the full lifecycle (reference:
jepsen/src/jepsen/core.clj `run!`, core.clj:276-382).

A *test map* is one flat dict carrying config and live objects alike
(the contract documented at core.clj:277-300):

    name         test name for the store directory
    nodes        list of node names
    concurrency  number of client worker threads
    ssh / remote transport config (ssh: {"dummy": True} for no cluster)
    os           OS protocol impl (jepsen_tpu.os)
    db           DB protocol impl (jepsen_tpu.db)
    net          Net protocol impl (jepsen_tpu.net)
    client       Client protocol impl
    nemesis      Nemesis protocol impl
    generator    the workload
    checker      Checker protocol impl
    model        optional model for checkers

`run(test)` executes the 10-step lifecycle: logging, sessions, OS setup,
DB cycle, client/nemesis setup, interpreter, log snarfing, teardown,
history save, analysis. `analyze(test, history)` is the re-check path
(core.clj:223-238) — the fastest dev loop, no cluster needed.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from jepsen_tpu import control as c
from jepsen_tpu import db as jdb
from jepsen_tpu import store as jstore
from jepsen_tpu.checker.core import check_safe
from jepsen_tpu.generator import interpreter
from jepsen_tpu.history import History
from jepsen_tpu.util import reset_relative_time

log = logging.getLogger("jepsen")


DEFAULTS: Dict[str, Any] = {
    # tests.clj:12-25 noop-test defaults
    "name": "noop",
    "nodes": ["n1", "n2", "n3", "n4", "n5"],
    "concurrency": 5,
    "ssh": {"dummy": True},
}


def make_test(overrides: Optional[Dict] = None) -> Dict:
    """Base test map merged with overrides (tests.clj:12-25 pattern)."""
    from jepsen_tpu import client as jclient
    from jepsen_tpu import net as jnet
    from jepsen_tpu import nemesis as jnemesis
    from jepsen_tpu import os as jos
    from jepsen_tpu.checker.core import noop as noop_checker

    t = dict(DEFAULTS)
    t.update({
        "os": jos.noop(),
        "db": jdb.noop(),
        "net": jnet.noop(),
        "client": jclient.noop(),
        "nemesis": jnemesis.noop(),
        "generator": None,
        "checker": noop_checker(),
    })
    t.update(overrides or {})
    return t


def primary(test: Dict):
    """The first node (core.clj:66-69)."""
    nodes = test.get("nodes") or []
    return nodes[0] if nodes else None


def snarf_logs(test: Dict):
    """Download DB log files from each node into the store
    (core.clj:103-149)."""
    db = test.get("db")
    store: Optional[jstore.Store] = test.get("store")
    if store is None or db is None:
        return
    lf = getattr(db, "log_files", None)
    if lf is None:
        return

    def snarf(t, node):
        for path in lf(test, node) or []:
            try:
                c.download([path], store.path(node, path.split("/")[-1]))
            except Exception as e:  # noqa: BLE001
                log.warning("couldn't snarf %s from %s: %s", path, node, e)

    c.on_nodes(test, snarf)


def run_case(test: Dict) -> History:
    """Client/nemesis setup, interpreter, teardown (core.clj:182-221)."""
    client = test.get("client")
    nemesis = test.get("nemesis")
    nodes = test.get("nodes") or [None]

    # open + setup ONE client on the first node for the setup/teardown
    # lifecycle (core.clj:182-199); the interpreter opens its own
    # per-worker clients, so more opens here would be pure churn
    setup_client = None
    try:
        if client is not None:
            setup_client = client.open(test, nodes[0])
            setup_client.setup(test)
        if nemesis is not None:
            test["nemesis"] = nemesis = nemesis.setup(test)

        return interpreter.run(test)
    finally:
        try:
            if nemesis is not None:
                nemesis.teardown(test)
        finally:
            if setup_client is not None:
                try:
                    setup_client.teardown(test)
                except Exception:  # noqa: BLE001
                    pass
                try:
                    setup_client.close(test)
                except Exception:  # noqa: BLE001
                    pass


def analyze(test: Dict, history: History) -> Dict:
    """Index the history, run the checker, persist results
    (core.clj:223-238)."""
    history.index()
    checker = test.get("checker")
    if checker is None:
        results = {"valid?": True}
    else:
        results = check_safe(checker, test, history)
    store: Optional[jstore.Store] = test.get("store")
    if store is not None:
        store.save_2(results)
        # span/metric artifacts ride the same run dir as the results
        # they describe (no-op unless JEPSEN_TPU_TRACE is on)
        store.save_telemetry()
    test["results"] = results
    return results


def run(test: Dict) -> Dict:
    """The full lifecycle (core.clj:276-382). Returns the completed test
    map with :history and :results."""
    test = dict(test)
    store = jstore.Store(test.get("name", "test"))
    test["store"] = store
    store.start_logging()
    reset_relative_time()
    log.info("Running test: %s", test.get("name"))
    try:
        with c.with_sessions(test):
            os_ = test.get("os")
            db = test.get("db")
            try:
                if os_ is not None:
                    c.on_nodes(test, os_.setup)
                if db is not None:
                    jdb.cycle(db, test)
                history = run_case(test)
                log.info("Run complete, writing history")
                test["history"] = history
                store.save_1(test, history)
                snarf_logs(test)
            finally:
                try:
                    if db is not None:
                        c.on_nodes(test, db.teardown)
                    if os_ is not None:
                        c.on_nodes(test, os_.teardown)
                except Exception as e:  # noqa: BLE001
                    log.warning("teardown failed: %s", e)
        log.info("Analyzing history")
        results = analyze(test, test["history"])
        log.info("Analysis complete: valid? = %s", results.get("valid?"))
        return test
    finally:
        store.stop_logging()
