"""Combined nemesis packages (reference: jepsen/src/jepsen/nemesis/combined.clj).

A *package* is a dict {nemesis, generator, final_generator, perf}
composing a fault's nemesis with the generator that schedules it and
the perf-graph legend describing it (combined.clj:8-15,295-341). The
algebra: build one package per enabled fault (partition / kill / pause
/ clock), then `compose_packages` mixes the generators, sequences the
final generators, and :f-routes one composed nemesis."""

from __future__ import annotations

from typing import Optional, Sequence

from jepsen_tpu import control as c
from jepsen_tpu import db as _db
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as n
from jepsen_tpu.nemesis import time as nt
from jepsen_tpu.util import majority, minority_third, random_nonempty_subset

DEFAULT_INTERVAL = 10  # seconds between nemesis ops (combined.clj:26-28)


# ----------------------------------------------------------- node specs


def db_nodes(test: dict, db, node_spec) -> list:
    """Resolve a node specification to concrete nodes
    (combined.clj:30-53). Specs: None (random nonempty subset), "one",
    "minority", "majority", "minority-third", "primaries", "all", or an
    explicit list of nodes."""
    nodes = list(test.get("nodes") or [])
    if node_spec is None:
        return random_nonempty_subset(nodes)
    if node_spec == "one":
        return [gen.rand.choice(nodes)]
    if node_spec == "minority":
        k = majority(len(nodes)) - 1
        return _shuffled(nodes)[:k]
    if node_spec == "majority":
        return _shuffled(nodes)[:majority(len(nodes))]
    if node_spec == "minority-third":
        return _shuffled(nodes)[:minority_third(len(nodes))]
    if node_spec == "primaries":
        assert isinstance(db, _db.Primary), "db has no Primary support"
        return random_nonempty_subset(db.primaries(test))
    if node_spec == "all":
        return nodes
    return list(node_spec)


def node_specs(db) -> list:
    """All specs valid for this DB (combined.clj:55-60)."""
    specs = [None, "one", "minority-third", "minority", "majority", "all"]
    if isinstance(db, _db.Primary):
        specs.append("primaries")
    return specs


_shuffled = n._shuffled


# ----------------------------------------------------- db start/kill/pause


class DbNemesis(n.Nemesis):
    """start/kill/pause/resume the DB's process on a node spec
    (combined.clj:62-90)."""

    def __init__(self, db):
        self.db = db

    def invoke(self, test, op):
        f = op.get("f")
        fns = {"start": lambda t, node: self.db.start(t, node),
               "kill": lambda t, node: self.db.kill(t, node),
               "pause": lambda t, node: self.db.pause(t, node),
               "resume": lambda t, node: self.db.resume(t, node)}
        if f not in fns:
            raise ValueError(f"db nemesis doesn't handle :f {f!r}")
        nodes = db_nodes(test, self.db, op.get("value"))
        res = c.on_nodes(test, fns[f], nodes)
        out = n._ok(op)
        out["value"] = res
        return out

    def fs(self):
        return {"start", "kill", "pause", "resume"}


def db_generators(opts: dict) -> dict:
    """{:generator :final-generator} for DB process faults
    (combined.clj:92-131)."""
    db = opts["db"]
    faults = set(opts.get("faults") or ())
    kill = isinstance(db, _db.Process) and "kill" in faults
    pause = isinstance(db, _db.Pause) and "pause" in faults

    kill_targets = (opts.get("kill") or {}).get("targets") or node_specs(db)
    pause_targets = (opts.get("pause") or {}).get("targets") or node_specs(db)

    start = {"type": "info", "f": "start", "value": "all"}
    resume = {"type": "info", "f": "resume", "value": "all"}

    def kill_op(test, ctx):
        return {"type": "info", "f": "kill",
                "value": gen.rand.choice(kill_targets)}

    def pause_op(test, ctx):
        return {"type": "info", "f": "pause",
                "value": gen.rand.choice(pause_targets)}

    modes, final = [], []
    if pause:
        modes.append(gen.flip_flop(pause_op, gen.repeat(resume)))
        final.append(resume)
    if kill:
        modes.append(gen.flip_flop(kill_op, gen.repeat(start)))
        final.append(start)
    return {"generator": gen.mix(modes) if modes else None,
            "final_generator": final}


def db_package(opts: dict) -> Optional[dict]:
    """Package for DB process faults, or None when neither kill nor
    pause is enabled (combined.clj:133-152)."""
    faults = set(opts.get("faults") or ())
    if not faults & {"kill", "pause"}:
        return None
    gens = db_generators(opts)
    interval = opts.get("interval", DEFAULT_INTERVAL)
    return {"generator": gen.stagger(interval, gens["generator"]),
            "final_generator": gens["final_generator"],
            "nemesis": DbNemesis(opts["db"]),
            "perf": [{"name": "kill", "start": {"kill"},
                      "stop": {"start"}, "color": "#E9A4A0"},
                     {"name": "pause", "start": {"pause"},
                      "stop": {"resume"}, "color": "#A0B1E9"}]}


# ----------------------------------------------------------- partitions


def grudge(test: dict, db, part_spec):
    """Compute a grudge from a partition spec (combined.clj:154-180).
    None isolates a random proper nonempty subset."""
    nodes = list(test.get("nodes") or [])
    if part_spec is None:
        k = gen.rand.randint(1, max(1, len(nodes) - 1))
        shuf = _shuffled(nodes)
        return n.complete_grudge([shuf[:k], shuf[k:]])
    if part_spec == "one":
        return n.complete_grudge(n.split_one(nodes))
    if part_spec == "majority":
        return n.complete_grudge(n.bisect(_shuffled(nodes)))
    if part_spec == "majorities-ring":
        return n.majorities_ring(nodes)
    if part_spec == "minority-third":
        k = minority_third(len(nodes))
        shuf = _shuffled(nodes)
        return n.complete_grudge([shuf[:k], shuf[k:]])
    if part_spec == "primaries":
        assert isinstance(db, _db.Primary), "db has no Primary support"
        prim = random_nonempty_subset(db.primaries(test))
        others = [x for x in nodes if x not in set(prim)]
        return n.complete_grudge([others] + [[p] for p in prim])
    return part_spec  # already a grudge map


def partition_specs(db) -> list:
    """(combined.clj:182-186)."""
    specs = [None, "one", "majority", "majorities-ring"]
    if isinstance(db, _db.Primary):
        specs.append("primaries")
    return specs


class PartitionNemesis(n.Nemesis):
    """Wraps a Partitioner with partition-spec support
    (combined.clj:188-216). Handles :start-partition/:stop-partition."""

    def __init__(self, db, partitioner: Optional[n.Partitioner] = None):
        self.db = db
        self.p = partitioner or n.partitioner()

    def setup(self, test):
        self.p = self.p.setup(test)
        return self

    def invoke(self, test, op):
        f = op.get("f")
        inner = dict(op)
        if f == "start-partition":
            g = op.get("value")
            if g is None or isinstance(g, str):
                g = grudge(test, self.db, g)
            inner.update(f="start", value=g)
        elif f == "stop-partition":
            inner.update(f="stop", value=None)
        else:
            raise ValueError(f"partition nemesis doesn't handle :f {f!r}")
        from jepsen_tpu.history import Op
        res = self.p.invoke(test, Op(inner))
        out = n._ok(res)
        out["f"] = f
        return out

    def teardown(self, test):
        self.p.teardown(test)

    def fs(self):
        return {"start-partition", "stop-partition"}


def partition_package(opts: dict) -> Optional[dict]:
    """(combined.clj:218-238)."""
    if "partition" not in set(opts.get("faults") or ()):
        return None
    db = opts["db"]
    targets = ((opts.get("partition") or {}).get("targets")
               or partition_specs(db))

    def start(test, ctx):
        return {"type": "info", "f": "start-partition",
                "value": gen.rand.choice(targets)}

    stop = {"type": "info", "f": "stop-partition", "value": None}
    interval = opts.get("interval", DEFAULT_INTERVAL)
    g = gen.stagger(interval, gen.flip_flop(start, gen.repeat(stop)))
    return {"generator": g,
            "final_generator": stop,
            "nemesis": PartitionNemesis(db),
            "perf": [{"name": "partition", "start": {"start-partition"},
                      "stop": {"stop-partition"}, "color": "#E9DCA0"}]}


# --------------------------------------------------------------- clocks


def clock_package(opts: dict) -> Optional[dict]:
    """Clock-skew package; renames the clock nemesis fs so they can't
    collide with other packages' (combined.clj:240-272)."""
    if "clock" not in set(opts.get("faults") or ()):
        return None
    db = opts["db"]
    nem = n.compose([({"reset-clock": "reset",
                       "check-clock-offsets": "check-offsets",
                       "strobe-clock": "strobe",
                       "bump-clock": "bump"}, nt.clock_nemesis())])
    target_specs = (opts.get("clock") or {}).get("targets") or node_specs(db)

    def targets(test):
        spec = gen.rand.choice(target_specs) if target_specs else None
        return db_nodes(test, db, spec)

    g = gen.f_map({"reset": "reset-clock",
                   "check-offsets": "check-clock-offsets",
                   "strobe": "strobe-clock",
                   "bump": "bump-clock"},
                  nt.clock_gen(targets))
    interval = opts.get("interval", DEFAULT_INTERVAL)
    return {"generator": gen.stagger(interval, g),
            "final_generator": {"type": "info", "f": "reset-clock"},
            "nemesis": nem,
            "perf": [{"name": "clock", "start": {"bump-clock"},
                      "stop": {"reset-clock"}, "fs": {"strobe-clock"},
                      "color": "#A0E9E3"}]}


# ---------------------------------------------------------- composition


def compose_packages(packages: Sequence[dict]) -> dict:
    """Mix generators, sequence final generators, :f-route nemeses,
    union perf legends (combined.clj:274-283)."""
    packages = [p for p in packages if p]
    return {"generator": gen.mix([p["generator"] for p in packages
                                  if p.get("generator") is not None]),
            "final_generator": [p["final_generator"] for p in packages
                                if p.get("final_generator") is not None],
            "nemesis": n.compose([(p["nemesis"].fs(), p["nemesis"])
                                  for p in packages]),
            "perf": [spec for p in packages for spec in p.get("perf", [])]}


def nemesis_packages(opts: dict) -> list:
    """One package per enabled fault (combined.clj:285-293)."""
    faults = set(opts["faults"] if "faults" in opts
                 else ["partition", "kill", "pause", "clock"])
    opts = dict(opts, faults=faults)
    pkgs = [partition_package(opts), clock_package(opts), db_package(opts)]
    try:  # membership is optional and opt-in (membership.clj:254-266)
        from jepsen_tpu.nemesis import membership as _membership
        pkgs.append(_membership.package(opts))
    except ImportError:  # pragma: no cover
        pass
    return [p for p in pkgs if p]


def nemesis_package(opts: dict) -> dict:
    """The one-stop combined package (combined.clj:295-341). Options:
    :db (required), :interval, :faults, and per-fault target options
    {:partition {:targets [...]}, :kill {...}, :pause {...},
    :clock {...}}."""
    return compose_packages(nemesis_packages(opts))
