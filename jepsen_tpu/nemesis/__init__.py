"""Nemesis protocol and fault-injection primitives
(reference: jepsen/src/jepsen/nemesis.clj).

A nemesis is a special client that injects faults into the cluster
rather than applying ops to the data plane. Protocol
(nemesis.clj:10-20): setup / invoke / teardown, plus an optional `fs()`
reflection method enumerating which :f values it handles (used by
composition and the combined packages).

Grudge-based network partitions: a *grudge* is a map
node -> collection-of-nodes-to-drop (nemesis.clj:100-135); `partitioner`
applies one via net.drop_all (nemesis.clj:137-163).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from jepsen_tpu import generator as _generator
from jepsen_tpu.history import Op
from jepsen_tpu.util import majority


class Nemesis:
    def setup(self, test) -> "Nemesis":
        return self

    def invoke(self, test, op: Op) -> Op:
        raise NotImplementedError

    def teardown(self, test) -> None:
        pass

    def fs(self) -> Optional[set]:
        """The set of :f values this nemesis handles (Reflection,
        nemesis.clj:17-20); None = unknown."""
        return None


class Noop(Nemesis):
    """Does nothing (nemesis.clj:22-27)."""

    def invoke(self, test, op):
        return _ok(op)

    def fs(self):
        return set()


def noop() -> Noop:
    return Noop()


class Validate(Nemesis):
    """Checks completions parallel jepsen.client/validate
    (nemesis.clj:29-70)."""

    def __init__(self, nemesis: Nemesis):
        self.nemesis = nemesis

    def setup(self, test):
        return Validate(self.nemesis.setup(test))

    def invoke(self, test, op):
        res = self.nemesis.invoke(test, op)
        if not isinstance(res, dict):
            raise RuntimeError(
                f"Nemesis returned {res!r} for {op!r}: not an op map")
        return res

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return self.nemesis.fs()


def validate(nemesis: Nemesis) -> Validate:
    return Validate(nemesis)


def _ok(op: Op, value=None) -> Op:
    o = Op(op)
    o["type"] = "info"  # nemesis completions are :info by convention
    if value is not None:
        o["value"] = value
    return o


# ------------------------------------------------------------- grudges


def complete_grudge(components: Sequence[Sequence]) -> Dict:
    """Takes a collection of node components; returns a grudge where
    every node drops traffic from every node outside its component
    (nemesis.clj:100-112)."""
    out: Dict = {}
    all_nodes = [n for comp in components for n in comp]
    for comp in components:
        others = [n for n in all_nodes if n not in comp]
        for node in comp:
            out[node] = list(others)
    return out


def bridge(nodes: Sequence) -> Dict:
    """Splits nodes into two halves joined by a single bridge node that
    can see both (nemesis.clj:114-135)."""
    ns = list(nodes)
    m = len(ns) // 2
    bridge_node, left, right = ns[m], ns[:m], ns[m + 1:]
    grudge = {}
    for node in left:
        grudge[node] = list(right)
    for node in right:
        grudge[node] = list(left)
    grudge[bridge_node] = []
    return grudge


def split_one(nodes: Sequence, node=None) -> List[List]:
    """Isolate one node (given or random) from the rest
    (nemesis.clj:165-172 `partition-random-node`)."""
    ns = list(nodes)
    n = node if node is not None else _generator.rand.choice(ns)
    return [[n], [x for x in ns if x != n]]


def split_halves(nodes: Sequence) -> List[List]:
    """Random [minority-half, majority-half] (nemesis.clj:85-98 bisect
    over a shuffle)."""
    ns = list(nodes)
    _generator.rand.shuffle(ns)
    return bisect(ns)


def bisect(xs: Sequence) -> List[List]:
    """Split into [smaller-half, larger-half] (nemesis.clj:79-83)."""
    xs = list(xs)
    m = len(xs) // 2
    return [xs[:m], xs[m:]]


def majorities_ring(nodes: Sequence) -> Dict:
    """A grudge where every node sees a majority, but no two nodes see
    the same majority — the overlapping-rings partition. Exact for ≤5
    nodes, stochastic for larger clusters (nemesis.clj:183-261)."""
    ns = list(nodes)
    n = len(ns)
    if n <= 5:
        return _majorities_ring_perfect(ns)
    return _majorities_ring_stochastic(ns)


def _majorities_ring_perfect(ns: List) -> Dict:
    n = len(ns)
    m = majority(n)
    grudge = {}
    for i, node in enumerate(ns):
        # node i sees the m nodes centred on it in ring order
        visible = {ns[(i + d) % n] for d in range(-(m // 2), m - m // 2)}
        visible.add(node)
        grudge[node] = [x for x in ns if x not in visible]
    return grudge


def _majorities_ring_stochastic(ns: List) -> Dict:
    n = len(ns)
    m = majority(n)
    for _ in range(1000):
        grudge = {}
        ok = True
        seen_majorities = set()
        for node in ns:
            others = [x for x in ns if x != node]
            _generator.rand.shuffle(others)
            visible = frozenset([node] + others[:m - 1])
            if visible in seen_majorities:
                ok = False
                break
            seen_majorities.add(visible)
            grudge[node] = [x for x in ns if x not in visible]
        if ok:
            return grudge
    raise RuntimeError("couldn't find distinct majorities")


class Partitioner(Nemesis):
    """Responds to {:f :start, :value grudge-or-nil} by partitioning the
    network per the grudge (or (grudge-fn nodes)), and {:f :stop} by
    healing (nemesis.clj:137-163)."""

    def __init__(self, grudge_fn: Optional[Callable] = None):
        self.grudge_fn = grudge_fn

    def setup(self, test):
        _net(test).heal(test)
        return self

    def invoke(self, test, op):
        f = op.get("f")
        if f == "start":
            grudge = op.get("value")
            if grudge is None:
                assert self.grudge_fn is not None, \
                    "no grudge in op and no grudge function"
                grudge = self.grudge_fn(test["nodes"])
            _net(test).drop_all(test, grudge)
            return _ok(op, value=f"Cut off {grudge!r}")
        if f == "stop":
            _net(test).heal(test)
            return _ok(op, value="fully connected")
        raise ValueError(f"partitioner doesn't handle :f {f!r}")

    def teardown(self, test):
        _net(test).heal(test)

    def fs(self):
        return {"start", "stop"}


def _net(test):
    net = test.get("net")
    assert net is not None, "test map has no :net"
    return net


def partitioner(grudge_fn: Optional[Callable] = None) -> Partitioner:
    return Partitioner(grudge_fn)


def partition_halves() -> Partitioner:
    """Partition into two halves at :start (nemesis.clj:165-170)."""
    return Partitioner(lambda nodes: complete_grudge(bisect(_shuffled(nodes))))


def partition_random_halves() -> Partitioner:
    return partition_halves()


def partition_random_node() -> Partitioner:
    """Isolate a single random node (nemesis.clj:172-180)."""
    return Partitioner(lambda nodes: complete_grudge(split_one(nodes)))


def partition_majorities_ring() -> Partitioner:
    """Every node sees a distinct majority (nemesis.clj:255-261)."""
    return Partitioner(majorities_ring)


def _shuffled(nodes):
    ns = list(nodes)
    _generator.rand.shuffle(ns)
    return ns


# ----------------------------------------------------------- processes


class NodeStartStopper(Nemesis):
    """On {:f start}, runs stop-fn! on targeted nodes (e.g. kill/pause);
    on {:f stop}, runs start-fn! on the affected nodes
    (nemesis.clj:370-429 `node-start-stopper`)."""

    def __init__(self, targeter: Callable, start_fn: Callable, stop_fn: Callable):
        self.targeter = targeter
        self.start_fn = start_fn
        self.stop_fn = stop_fn
        self.affected: Optional[list] = None  # None = not disrupting

    def invoke(self, test, op):
        f = op.get("f")
        if f == "start":
            if self.affected is not None:
                # compare-and-set! guard (nemesis.clj:388-396): refuse to
                # stack disruptions, which would leak stopped nodes.
                return _ok(op, value="nemesis already disrupting "
                                     + repr(self.affected))
            nodes = self.targeter(test["nodes"])
            if not isinstance(nodes, (list, tuple)):
                nodes = [nodes]
            self.affected = list(nodes)
            results = {n: self.start_fn(test, n) for n in nodes}
            return _ok(op, value=results)
        if f == "stop":
            results = {n: self.stop_fn(test, n) for n in (self.affected or [])}
            self.affected = None
            return _ok(op, value=results)
        raise ValueError(f"node-start-stopper doesn't handle :f {f!r}")

    def teardown(self, test):
        # Resume anything still disrupted so a stopped process never
        # outlives the test.
        for n in (self.affected or []):
            try:
                self.stop_fn(test, n)
            except Exception:  # noqa: BLE001
                pass
        self.affected = None

    def fs(self):
        return {"start", "stop"}


def hammer_time(targeter=None, process: str = "db") -> NodeStartStopper:
    """SIGSTOP/SIGCONT the given process name on a random node
    (nemesis.clj:411-429)."""
    targeter = targeter or (lambda nodes: _generator.rand.choice(list(nodes)))

    def pause(test, node):
        _control(test).on(node, ["killall", "-s", "STOP", process])
        return "paused"

    def resume(test, node):
        _control(test).on(node, ["killall", "-s", "CONT", process])
        return "resumed"

    return NodeStartStopper(targeter, pause, resume)


def _control(test):
    c = test.get("control")
    assert c is not None, "test map has no :control (remote runner)"
    return c


class Truncator(Nemesis):
    """Truncates the tail of a file on random nodes: {:f :truncate,
    :value {node: {:file f, :bytes n}}} (nemesis.clj:431-457)."""

    def invoke(self, test, op):
        plan = op.get("value") or {}
        for node, spec in plan.items():
            _control(test).on(
                node, ["truncate", "-c", "-s", f"-{spec['bytes']}",
                       spec["file"]])
        return _ok(op)

    def fs(self):
        return {"truncate"}


def truncate_file() -> Truncator:
    return Truncator()


# --------------------------------------------------------- composition


class Compose(Nemesis):
    """Routes ops to sub-nemeses by :f (nemesis.clj:263-346). Takes a
    sequence of (route, nemesis) pairs where route is either a set of fs
    handled directly, or a dict renaming outer fs to the inner fs the
    sub-nemesis understands (the reference's {fs-or-fmap: nemesis} map
    form; Python dicts are unhashable as keys, so pairs it is —
    `compose` also accepts a dict whose keys are frozensets/tuples)."""

    def __init__(self, routes):
        self.routes = list(routes)  # [(set-or-dict, nemesis)]

    def setup(self, test):
        return Compose([(k, n.setup(test)) for k, n in self.routes])

    def _route(self, f):
        for k, n in self.routes:
            if isinstance(k, dict):
                if f in k:
                    return n, k[f]
            elif f in k:
                return n, f
        raise ValueError(f"no nemesis handles :f {f!r} "
                         f"(have {[k for k, _ in self.routes]!r})")

    def invoke(self, test, op):
        n, inner_f = self._route(op.get("f"))
        inner = Op(op)
        inner["f"] = inner_f
        res = n.invoke(test, inner)
        out = Op(res)
        out["f"] = op.get("f")
        return out

    def teardown(self, test):
        for _, n in self.routes:
            n.teardown(test)

    def fs(self):
        out = set()
        for k, _ in self.routes:
            out |= set(k)
        return out


def compose(nemeses) -> Compose:
    """nemeses: dict {hashable-route: nemesis} or iterable of
    (route, nemesis) pairs (routes may be dicts in pair form)."""
    if isinstance(nemeses, dict):
        return Compose(nemeses.items())
    return Compose(nemeses)
