"""Membership-change nemesis: add/remove nodes from a live cluster
(reference: jepsen/src/jepsen/nemesis/membership.clj + membership/state.clj).

Cluster membership is the hardest fault to standardize: Jepsen's view,
each node's view, and reality all diverge. The design (membership.clj:
1-47): a State object tracks {node_views, view, pending}; background
pollers refresh each node's view every few seconds; a generator asks
the state for legal next ops; invoke applies an op and remembers it as
pending until `resolve_op` can prove it completed.

State protocol (membership/state.clj:6-32): node_view / merge_views /
fs / op / invoke / resolve / resolve_op."""

from __future__ import annotations

import threading
import time as _time
from typing import Optional

from jepsen_tpu import generator as gen
from jepsen_tpu.history import Op
from jepsen_tpu.nemesis import Nemesis

NODE_VIEW_INTERVAL = 5  # seconds between node-view refreshes


class State:
    """Membership state machine protocol (membership/state.clj:6-32).
    Implementations are immutable-ish: mutating methods return a new
    (or the same) State. The framework adds the bookkeeping keys
    node_views / view / pending via attributes on the instance."""

    node_views: dict
    view: object
    pending: tuple  # of (invoke_op, completion_op) dict pairs

    def node_view(self, test, node):
        """This node's current view of the cluster, or None if unknown."""
        raise NotImplementedError

    def merge_views(self, test):
        """Merge self.node_views into one authoritative view."""
        raise NotImplementedError

    def fs(self) -> set:
        """All op :f values this state machine can generate."""
        raise NotImplementedError

    def op(self, test):
        """Next operation dict to perform, "pending" if none available
        now, or None if no ops can ever be performed again."""
        raise NotImplementedError

    def invoke(self, test, op: Op) -> Op:
        """Apply a generated operation; return the completed op."""
        raise NotImplementedError

    def resolve(self, test) -> "State":
        """Evolve toward a fixed point (e.g. fold a confirmed change
        into the view). Must be idempotent at the fixed point."""
        return self

    def resolve_op(self, test, op_pair) -> Optional["State"]:
        """Given [invocation, completion], return the state with that
        op considered complete, or None if it is still pending."""
        return None

    # -- bookkeeping helpers (shared by all implementations) ----------

    def with_updates(self, **kw) -> "State":
        import copy
        s = copy.copy(self)
        for k, v in kw.items():
            setattr(s, k, v)
        return s


def initial_bookkeeping() -> dict:
    """The framework-owned part of the state (membership.clj:68-77)."""
    return {"node_views": {}, "view": None, "pending": ()}


def _resolve_ops(state: State, test, opts) -> State:
    """Try to resolve every pending [op, op'] pair
    (membership.clj:79-93). Pairs are (invocation, completion) dicts,
    exactly as invoke recorded them."""
    for pair in list(state.pending):
        s2 = state.resolve_op(test, [pair[0], pair[1]])
        if s2 is not None:
            state = s2.with_updates(
                pending=tuple(p for p in s2.pending if p is not pair))
    return state


def resolve(state: State, test, opts=None) -> State:
    """resolve + resolve_ops to a fixed point (membership.clj:95-107)."""
    opts = opts or {}

    def step(s):
        return _resolve_ops(s.resolve(test), test, opts)

    # States aren't required to be value-comparable; iterate until the
    # pending set and view stop changing.
    prev = None
    for _ in range(1000):
        cur = step(state)
        key = (repr(getattr(cur, "pending", None)),
               repr(getattr(cur, "view", None)))
        if key == prev:
            return cur
        prev = key
        state = cur
    raise RuntimeError("membership resolve did not converge")


class MembershipNemesis(Nemesis):
    """(membership.clj:159-206). Holds the state under a lock; spawns a
    poller thread per node refreshing node views."""

    def __init__(self, state: State, opts: Optional[dict] = None):
        self.lock = threading.RLock()
        self.state = state
        self.opts = opts or {}
        self.running = threading.Event()
        self._stop_signal = threading.Event()  # set at teardown: wakes pollers
        self.pollers: list = []

    # -- view maintenance --------------------------------------------

    def _update_node_view(self, test, node):
        """Poll one node and merge its view in (membership.clj:109-140)."""
        with self.lock:
            state = self.state
        nv = state.node_view(test, node)
        if nv is None:
            return
        with self.lock:
            views = dict(self.state.node_views)
            views[node] = nv
            s = self.state.with_updates(node_views=views)
            s = s.with_updates(view=s.merge_views(test))
            self.state = resolve(s, test, self.opts)

    def _poller(self, test, node):
        while self.running.is_set():
            try:
                self._update_node_view(test, node)
            except Exception:  # noqa: BLE001 - keep polling (clj:150-156)
                pass
            # Sleep in small slices so teardown is prompt.
            interval = self.opts.get("node_view_interval",
                                     NODE_VIEW_INTERVAL)
            deadline = _time.monotonic() + interval
            while self.running.is_set():
                left = deadline - _time.monotonic()
                if left <= 0:
                    break
                self._stop_signal.wait(min(0.1, left))

    # -- Nemesis protocol --------------------------------------------

    def setup(self, test):
        with self.lock:
            updates = {k: v for k, v in initial_bookkeeping().items()
                       if getattr(self.state, k, None) is None}
            if updates:
                self.state = self.state.with_updates(**updates)
        self.running.set()
        self._stop_signal.clear()
        self.pollers = []
        for node in test.get("nodes") or []:
            t = threading.Thread(target=self._poller, args=(test, node),
                                 daemon=True,
                                 name=f"membership-view-{node}")
            t.start()
            self.pollers.append(t)
        return self

    def invoke(self, test, op):
        with self.lock:
            state = self.state
        op2 = state.invoke(test, op)
        with self.lock:
            pending = tuple(self.state.pending) + ((dict(op), dict(op2)),)
            s = self.state.with_updates(pending=pending)
            self.state = resolve(s, test, self.opts)
        return op2

    def teardown(self, test):
        self.running.clear()
        self._stop_signal.set()
        for t in self.pollers:
            t.join(timeout=2)
        self.pollers = []

    def fs(self):
        return set(self.state.fs())




class MembershipGenerator(gen.Generator):
    """Asks the shared state for the next legal op
    (membership.clj:208-218)."""

    def __init__(self, nemesis: MembershipNemesis):
        self.nemesis = nemesis

    def op(self, test, ctx):
        with self.nemesis.lock:
            state = self.nemesis.state
        o = state.op(test)
        if o is None:
            return None
        if o == "pending":
            return gen.PENDING, self
        return gen.fill_in_op(dict(o), ctx), self

    def update(self, test, ctx, event):
        return self


def package(opts: dict) -> Optional[dict]:
    """Package for combined-nemesis composition (membership.clj:220-266).
    opts: {faults: {..., "membership"}, membership: {state: State,
    interval, node_view_interval, ...}}."""
    if "membership" not in set(opts.get("faults") or ()):
        return None
    mopts = dict(opts.get("membership") or {})
    state = mopts.pop("state")
    nem = MembershipNemesis(state, mopts)
    g = gen.stagger(opts.get("interval", 10), MembershipGenerator(nem))
    return {"generator": g,
            "final_generator": None,
            "nemesis": nem,
            "perf": [{"name": "membership",
                      "fs": set(state.fs()),
                      "color": "#A0E9B6"}]}
