"""Clock-manipulation nemesis (reference: jepsen/src/jepsen/nemesis/time.clj).

Messes with node wall clocks four ways (nemesis/time.clj:89-139):

    {:f :reset,  :value [node1 ...]}                       # back to NTP
    {:f :bump,   :value {node: delta-ms, ...}}             # one-shot skew
    {:f :strobe, :value {node: {:delta :period :duration}}}# oscillation
    {:f :check-offsets}                                    # measure only

The heavy lifting happens in two small C programs (this repo's
jepsen_tpu/resources/{bump,strobe}-time.c, paralleling the reference's
jepsen/resources/*.c) which are uploaded to each node and compiled with
the *node's* gcc at nemesis setup, exactly as the reference does
(nemesis/time.clj:14-52) — nodes may be a different architecture or
libc than the control host, so shipping source beats shipping binaries.

Every completion op carries :clock-offsets {node: seconds}, consumed by
the clock-skew plot (checker/clock.clj:47-75 parallel)."""

from __future__ import annotations

import math
import time as _time
from pathlib import Path
from typing import Callable, Optional

from jepsen_tpu import control as c
from jepsen_tpu import generator as gen
from jepsen_tpu.history import Op
from jepsen_tpu.nemesis import Nemesis, _ok
from jepsen_tpu.util import random_nonempty_subset

RESOURCE_DIR = Path(__file__).resolve().parent.parent / "resources"
INSTALL_DIR = "/opt/jepsen"


# --------------------------------------------- on-node tool management
# All of these assume an ambient control session (c.on_host) — they are
# called from inside c.on_nodes thunks, like the reference's c/su forms.


def compile_tool(src: str, bin_name: str) -> str:
    """Uploads resources/<src> to the current node and compiles it to
    /opt/jepsen/<bin_name> (nemesis/time.clj:14-30)."""
    with c.su():
        c.exec_("mkdir", "-p", INSTALL_DIR)
        c.exec_("chmod", "a+rwx", INSTALL_DIR)
        c.upload([str(RESOURCE_DIR / src)], f"{INSTALL_DIR}/{bin_name}.c")
        with c.cd(INSTALL_DIR):
            c.exec_("gcc", "-O2", "-o", bin_name, f"{bin_name}.c")
    return bin_name


def install() -> None:
    """Uploads and compiles the clock tools on the current node
    (nemesis/time.clj:38-52). Tries a build-essential install on
    failure, as the reference does, then retries once."""
    try:
        compile_tool("strobe-time.c", "strobe-time")
        compile_tool("bump-time.c", "bump-time")
    except Exception:  # noqa: BLE001 - node may lack a compiler
        with c.su():
            try:
                c.exec_("apt-get", "install", "-y", "build-essential")
            except Exception:  # noqa: BLE001
                c.exec_("yum", "install", "-y", "gcc")
        compile_tool("strobe-time.c", "strobe-time")
        compile_tool("bump-time.c", "bump-time")


# ----------------------------------------------------- clock primitives


def parse_time(s: str) -> float:
    """Decimal unix seconds from a `date +%s.%N` string
    (nemesis/time.clj:54-58)."""
    return float(s.strip())


def clock_offset(remote_time: float) -> float:
    """Remote seconds-since-epoch minus local control-host time: the
    node's relative skew in seconds (nemesis/time.clj:60-64)."""
    return remote_time - _time.time()


def current_offset() -> float:
    """Clock offset of the current ambient node (nemesis/time.clj:66-69)."""
    return clock_offset(parse_time(c.exec_("date", "+%s.%N")))


def reset_time() -> None:
    """Reset the ambient node's clock to NTP (nemesis/time.clj:71-75)."""
    with c.su():
        c.exec_("ntpdate", "-b", "time.google.com")


def reset_time_test(test: dict) -> None:
    c.on_nodes(test, lambda t, n: reset_time())


def bump_time(delta_ms) -> float:
    """Adjust the ambient node's clock by delta ms; returns the node's
    resulting offset in seconds (nemesis/time.clj:77-81)."""
    with c.su():
        return clock_offset(parse_time(
            c.exec_(f"{INSTALL_DIR}/bump-time", delta_ms)))


def strobe_time(delta_ms, period_ms, duration_s) -> None:
    """Oscillate the ambient node's clock (nemesis/time.clj:83-87)."""
    with c.su():
        c.exec_(f"{INSTALL_DIR}/strobe-time", delta_ms, period_ms,
                duration_s)


# ------------------------------------------------------------- nemesis


class ClockNemesis(Nemesis):
    """The clock nemesis proper (nemesis/time.clj:89-139)."""

    def setup(self, test):
        c.on_nodes(test, lambda t, n: install())

        def stop_ntp(t, n):
            for svc in ("ntp", "ntpd"):
                try:
                    with c.su():
                        c.exec_("service", svc, "stop")
                except Exception:  # noqa: BLE001 - service may not exist
                    pass

        c.on_nodes(test, stop_ntp)
        reset_time_test(test)
        return self

    def invoke(self, test, op: Op) -> Op:
        f = op.get("f")
        if f == "reset":
            res = c.on_nodes(
                test, lambda t, n: (reset_time(), current_offset())[1],
                op.get("value"))
        elif f == "check-offsets":
            res = c.on_nodes(test, lambda t, n: current_offset())
        elif f == "strobe":
            m = op.get("value") or {}

            def do_strobe(t, n):
                spec = m[n]
                strobe_time(spec["delta"], spec["period"], spec["duration"])
                return current_offset()

            res = c.on_nodes(test, do_strobe, list(m))
        elif f == "bump":
            m = op.get("value") or {}
            res = c.on_nodes(test, lambda t, n: bump_time(m[n]), list(m))
        else:
            raise ValueError(f"clock nemesis doesn't handle :f {f!r}")
        out = _ok(op)
        out["clock-offsets"] = res
        return out

    def teardown(self, test):
        reset_time_test(test)

    def fs(self):
        return {"reset", "strobe", "bump", "check-offsets"}


def clock_nemesis() -> ClockNemesis:
    return ClockNemesis()


# ---------------------------------------------------------- generators
# Op generators mirroring nemesis/time.clj:141-198: exponential deltas
# from ~4ms to ~262s (2^(2+rand*16) ms), strobe periods 1ms-1s,
# durations 0-32s.


def _default_select(test):
    return random_nonempty_subset(test.get("nodes") or [])


def reset_gen_select(select: Callable) -> Callable:
    def reset_op(test, ctx):
        return {"type": "info", "f": "reset", "value": list(select(test))}
    return reset_op


def bump_gen_select(select: Callable) -> Callable:
    def bump_op(test, ctx):
        value = {n: int(gen.rand.choice([-1, 1])
                        * math.pow(2, 2 + gen.rand.random() * 16))
                 for n in select(test)}
        return {"type": "info", "f": "bump", "value": value}
    return bump_op


def strobe_gen_select(select: Callable) -> Callable:
    def strobe_op(test, ctx):
        value = {n: {"delta": int(math.pow(2, 2 + gen.rand.random() * 16)),
                     "period": int(math.pow(2, gen.rand.random() * 10)),
                     "duration": gen.rand.random() * 32}
                 for n in select(test)}
        return {"type": "info", "f": "strobe", "value": value}
    return strobe_op


reset_gen = reset_gen_select(_default_select)
bump_gen = bump_gen_select(_default_select)
strobe_gen = strobe_gen_select(_default_select)


def clock_gen(select: Optional[Callable] = None):
    """Random schedule of clock-skew ops, always opening with a
    check-offsets to establish a baseline (nemesis/time.clj:192-198)."""
    select = select or _default_select
    return gen.phases(
        {"type": "info", "f": "check-offsets"},
        gen.mix([reset_gen_select(select),
                 bump_gen_select(select),
                 strobe_gen_select(select)]))
