"""Consistency models (knossos.model equivalents).

A model is an immutable object with `step(op) -> model'` raising/returning
`Inconsistent` when the op cannot apply — exactly the knossos.model
contract the reference consumes (`model/step`, `model/inconsistent?` at
jepsen/src/jepsen/checker.clj:230-233; `model/cas-register` at
tendermint/src/jepsen/tendermint/core.clj:363).

Two tiers:

  * Host models (`Register`, `CASRegister`, `Mutex`, `UnorderedQueue`,
    `FIFOQueue`, `GSet`) — general Python objects, used by the CPU
    reference checker (`jepsen_tpu.checker.wgl`) and by simple checkers
    like `checker.queue`.

  * Packed models — fixed-width integer state + a pure `jnp` step
    function, the TPU tier (SURVEY.md §7.1 step 4: "Model as jit'd pure
    function: step(state, op) -> (state', ok); state packed into
    fixed-width ints"). `pack_spec(model)` returns a `PackedSpec` when the
    model family is device-packable; the linearizability dispatcher falls
    back to the host checker otherwise (SURVEY.md §7.3 hard part #4).

Op convention for model steps: ops are `history.Op`-like with .f and
.value; read ops carry the *returned* value (filled by
`History.complete()`), or None when unknown (crashed reads).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

import numpy as np


class Inconsistent:
    """The op cannot be applied to this state (knossos.model/inconsistent)."""

    __slots__ = ("msg",)

    def __init__(self, msg: str):
        self.msg = msg

    def __repr__(self):
        return f"Inconsistent({self.msg!r})"

    def __eq__(self, other):
        return isinstance(other, Inconsistent)

    def __hash__(self):
        return hash("Inconsistent")


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


def is_inconsistent(m) -> bool:
    return isinstance(m, Inconsistent)


class Model:
    """Base: immutable; subclasses implement step and value-based equality."""

    def step(self, op) -> "Model | Inconsistent":
        raise NotImplementedError

    # Subclasses must be hashable on their state — the visited cache
    # (both host DFS and device hash set) depends on it.


@dataclass(frozen=True)
class Register(Model):
    """A single read/write register (knossos.model/register)."""

    value: Any = None

    def step(self, op):
        if op.f == "write":
            return Register(op.value)
        if op.f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(f"read {op.value!r} from register {self.value!r}")
        return inconsistent(f"unknown op f={op.f}")


@dataclass(frozen=True)
class CASRegister(Model):
    """Read/write/compare-and-set register (knossos.model/cas-register) —
    the model of the Tendermint cas-register workload
    (tendermint/src/jepsen/tendermint/core.clj:363)."""

    value: Any = None

    def step(self, op):
        if op.f == "write":
            return CASRegister(op.value)
        if op.f == "cas":
            if op.value is None:
                return self  # crashed CAS with unknown args: can't constrain
            old, new = op.value
            if self.value == old:
                return CASRegister(new)
            return inconsistent(f"cas {old!r}->{new!r} on {self.value!r}")
        if op.f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(f"read {op.value!r} from {self.value!r}")
        return inconsistent(f"unknown op f={op.f}")


@dataclass(frozen=True)
class Mutex(Model):
    """Lock with acquire/release (knossos.model/mutex)."""

    locked: bool = False

    def step(self, op):
        if op.f == "acquire":
            if self.locked:
                return inconsistent("acquire on locked mutex")
            return Mutex(True)
        if op.f == "release":
            if not self.locked:
                return inconsistent("release on unlocked mutex")
            return Mutex(False)
        return inconsistent(f"unknown op f={op.f}")


@dataclass(frozen=True)
class UnorderedQueue(Model):
    """Queue where dequeue may return any pending element
    (knossos.model/unordered-queue, used by the reference's queue checker
    test, jepsen/test/jepsen/checker_test.clj:70)."""

    pending: frozenset = frozenset()  # of (value, count) — multiset as tuples

    @staticmethod
    def of(*vals):
        from collections import Counter
        return UnorderedQueue(frozenset(Counter(vals).items()))

    def _counter(self):
        from collections import Counter
        return Counter(dict(self.pending))

    def step(self, op):
        c = self._counter()
        if op.f == "enqueue":
            c[op.value] += 1
            return UnorderedQueue(frozenset(c.items()))
        if op.f == "dequeue":
            if op.value is None:
                return self  # unknown dequeue result: unconstrained
            if c.get(op.value, 0) > 0:
                c[op.value] -= 1
                if c[op.value] == 0:
                    del c[op.value]
                return UnorderedQueue(frozenset(c.items()))
            return inconsistent(f"dequeue {op.value!r} not pending")
        return inconsistent(f"unknown op f={op.f}")


@dataclass(frozen=True)
class FIFOQueue(Model):
    """Strict FIFO queue (knossos.model/fifo-queue)."""

    items: tuple = ()

    def step(self, op):
        if op.f == "enqueue":
            return FIFOQueue(self.items + (op.value,))
        if op.f == "dequeue":
            if not self.items:
                return inconsistent("dequeue from empty queue")
            head, rest = self.items[0], self.items[1:]
            if op.value is None or head == op.value:
                return FIFOQueue(rest)
            return inconsistent(f"dequeue {op.value!r}, head is {head!r}")
        return inconsistent(f"unknown op f={op.f}")


@dataclass(frozen=True)
class GSet(Model):
    """Grow-only set with add/read (knossos.model/set)."""

    items: frozenset = frozenset()

    def step(self, op):
        if op.f == "add":
            return GSet(self.items | {op.value})
        if op.f == "read":
            if op.value is None:
                return self
            got = frozenset(op.value)
            if got == self.items:
                return self
            return inconsistent(
                f"read {sorted(got, key=repr)} != {sorted(self.items, key=repr)}"
            )
        return inconsistent(f"unknown op f={op.f}")


# Clojure-flavoured constructors matching knossos.model names
def register(value=None):
    return Register(value)


def cas_register(value=None):
    return CASRegister(value)


def mutex():
    return Mutex()


def unordered_queue():
    return UnorderedQueue()


def fifo_queue():
    return FIFOQueue()


def gset():
    return GSet()


# =====================================================================
# Packed (device) tier
# =====================================================================

# f-codes shared by host encoder and device step functions
F_READ, F_WRITE, F_CAS, F_ACQUIRE, F_RELEASE = 0, 1, 2, 3, 4
F_ADD, F_ENQ, F_DEQ = 5, 6, 7


@dataclass
class PackedSpec:
    """Device encoding of a model family.

    state0        initial state as int32
    f_codes       map f-name -> small int code
    encode_call   (f, invoke_value, result, crashed) ->
                  (f_code, arg0, arg1, wildcard) ints; values interned via
                  the supplied Intern table
    step_name     key into jepsen_tpu.parallel.steps registry of pure
                  jnp step functions step(state, f, a0, a1, wild) ->
                  (state', ok) — kept as a name so specs stay picklable
                  and the engine jit-caches per family, not per key.
    """

    state0: int
    step_name: str
    encode_call: Callable[..., Tuple[int, int, int, bool]]
    f_codes: dict
    # optional bulk hook: (calls) -> (f, a0, a1, wild) numpy arrays —
    # row i identical to encode_call(calls[i].f, .value, .result,
    # .crashed), including the interning ORDER (encode() must produce
    # the same arrays whichever path runs). Exists because the
    # per-call Python loop is the measured constant on the batched
    # end-to-end path (PERF_R05: encode-bound, not search-bound); the
    # bulk form preallocates the arrays and keeps the dispatch
    # overhead to one call per history instead of one per op.
    encode_calls: Callable = None
    # dense-engine state domain: states are the contiguous ints
    # [state_lo, state_lo + n_states(intern)); register family uses
    # interned value codes with nil = -1, mutex uses {0, 1}
    state_lo: int = -1
    n_states: Callable = None  # (intern) -> int
    # (packed state, intern) -> Model instance at that state — lets the
    # host seed a re-search from a device frontier checkpoint
    # (counterexample extraction for long histories)
    unpack_state: Callable = None
    # optional pre-pass over the full (pruned) call list, run by
    # encode() before any encode_call: models whose packing needs
    # global knowledge of the history (GSet element lanes, queue count
    # widths) build their tables here and may raise EncodeError to
    # send the history to the host engine
    prepare: Callable = None


def pack_spec(model: Model, intern) -> Optional[PackedSpec]:
    """Return the PackedSpec for device-packable models, else None.

    Packable today — all six knossos model families: Register /
    CASRegister (state = interned value id, nil = -1), Mutex (state =
    0/1), GSet (state = element bitmask, up to 31 distinct elements),
    UnorderedQueue (state = packed count lanes, up to 31 total bits),
    and FIFOQueue (state = v-bit value-code lanes, head at the low
    bits, depth bound x width <= 31). GSet/queue packing is
    history-bounded, not unbounded: their `prepare` pass sizes the
    state from the actual call list and raises EncodeError past the
    31-bit budget, falling back to the host checker (SURVEY.md §7.3 #4).
    """
    if isinstance(model, (Register, CASRegister)):
        state0 = intern.code(model.value)

        def encode_call(f, value, result, crashed):
            if f == "read":
                # reads learn their value at completion; crashed/nil reads
                # are wildcards (any state linearizes them)
                v = result if result is not None else value
                if crashed or v is None:
                    return (F_READ, -1, -1, True)
                return (F_READ, intern.code(v), -1, False)
            if f == "write":
                if value is None:
                    return (F_READ, -1, -1, True)  # unknown write: no-op? —
                    # a write with unknown value can't occur in practice;
                    # treat as wildcard read to stay sound-ish
                return (F_WRITE, intern.code(value), -1, False)
            if f == "cas":
                if value is None:
                    return (F_READ, -1, -1, True)
                old, new = value
                return (F_CAS, intern.code(old), intern.code(new), False)
            raise ValueError(f"register family: unknown f {f!r}")

        def encode_calls(cs):
            # bulk row-wise mirror of encode_call — same branches, same
            # interning order (read interns only when constraining).
            # Accumulates in Python lists and converts once at the end:
            # per-element numpy stores cost more than the whole row's
            # logic, and the per-call tuple + function call are the
            # measured overhead this hook exists to remove
            # (tools/perf_encode.py).
            fs, a0, a1, wild = [], [], [], []
            code = intern.code
            for c in cs:
                cf = c.f
                w = False
                x0 = x1 = -1
                if cf == "read":
                    v = c.result if c.result is not None else c.value
                    fc = F_READ
                    if c.crashed or v is None:
                        w = True
                    else:
                        x0 = code(v)
                elif cf == "write":
                    if c.value is None:
                        fc = F_READ
                        w = True
                    else:
                        fc = F_WRITE
                        x0 = code(c.value)
                elif cf == "cas":
                    if c.value is None:
                        fc = F_READ
                        w = True
                    else:
                        old, new = c.value
                        fc = F_CAS
                        x0 = code(old)
                        x1 = code(new)
                else:
                    raise ValueError(f"register family: unknown f {cf!r}")
                fs.append(fc)
                a0.append(x0)
                a1.append(x1)
                wild.append(w)
            return (np.array(fs, np.int32), np.array(a0, np.int32),
                    np.array(a1, np.int32), np.array(wild, bool))

        cls = type(model)
        return PackedSpec(
            state0=state0,
            step_name="register",
            encode_call=encode_call,
            encode_calls=encode_calls,
            f_codes={"read": F_READ, "write": F_WRITE, "cas": F_CAS},
            state_lo=-1,
            n_states=lambda intern: len(intern) + 1,
            unpack_state=lambda code, intern: cls(intern.value(code)),
        )

    if isinstance(model, Mutex):
        def encode_call(f, value, result, crashed):
            if f == "acquire":
                return (F_ACQUIRE, -1, -1, False)
            if f == "release":
                return (F_RELEASE, -1, -1, False)
            raise ValueError(f"mutex: unknown f {f!r}")

        def encode_calls(cs):
            fs = []
            for c in cs:
                if c.f == "acquire":
                    fs.append(F_ACQUIRE)
                elif c.f == "release":
                    fs.append(F_RELEASE)
                else:
                    raise ValueError(f"mutex: unknown f {c.f!r}")
            n = len(cs)
            return (np.array(fs, np.int32), np.full(n, -1, np.int32),
                    np.full(n, -1, np.int32), np.zeros(n, bool))

        return PackedSpec(
            state0=1 if model.locked else 0,
            step_name="mutex",
            encode_call=encode_call,
            encode_calls=encode_calls,
            f_codes={"acquire": F_ACQUIRE, "release": F_RELEASE},
            state_lo=0,
            n_states=lambda intern: 2,
            unpack_state=lambda code, intern: Mutex(bool(code)),
        )

    if isinstance(model, GSet):
        return _gset_spec(model)

    if isinstance(model, UnorderedQueue):
        return _uqueue_spec(model)

    if isinstance(model, FIFOQueue):
        return _fifo_spec(model)

    return None


def _encode_error(msg: str):
    from jepsen_tpu.parallel.encode import EncodeError
    return EncodeError(msg)


def _gset_spec(model: "GSet") -> PackedSpec:
    """GSet packing: state IS the element bitmask. Lanes (element ->
    bit) are assigned by `prepare` from the history — adds first, then
    read sets — so the device step sees only small ints."""
    lanes: dict = {}

    def prepare(cs, intern):
        elems = list(model.items)
        for c in cs:
            # None is an ordinary addable element (the host model adds
            # it literally, and reads observe it) — lane like any other
            if c.f == "add":
                elems.append(c.value)
        for c in cs:
            if c.f == "read" and not c.crashed and c.result is not None:
                elems.extend(c.result)
        lanes.clear()
        try:
            for v in elems:
                if v not in lanes:
                    lanes[v] = len(lanes)
        except TypeError as err:  # unhashable element
            raise _encode_error(f"gset element not hashable: {err}")
        if len(lanes) > 31:
            raise _encode_error(
                f"gset has {len(lanes)} distinct elements; the packed "
                f"bitmask state holds 31 — use the host engine")
        spec.state0 = _gset_mask(model.items)

    def _gset_mask(items):
        m = 0
        for v in items:
            m |= 1 << lanes[v]
        return m

    def encode_call(f, value, result, crashed):
        if f == "add":
            return (F_ADD, lanes[value], -1, False)
        if f == "read":
            v = result if not crashed else None
            if v is None:
                return (F_READ, -1, -1, True)
            return (F_READ, _gset_mask(v), -1, False)
        raise ValueError(f"gset: unknown f {f!r}")

    def encode_calls(cs):
        fs, a0, wild = [], [], []
        for c in cs:
            if c.f == "add":
                fs.append(F_ADD)
                a0.append(lanes[c.value])
                wild.append(False)
            elif c.f == "read":
                v = c.result if not c.crashed else None
                fs.append(F_READ)
                if v is None:
                    a0.append(-1)
                    wild.append(True)
                else:
                    a0.append(_gset_mask(v))
                    wild.append(False)
            else:
                raise ValueError(f"gset: unknown f {c.f!r}")
        return (np.array(fs, np.int32), np.array(a0, np.int32),
                np.full(len(cs), -1, np.int32), np.array(wild, bool))

    def unpack_state(code, intern):
        return GSet(frozenset(v for v, b in lanes.items()
                              if (code >> b) & 1))

    spec = PackedSpec(
        state0=0,  # finalized by prepare (needs the lane table)
        step_name="gset",
        encode_call=encode_call,
        encode_calls=encode_calls,
        f_codes={"add": F_ADD, "read": F_READ},
        state_lo=0,
        n_states=lambda intern: 1 << len(lanes),
        unpack_state=unpack_state,
        prepare=prepare,
    )
    return spec


def _fifo_spec(model: "FIFOQueue") -> PackedSpec:
    """FIFOQueue packing: the queue IS the state — v-bit value-code
    lanes (code 0 = empty, codes 1..K assigned by `prepare`), head at
    the low bits, depth implicit in the bit length. `prepare` proves a
    depth bound B = initial depth + max over event positions of
    (enqueues invoked so far - ok-dequeues completed so far): any
    config reachable at any return event holds <= B elements (a
    completed dequeue must have linearized; an open enqueue may have),
    so B*v <= 31 guarantees enqueue shifts stay inside the int32.
    Past that budget the history goes to the host engine."""
    lanes: dict = {}        # value -> code 1..K
    width = [0]             # v bits per lane
    bound = [0]

    def prepare(cs, intern):
        try:
            for v in model.items:
                if v not in lanes:
                    lanes[v] = len(lanes) + 1
            for c in cs:
                # None is an ordinary enqueueable value (the host model
                # appends it literally), so it gets a lane like any other
                if c.f == "enqueue":
                    if c.value not in lanes:
                        lanes[c.value] = len(lanes) + 1
                elif c.f == "dequeue" and not c.crashed \
                        and c.result is not None:
                    if c.result not in lanes:
                        lanes[c.result] = len(lanes) + 1
        except TypeError as err:
            raise _encode_error(f"fifo element not hashable: {err}")
        width[0] = max(1, len(lanes).bit_length())
        events = []
        for c in cs:
            if c.f == "enqueue":
                events.append((c.invoke_index, 1))
            elif c.f == "dequeue" and not c.crashed:
                events.append((c.complete_index, -1))
        events.sort()
        depth = peak = len(model.items)
        for _, d in events:
            depth += d
            peak = max(peak, depth)
        bound[0] = max(1, peak)
        if bound[0] * width[0] > 31:
            raise _encode_error(
                f"fifo needs {bound[0]} lanes x {width[0]} bits; the "
                f"packed state holds 31 — use the host engine")
        s0 = 0
        for i, v in enumerate(model.items):
            s0 |= lanes[v] << (i * width[0])
        spec.state0 = s0

    def encode_call(f, value, result, crashed):
        if f == "enqueue":
            return (F_ENQ, lanes[value], width[0], False)
        if f == "dequeue":
            # dequeues are completion-valued; a crashed dequeue's result
            # is unknown regardless of its invoke value (the host oracle
            # sets value=None for crashed dequeues, wgl._StepOp) and
            # pops ANY head — match-any, not a wildcard identity
            v = None if crashed else result
            if v is None:
                return (F_DEQ, -1, width[0], False)
            return (F_DEQ, lanes[v], width[0], False)
        raise ValueError(f"fifo-queue: unknown f {f!r}")

    def encode_calls(cs):
        fs, a0 = [], []
        for c in cs:
            if c.f == "enqueue":
                fs.append(F_ENQ)
                a0.append(lanes[c.value])
            elif c.f == "dequeue":
                v = None if c.crashed else c.result
                fs.append(F_DEQ)
                a0.append(-1 if v is None else lanes[v])
            else:
                raise ValueError(f"fifo-queue: unknown f {c.f!r}")
        n = len(cs)
        return (np.array(fs, np.int32), np.array(a0, np.int32),
                np.full(n, width[0], np.int32), np.zeros(n, bool))

    def unpack_state(code, intern):
        by_code = {c: v for v, c in lanes.items()}
        items = []
        v = width[0]
        while code:
            items.append(by_code[code & ((1 << v) - 1)])
            code >>= v
        return FIFOQueue(tuple(items))

    spec = PackedSpec(
        state0=0,  # finalized by prepare
        step_name="fifo",
        encode_call=encode_call,
        encode_calls=encode_calls,
        f_codes={"enqueue": F_ENQ, "dequeue": F_DEQ},
        state_lo=0,
        n_states=lambda intern: 1 << (bound[0] * width[0]),
        unpack_state=unpack_state,
        prepare=prepare,
    )
    return spec


def _uqueue_spec(model: "UnorderedQueue") -> PackedSpec:
    """UnorderedQueue packing: one count lane per distinct value, width
    sized by `prepare` from the history's total enqueues (plus initial
    pending) — counts can never overflow their lane by construction.
    lanes maps value -> (bit offset, unshifted mask)."""
    lanes: dict = {}
    total_bits = [0]

    def prepare(cs, intern):
        from collections import Counter
        cap: Counter = Counter()
        try:
            for v, k in model.pending:
                cap[v] += k
            for c in cs:
                if c.f == "enqueue" and c.value is not None:
                    cap[c.value] += 1
            for c in cs:
                if c.f == "dequeue":
                    v = c.value if c.crashed else c.result
                    if v is not None and v not in cap:
                        cap[v] = 0  # dequeue-only value: 1-bit zero lane
        except TypeError as err:
            raise _encode_error(f"queue element not hashable: {err}")
        lanes.clear()
        off = 0
        for v, k in cap.items():
            w = max(1, int(k).bit_length())
            lanes[v] = (off, (1 << w) - 1)
            off += w
        if off > 31:
            raise _encode_error(
                f"queue count lanes need {off} bits; the packed state "
                f"holds 31 — use the host engine")
        total_bits[0] = off
        s0 = 0
        for v, k in model.pending:
            s0 += k << lanes[v][0]
        spec.state0 = s0

    def encode_call(f, value, result, crashed):
        if f == "enqueue":
            if value is None:
                return (F_READ, -1, -1, True)
            o, m = lanes[value]
            return (F_ENQ, o, m, False)
        if f == "dequeue":
            # completion-valued: the dequeued element is learned at ok;
            # unknown results are unconstrained. A crashed dequeue's
            # result is unknown REGARDLESS of its invoke value (the
            # host oracle sets value=None for crashed dequeues,
            # wgl._StepOp) — constraining on the invoke value would
            # report false violations the host accepts
            v = None if crashed else result
            if v is None:
                return (F_READ, -1, -1, True)
            o, m = lanes[v]
            return (F_DEQ, o, m, False)
        raise ValueError(f"unordered-queue: unknown f {f!r}")

    def encode_calls(cs):
        fs, a0, a1, wild = [], [], [], []
        for c in cs:
            w = False
            x0 = x1 = -1
            if c.f == "enqueue":
                if c.value is None:
                    fc = F_READ
                    w = True
                else:
                    fc = F_ENQ
                    x0, x1 = lanes[c.value]
            elif c.f == "dequeue":
                v = None if c.crashed else c.result
                if v is None:
                    fc = F_READ
                    w = True
                else:
                    fc = F_DEQ
                    x0, x1 = lanes[v]
            else:
                raise ValueError(f"unordered-queue: unknown f {c.f!r}")
            fs.append(fc)
            a0.append(x0)
            a1.append(x1)
            wild.append(w)
        return (np.array(fs, np.int32), np.array(a0, np.int32),
                np.array(a1, np.int32), np.array(wild, bool))

    def unpack_state(code, intern):
        items = []
        for v, (o, m) in lanes.items():
            k = (code >> o) & m
            if k:
                items.append((v, k))
        return UnorderedQueue(frozenset(items))

    spec = PackedSpec(
        state0=0,  # finalized by prepare
        step_name="uqueue",
        encode_call=encode_call,
        encode_calls=encode_calls,
        f_codes={"enqueue": F_ENQ, "dequeue": F_DEQ},
        state_lo=0,
        n_states=lambda intern: 1 << total_bits[0],
        unpack_state=unpack_state,
        prepare=prepare,
    )
    return spec
