"""Object <-> bytes serialization (reference: jepsen/src/jepsen/codec.clj).

Used for nemesis payloads and anywhere a value must cross a byte
boundary. EDN text encoding, like the reference; None round-trips as
zero bytes (codec.clj:9-28)."""

from __future__ import annotations

from typing import Optional

from jepsen_tpu import edn


def encode(o) -> bytes:
    """Serialize an object to EDN bytes; None -> b'' (codec.clj:9-15)."""
    if o is None:
        return b""
    return edn.dumps(o).encode("utf-8")


def decode(data: Optional[bytes]):
    """Deserialize EDN bytes; b'' or None -> None (codec.clj:17-28)."""
    if not data:
        return None
    return edn.loads(data.decode("utf-8"))
