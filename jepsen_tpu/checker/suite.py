"""The linear-time checker suite (reference: jepsen/src/jepsen/checker.clj:115-792).

Faithful re-implementations of the reference's cheap checkers: stats,
unhandled-exceptions, queue, set, set-full, total-queue, unique-ids,
counter. All O(n) single passes over the history; vectorisation isn't
worth the obscurity at these sizes — the exponential work lives in
`jepsen_tpu.checker.linearizable` / `jepsen_tpu.parallel.engine`.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Optional

from jepsen_tpu import models as model_ns
from jepsen_tpu.checker.core import Checker, UNKNOWN, merge_valid
from jepsen_tpu.util import integer_interval_set_str


def _is_client(o) -> bool:
    return isinstance(o.get("process"), int)


class UnhandledExceptions(Checker):
    """Ranks ops carrying errors/exceptions by frequency
    (checker.clj:121-148). Always valid; purely informational."""

    def check(self, test, history, opts=None):
        exes = [o for o in history
                if o.get("type") in ("info", "fail") and o.get("error")]
        if not exes:
            return {"valid?": True}
        groups: dict = {}
        for o in exes:
            key = str(o.get("error")).split("\n")[0][:200]
            groups.setdefault(key, []).append(o)
        ranked = sorted(groups.values(), key=len, reverse=True)
        return {
            "valid?": True,
            "exceptions": [
                {"class": str(ops[0].get("error")).split("\n")[0][:200],
                 "count": len(ops),
                 "example": dict(ops[0])}
                for ops in ranked
            ],
        }


def _stats_map(completions) -> dict:
    ok = sum(1 for o in completions if o.get("type") == "ok")
    fail = sum(1 for o in completions if o.get("type") == "fail")
    info = sum(1 for o in completions if o.get("type") == "info")
    return {
        "valid?": ok > 0,
        "count": ok + fail + info,
        "ok-count": ok,
        "fail-count": fail,
        "info-count": info,
    }


class Stats(Checker):
    """ok/fail/info counts overall and by :f; valid iff every :f has some
    ok ops (checker.clj:150-180)."""

    def check(self, test, history, opts=None):
        comps = [o for o in history
                 if o.get("type") != "invoke" and o.get("process") != "nemesis"]
        by_f: dict = {}
        for o in comps:
            by_f.setdefault(o.get("f"), []).append(o)
        groups = {f: _stats_map(ops) for f, ops in sorted(by_f.items(),
                                                          key=lambda kv: str(kv[0]))}
        out = _stats_map(comps)
        out["by-f"] = groups
        out["valid?"] = merge_valid(g["valid?"] for g in groups.values()) \
            if groups else UNKNOWN
        return out


class Queue(Checker):
    """Every dequeue must come from somewhere: assume every non-failing
    enqueue succeeded and only OK dequeues succeeded, then fold the model
    over that history (checker.clj:215-235). Use with UnorderedQueue."""

    def __init__(self, model):
        self.model = model

    def check(self, test, history, opts=None):
        m = self.model
        for o in history:
            f = o.get("f")
            take = (f == "enqueue" and o.get("type") == "invoke") or \
                   (f == "dequeue" and o.get("type") == "ok")
            if not take:
                continue
            m = m.step(o)
            if model_ns.is_inconsistent(m):
                return {"valid?": False, "error": m.msg}
        return {"valid?": True, "final-queue": m}


class Set(Checker):
    """:add ops followed by a final :read; every successful add must be
    present; only attempted elements may appear (checker.clj:237-288)."""

    def check(self, test, history, opts=None):
        attempts = {o.get("value") for o in history
                    if o.get("type") == "invoke" and o.get("f") == "add"}
        adds = {o.get("value") for o in history
                if o.get("type") == "ok" and o.get("f") == "add"}
        final_read = None
        for o in history:
            if o.get("type") == "ok" and o.get("f") == "read":
                final_read = o.get("value")
        if final_read is None:
            return {"valid?": UNKNOWN, "error": "Set was never read"}
        final_read = set(final_read)
        ok = final_read & attempts
        unexpected = final_read - attempts
        lost = adds - final_read
        recovered = ok - adds
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": len(attempts),
            "acknowledged-count": len(adds),
            "ok-count": len(ok),
            "lost-count": len(lost),
            "recovered-count": len(recovered),
            "unexpected-count": len(unexpected),
            "ok": _interval_or_list(ok),
            "lost": _interval_or_list(lost),
            "unexpected": _interval_or_list(unexpected),
            "recovered": _interval_or_list(recovered),
        }


def _interval_or_list(xs):
    if all(isinstance(x, int) for x in xs):
        return integer_interval_set_str(xs)
    return sorted(xs, key=repr)


class _SetFullElement:
    """Per-element timeline state (checker.clj:291-338 SetFullElement)."""

    __slots__ = ("element", "known", "last_present", "last_absent")

    def __init__(self, element):
        self.element = element
        self.known = None          # op confirming existence (add ok / read)
        self.last_present = None   # most recent read invocation observing it
        self.last_absent = None    # most recent read invocation missing it

    def add_ok(self, op):
        if self.known is None:
            self.known = op

    def read_present(self, inv, op):
        if self.known is None:
            self.known = op
        if self.last_present is None or self.last_present["index"] < inv["index"]:
            self.last_present = inv

    def read_absent(self, inv, op):
        if self.last_absent is None or self.last_absent["index"] < inv["index"]:
            self.last_absent = inv


def _set_full_element_results(e: _SetFullElement) -> dict:
    """checker.clj:343-404 semantics, including both asymmetries: an
    element must be known for absence to matter, and an absent read
    concurrent with the add counts as never-read, not lost."""
    def idx(op, default=-1):
        return op["index"] if op is not None else default

    stable = bool(e.last_present is not None
                  and idx(e.last_absent) < idx(e.last_present))
    lost = bool(e.known is not None
                and e.last_absent is not None
                and idx(e.last_present) < idx(e.last_absent)
                and e.known["index"] < e.last_absent["index"])
    never_read = not (stable or lost)
    known_time = e.known.get("time", 0) if e.known else 0

    stable_latency = lost_latency = None
    if stable:
        stable_time = (e.last_absent.get("time") or 0) + 1 if e.last_absent else 0
        stable_latency = max(0, stable_time - (known_time or 0)) // 1_000_000
    if lost:
        lost_time = (e.last_present.get("time") or 0) + 1 if e.last_present else 0
        lost_latency = max(0, lost_time - (known_time or 0)) // 1_000_000
    return {
        "element": e.element,
        "outcome": "stable" if stable else ("lost" if lost else "never-read"),
        "stable-latency": stable_latency,
        "lost-latency": lost_latency,
        "known": dict(e.known) if e.known else None,
        "last-absent": dict(e.last_absent) if e.last_absent else None,
    }


def _frequency_distribution(points, xs):
    xs = sorted(xs)
    if not xs:
        return None
    n = len(xs)
    return {p: xs[min(n - 1, int(math.floor(n * p)))] for p in points}


class SetFull(Checker):
    """Per-element visibility-timeline set analysis (checker.clj:470-589).

    Options: linearizable (bool) — elements must be visible immediately
    after their add completes; stale elements then invalidate the test.
    """

    def __init__(self, linearizable: bool = False):
        self.linearizable = linearizable

    def check(self, test, history, opts=None):
        elements: dict = {}   # value -> _SetFullElement
        inv_by_process: dict = {}
        for o in history:
            t, f = o.get("type"), o.get("f")
            if t == "invoke":
                inv_by_process[o.get("process")] = o
                if f == "add":
                    v = o.get("value")
                    if v not in elements:
                        elements[v] = _SetFullElement(v)
            elif t == "ok":
                inv = inv_by_process.pop(o.get("process"), o)
                if f == "add":
                    e = elements.get(o.get("value"))
                    if e is not None:
                        e.add_ok(o)
                elif f == "read":
                    read = set(o.get("value") or ())
                    for v, e in elements.items():
                        # only elements whose add was invoked before this
                        # read's invocation can be judged absent
                        if v in read:
                            e.read_present(inv, o)
                        else:
                            e.read_absent(inv, o)
            else:
                inv_by_process.pop(o.get("process"), None)

        rs = [_set_full_element_results(e) for e in elements.values()]
        outcomes: dict = {}
        for r in rs:
            outcomes.setdefault(r["outcome"], []).append(r)
        stable = outcomes.get("stable", [])
        lost = outcomes.get("lost", [])
        never_read = outcomes.get("never-read", [])
        stale = [r for r in stable if r["stable-latency"] and r["stable-latency"] > 0]
        worst_stale = sorted(stale, key=lambda r: r["stable-latency"],
                             reverse=True)[:8]
        if lost:
            valid = False
        elif not stable:
            valid = UNKNOWN
        elif self.linearizable and stale:
            valid = False
        else:
            valid = True
        out = {
            "valid?": valid,
            "attempt-count": len(rs),
            "stable-count": len(stable),
            "lost-count": len(lost),
            "lost": sorted((r["element"] for r in lost), key=repr),
            "never-read-count": len(never_read),
            "never-read": sorted((r["element"] for r in never_read), key=repr),
            "stale-count": len(stale),
            "stale": sorted((r["element"] for r in stale), key=repr),
            "worst-stale": worst_stale,
        }
        points = (0, 0.5, 0.95, 0.99, 1)
        sl = _frequency_distribution(points, [r["stable-latency"] for r in rs
                                              if r["stable-latency"] is not None])
        ll = _frequency_distribution(points, [r["lost-latency"] for r in rs
                                              if r["lost-latency"] is not None])
        if sl:
            out["stable-latencies"] = sl
        if ll:
            out["lost-latencies"] = ll
        return out


class TotalQueue(Checker):
    """What goes in must come out — multiset conservation over
    enqueue/dequeue (checker.clj:625-684). Drain ops (:f :drain with ok
    values lists) are expanded into dequeues first."""

    def check(self, test, history, opts=None):
        attempts: Counter = Counter()
        enqueues: Counter = Counter()
        dequeues: Counter = Counter()
        for o in history:
            f, t = o.get("f"), o.get("type")
            if f == "enqueue":
                if t == "invoke":
                    attempts[o.get("value")] += 1
                elif t == "ok":
                    enqueues[o.get("value")] += 1
            elif f == "dequeue" and t == "ok":
                dequeues[o.get("value")] += 1
            elif f == "drain" and t == "ok":
                for v in o.get("value") or ():
                    dequeues[v] += 1
        ok = dequeues & attempts
        unexpected = Counter({v: c for v, c in dequeues.items()
                              if v not in attempts})
        duplicated = dequeues - attempts - unexpected
        lost = enqueues - dequeues
        recovered = ok - enqueues
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": sum(attempts.values()),
            "acknowledged-count": sum(enqueues.values()),
            "ok-count": sum(ok.values()),
            "unexpected-count": sum(unexpected.values()),
            "duplicated-count": sum(duplicated.values()),
            "lost-count": sum(lost.values()),
            "recovered-count": sum(recovered.values()),
            "lost": dict(lost),
            "unexpected": dict(unexpected),
            "duplicated": dict(duplicated),
            "recovered": dict(recovered),
        }


class UniqueIds(Checker):
    """A unique-id generator must emit distinct ids
    (checker.clj:686-731)."""

    def check(self, test, history, opts=None):
        attempted = sum(1 for o in history
                        if o.get("type") == "invoke" and o.get("f") == "generate")
        acks = [o.get("value") for o in history
                if o.get("type") == "ok" and o.get("f") == "generate"]
        counts = Counter(acks)
        dups = {v: c for v, c in counts.items() if c > 1}
        rng = [min(acks, key=_cmp_key), max(acks, key=_cmp_key)] if acks else None
        return {
            "valid?": not dups,
            "attempted-count": attempted,
            "acknowledged-count": len(acks),
            "duplicated-count": len(dups),
            "duplicated": dict(sorted(dups.items(), key=lambda kv: -kv[1])[:48]),
            "range": rng,
        }


def _cmp_key(x):
    return (0, x) if isinstance(x, (int, float)) else (1, repr(x))


class CounterChecker(Checker):
    """Monotonically-increasing counter: each read must land within
    [sum of ok adds at invoke, sum of attempted adds at completion]
    (checker.clj:734-792 — exact bound-update discipline mirrored)."""

    def check(self, test, history, opts=None):
        # the reference preprocesses with history/complete and drops failed
        # ops *and their invocations* (remove :fails? / op/fail?,
        # checker.clj:756-759) — a failed add never inflates the bounds
        failed_invokes = set()
        open_by_process: dict = {}
        for i, o in enumerate(history):
            p = o.get("process")
            if o.get("type") == "invoke":
                open_by_process[p] = i
            else:
                j = open_by_process.pop(p, None)
                if o.get("type") == "fail" and j is not None:
                    failed_invokes.add(j)

        lower = 0
        upper = 0
        pending_reads: dict = {}  # process -> [lower_at_invoke, value]
        reads = []
        for i, o in enumerate(history):
            t, f, p = o.get("type"), o.get("f"), o.get("process")
            if t == "fail" or i in failed_invokes:
                continue
            if (t, f) == ("invoke", "read"):
                pending_reads[p] = [lower, o.get("value")]
            elif (t, f) == ("ok", "read"):
                r = pending_reads.pop(p, [lower, o.get("value")])
                reads.append([r[0], o.get("value"), upper])
            elif (t, f) == ("invoke", "add"):
                v = o.get("value") or 0
                assert v >= 0, "counter checker assumes non-negative adds"
                upper += v
            elif (t, f) == ("ok", "add"):
                lower += o.get("value") or 0
        errors = [r for r in reads
                  if not (r[0] <= r[1] <= r[2]) if r[1] is not None]
        return {"valid?": not errors, "reads": reads, "errors": errors}


# constructor-style API mirroring jepsen.checker names
def stats():
    return Stats()


def unhandled_exceptions():
    return UnhandledExceptions()


def queue(model):
    return Queue(model)


def set_checker():
    return Set()


def set_full(linearizable: bool = False):
    return SetFull(linearizable)


def total_queue():
    return TotalQueue()


def unique_ids():
    return UniqueIds()


def counter():
    return CounterChecker()
