"""Packed host linearizability engine — the honest CPU baseline.

Same JIT-linearization frontier as `checker.linear` (that docstring is
the spec), but configurations are plain ints — (packed model state,
linearized-open-slot bitmask) — produced by the SAME encoding the
device engines use (`parallel.encode`), and model steps are direct
integer functions mirroring `parallel.steps`. No Model objects, no
frozensets: this is the fastest fair CPU implementation of the search
we know how to write, and it is the baseline `bench.py` measures the
device against (a slow baseline would flatter the speedup — VERDICT r2
"vs_baseline is still a misleading number").

Also useful in its own right: the quick host path for histories whose
windows are small, and a third differential oracle for the device
engines (same encoding, independent execution).
"""

from __future__ import annotations

import time as _time
from typing import Optional


def _py_steps(step_name: str):
    """Integer twins of parallel.steps — scalar Python, same contract:
    step(state, f, a0, a1, wild) -> (state', ok)."""
    from jepsen_tpu.models import (
        F_ACQUIRE, F_ADD, F_CAS, F_DEQ, F_ENQ, F_READ, F_RELEASE, F_WRITE)

    if step_name == "register":
        def step(s, f, a0, a1, wild):
            if wild:
                return s, True
            if f == F_READ:
                return s, s == a0
            if f == F_WRITE:
                return a0, True
            if f == F_CAS:
                return (a1, True) if s == a0 else (s, False)
            return s, False
    elif step_name == "mutex":
        def step(s, f, a0, a1, wild):
            if wild:
                return s, True
            if f == F_ACQUIRE:
                return (1, True) if s == 0 else (s, False)
            if f == F_RELEASE:
                return (0, True) if s == 1 else (s, False)
            return s, False
    elif step_name == "gset":
        def step(s, f, a0, a1, wild):
            if wild:
                return s, True
            if f == F_ADD:
                return s | (1 << a0), True
            if f == F_READ:
                return s, s == a0
            return s, False
    elif step_name == "uqueue":
        def step(s, f, a0, a1, wild):
            if wild:
                return s, True
            if f == F_ENQ:
                return s + (1 << a0), True
            if f == F_DEQ:
                cnt = (s >> a0) & a1
                return (s - (1 << a0), True) if cnt > 0 else (s, False)
            return s, False
    elif step_name == "fifo":
        def step(s, f, a0, a1, wild):
            if wild:
                return s, True
            if f == F_ENQ:
                depth = (s.bit_length() + a1 - 1) // a1
                return s | (a0 << (a1 * depth)), True
            if f == F_DEQ:
                head = s & ((1 << a1) - 1)
                if head != 0 and (a0 < 0 or head == a0):
                    return s >> a1, True
                return s, False
            return s, False
    else:
        raise ValueError(f"no packed host step for {step_name!r}")
    return step


def check_encoded(e, max_configs: int = 2_000_000,
                  deadline: Optional[float] = None,
                  cancel=None) -> dict:
    """Run the frontier search over an EncodedHistory on the host with
    int configs. Same result shape as linear.check_calls (sans paths).
    `cancel` (a threading.Event) is polled wherever the deadline is: a
    competition race sets it when another arm already won."""
    def _stop():
        """Indecisive-return fields when the search must stop, else None
        ("timeout" for a blown deadline, "cancelled" for a lost race)."""
        if deadline is not None and _time.monotonic() > deadline:
            return {"valid?": "unknown", "timeout": True}
        if cancel is not None and cancel.is_set():
            return {"valid?": "unknown", "error": "cancelled"}
        return None
    from jepsen_tpu.parallel.encode import fail_op_fields

    step = _py_steps(e.step_name)
    configs = {(int(e.state0), 0)}
    explored = 0
    max_frontier = 1
    R = e.n_returns
    # plain Python lists: per-element numpy scalar indexing in the hot
    # loop would slow the baseline and flatter the device comparison
    slot_f, slot_a0 = e.slot_f.tolist(), e.slot_a0.tolist()
    slot_a1, slot_wild = e.slot_a1.tolist(), e.slot_wild.tolist()
    slot_occ, ev_slot = e.slot_occ.tolist(), e.ev_slot.tolist()
    C = len(slot_f[0]) if R else 0

    for r in range(R):
        stop = _stop()
        if stop:
            return {**stop, "events-done": r,
                    "explored": explored, "max-frontier": max_frontier}
        occ = [(j, slot_f[r][j], slot_a0[r][j], slot_a1[r][j],
                slot_wild[r][j])
               for j in range(C) if slot_occ[r][j]]
        frontier = configs
        next_check = explored + 131072
        while frontier:
            new = set()
            for s, m in frontier:
                if explored >= next_check:
                    # stride deadline check: even ONE expansion round
                    # over a 2^k frontier must not overshoot unboundedly
                    next_check = explored + 131072
                    stop = _stop()
                    if stop:
                        return {**stop,
                                "events-done": r, "explored": explored,
                                "max-frontier": max(max_frontier,
                                                    len(configs))}
                for j, f, a0, a1, wild in occ:
                    bit = 1 << j
                    if m & bit:
                        continue
                    s2, ok = step(s, f, a0, a1, wild)
                    explored += 1
                    if not ok:
                        continue
                    cfg = (s2, m | bit)
                    if cfg not in configs and cfg not in new:
                        new.add(cfg)
            configs |= new
            frontier = new
            if len(configs) > max_configs:
                return {"valid?": "unknown",
                        "error": f"config budget exceeded ({max_configs})",
                        "events-done": r, "explored": explored,
                        "max-frontier": max(max_frontier, len(configs))}
            stop = _stop()
            if stop:
                # mid-window stop check: a single wide window's
                # expansion must not overshoot unboundedly
                return {**stop,
                        "events-done": r, "explored": explored,
                        "max-frontier": max(max_frontier, len(configs))}
        max_frontier = max(max_frontier, len(configs))
        bit = 1 << int(ev_slot[r])
        configs = {(s, m & ~bit) for s, m in configs if m & bit}
        if not configs:
            out = {"valid?": False, "explored": explored,
                   "max-frontier": max_frontier,
                   "final-paths": [], "configs": []}
            out.update(fail_op_fields(e, r))
            return out

    return {"valid?": True, "explored": explored,
            "max-frontier": max_frontier, "configs": [], "final-paths": []}


def analysis(model, history, max_configs: int = 2_000_000,
             deadline: Optional[float] = None, cancel=None) -> dict:
    """knossos-style (model, history) -> result, packed host engine.
    Raises EncodeError (via parallel.encode) for non-packable inputs —
    callers fall back to checker.linear / checker.wgl."""
    from jepsen_tpu.history import History
    from jepsen_tpu.parallel import encode as enc_mod
    h = history if isinstance(history, History) else History.wrap(history)
    e = enc_mod.encode(model, h)
    return check_encoded(e, max_configs=max_configs, deadline=deadline,
                         cancel=cancel)
