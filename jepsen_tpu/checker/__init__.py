"""Checker suite — validity analysis over histories.

Re-exports the protocol + combinators (`core`), the linear-time checkers
(`suite`), and the linearizability dispatcher (`linearizable`). Reference:
jepsen/src/jepsen/checker.clj (834 LoC) — see each module's docstring for
the file:line parity map.
"""

from jepsen_tpu.checker.core import (  # noqa: F401
    Checker,
    FnChecker,
    UNKNOWN,
    check_safe,
    compose,
    concurrency_limit,
    merge_valid,
    noop,
    unbridled_optimism,
    valid_priority,
)
from jepsen_tpu.checker.suite import (  # noqa: F401
    counter,
    queue,
    set_checker,
    set_full,
    stats,
    total_queue,
    unhandled_exceptions,
    unique_ids,
)
from jepsen_tpu.checker.linearizable import linearizable  # noqa: F401
from jepsen_tpu.checker.clock import clock_plot  # noqa: F401
from jepsen_tpu.checker.perf import perf as perf_checker  # noqa: F401
from jepsen_tpu.checker.timeline import html as timeline_html  # noqa: F401
