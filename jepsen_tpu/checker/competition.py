"""First-decisive-verdict-wins race between the device and host engines.

The reference's default analyzer races knossos `linear` and `wgl` in
parallel futures; the first decisive result wins and the loser's future
is cancelled (jepsen/src/jepsen/checker.clj:199,
knossos/src/knossos/competition.clj). Here the arms are:

    jax     the TPU engine (jepsen_tpu.parallel.engine) — normally the
            winner by orders of magnitude, but it can WEDGE when the
            device runtime dies mid-call (observed: a TPU tunnel outage
            blocks forever inside PJRT with no Python-level signal
            delivery);
    packed  the int-config host frontier — fastest host arm, the hedge
            that keeps a dead device runtime from turning a check into
            a hang;
    wgl     the host depth-first search — decisive where the frontier
            arms go "unknown" (config-budget blowups), and the only arm
            for models that don't pack.

Cancellation is cooperative for the host arms (a threading.Event they
poll at their deadline stride). The device arm cannot be interrupted
mid-dispatch — the same is true of a JVM future blocked in native code,
which `future-cancel` also cannot stop — so its thread is a daemon and
the race simply stops waiting for it once another arm is decisive.

A decisive verdict is `valid?` in {True, False}; "unknown" and crashes
are indecisive, and the race returns the best indecisive result only
when every arm failed to decide.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

# Grace period once only device arms remain undecided: host arms have
# all reported indecisive, so the race is waiting on an arm that may be
# wedged in PJRT — wait this long, then report rather than hang.
DEVICE_ARM_GRACE_SECS = 60.0

# Device arms their race has given up on: thread -> the instant the
# race returned without them. An ORPHANED arm that stays silent long
# past any sane dispatch is the mid-process wedge signature (tunnel
# died AFTER the availability probe cached healthy);
# device_engine_suspect() lets the dispatcher stop adding device arms —
# and leaking one stuck thread — to further checks. Orphans that do
# eventually report are removed, so a merely-slow (but healthy) device
# clears the suspicion and rejoins later races: suspicion is
# RECOVERABLE, unlike the process-wide availability cache.
_device_arms: dict = {}      # running device arms: thread -> start
_orphaned: dict = {}         # given-up device arms: thread -> orphaned-at
_device_arms_lock = threading.Lock()
DEVICE_WEDGE_SUSPECT_SECS = 120.0


def device_engine_suspect() -> bool:
    """True while some device arm whose race already gave up on it has
    stayed silent for DEVICE_WEDGE_SUSPECT_SECS past the give-up — the
    mid-process device-runtime wedge signature. Self-clearing: the arm
    reporting (however late) removes it."""
    now = time.monotonic()
    with _device_arms_lock:
        return any(now - t0 > DEVICE_WEDGE_SUSPECT_SECS
                   for t0 in _orphaned.values())


def analysis(model, history, arms=("jax", "packed", "wgl"),
             timeout: Optional[float] = None) -> dict:
    """Race the given arms over (model, history); first decisive verdict
    wins. Returns the winner's result with "analyzer" set to the winning
    arm and a "competition" field naming winner and arms. `timeout`
    bounds the WHOLE race (one monotonic deadline, seconds); on expiry
    the best indecisive result so far is returned with valid?
    "unknown". Even without a timeout the race cannot hang on a wedged
    device arm: once every host arm has reported, the wait for the
    remaining device arm(s) is bounded by DEVICE_ARM_GRACE_SECS."""
    cancel = threading.Event()
    results: queue.Queue = queue.Queue()

    def run_arm(name):
        try:
            if name == "jax":
                from jepsen_tpu.parallel import engine
                me = threading.current_thread()
                try:
                    with _device_arms_lock:
                        _device_arms[me] = time.monotonic()
                    r = engine.analysis(model, history)
                finally:
                    with _device_arms_lock:
                        _device_arms.pop(me, None)
                        _orphaned.pop(me, None)
            elif name == "packed":
                from jepsen_tpu.checker import linear_packed
                r = linear_packed.analysis(model, history, cancel=cancel)
            elif name == "linear":
                from jepsen_tpu.checker import linear
                r = linear.analysis(model, history, cancel=cancel)
            elif name == "wgl":
                from jepsen_tpu.checker import wgl
                r = wgl.analysis(model, history, cancel=cancel)
            else:
                raise ValueError(f"unknown competition arm {name!r}")
        except Exception as err:  # noqa: BLE001 — a crashed arm loses;
            # the race decides from the survivors (crash kept for the
            # all-indecisive report)
            r = {"valid?": "unknown", "error": repr(err)}
        results.put((name, r))

    threads = []
    for name in arms:
        # daemon: a wedged device arm must never block process exit
        t = threading.Thread(target=run_arm, args=(name,), daemon=True,
                             name=f"competition-{name}")
        t.start()
        threads.append((name, t))

    deadline = None if timeout is None else time.monotonic() + timeout
    grace_deadline = None
    indecisive = {}
    pending = set(arms)

    def handle(name, r):
        """Absorb one arm result; the winner's dict when decisive."""
        pending.discard(name)
        if r.get("valid?") in (True, False):
            cancel.set()
            out = dict(r)
            out["analyzer"] = name
            out["competition"] = {"winner": name, "arms": list(arms)}
            return out
        indecisive[name] = r
        return None

    try:
        while pending:
            now = time.monotonic()
            limits = []
            if deadline is not None:
                limits.append(deadline)
            if pending <= {"jax"}:
                # only wedge-prone device arms are left: bound the wait
                # instead of trusting PJRT to return — even when an
                # explicit (possibly large) race timeout is set
                if grace_deadline is None:
                    grace_deadline = now + DEVICE_ARM_GRACE_SECS
                limits.append(grace_deadline)
            wait = min(limits) - now if limits else None
            if wait is not None and wait <= 0:
                # expiry: drain anything already posted — an arm may
                # have delivered a decisive verdict just before the
                # deadline, and "unknown" must not beat it
                while True:
                    try:
                        name, r = results.get_nowait()
                    except queue.Empty:
                        break
                    win = handle(name, r)
                    if win:
                        return win
                break
            try:
                name, r = results.get(timeout=wait)
            except queue.Empty:
                continue  # re-check deadlines; expiry handled above
            win = handle(name, r)
            if win:
                return win

        cancel.set()
        return {"valid?": "unknown",
                "error": "no competition arm produced a decisive verdict"
                         + ("" if not pending
                            else f" in time ({sorted(pending)} still "
                                 f"running)"),
                "analyzer": "competition",
                "competition": {"winner": None, "arms": list(arms),
                                "results": indecisive}}
    finally:
        # any device arm we stop waiting for becomes an orphan — the
        # input to the mid-process wedge detection above
        if "jax" in pending:
            now = time.monotonic()
            with _device_arms_lock:
                for name, t in threads:
                    if name == "jax" and t in _device_arms:
                        _orphaned[t] = now
