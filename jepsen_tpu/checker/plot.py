"""Self-contained SVG plotting engine for performance graphs
(reference: jepsen/src/jepsen/checker/perf.clj's gnuplot layer).

The reference shells out to gnuplot (perf.clj:417-480 `plot!`); this
build renders SVG directly — no external process, no raster output,
and the artifacts diff cleanly in version control. The plot *model* is
kept the same shape as the reference's so perf.py reads like its
counterpart: a plot is a dict

    {"title":     str,
     "ylabel":    str,
     "series":    [series...],
     "logscale":  "y" | None,
     "xrange":    (xmin, xmax) | None,
     "yrange":    (ymin, ymax) | None,
     "nemeses":   [nemesis-activity...]}     # see with_nemeses

and a series is

    {"title": str | None,
     "with":  "points" | "lines" | "linespoints" | "steps",
     "color": "#rrggbb",
     "point_type": int,          # marker shape index
     "data":  [(x, y), ...]}

Bucketing/quantile helpers mirror perf.clj:21-85; range broadening
mirrors perf.clj:334-360; nemesis regions/lines mirror
perf.clj:240-310."""

from __future__ import annotations

import html as _html
import math
from typing import Dict, List, Optional, Sequence, Tuple

from jepsen_tpu.util import nanos_to_secs, nemesis_intervals

DEFAULT_NEMESIS_COLOR = "#cccccc"
NEMESIS_ALPHA = 0.6

WIDTH, HEIGHT = 900, 400          # plot canvas (perf.clj preamble size)
LEGEND_WIDTH = 180
MARGIN = {"left": 70, "right": 20, "top": 40, "bottom": 45}


# ------------------------------------------------------------ buckets


def bucket_scale(dt: float, b: int) -> float:
    """Midpoint time of bucket number b (perf.clj:21-25)."""
    return b * dt + dt / 2


def bucket_time(dt: float, t: float) -> float:
    """Midpoint of the bucket containing time t (perf.clj:27-31)."""
    return bucket_scale(dt, int(t // dt))


def buckets(dt: float, tmax: float) -> List[float]:
    """Bucket midpoints from 0 up to tmax (perf.clj:33-39)."""
    out, b = [], 0
    while True:
        t = bucket_scale(dt, b)
        if t > tmax:
            return out
        out.append(t)
        b += 1


def bucket_points(dt: float, points: Sequence) -> Dict[float, list]:
    """Group [t, v] points by bucket midpoint, sorted (perf.clj:41-48)."""
    out: Dict[float, list] = {}
    for p in points:
        out.setdefault(bucket_time(dt, p[0]), []).append(p)
    return dict(sorted(out.items()))


def quantiles(qs: Sequence[float], points: Sequence) -> Optional[dict]:
    """Map of quantile -> value at that quantile (perf.clj:50-61)."""
    s = sorted(points)
    if not s:
        return None
    n = len(s)
    return {q: s[min(n - 1, int(math.floor(n * q)))] for q in qs}


def latencies_to_quantiles(dt: float, qs: Sequence[float],
                           points: Sequence) -> Dict[float, list]:
    """Per-bucket latency quantiles: {q: [[t, v], ...]} (perf.clj:63-86)."""
    bs = [(t, quantiles(qs, [p[1] for p in ps]))
          for t, ps in bucket_points(dt, points).items()]
    return {q: [[t, qv[q]] for t, qv in bs] for q in qs}


def broaden_range(rng: Tuple[float, float]) -> Tuple[float, float]:
    """Expand a range to land on tidy integral boundaries
    (perf.clj:334-357)."""
    a, b = rng
    if a == b:
        return (a - 1, a + 1)
    size = abs(float(b) - float(a))
    grid = size / 10
    scale = 10 ** round(math.log10(grid)) if grid > 0 else 1
    a2 = a - (a % scale)
    m = b % scale
    b2 = b if (m / scale) < 0.001 else (scale + b - m)
    return (min(a, a2), max(b, b2))


def with_range(plot: dict) -> dict:
    """Fill in missing xrange/yrange from the series data
    (perf.clj:368-392). Raises NoPoints when every series is empty."""
    data = [p for s in plot.get("series", []) for p in s.get("data", [])]
    if not data:
        raise NoPoints(plot)
    xs = [p[0] for p in data]
    ys = [p[1] for p in data]
    xrange = broaden_range((min(xs), max(xs)))
    if plot.get("logscale") == "y":
        yrange = (min(ys), max(ys))  # don't broaden toward 0 on log scale
    else:
        yrange = broaden_range((min(ys), max(ys)))
    plot = dict(plot)
    plot.setdefault("xrange", xrange)
    plot.setdefault("yrange", yrange)
    if plot["xrange"] is None:
        plot["xrange"] = xrange
    if plot["yrange"] is None:
        plot["yrange"] = yrange
    return plot


class NoPoints(Exception):
    """No data to plot (perf.clj's ::no-points condition)."""


def has_data(plot: dict) -> bool:
    return any(s.get("data") for s in plot.get("series", []))


def without_empty_series(plot: dict) -> dict:
    plot = dict(plot)
    plot["series"] = [s for s in plot.get("series", []) if s.get("data")]
    return plot


# ----------------------------------------------------- nemesis overlay


def nemesis_ops(nemeses: Optional[Sequence[dict]], history) -> List[dict]:
    """Partition nemesis ops in the history among the nemesis specs by
    their :f sets; unmatched ops get a default spec (perf.clj:145-177).
    Spec keys: name, color, start (set of fs), stop, fs."""
    nemeses = list(nemeses or [])
    index = {}
    for spec in nemeses:
        index.update({f: spec.get("name") for f in _spec_fs(spec)})
    by_name: Dict[Optional[str], list] = {}
    for o in history:
        if o.get("process") == "nemesis":
            by_name.setdefault(index.get(o.get("f")), []).append(o)
    out = []
    for spec in nemeses:
        ops = by_name.get(spec.get("name"))
        if ops:
            out.append({**spec, "ops": ops})
    if by_name.get(None):
        out.append({"name": "nemesis", "ops": by_name[None]})
    return out


def _spec_fs(spec: dict) -> tuple:
    """(starts, stops, others) for a nemesis spec, flattened. The
    'start'/'stop' defaults apply only to specs that name no fs at all —
    an fs-only spec (e.g. membership) must not capture other packages'
    start/stop ops."""
    starts, stops = spec.get("start"), spec.get("stop")
    others = list(spec.get("fs") or [])
    if starts is None and stops is None and not others:
        starts, stops = ["start"], ["stop"]
    return list(starts or []) + list(stops or []) + others


def _spec_start_stop(spec: dict) -> tuple:
    starts, stops = spec.get("start"), spec.get("stop")
    if starts is None and stops is None and not spec.get("fs"):
        starts, stops = ["start"], ["stop"]
    return tuple(starts or []), tuple(stops or [])


def nemesis_activity(nemeses: Optional[Sequence[dict]],
                     history) -> List[dict]:
    """Augment each active spec with [start, stop] op intervals
    (perf.clj:179-190)."""
    out = []
    for spec in nemesis_ops(nemeses, history):
        starts, stops = _spec_start_stop(spec)
        ivs = nemesis_intervals(spec["ops"], fs_start=starts,
                                fs_stop=stops)
        out.append({**spec, "intervals": ivs})
    return out


def with_nemeses(plot: dict, history, nemeses) -> dict:
    plot = dict(plot)
    plot["nemeses"] = nemesis_activity(nemeses, history)
    return plot


# ------------------------------------------------------------- render


MARKERS = ("circle", "square", "triangle", "diamond", "plus", "cross")


def _marker_svg(shape: str, x: float, y: float, r: float,
                color: str) -> str:
    if shape == "circle":
        return (f'<circle cx="{x:.1f}" cy="{y:.1f}" r="{r}" '
                f'fill="{color}"/>')
    if shape == "square":
        return (f'<rect x="{x - r:.1f}" y="{y - r:.1f}" width="{2 * r}" '
                f'height="{2 * r}" fill="{color}"/>')
    if shape == "triangle":
        pts = f"{x:.1f},{y - r:.1f} {x - r:.1f},{y + r:.1f} " \
              f"{x + r:.1f},{y + r:.1f}"
        return f'<polygon points="{pts}" fill="{color}"/>'
    if shape == "diamond":
        pts = f"{x:.1f},{y - r:.1f} {x + r:.1f},{y:.1f} " \
              f"{x:.1f},{y + r:.1f} {x - r:.1f},{y:.1f}"
        return f'<polygon points="{pts}" fill="{color}"/>'
    if shape == "plus":
        return (f'<path d="M{x - r:.1f} {y:.1f}H{x + r:.1f}'
                f'M{x:.1f} {y - r:.1f}V{y + r:.1f}" stroke="{color}" '
                f'stroke-width="1.5"/>')
    return (f'<path d="M{x - r:.1f} {y - r:.1f}L{x + r:.1f} {y + r:.1f}'
            f'M{x + r:.1f} {y - r:.1f}L{x - r:.1f} {y + r:.1f}" '
            f'stroke="{color}" stroke-width="1.5"/>')


def _ticks_linear(lo: float, hi: float, n: int = 6) -> List[float]:
    if hi <= lo:
        return [lo]
    raw = (hi - lo) / n
    mag = 10 ** math.floor(math.log10(raw))
    step = min((m * mag for m in (1, 2, 5, 10) if m * mag >= raw),
               default=mag)
    t = math.ceil(lo / step) * step
    out = []
    while t <= hi + 1e-12:
        out.append(round(t, 10))
        t += step
    return out or [lo]


def _ticks_log(lo: float, hi: float) -> List[float]:
    lo = max(lo, 1e-12)
    out = []
    e = math.floor(math.log10(lo))
    while 10 ** e <= hi * 1.0001:
        if 10 ** e >= lo * 0.9999:
            out.append(10 ** e)
        e += 1
    return out or [lo]


def _fmt(v: float) -> str:
    if v == 0:
        return "0"
    if abs(v) >= 1000 or abs(v) < 0.01:
        return f"{v:.0e}".replace("e+0", "e").replace("e-0", "e-")
    if float(v) == int(v):
        return str(int(v))
    return f"{v:g}"


def render(plot: dict) -> str:
    """Render the plot model to an SVG string."""
    plot = without_empty_series(plot)
    plot = with_range(plot)
    x0, x1 = plot["xrange"]
    y0, y1 = plot["yrange"]
    log_y = plot.get("logscale") == "y"
    if log_y:
        y0 = max(y0, 1e-9)
        y1 = max(y1, y0 * 10)

    pl, pr = MARGIN["left"], WIDTH - MARGIN["right"]
    pt, pb = MARGIN["top"], HEIGHT - MARGIN["bottom"]

    def sx(x: float) -> float:
        return pl + (x - x0) / (x1 - x0 or 1) * (pr - pl)

    def sy(y: float) -> float:
        if log_y:
            ly0, ly1 = math.log10(y0), math.log10(y1)
            ly = math.log10(max(y, 1e-12))
            return pb - (ly - ly0) / (ly1 - ly0 or 1) * (pb - pt)
        return pb - (y - y0) / (y1 - y0 or 1) * (pb - pt)

    svg: List[str] = []
    total_w = WIDTH + LEGEND_WIDTH
    svg.append(
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{total_w}" '
        f'height="{HEIGHT}" viewBox="0 0 {total_w} {HEIGHT}" '
        f'font-family="Helvetica,Arial,sans-serif" font-size="11">')
    svg.append(f'<rect width="{total_w}" height="{HEIGHT}" fill="white"/>')

    # nemesis regions: stacked twelfth-height bands (perf.clj:240-269)
    for i, nem in enumerate(plot.get("nemeses") or []):
        color = nem.get("fill-color") or nem.get("color") \
            or DEFAULT_NEMESIS_COLOR
        alpha = nem.get("transparency", NEMESIS_ALPHA)
        height, padding = 0.0834, 0.00615
        bot = 1 - height * (i + 1)
        top = bot + height
        ry0 = pt + (1 - (top - padding)) * (pb - pt)
        ry1 = pt + (1 - (bot + padding)) * (pb - pt)
        for start, stop in nem.get("intervals", []):
            t_start = nanos_to_secs(start.get("time") or 0)
            rx0 = max(pl, min(pr, sx(t_start)))
            rx1 = pr if stop is None else \
                max(pl, min(pr, sx(nanos_to_secs(stop.get("time") or 0))))
            svg.append(
                f'<rect x="{rx0:.1f}" y="{ry0:.1f}" '
                f'width="{max(0.5, rx1 - rx0):.1f}" '
                f'height="{ry1 - ry0:.1f}" fill="{color}" '
                f'fill-opacity="{alpha}"/>')
        # vertical event lines (perf.clj:271-293)
        line_color = nem.get("line-color") or nem.get("color") \
            or DEFAULT_NEMESIS_COLOR
        for o in nem.get("ops", []):
            t = nanos_to_secs(o.get("time") or 0)
            if x0 <= t <= x1:
                lx = sx(t)
                svg.append(
                    f'<line x1="{lx:.1f}" y1="{pt}" x2="{lx:.1f}" '
                    f'y2="{pb}" stroke="{line_color}" '
                    f'stroke-width="1"/>')

    # axes + grid
    xticks = _ticks_linear(x0, x1)
    yticks = _ticks_log(y0, y1) if log_y else _ticks_linear(y0, y1)
    for t in xticks:
        tx = sx(t)
        svg.append(f'<line x1="{tx:.1f}" y1="{pt}" x2="{tx:.1f}" '
                   f'y2="{pb}" stroke="#eeeeee"/>')
        svg.append(f'<text x="{tx:.1f}" y="{pb + 16}" '
                   f'text-anchor="middle">{_fmt(t)}</text>')
    for t in yticks:
        ty = sy(t)
        svg.append(f'<line x1="{pl}" y1="{ty:.1f}" x2="{pr}" '
                   f'y2="{ty:.1f}" stroke="#eeeeee"/>')
        svg.append(f'<text x="{pl - 6}" y="{ty + 4:.1f}" '
                   f'text-anchor="end">{_fmt(t)}</text>')
    svg.append(f'<rect x="{pl}" y="{pt}" width="{pr - pl}" '
               f'height="{pb - pt}" fill="none" stroke="#333333"/>')

    # titles + labels (preamble: perf.clj:325-332,394-407)
    if plot.get("title"):
        svg.append(f'<text x="{(pl + pr) / 2}" y="20" text-anchor="middle" '
                   f'font-size="14">{_html.escape(plot["title"])}</text>')
    svg.append(f'<text x="{(pl + pr) / 2}" y="{HEIGHT - 8}" '
               f'text-anchor="middle">Time (s)</text>')
    if plot.get("ylabel"):
        svg.append(f'<text x="14" y="{(pt + pb) / 2}" text-anchor="middle" '
                   f'transform="rotate(-90 14 {(pt + pb) / 2})">'
                   f'{_html.escape(plot["ylabel"])}</text>')

    # series: fewest points drawn last = on top (perf.clj:447-462)
    ordered = sorted(plot["series"], key=lambda s: -len(s["data"]))
    for s in ordered:
        color = s.get("color", "#3366cc")
        mode = s.get("with", "points")
        marker = MARKERS[s.get("point_type", 0) % len(MARKERS)]
        pts = [(sx(x), sy(y)) for x, y in s["data"]
               if x0 <= x <= x1]
        if not pts:
            continue
        if mode in ("lines", "linespoints", "steps"):
            if mode == "steps":
                d = f"M{pts[0][0]:.1f} {pts[0][1]:.1f}"
                for (px, _), (qx, qy) in zip(pts, pts[1:]):
                    d += f"H{qx:.1f}V{qy:.1f}"
            else:
                d = "M" + "L".join(f"{x:.1f} {y:.1f}" for x, y in pts)
            svg.append(f'<path d="{d}" fill="none" stroke="{color}" '
                       f'stroke-width="1.3"/>')
        if mode in ("points", "linespoints"):
            for x, y in pts:
                svg.append(_marker_svg(marker, x, y, 2.5, color))

    # legend (outside right, like `set key outside top right`)
    ly = pt
    for s in plot["series"]:
        if not s.get("title"):
            continue
        color = s.get("color", "#3366cc")
        marker = MARKERS[s.get("point_type", 0) % len(MARKERS)]
        svg.append(_marker_svg(marker, WIDTH + 12, ly + 4, 3.5, color))
        svg.append(f'<text x="{WIDTH + 22}" y="{ly + 8}">'
                   f'{_html.escape(str(s["title"]))}</text>')
        ly += 16
    for nem in plot.get("nemeses") or []:
        color = nem.get("fill-color") or nem.get("color") \
            or DEFAULT_NEMESIS_COLOR
        svg.append(f'<rect x="{WIDTH + 6}" y="{ly}" width="12" height="8" '
                   f'fill="{color}" fill-opacity="{NEMESIS_ALPHA}"/>')
        svg.append(f'<text x="{WIDTH + 22}" y="{ly + 8}">'
                   f'{_html.escape(str(nem.get("name")))}</text>')
        ly += 16

    svg.append("</svg>")
    return "\n".join(svg)


