"""Clock-skew plot (reference: jepsen/src/jepsen/checker/clock.clj).

Consumes ops carrying :clock-offsets {node: seconds} — produced by the
clock nemesis (nemesis/time.py) — and renders each node's skew over
time as a step series into clock-skew.svg (clock.clj:13-75)."""

from __future__ import annotations

from typing import Dict, List

from jepsen_tpu.checker import plot as pl
from jepsen_tpu.checker.core import Checker
from jepsen_tpu.util import nanos_to_secs

SERIES_COLORS = ("#1f77b4", "#ff7f0e", "#2ca02c", "#d62728", "#9467bd",
                 "#8c564b", "#e377c2", "#7f7f7f", "#bcbd22", "#17becf")


def history_to_datasets(history) -> Dict:
    """node -> [[t, offset], ...], with a final sample pinned at the
    history's last time so steps extend to the edge (clock.clj:13-34)."""
    final_time = 0.0
    for o in history:
        if o.get("time") is not None:
            final_time = max(final_time, nanos_to_secs(o["time"]))
    series: Dict[str, List[list]] = {}
    for o in history:
        offsets = o.get("clock-offsets")
        if not offsets:
            continue
        t = nanos_to_secs(o.get("time") or 0)
        for node, off in offsets.items():
            series.setdefault(node, []).append([t, off])
    for node, points in series.items():
        points.append([final_time, points[-1][1]])
    return series


def short_node_names(nodes: List[str]) -> List[str]:
    """Strip common trailing domain components (clock.clj:36-45)."""
    split = [str(n).split(".") for n in nodes]
    if len(split) < 2:
        return [str(n) for n in nodes]
    # Longest common suffix across all names, kept only while proper.
    k = 0
    while all(len(s) > k + 1 for s in split) and \
            len({tuple(s[len(s) - k - 1:]) for s in split}) == 1:
        k += 1
    return [".".join(s[:len(s) - k]) for s in split]


class ClockPlot(Checker):
    """(clock.clj:47-75). Always valid; writes clock-skew.svg."""

    def check(self, test, history, opts=None):
        datasets = history_to_datasets(history)
        path = None
        if datasets:
            nodes = sorted(datasets, key=str)
            names = short_node_names(nodes)
            series = [{"title": name,
                       "with": "steps",
                       "color": SERIES_COLORS[i % len(SERIES_COLORS)],
                       "point_type": i,
                       "data": datasets[node]}
                      for i, (node, name) in enumerate(zip(nodes, names))]
            plot = {"title": f"{(test or {}).get('name', 'test')} "
                             f"clock skew",
                    "ylabel": "Skew (s)",
                    "series": series}
            nemeses = ((opts or {}).get("nemeses")
                       or ((test or {}).get("plot") or {}).get("nemeses"))
            try:
                plot = pl.with_nemeses(plot, history, nemeses)
                svg = pl.render(plot)
                store = (test or {}).get("store")
                if store is not None:
                    sub = (opts or {}).get("subdirectory")
                    parts = ([sub, "clock-skew.svg"] if sub
                             else ["clock-skew.svg"])
                    store.write_file(parts, svg)
                    path = store.path(*parts)
            except pl.NoPoints:
                pass
        return {"valid?": True, "clock-skew-graph": path}


def clock_plot() -> ClockPlot:
    return ClockPlot()
