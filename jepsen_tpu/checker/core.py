"""Checker protocol and combinators (reference: jepsen/src/jepsen/checker.clj:26-113).

A checker consumes a test map, an indexed history, and an opts dict, and
produces a results dict whose `"valid?"` key is True, False, or
`"unknown"`. This is the plugin boundary the TPU linearizability engine
slots in behind (SURVEY.md §2.10: "the plugin boundary the TPU backend
targets").

Validity lattice (checker.clj merge-valid): False > "unknown" > True —
any invalid makes the composition invalid; any unknown (absent invalid)
makes it unknown.
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Dict, Optional

from jepsen_tpu.util import bounded_pmap

UNKNOWN = "unknown"


def valid_priority(v) -> int:
    if v is False:
        return 0
    if v == UNKNOWN:
        return 1
    return 2


def merge_valid(vs) -> Any:
    """Worst-of validity (checker.clj:31-45)."""
    out = True
    for v in vs:
        if valid_priority(v) < valid_priority(out):
            out = v
    return out


class Checker:
    """Protocol: (check test history opts) -> results dict
    (checker.clj:49-64)."""

    def check(self, test, history, opts: Optional[dict] = None) -> Dict[str, Any]:
        raise NotImplementedError

    # name used by compose results and stores
    @property
    def checker_name(self) -> str:
        return type(self).__name__.lower()


class FnChecker(Checker):
    def __init__(self, fn, name="fn"):
        self._fn = fn
        self._name = name

    def check(self, test, history, opts=None):
        return self._fn(test, history, opts or {})

    @property
    def checker_name(self):
        return self._name


def check_safe(checker: Checker, test, history, opts=None) -> Dict[str, Any]:
    """Run a checker, converting exceptions into
    {"valid?": "unknown", "error": <trace>} (checker.clj:66-75) so one
    broken checker never loses a test's results."""
    try:
        return checker.check(test, history, opts or {})
    except Exception:  # noqa: BLE001
        return {"valid?": UNKNOWN, "error": traceback.format_exc()}


class Compose(Checker):
    """Map of name -> checker, all run (in parallel — checker.clj:84-96
    runs via pmap); results nested under each name plus merged validity."""

    def __init__(self, checkers: Dict[str, Checker]):
        self.checkers = dict(checkers)

    def check(self, test, history, opts=None):
        names = list(self.checkers)
        results = bounded_pmap(
            lambda n: check_safe(self.checkers[n], test, history, opts), names
        )
        out = dict(zip(names, results))
        out["valid?"] = merge_valid(r.get("valid?", UNKNOWN) for r in results)
        return out


def compose(checkers: Dict[str, Checker]) -> Compose:
    return Compose(checkers)


class ConcurrencyLimit(Checker):
    """At most `limit` concurrent executions of the wrapped checker across
    threads — bounds memory-hungry checks (checker.clj:98-113)."""

    def __init__(self, limit: int, checker: Checker):
        self.checker = checker
        self._sem = threading.Semaphore(limit)

    def check(self, test, history, opts=None):
        with self._sem:
            return self.checker.check(test, history, opts)


def concurrency_limit(limit: int, checker: Checker) -> ConcurrencyLimit:
    return ConcurrencyLimit(limit, checker)


class Noop(Checker):
    """Always valid, no analysis (checker.clj noop)."""

    def check(self, test, history, opts=None):
        return {"valid?": True}


class UnbridledOptimism(Checker):
    """Everything is awesome (checker.clj:115-119)."""

    def check(self, test, history, opts=None):
        return {"valid?": True, "everything": "awesome"}


def noop():
    return Noop()


def unbridled_optimism():
    return UnbridledOptimism()
