"""SVG rendering of a failed linearization analysis
(capability parallel of knossos.linear.report/render-analysis!, invoked
by the reference at jepsen/src/jepsen/checker.clj:203-207 to produce
linear.svg when a linearizability check fails).

Layout: time flows left to right; one horizontal lane per process; each
op is a rounded bar spanning invoke → completion. The counterexample op
(analysis["op"]) is outlined red. Each final-path (a maximal
linearization attempt, [{"op": .., "model": ..}, ...]) is drawn as a
colored polyline threading the linearized ops in order, its model state
annotated at every hop, ending at the point where no continuation was
legal."""

from __future__ import annotations

import html as _html
from typing import Dict, List, Optional

from jepsen_tpu.util import nanos_to_secs

BAR_H = 22
LANE_GAP = 14
LEFT = 110
RIGHT_PAD = 40
TOP = 50
TIME_W = 760

TYPE_FILL = {"ok": "#6DB6FE", "info": "#FFAA26", "fail": "#FEB5DA"}
PATH_COLORS = ("#d62728", "#9467bd", "#2ca02c", "#ff7f0e", "#17becf",
               "#8c564b", "#e377c2")


def _esc(s) -> str:
    return _html.escape(str(s))


def _pairs(history) -> List[dict]:
    """[{invoke, completion?}] spans in invocation order."""
    spans, open_by_p = [], {}
    for op in history:
        t, p = op.get("type"), op.get("process")
        if t == "invoke":
            span = {"invoke": op, "completion": None}
            open_by_p[p] = span
            spans.append(span)
        elif t in ("ok", "fail", "info") and p in open_by_p:
            open_by_p.pop(p)["completion"] = op
    return spans


def render_analysis(history, analysis: Dict,
                    title: str = "linearizability analysis") -> str:
    """The SVG document for a (typically failed) analysis."""
    spans = _pairs(history)
    if not spans:
        return ('<svg xmlns="http://www.w3.org/2000/svg" width="400" '
                'height="60"><text x="10" y="30">empty history</text></svg>')

    times = [s["invoke"].get("time") or 0 for s in spans] + \
            [s["completion"].get("time") or 0 for s in spans
             if s["completion"] is not None]
    t0, t1 = min(times), max(times)
    t1 = t1 if t1 > t0 else t0 + 1

    def sx(t) -> float:
        return LEFT + (t - t0) / (t1 - t0) * TIME_W

    procs: List = []
    for s in spans:
        p = s["invoke"].get("process")
        if p not in procs:
            procs.append(p)
    lane = {p: i for i, p in enumerate(procs)}

    def sy(p) -> float:
        return TOP + lane[p] * (BAR_H + LANE_GAP)

    height = TOP + len(procs) * (BAR_H + LANE_GAP) + 60
    width = LEFT + TIME_W + RIGHT_PAD
    bad_index = (analysis.get("op") or {}).get("index")

    svg = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
           f'height="{height}" viewBox="0 0 {width} {height}" '
           f'font-family="Helvetica,Arial,sans-serif" font-size="11">',
           f'<rect width="{width}" height="{height}" fill="white"/>',
           f'<text x="{LEFT}" y="24" font-size="14">{_esc(title)}</text>']

    for p in procs:
        svg.append(f'<text x="8" y="{sy(p) + BAR_H / 2 + 4:.0f}">'
                   f'process {_esc(p)}</text>')

    # op bars; remember each op's anchor point for path polylines
    anchor: Dict[int, tuple] = {}
    for s in spans:
        inv, comp = s["invoke"], s["completion"]
        x0 = sx(inv.get("time") or 0)
        x1 = sx(comp.get("time") or t1) if comp is not None \
            else LEFT + TIME_W
        y = sy(inv.get("process"))
        fill = TYPE_FILL.get((comp or {}).get("type"), "#eeeeee")
        idx = inv.get("index")
        is_bad = bad_index is not None and idx == bad_index
        stroke = ' stroke="#d00000" stroke-width="2"' if is_bad \
            else ' stroke="#888888" stroke-width="0.5"'
        svg.append(f'<rect x="{x0:.1f}" y="{y:.1f}" '
                   f'width="{max(3.0, x1 - x0):.1f}" height="{BAR_H}" '
                   f'rx="3" fill="{fill}"{stroke}/>')
        val = inv.get("value")
        if comp is not None and comp.get("value") != val and \
                comp.get("value") is not None:
            label = f"{inv.get('f')} {val!r} → {comp.get('value')!r}"
        else:
            label = f"{inv.get('f')} {val!r}"
        svg.append(f'<text x="{x0 + 3:.1f}" y="{y + BAR_H - 7:.1f}">'
                   f'{_esc(label)}</text>')
        if idx is not None:
            anchor[idx] = ((x0 + min(x1, x0 + 60)) / 2, y + BAR_H / 2)

    # final paths: polylines through linearized ops with model labels
    for i, path in enumerate(analysis.get("final-paths") or []):
        color = PATH_COLORS[i % len(PATH_COLORS)]
        pts, labels = [], []
        for step in path:
            op = step.get("op") or {}
            idx = op.get("index")
            if idx in anchor:
                x, y = anchor[idx]
                x += i * 3  # de-overlap concurrent paths slightly
                pts.append((x, y))
                labels.append((x, y, step.get("model")))
        if len(pts) >= 2:
            d = "M" + "L".join(f"{x:.1f} {y:.1f}" for x, y in pts)
            svg.append(f'<path d="{d}" fill="none" stroke="{color}" '
                       f'stroke-width="1.5" stroke-opacity="0.8"/>')
        for x, y, model in labels:
            if model is not None:
                svg.append(f'<text x="{x + 4:.1f}" y="{y - 4:.1f}" '
                           f'fill="{color}" font-size="9">'
                           f'{_esc(model)}</text>')
        if pts:
            x, y = pts[-1]
            svg.append(f'<circle cx="{x:.1f}" cy="{y:.1f}" r="4" '
                       f'fill="none" stroke="{color}" stroke-width="2"/>')

    if bad_index is not None:
        svg.append(f'<text x="{LEFT}" y="{height - 14}" fill="#d00000">'
                   f'No legal linearization past op {bad_index} '
                   f'({_esc((analysis.get("op") or {}).get("f"))} '
                   f'{_esc((analysis.get("op") or {}).get("value"))})'
                   f'</text>')
    svg.append("</svg>")
    return "\n".join(svg)


def render_analysis_file(history, analysis: Dict, test: Optional[dict],
                         opts: Optional[dict] = None) -> Optional[str]:
    """Write linear.svg into the test store, as the reference does on
    failure (checker.clj:203-207). Returns the path, or None without a
    store."""
    store = (test or {}).get("store")
    if store is None:
        return None
    sub = (opts or {}).get("subdirectory")
    parts = [sub, "linear.svg"] if sub else ["linear.svg"]
    store.write_file(parts, render_analysis(history, analysis))
    return store.path(*parts)
