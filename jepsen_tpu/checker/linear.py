"""Host-side just-in-time linearization checker (knossos.linear equivalent).

Lowe/Horn-style JIT linearization (the algorithm behind
`knossos.linear/analysis`, dispatched at reference
jepsen/src/jepsen/checker.clj:194-200): a *configuration* is
(model state, set of open calls already linearized). The history is
processed event by event; at each **return** event the frontier is
closed under "linearize any open, unlinearized call", then filtered to
configurations where the returning call has linearized. The history is
linearizable iff the frontier is non-empty after the last return —
crashed (:info) calls never return, so they stay optional
(SURVEY.md §7.3 hard part #2).

Completeness: any linearization can be reshuffled so every linearization
point sits immediately before the next return event, so closing only at
returns loses nothing.

This formulation is the *spec* for the TPU engine
(`jepsen_tpu.parallel.engine`): same frontier, same closure, same
filter — there the config packs into (i32 state, u64 slot-mask) and the
closure is a vmap'd, device-sharded expansion. Differential tests pin
the two (and `checker.wgl`) together.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from jepsen_tpu import models as model_ns
from jepsen_tpu.history import Call, calls as history_calls
from jepsen_tpu.checker.wgl import _StepOp


def _events(cs: List[Call]) -> List[Tuple[int, int, int]]:
    """(history_position, kind, call_id); kind 0=invoke, 1=return.
    Crashed calls emit no return."""
    ev = []
    for c in cs:
        ev.append((c.invoke_index, 0, c.index))
        if not c.crashed:
            ev.append((c.complete_index, 1, c.index))
    ev.sort()
    return ev


def check_calls(model, cs: List[Call], n_history: int,
                max_configs: int = 2_000_000,
                deadline: Optional[float] = None,
                cancel=None) -> dict:
    """With `deadline` (a time.monotonic() instant), the search returns
    {"valid?": "unknown", "timeout": True, "events-done": k, ...} when
    the budget runs out — cooperative, checked once per return event,
    so benchmark timeouts measure real search progress. `cancel` (a
    threading.Event) is polled at the same points: a competition race
    sets it when another arm already produced a decisive verdict."""
    import time as _time
    if not cs:
        return {"valid?": True, "configs": [], "final-paths": []}
    step_ops = [_StepOp(c) for c in cs]
    open_calls: set = set()
    configs = {(model, frozenset())}
    explored = 0
    max_frontier = 1
    events_done = 0

    for pos, kind, cid in _events(cs):
        if deadline is not None and _time.monotonic() > deadline:
            return {"valid?": "unknown", "timeout": True,
                    "events-done": events_done, "explored": explored,
                    "max-frontier": max_frontier}
        if cancel is not None and cancel.is_set():
            return {"valid?": "unknown", "error": "cancelled",
                    "events-done": events_done, "explored": explored,
                    "max-frontier": max_frontier}
        if kind == 0:
            open_calls.add(cid)
            continue
        events_done += 1
        # return event: closure, then require cid linearized
        frontier = set(configs)
        while frontier:
            new = set()
            for s, lin in frontier:
                for oc in open_calls:
                    if oc in lin:
                        continue
                    s2 = s.step(step_ops[oc])
                    explored += 1
                    if model_ns.is_inconsistent(s2):
                        continue
                    cfg = (s2, lin | {oc})
                    if cfg not in configs and cfg not in new:
                        new.add(cfg)
            configs |= new
            frontier = new
            if len(configs) > max_configs:
                # events_done was bumped when THIS event started; only
                # completed events count (matches the timeout path and
                # linear_packed)
                return {"valid?": "unknown",
                        "error": f"config budget exceeded ({max_configs})",
                        "events-done": events_done - 1,
                        "explored": explored,
                        "max-frontier": max(max_frontier, len(configs))}
        max_frontier = max(max_frontier, len(configs))
        configs = {(s, lin - {cid}) for s, lin in configs if cid in lin}
        open_calls.discard(cid)
        if not configs:
            c = cs[cid]
            return {
                "valid?": False,
                "op": {"process": c.process, "f": c.f,
                       "value": c.result if c.f in ("read", "dequeue")
                       else c.value,
                       "index": c.invoke_index},
                "explored": explored,
                "max-frontier": max_frontier,
                "final-paths": [],
                "configs": [],
            }

    return {"valid?": True, "explored": explored,
            "max-frontier": max_frontier, "configs": [], "final-paths": []}


def analysis(model, history, max_configs: int = 2_000_000,
             deadline: Optional[float] = None, cancel=None) -> dict:
    """knossos.linear/analysis equivalent."""
    from jepsen_tpu.history import History, prune_wildcard_calls
    h = history if isinstance(history, History) else History.wrap(history)
    cs = prune_wildcard_calls(history_calls(h))
    return check_calls(model, cs, len(h), max_configs=max_configs,
                       deadline=deadline, cancel=cancel)
