"""HTML op timeline (reference: jepsen/src/jepsen/checker/timeline.clj).

One column per process, one absolutely-positioned box per op pair, box
height proportional to duration (1 ms of history per pixel), colored by
completion type, with full op details in the hover title
(timeline.clj:20-33,85-158)."""

from __future__ import annotations

import html as _html
import json
from typing import List, Optional

from jepsen_tpu.checker.core import Checker

TIMESCALE = 1e6     # nanoseconds per pixel (timeline.clj:20)
COL_WIDTH = 100     # px (timeline.clj:21)
GUTTER_WIDTH = 106  # px (timeline.clj:22)
HEIGHT = 16         # px minimum box height (timeline.clj:23)

STYLESHEET = """\
.ops        { position: absolute; }
.op         { position: absolute; padding: 2px; border-radius: 2px;
              box-shadow: 0 1px 3px rgba(0,0,0,0.12),
                          0 1px 2px rgba(0,0,0,0.24);
              overflow: hidden; font-size: 10px;
              font-family: Helvetica, Arial, sans-serif; }
.op.invoke  { background: #eeeeee; }
.op.ok      { background: #6DB6FE; }
.op.info    { background: #FFAA26; }
.op.fail    { background: #FEB5DA; }
.op:target  { box-shadow: 0 14px 28px rgba(0,0,0,0.25),
                          0 10px 10px rgba(0,0,0,0.22); }
"""


def pairs(history) -> List[list]:
    """[invoke, completion] pairs (or [info] for unmatched infos),
    in history order (timeline.clj:35-54)."""
    out, open_by_process = [], {}
    for op in history:
        t, p = op.get("type"), op.get("process")
        if t == "invoke":
            open_by_process[p] = op
        elif t == "info" and p not in open_by_process:
            out.append([op])
        elif t in ("ok", "fail", "info"):
            inv = open_by_process.pop(p, None)
            out.append([inv, op] if inv is not None else [op])
    for inv in open_by_process.values():
        out.append([inv])
    return out


def _processes(history) -> List:
    """Processes in order of first appearance, nemesis last
    (timeline.clj:145-149 sort-processes)."""
    seen, order = set(), []
    for op in history:
        p = op.get("process")
        if p not in seen:
            seen.add(p)
            order.append(p)
    nums = sorted(p for p in order if isinstance(p, int))
    others = [p for p in order if not isinstance(p, int)]
    return nums + others


def _title(op, start, stop) -> str:
    lines = []
    if stop is not None and start.get("time") is not None \
            and stop.get("time") is not None:
        lines.append(f"Dur: {(stop['time'] - start['time']) // 1_000_000} ms")
    if op.get("error") is not None:
        lines.append(f"Err: {op['error']!r}")
    lines.append("Op:")
    lines.append(json.dumps({k: v for k, v in op.items()}, default=repr,
                            indent=1))
    return "\n".join(lines)


def _esc(s) -> str:
    return _html.escape(str(s))


def render_html(test, history) -> str:
    """The timeline document (timeline.clj:110-158)."""
    procs = _processes(history)
    col = {p: i for i, p in enumerate(procs)}
    t0 = next((o.get("time") for o in history
               if o.get("time") is not None), 0)
    body = []
    # process headers
    for p, i in col.items():
        body.append(
            f'<div style="position:absolute; left:{i * GUTTER_WIDTH}px; '
            f'top:0px; width:{COL_WIDTH}px; font-weight:bold">'
            f'{_esc(p)}</div>')
    for pair in pairs(history):
        start = pair[0]
        stop = pair[1] if len(pair) > 1 else None
        op = stop or start
        p = op.get("process")
        left = col.get(p, 0) * GUTTER_WIDTH
        start_t = start.get("time")
        start_t = t0 if start_t is None else start_t
        top = HEIGHT + (start_t - t0) / TIMESCALE
        if stop is not None and stop.get("time") is not None:
            h = max(HEIGHT, (stop["time"] - start_t) / TIMESCALE)
        else:
            h = HEIGHT
        idx = op.get("index", "")
        cls = op.get("type", "invoke")
        val = start.get("value")
        if stop is not None and stop.get("value") != val:
            txt = f"{op.get('f')} {val!r} → {stop.get('value')!r}"
        else:
            txt = f"{op.get('f')} {val!r}"
        body.append(
            f'<a href="#i{idx}"><div id="i{idx}" class="op {cls}" '
            f'style="left:{left}px; top:{top:.0f}px; '
            f'width:{COL_WIDTH}px; height:{h:.0f}px" '
            f'title="{_esc(_title(op, start, stop))}">'
            f'{_esc(p)} {_esc(txt)}</div></a>')
    name = (test or {}).get("name", "test")
    return (f"<!DOCTYPE html><html><head><title>{_esc(name)} timeline"
            f"</title><style>{STYLESHEET}</style></head>"
            f'<body><h1>{_esc(name)}</h1><div class="ops">'
            + "\n".join(body) + "</div></body></html>")


class Timeline(Checker):
    """Writes timeline.html into the store (timeline.clj:159-179)."""

    def check(self, test, history, opts=None):
        html_doc = render_html(test, history)
        store = (test or {}).get("store")
        path = None
        if store is not None:
            sub = (opts or {}).get("subdirectory")
            parts = [sub, "timeline.html"] if sub else ["timeline.html"]
            store.write_file(parts, html_doc)
            path = store.path(*parts)
        return {"valid?": True, "timeline": path}


def html() -> Timeline:
    return Timeline()
