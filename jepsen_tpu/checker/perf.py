"""Performance graphs: latency points, latency quantiles, op rate
(reference: jepsen/src/jepsen/checker/perf.clj + checker.clj:794-826).

Artifacts are SVG (latency-raw.svg, latency-quantiles.svg, rate.svg)
written into the test's store directory; the reference writes PNGs via
gnuplot. The perf *checker* composes all three and always returns
{"valid?": True} — graphs are diagnostics, not validity judgments
(checker.clj:794-826)."""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from jepsen_tpu.checker import plot as pl
from jepsen_tpu.checker.core import Checker
from jepsen_tpu.util import history_to_latencies, nanos_to_secs

TYPES = ("ok", "info", "fail")  # perf.clj:173-175

TYPE_COLOR = {"ok": "#81BFFC",  # perf.clj:177-181
              "info": "#FFA400",
              "fail": "#FF1E90"}

QUANTILE_COLORS = ("red", "orange", "purple", "blue", "green", "grey")


def latency_point(inv, lat_ns) -> list:
    """[time-of-invoke (s), latency (ms)] (perf.clj:143-148)."""
    return [nanos_to_secs(inv.get("time") or 0), lat_ns / 1e6]


def fs_to_points(fs: List) -> Dict:
    """f -> marker index, one marker shape per :f (perf.clj:150-156)."""
    return {f: i for i, f in enumerate(fs)}


def qs_to_colors(qs: List[float]) -> Dict:
    """quantile -> color, highest quantile reddest (perf.clj:158-171)."""
    return dict(zip(sorted(qs, reverse=True),
                    itertools.cycle(QUANTILE_COLORS)))


def invokes_by_f_type(pairs) -> Dict:
    """f -> completion-type -> [(invoke, latency)] (perf.clj:96-117)."""
    out: Dict = {}
    for inv, comp, lat in pairs:
        out.setdefault(inv.get("f"), {}) \
           .setdefault(comp.get("type"), []).append((inv, lat))
    return out


def _polysort(xs):
    return sorted(xs, key=lambda x: (str(type(x)), str(x)))


def _write(test, opts, filename: str, svg: str) -> Optional[str]:
    store = (test or {}).get("store")
    if store is None:
        return None
    sub = (opts or {}).get("subdirectory")
    parts = [sub, filename] if sub else [filename]
    store.write_file(parts, svg)
    return store.path(*parts)


def _nemeses(test, opts):
    return ((opts or {}).get("nemeses")
            or ((test or {}).get("plot") or {}).get("nemeses"))


def point_graph(test, history, opts=None, pairs=None,
                activity=None) -> Optional[str]:
    """Raw latency scatter: one point per completed op, colored by
    completion type, marker by :f (perf.clj:484-511). Returns the
    written path, or None with no data or no store to write to. Pass
    precomputed history_to_latencies pairs to avoid re-pairing."""
    if (test or {}).get("store") is None:
        return None
    pairs = pairs if pairs is not None else history_to_latencies(history)
    datasets = invokes_by_f_type(pairs)
    fs = _polysort(datasets)
    f_marker = fs_to_points(fs)
    series = []
    for f in fs:
        for t in TYPES:
            data = datasets.get(f, {}).get(t)
            if data:
                series.append({
                    "title": f"{f} {t}",
                    "with": "points",
                    "color": TYPE_COLOR[t],
                    "point_type": f_marker[f],
                    "data": [latency_point(inv, lat) for inv, lat in data]})
    plot = {"title": f"{(test or {}).get('name', 'test')} latency",
            "ylabel": "Latency (ms)",
            "logscale": "y",
            "series": series}
    try:
        plot["nemeses"] = (activity if activity is not None else
                           pl.nemesis_activity(_nemeses(test, opts),
                                               history))
        svg = pl.render(plot)
    except pl.NoPoints:
        return None
    return _write(test, opts, "latency-raw.svg", svg)


def quantiles_graph(test, history, opts=None,
                    dt: float = 30,
                    qs=(0.5, 0.95, 0.99, 1), pairs=None,
                    activity=None) -> Optional[str]:
    """Latency quantiles over dt-second windows, per :f
    (perf.clj:513-552)."""
    if (test or {}).get("store") is None:
        return None
    pairs = pairs if pairs is not None else history_to_latencies(history)
    by_f: Dict = {}
    for inv, _comp, lat in pairs:
        by_f.setdefault(inv.get("f"), []).append(latency_point(inv, lat))
    fs = _polysort(by_f)
    f_marker = fs_to_points(fs)
    q_color = qs_to_colors(list(qs))
    series = []
    for f in fs:
        quant = pl.latencies_to_quantiles(dt, list(qs), by_f[f])
        for q in qs:
            series.append({"title": f"{f} {q}",
                           "with": "linespoints",
                           "color": q_color[q],
                           "point_type": f_marker[f],
                           "data": quant.get(q) or []})
    plot = {"title": f"{(test or {}).get('name', 'test')} latency",
            "ylabel": "Latency (ms)",
            "logscale": "y",
            "series": series}
    try:
        plot["nemeses"] = (activity if activity is not None else
                           pl.nemesis_activity(_nemeses(test, opts),
                                               history))
        svg = pl.render(plot)
    except pl.NoPoints:
        return None
    return _write(test, opts, "latency-quantiles.svg", svg)


def rate_graph(test, history, opts=None, dt: float = 10,
               activity=None) -> Optional[str]:
    """Completion rate (hz) in dt-second buckets, by f and type
    (perf.clj:554-599). Nemesis completions are excluded (only integer
    processes count)."""
    if (test or {}).get("store") is None:
        return None
    td = 1.0 / dt
    t_max = 0.0
    rates: Dict = {}
    for o in history:
        t_max = max(t_max, nanos_to_secs(o.get("time") or 0))
        if o.get("type") == "invoke" or \
                not isinstance(o.get("process"), int):
            continue
        b = pl.bucket_time(dt, nanos_to_secs(o.get("time") or 0))
        key = (o.get("f"), o.get("type"))
        rates[key] = rates.get(key, {})
        rates[key][b] = rates[key].get(b, 0.0) + td
    fs = _polysort({f for f, _t in rates})
    f_marker = fs_to_points(fs)
    series = []
    for f in fs:
        for t in TYPES:
            m = rates.get((f, t))
            if m:
                series.append({
                    "title": f"{f} {t}",
                    "with": "linespoints",
                    "color": TYPE_COLOR[t],
                    "point_type": f_marker[f],
                    "data": [[b, m.get(b, 0.0)]
                             for b in pl.buckets(dt, t_max)]})
    plot = {"title": f"{(test or {}).get('name', 'test')} rate",
            "ylabel": "Throughput (hz)",
            "series": series}
    try:
        plot["nemeses"] = (activity if activity is not None else
                           pl.nemesis_activity(_nemeses(test, opts),
                                               history))
        svg = pl.render(plot)
    except pl.NoPoints:
        return None
    return _write(test, opts, "rate.svg", svg)


class Perf(Checker):
    """Renders latency and rate graphs (checker.clj:794-826). Always
    valid; the value is the artifacts."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}

    def check(self, test, history, opts=None):
        o = {**self.opts, **(opts or {})}
        # Pair invocations with completions and partition nemesis
        # activity once; all three graphs reuse the results.
        if (test or {}).get("store") is None:
            return {"valid?": True, "latency-graph": None,
                    "latency-quantiles-graph": None, "rate-graph": None}
        pairs = history_to_latencies(history)
        activity = pl.nemesis_activity(_nemeses(test, o), history)
        return {"valid?": True,
                "latency-graph": point_graph(test, history, o, pairs=pairs,
                                             activity=activity),
                "latency-quantiles-graph":
                    quantiles_graph(test, history, o, pairs=pairs,
                                    activity=activity),
                "rate-graph": rate_graph(test, history, o,
                                         activity=activity)}


def perf(opts: Optional[dict] = None) -> Perf:
    return Perf(opts)
