"""Linearizability checker — the `checker/linearizable` dispatcher.

Mirrors the reference's algorithm dispatch (jepsen/src/jepsen/checker.clj:182-213):
`:algorithm` selects the engine —

    "wgl"          host depth-first WGL search (jepsen_tpu.checker.wgl)
    "linear"       host JIT-linearization frontier (jepsen_tpu.checker.linear)
    "packed"       host frontier over int configs — the device encoding
                   run on CPU (jepsen_tpu.checker.linear_packed); the
                   fastest host engine for packable models, and the
                   bench's baseline. Falls back to wgl when the model
                   can't pack.
    "jax"          the TPU engine (jepsen_tpu.parallel.engine) — batched,
                   device-sharded frontier expansion; the north star
    "competition"  a REAL first-decisive-wins race (checker.competition),
                   mirroring the reference's parallel linear-vs-wgl race
                   (checker.clj:199, knossos.competition): packable
                   models race jax + packed + wgl, others race
                   linear + wgl. The host arms hedge a wedged device
                   runtime; the losers are cooperatively cancelled.

Results mirror knossos: {"valid?", "op", "final-paths", "configs",
"analyzer"}. Like the reference, final-paths/configs are truncated to 10
(checker.clj:210-213 — "Writing these can take *hours*").
"""

from __future__ import annotations

from typing import Optional

from jepsen_tpu import models as model_ns
from jepsen_tpu.checker.core import Checker
from jepsen_tpu.history import History, Intern


def _truncate(result: dict, n: int = 10) -> dict:
    for k in ("final-paths", "configs"):
        if isinstance(result.get(k), list):
            result[k] = result[k][:n]
    return result


class Linearizable(Checker):
    def __init__(self, model=None, algorithm: str = "competition"):
        self.model = model
        self.algorithm = algorithm

    def check(self, test, history, opts=None):
        model = self.model or (test or {}).get("model")
        if model is None:
            raise ValueError("The linearizable checker requires a model")
        algo = self.algorithm or "competition"
        h = history if isinstance(history, History) else History.wrap(history)

        # Guard against mis-parsed histories (e.g. raw EDN keyword keys):
        # a non-empty history in which NO op has a recognizable :type
        # would otherwise sail through as trivially linearizable.
        if len(h) and not any(
                o.get("type") in ("invoke", "ok", "fail", "info")
                for o in h):
            raise ValueError(
                "history has no ops with a recognizable :type — was it "
                "parsed with History.from_edn / op_from_edn?")

        if algo == "competition":
            from jepsen_tpu.checker import competition
            packable = model_ns.pack_spec(model, Intern()) is not None
            if packable:
                # host arms always race; the device arm only joins when
                # the bounded probe says the runtime is usable (a wedged
                # runtime would leak one stuck thread per check). A
                # device arm orphaned by an EARLIER race and silent ever
                # since is the mid-process wedge signature — skip the
                # device arm while the suspicion lasts. It self-clears
                # if the arm ever reports (a slow-but-healthy device
                # rejoins later races; a wedged one never does).
                suspect = competition.device_engine_suspect()
                global _wedge_warned
                if suspect and not _wedge_warned:
                    import logging
                    logging.getLogger(__name__).warning(
                        "a device competition arm from an earlier check "
                        "has been silent for >%.0fs — racing host arms "
                        "only until it reports",
                        competition.DEVICE_WEDGE_SUSPECT_SECS)
                _wedge_warned = suspect
                arms = (("jax", "packed", "wgl")
                        if _engine_available() and not suspect
                        else ("packed", "wgl"))
            else:
                arms = ("linear", "wgl")   # the reference's exact race
            r = competition.analysis(
                model, h, arms=arms,
                timeout=(test or {}).get("competition-timeout"))
            algo = r.get("analyzer", "competition")

        elif algo == "wgl":
            from jepsen_tpu.checker import wgl
            r = wgl.analysis(model, h)
        elif algo == "linear":
            from jepsen_tpu.checker import linear
            r = linear.analysis(model, h)
        elif algo == "packed":
            from jepsen_tpu.checker import linear_packed, wgl
            from jepsen_tpu.parallel.encode import EncodeError
            try:
                r = linear_packed.analysis(model, h)
            except EncodeError as err:
                r = wgl.analysis(model, h)
                r["fallback"] = str(err)
                algo = "wgl"
        elif algo == "jax":
            from jepsen_tpu.parallel import engine
            r = engine.analysis(model, h)
        else:
            raise ValueError(f"unknown linearizability algorithm {algo!r}")
        r["analyzer"] = algo
        if (r.get("valid?") is False and not r.get("final-paths")
                and algo in ("linear", "packed") and len(h) <= 1000):
            # the frontier engines localize the failure but keep no
            # breadcrumbs; knossos's linear analysis always produces
            # final-paths (they feed linear.svg, checker.clj:203-207) —
            # attach them via a state-bounded WGL re-search
            from jepsen_tpu.checker import wgl as _wgl
            rw = _wgl.analysis(model, h, max_states=1_000_000)
            if rw.get("valid?") is False:
                # take wgl's whole failure report so op / final-paths /
                # configs describe the SAME stuck point (the frontier
                # engine may localize a different window)
                r["final-paths"] = rw.get("final-paths", [])
                r["configs"] = rw.get("configs", [])
                if rw.get("op"):
                    r["op"] = rw["op"]
            elif rw.get("valid?") is True:
                # the oracle contradicts the engine: surface it loudly —
                # a silent wrong verdict would hide an engine bug
                import logging
                logging.getLogger(__name__).warning(
                    "%s said invalid but the WGL oracle says valid — "
                    "engine disagreement", algo)
                r["oracle-disagreement"] = True
        r = _truncate(r)

        # On failure, render the counterexample SVG into the store, as
        # the reference does via knossos.linear.report
        # (checker.clj:203-207). Rendered from the truncated analysis:
        # thousands of final-paths would take hours, just like writing
        # them would (checker.clj:210-213).
        if r.get("valid?") is False and (test or {}).get("store"):
            try:
                from jepsen_tpu.checker import linear_report
                linear_report.render_analysis_file(h, r, test, opts)
            except Exception:  # noqa: BLE001 - plots must never fail a check
                pass
        return r


_engine_probe_result: Optional[bool] = None
_engine_probe: dict = {}   # in-flight probe: {"thread": t, "out": {...}}
_wedge_warned = False   # one warning per suspicion episode, not per check


def _engine_available(timeout: float = 15.0) -> bool:
    """Whether the device engine can run — probed with a BOUNDED wait.

    jax.devices() blocks forever inside PJRT client creation when the
    device runtime is wedged (observed: TPU tunnel outages), and it
    ignores Python signals — probing it inline would hang the check
    before the competition race could hedge anything. The probe runs in
    a daemon thread with a timeout instead; while it has not answered,
    the engine is treated as unavailable (so races run host arms only).
    Only an actual ANSWER is cached: a merely-slow first init (cold jax
    import on a loaded host) that finishes after the timeout flips
    later checks back to the device engine. One probe thread total —
    later calls re-join the same thread briefly rather than piling a
    new wedged thread onto every check."""
    global _engine_probe_result
    if _engine_probe_result is not None:
        return _engine_probe_result
    if not _engine_probe:
        out: dict = {}

        def probe():
            try:
                import jax
                from jepsen_tpu.parallel import engine  # noqa: F401
                out["ok"] = len(jax.devices()) > 0
            except Exception:  # noqa: BLE001
                out["ok"] = False

        import threading
        t = threading.Thread(target=probe, daemon=True,
                             name="engine-availability-probe")
        t.start()
        _engine_probe.update(thread=t, out=out)
        _engine_probe["thread"].join(timeout)
    else:
        # an earlier call already paid the full wait; just peek
        _engine_probe["thread"].join(0.1)
    out = _engine_probe["out"]
    if "ok" in out:
        _engine_probe_result = bool(out["ok"])
        return _engine_probe_result
    return False


def linearizable(model=None, algorithm: str = "competition") -> Linearizable:
    return Linearizable(model, algorithm)
