"""Host-side Wing–Gong–Lowe linearizability checker — the CPU oracle.

Re-implements the capability of `knossos.wgl/analysis` (reference call
surface: jepsen/src/jepsen/checker.clj:17-23,194-213): depth-first search
over linearization orders with Lowe's visited-(state, linearized-bitset)
cache, operating on the Call records produced by
`jepsen_tpu.history.calls`.

Crash semantics (SURVEY.md §7.3 hard part #2): a crashed (:info) call has
no return event — it stays concurrent with everything after it and may be
linearized at any point *or never*. The search succeeds when every
*completed* call is linearized; crashed calls are optional.

This is deliberately simple, allocation-light Python: it is the
differential-testing oracle for the TPU engine
(`jepsen_tpu.parallel.engine`) and the fallback for models whose state
can't be packed into fixed-width integers (queues, sets).

Result shape mirrors knossos: {"valid?", "op" (first stuck op),
"final-paths" (counter-example traces of {"op", "model"}), "configs"}.
"""

from __future__ import annotations

import time as _time
from typing import Any, List, Optional

from jepsen_tpu import models as model_ns
from jepsen_tpu.history import Call, calls as history_calls


class _EventList:
    """Doubly-linked list of call/return events over array storage.

    Node ids: 2*i = call event of call i, 2*i+1 = return event. Crashed
    calls have no return node. Lift/unlift are O(1), as WGL requires.
    """

    def __init__(self, cs: List[Call], n_history: int):
        events = []  # (position, node_id)
        for c in cs:
            events.append((c.invoke_index, 2 * c.index))
            if not c.crashed:
                events.append((c.complete_index, 2 * c.index + 1))
        events.sort()
        n_nodes = 2 * len(cs)
        self.next = [-1] * (n_nodes + 1)  # +1: virtual head at index n_nodes
        self.prev = [-1] * (n_nodes + 1)
        self.HEAD = n_nodes
        prev = self.HEAD
        for _, nid in events:
            self.next[prev] = nid
            self.prev[nid] = prev
            prev = nid
        self.next[prev] = -1

    def head(self) -> int:
        return self.next[self.HEAD]

    def lift(self, call_id: int, crashed: bool):
        """Remove call (and return, unless crashed) events of call_id."""
        for nid in ((2 * call_id,) if crashed else (2 * call_id, 2 * call_id + 1)):
            p, n = self.prev[nid], self.next[nid]
            self.next[p] = n
            if n != -1:
                self.prev[n] = p

    def unlift(self, call_id: int, crashed: bool):
        """Reinsert events (exact inverse of lift, relies on prev/next of
        the removed nodes being preserved)."""
        for nid in ((2 * call_id + 1, 2 * call_id) if not crashed
                    else (2 * call_id,)):
            p, n = self.prev[nid], self.next[nid]
            self.next[p] = nid
            if n != -1:
                self.prev[n] = nid


def _candidates(ev: _EventList, start_after: Optional[int] = None):
    """Call ids linearizable next: call events before the first return
    event in the remaining list. If start_after is a call id, resume
    enumeration after its call node (for backtracking)."""
    nid = ev.next[2 * start_after] if start_after is not None else ev.head()
    while nid != -1:
        if nid % 2 == 1:  # return event — nothing beyond is linearizable
            return
        yield nid // 2
        nid = ev.next[nid]


class _StepOp:
    """Adapter giving Call records the .f/.value interface models expect,
    with observing ops (reads, dequeues) carrying their completion value
    (knossos complete merges ok values into the invocation)."""

    __slots__ = ("f", "value")

    def __init__(self, c: Call):
        self.f = c.f
        if c.f in ("read", "dequeue"):
            self.value = c.result if not c.crashed else None
        else:
            self.value = c.value


def check_calls(model, cs: List[Call], n_history: int,
                max_states: int = 50_000_000,
                deadline: Optional[float] = None,
                cancel=None) -> dict:
    """Run WGL over prepared calls. Returns a knossos-shaped result.
    With `deadline` (a time.monotonic() instant) the search returns
    `{"valid?": "unknown", "timeout": True}` when it runs past it —
    the same cooperative contract as checker.linear — checked every
    4096 explored states so the overshoot is bounded. `cancel` (a
    threading.Event) is polled at the same stride: a competition race
    sets it when another arm has already produced a decisive verdict
    (knossos competition/analysis future-cancel parity)."""
    m = len(cs)
    if m == 0:
        return {"valid?": True, "configs": [], "final-paths": []}

    ev = _EventList(cs, n_history)
    step_ops = [_StepOp(c) for c in cs]
    crashed = [c.crashed for c in cs]
    completed_mask = 0
    for c in cs:
        if not c.crashed:
            completed_mask |= 1 << c.index

    visited = set()
    stack: list = []  # (call_id, prev_state)
    state = model
    linearized = 0
    explored = 0

    # best (deepest) failure info for counter-example reporting
    best_depth = -1
    best_path: list = []
    best_stuck: Optional[Call] = None

    cand_iter = _candidates(ev)

    while True:
        # success: every *completed* call linearized; crashed calls are
        # optional (checked at loop top so all-crashed histories pass
        # without forcing any crashed op to linearize)
        if (linearized & completed_mask) == completed_mask:
            return {"valid?": True,
                    "explored": explored,
                    "linearization": [cs[i].index for i, _ in stack],
                    "configs": [], "final-paths": []}
        # pick next candidate
        cid = None
        for cid in cand_iter:
            break
        else:
            cid = None
        if cid is not None:
            c = cs[cid]
            s2 = state.step(step_ops[cid])
            explored += 1
            if explored > max_states:
                return {"valid?": "unknown",
                        "error": f"state budget exceeded ({max_states})",
                        "explored": explored}
            if (explored & 0xFFF) == 0:
                if deadline is not None and _time.monotonic() > deadline:
                    return {"valid?": "unknown", "error": "deadline",
                            "timeout": True, "explored": explored}
                if cancel is not None and cancel.is_set():
                    return {"valid?": "unknown", "error": "cancelled",
                            "explored": explored}
            key = (s2, linearized | (1 << cid))
            if not model_ns.is_inconsistent(s2) and key not in visited:
                visited.add(key)
                stack.append((cid, state))
                ev.lift(cid, crashed[cid])
                linearized |= 1 << cid
                state = s2
                cand_iter = _candidates(ev)
            else:
                cand_iter = _resume(ev, cid)
        else:
            # exhausted candidates at this node: record, backtrack
            if len(stack) > best_depth:
                best_depth = len(stack)
                best_path = [(cs[i], st) for i, st in stack] + [(None, state)]
                head = ev.head()
                best_stuck = cs[head // 2] if head != -1 else None
            if not stack:
                return _invalid_result(model, best_path, best_stuck, explored,
                                       state, linearized, cs)
            cid_prev, state = stack.pop()
            ev.unlift(cid_prev, crashed[cid_prev])
            linearized &= ~(1 << cid_prev)
            cand_iter = _resume(ev, cid_prev)


def _resume(ev: _EventList, after_call_id: int):
    return _candidates(ev, start_after=after_call_id)


def _invalid_result(model, best_path, best_stuck, explored, state, linearized,
                    cs) -> dict:
    path = []
    stuck_state = model
    for c, st in best_path:
        if c is None:
            stuck_state = st  # sentinel carries the state at the dead end
            continue
        path.append({"op": {"process": c.process, "f": c.f,
                            "value": c.value, "index": c.invoke_index},
                     "model": str(st)})
    stuck_op = None
    if best_stuck is not None:
        # report the observed value for reads/dequeues (the completion
        # is what the search couldn't explain), invocation args otherwise
        v = (best_stuck.result
             if best_stuck.f in ("read", "dequeue") and not best_stuck.crashed
             else best_stuck.value)
        stuck_op = {"process": best_stuck.process, "f": best_stuck.f,
                    "value": v, "index": best_stuck.invoke_index}
    return {
        "valid?": False,
        "op": stuck_op,
        "explored": explored,
        "final-paths": [path[:64]] if path else [],
        "configs": [{"model": str(stuck_state)}],
    }


def analysis(model, history, max_states: int = 50_000_000,
             deadline: Optional[float] = None, cancel=None) -> dict:
    """knossos.wgl/analysis equivalent: (model, history) -> result.

    History may be a `History` or plain list of op dicts; invocations are
    paired/completed internally. `deadline` is a time.monotonic()
    instant for the cooperative timeout; `cancel` a threading.Event
    polled at the same stride (see check_calls).
    """
    from jepsen_tpu.history import History, prune_wildcard_calls
    h = history if isinstance(history, History) else History.wrap(history)
    cs = prune_wildcard_calls(history_calls(h))
    return check_calls(model, cs, len(h), max_states=max_states,
                       deadline=deadline, cancel=cancel)
