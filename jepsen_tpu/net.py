"""Network fault plane (reference: jepsen/src/jepsen/net.clj).

The `Net` protocol (net.clj:15-26): drop / heal / slow / flaky / fast,
plus the `PartitionAll` fast path `drop_all(grudge)` applying a whole
grudge map at once (net/proto.clj:5-12). Implementations:

    IPTables  iptables for drops + tc/netem for latency/loss
              (net.clj:58-111) — the production impl on Linux nodes
    MemNet    an in-memory connectivity matrix for tests and the
              in-process fake cluster (no root, no iptables); clients
              may consult `reachable` to simulate partitions
    NoopNet   ignores everything (net.clj:48-56)
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

from jepsen_tpu import control as c
from jepsen_tpu.control import RemoteError, lit


class Net:
    def drop(self, test, src, dest):
        """Drop traffic from src to dest."""
        raise NotImplementedError

    def drop_all(self, test, grudge: Dict):
        """Apply a whole grudge map {node: [nodes-to-drop-from]} at once
        (net/proto.clj:5-12 PartitionAll); default = per-edge drops."""
        for node, drop_from in (grudge or {}).items():
            for src in drop_from:
                self.drop(test, src, node)

    def heal(self, test):
        """End all partitions / faults."""
        raise NotImplementedError

    def slow(self, test, opts: Optional[dict] = None):
        """Add latency to the network (net.clj:21-23)."""
        raise NotImplementedError

    def flaky(self, test):
        """Introduce probabilistic loss (net.clj:24-25)."""
        raise NotImplementedError

    def fast(self, test):
        """Remove slow/flaky shaping (net.clj:26)."""
        raise NotImplementedError


class NoopNet(Net):
    def drop(self, test, src, dest):
        pass

    def heal(self, test):
        pass

    def slow(self, test, opts=None):
        pass

    def flaky(self, test):
        pass

    def fast(self, test):
        pass


def noop() -> NoopNet:
    return NoopNet()


class IPTables(Net):
    """iptables drops + tc netem shaping (net.clj:58-111). Runs on each
    node through the control session."""

    def drop(self, test, src, dest):
        c.on_nodes(test, lambda t, n: c.exec_(
            "iptables", "-A", "INPUT", "-s", _ip(src), "-j", "DROP",
            "-w"), [dest])

    def drop_all(self, test, grudge):
        def apply(t, node):
            drop_from = grudge.get(node) or []
            if not drop_from:
                return
            # one iptables invocation per node, comma-joined sources
            # (net.clj:92-99 batched grudge fast path)
            srcs = ",".join(_ip(s) for s in drop_from)
            c.exec_("iptables", "-A", "INPUT", "-s", srcs, "-j", "DROP",
                    "-w")
        c.on_nodes(test, apply, list(grudge or {}))

    def heal(self, test):
        def h(t, node):
            c.exec_("iptables", "-F", "-w")
            c.exec_("iptables", "-X", "-w")
        c.on_nodes(test, h)

    def slow(self, test, opts=None):
        o = opts or {}
        mean = o.get("mean", 50)       # ms (net.clj:76-84 defaults)
        variance = o.get("variance", 10)
        dist = o.get("distribution", "normal")
        c.on_nodes(test, lambda t, n: c.exec_(
            "tc", "qdisc", "add", "dev", "eth0", "root", "netem", "delay",
            f"{mean}ms", f"{variance}ms", "distribution", dist))

    def flaky(self, test):
        c.on_nodes(test, lambda t, n: c.exec_(
            "tc", "qdisc", "add", "dev", "eth0", "root", "netem", "loss",
            "20%", "75%"))

    def fast(self, test):
        def f(t, node):
            try:
                c.exec_("tc", "qdisc", "del", "dev", "eth0", "root")
            except RemoteError:
                pass  # no qdisc installed
        c.on_nodes(test, f)


def iptables() -> IPTables:
    return IPTables()


class IPFilter(Net):
    """SmartOS ipfilter drops (net.clj:113-145). Note: the reference's
    slow/flaky arms shell out to Linux tc/netem even in this impl
    (net.clj:123-144) and cannot work on actual illumos; partitions
    (drop/heal via ipf) are the useful surface, so slow/flaky/fast
    raise a clear error instead of failing with 'tc: not found'."""

    def drop(self, test, src, dest):
        c.on_nodes(test, lambda t, n: c.exec_(
            "sh", "-c",
            f"echo block in from {_ip(src)} to any | ipf -f -"), [dest])

    def drop_all(self, test, grudge):
        grudge = grudge or {}

        def apply(t, node):
            rules = "\n".join(f"block in from {_ip(s)} to any"
                               for s in grudge.get(node, []))
            if rules:
                c.exec_("sh", "-c", f"printf '{rules}\n' | ipf -f -")
        c.on_nodes(test, apply, list(grudge))

    def heal(self, test):
        c.on_nodes(test, lambda t, n: c.exec_("ipf", "-Fa"))

    def slow(self, test, opts=None):
        raise NotImplementedError(
            "ipfilter net has no traffic shaping: tc/netem is Linux-only")

    def flaky(self, test):
        raise NotImplementedError(
            "ipfilter net has no traffic shaping: tc/netem is Linux-only")

    def fast(self, test):
        # Nothing to undo: slow/flaky are unavailable on this platform.
        pass


def ipfilter() -> IPFilter:
    return IPFilter()


def _ip(node: str) -> str:
    return node  # hostnames resolve on the nodes (control/net.clj:8-20)


class MemNet(Net):
    """In-memory connectivity matrix — the fault plane for the
    in-process fake cluster. `reachable(src, dest)` is consulted by fake
    clients to simulate partitions; slow/flaky set latency/loss knobs
    the fake transport may honor."""

    def __init__(self):
        self.lock = threading.Lock()
        self.dropped: set = set()   # (src, dest) pairs
        self.latency_ms: float = 0.0
        self.loss: float = 0.0

    def drop(self, test, src, dest):
        with self.lock:
            self.dropped.add((src, dest))

    def drop_all(self, test, grudge):
        with self.lock:
            for node, drop_from in (grudge or {}).items():
                for src in drop_from:
                    self.dropped.add((src, node))

    def heal(self, test):
        with self.lock:
            self.dropped.clear()

    def slow(self, test, opts=None):
        with self.lock:
            self.latency_ms = (opts or {}).get("mean", 50)

    def flaky(self, test):
        with self.lock:
            self.loss = 0.2

    def fast(self, test):
        with self.lock:
            self.latency_ms = 0.0
            self.loss = 0.0

    def reachable(self, src, dest) -> bool:
        with self.lock:
            return (src, dest) not in self.dropped

    def partitioned(self) -> bool:
        with self.lock:
            return bool(self.dropped)


def mem() -> MemNet:
    return MemNet()
