"""Operating-system setup protocol (reference: jepsen/src/jepsen/os.clj
+ os/debian.clj etc.).

`OS` (os.clj:4-8): prepare a node's operating system before the DB is
installed — package installs, hostfiles, users. The debian impl mirrors
os/debian.clj:13-201 (apt pipeline + base packages); it requires a root
session on a debian-family node and is exercised only against a real
cluster."""

from __future__ import annotations

from typing import Sequence

from jepsen_tpu import control as c
from jepsen_tpu.control import RemoteError, lit


class OS:
    def setup(self, test, node) -> None:
        """Prepare the OS."""

    def teardown(self, test, node) -> None:
        """Clean up any OS changes."""


class Noop(OS):
    """Does nothing (os.clj:10-14)."""


def noop() -> Noop:
    return Noop()


BASE_PACKAGES = [
    # os/debian.clj:141-160 base package set (the subset that matters
    # for running DB tarballs + nemeses)
    "curl", "wget", "unzip", "iptables", "iputils-ping", "logrotate",
    "man-db", "faketime", "ntpdate", "netcat-openbsd", "rsyslog", "psmisc",
    "tar", "gzip",
]


class Debian(OS):
    """Debian-family setup: noninteractive apt, hostfile, base packages
    (os/debian.clj:13-201)."""

    def __init__(self, extra_packages: Sequence[str] = ()):
        self.extra_packages = list(extra_packages)

    def setup(self, test, node):
        with c.su():
            self._hostfile(test, node)
            c.exec_("env", "DEBIAN_FRONTEND=noninteractive",
                    "apt-get", "update")
            c.exec_("env", "DEBIAN_FRONTEND=noninteractive",
                    "apt-get", "install", "-y", "--no-install-recommends",
                    *(BASE_PACKAGES + self.extra_packages))

    def teardown(self, test, node):
        pass

    def _hostfile(self, test, node):
        # os/debian.clj hostname wiring: every node resolves every
        # other. IPs come from an explicit test["node-ips"] map when
        # given (the usual case for fresh clusters with no DNS), else
        # from resolution on the node itself. Failure to obtain an IP
        # is an error -- writing a hostfile that silently omits peers
        # is exactly the failure mode this exists to prevent.
        nodes = test.get("nodes") or []
        node_ips = test.get("node-ips") or {}
        lines = ["127.0.0.1 localhost"]
        for n in nodes:
            ip = node_ips.get(n)
            if ip is None:
                out = c.exec_("getent", "hosts", n)  # raises on failure
                ip = out.split()[0]
            lines.append(f"{ip} {n}")
        content = "\\n".join(lines)
        c.exec_("bash", "-c", lit(c.escape(
            f"printf '{content}\\n' > /etc/hosts")))


def debian(extra_packages: Sequence[str] = ()) -> Debian:
    return Debian(extra_packages)
