"""Operating-system setup protocol (reference: jepsen/src/jepsen/os.clj
+ os/debian.clj etc.).

`OS` (os.clj:4-8): prepare a node's operating system before the DB is
installed — package installs, hostfiles, users. The debian impl mirrors
os/debian.clj:13-201 (apt pipeline + base packages); it requires a root
session on a debian-family node and is exercised only against a real
cluster."""

from __future__ import annotations

from typing import Sequence

from jepsen_tpu import control as c
from jepsen_tpu.control import RemoteError, lit


class OS:
    def setup(self, test, node) -> None:
        """Prepare the OS."""

    def teardown(self, test, node) -> None:
        """Clean up any OS changes."""


class Noop(OS):
    """Does nothing (os.clj:10-14)."""


def noop() -> Noop:
    return Noop()


BASE_PACKAGES = [
    # os/debian.clj:141-160 base package set (the subset that matters
    # for running DB tarballs + nemeses)
    "curl", "wget", "unzip", "iptables", "iputils-ping", "logrotate",
    "man-db", "faketime", "ntpdate", "netcat-openbsd", "rsyslog", "psmisc",
    "tar", "gzip",
]


class Debian(OS):
    """Debian-family setup: noninteractive apt, hostfile, base packages
    (os/debian.clj:13-201)."""

    def __init__(self, extra_packages: Sequence[str] = ()):
        self.extra_packages = list(extra_packages)

    def setup(self, test, node):
        with c.su():
            self._hostfile(test, node)
            c.exec_("env", "DEBIAN_FRONTEND=noninteractive",
                    "apt-get", "update")
            c.exec_("env", "DEBIAN_FRONTEND=noninteractive",
                    "apt-get", "install", "-y", "--no-install-recommends",
                    *(BASE_PACKAGES + self.extra_packages))

    def teardown(self, test, node):
        pass

    def _hostfile(self, test, node):
        # os/debian.clj hostname wiring: every node resolves every
        # other. IPs come from an explicit test["node-ips"] map when
        # given (the usual case for fresh clusters with no DNS), else
        # from resolution on the node itself. Failure to obtain an IP
        # is an error -- writing a hostfile that silently omits peers
        # is exactly the failure mode this exists to prevent.
        nodes = test.get("nodes") or []
        node_ips = test.get("node-ips") or {}
        lines = ["127.0.0.1 localhost"]
        for n in nodes:
            ip = node_ips.get(n)
            if ip is None:
                out = c.exec_("getent", "hosts", n)  # raises on failure
                ip = out.split()[0]
            lines.append(f"{ip} {n}")
        content = "\\n".join(lines)
        c.exec_("bash", "-c", lit(c.escape(
            f"printf '{content}\\n' > /etc/hosts")))


def debian(extra_packages: Sequence[str] = ()) -> Debian:
    return Debian(extra_packages)


class Ubuntu(Debian):
    """Alias of Debian: the reference's ubuntu os only adds sudo-group
    bookkeeping for non-root users, which this control plane (always
    root or explicit su) does not need (os/ubuntu.clj:1-46)."""


def ubuntu(extra_packages: Sequence[str] = ()) -> Ubuntu:
    return Ubuntu(extra_packages)


CENTOS_BASE_PACKAGES = [
    "wget", "curl", "unzip", "iptables", "logrotate", "tar", "gzip",
    "ntpdate", "psmisc", "man-db",
]


class Centos(OS):
    """CentOS-family setup: hostfile loopback fix + yum packages
    (os/centos.clj:12-158)."""

    def __init__(self, extra_packages: Sequence[str] = ()):
        self.extra_packages = list(extra_packages)

    def setup(self, test, node):
        with c.su():
            self._hostfile_loopback()
            c.exec_("yum", "install", "-y",
                    *(CENTOS_BASE_PACKAGES + self.extra_packages))

    def teardown(self, test, node):
        pass

    def _hostfile_loopback(self):
        """Ensure /etc/hosts' 127.0.0.1 line mentions the local hostname
        as a whole token (os/centos.clj:12-26 setup-hostfile!). The file
        is shipped back via upload, not a shell printf: existing lines
        may contain %/backslash sequences a format string would eat."""
        name = c.exec_("hostname")
        hosts = c.exec_("cat", "/etc/hosts")
        out = []
        for line in hosts.splitlines():
            if line.startswith("127.0.0.1") and name not in line.split():
                line = f"{line} {name}"
            out.append(line)
        import os as _os
        import tempfile
        fd, tmp = tempfile.mkstemp(suffix=".hosts")
        try:
            with _os.fdopen(fd, "w") as f:
                f.write("\n".join(out) + "\n")
            # Upload to /tmp first: uploads run as the login user (scp
            # has no sudo), while the final cp honors the su binding.
            staged = "/tmp/jepsen-hosts"
            c.upload([tmp], staged)
            c.exec_("cp", staged, "/etc/hosts")
            c.exec_("rm", "-f", staged)
        finally:
            _os.unlink(tmp)

    def installed(self, pkgs: Sequence[str]) -> set:
        """Subset of pkgs currently yum-installed (os/centos.clj:46-57)."""
        want = {str(p) for p in pkgs}
        have = set()
        for line in c.exec_("yum", "list", "installed").splitlines():
            namever = line.split()[0] if line.split() else ""
            base = namever.rsplit(".", 1)[0]
            if base in want:
                have.add(base)
        return have


def centos(extra_packages: Sequence[str] = ()) -> Centos:
    return Centos(extra_packages)


SMARTOS_BASE_PACKAGES = ["curl", "wget", "gtar", "gzip", "coreutils"]


class SmartOS(OS):
    """SmartOS setup: pkgin packages + loopback hostfile fix
    (os/smartos.clj:12-132). Pairs with net.ipfilter()."""

    def __init__(self, extra_packages: Sequence[str] = ()):
        self.extra_packages = list(extra_packages)

    def setup(self, test, node):
        with c.su():
            name = c.exec_("hostname")
            hosts = c.exec_("cat", "/etc/hosts")
            if name not in hosts.split():
                c.exec_("sh", "-c",
                        f"echo '127.0.0.1 {name}' >> /etc/hosts")
            c.exec_("pkgin", "-y", "install",
                    *(SMARTOS_BASE_PACKAGES + self.extra_packages))

    def teardown(self, test, node):
        pass


def smartos(extra_packages: Sequence[str] = ()) -> SmartOS:
    return SmartOS(extra_packages)
