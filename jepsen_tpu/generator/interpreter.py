"""The execution engine: turns a pure generator into a real concurrent
history (reference: jepsen/src/jepsen/generator/interpreter.clj).

One thread per worker (clients + nemesis), coupled to a single-threaded
scheduler loop by queues:

  * each worker has a 1-slot inbox (interpreter.clj:110);
  * all workers share one completion queue (interpreter.clj:197);
  * the scheduler polls completions FIRST — they are latency-sensitive;
    waiting would introduce false concurrency (interpreter.clj:212-215);
  * when the generator is PENDING or ahead of the clock, the scheduler
    polls with a bounded timeout (max 1000 us, interpreter.clj:166-170);
  * a worker that throws converts the op to :info with
    "indeterminate: ..." (interpreter.clj:142-157);
  * threads whose process crashed get a fresh process id
    (interpreter.clj:233-236) and a fresh client on next use
    (interpreter.clj:40-60);
  * :sleep and :log ops are executed but excluded from the history
    (interpreter.clj:172-179).
"""

from __future__ import annotations

import queue
import threading
import time as _time
import traceback
from typing import Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.generator import (
    Ctx, NEMESIS, PENDING, friendly_exceptions, gen_op, gen_update, validate,
)
from jepsen_tpu.history import History, Op
from jepsen_tpu.util import relative_time_nanos

MAX_PENDING_INTERVAL_US = 1000  # interpreter.clj:166-170


class Worker:
    """Lifecycle protocol; every method runs on one thread
    (interpreter.clj:19-31)."""

    def open(self, test, worker_id) -> "Worker":
        return self

    def invoke(self, test, op: Op) -> Op:
        raise NotImplementedError

    def close(self, test) -> None:
        pass


class ClientWorker(Worker):
    """Wraps a Client; opens a fresh client whenever the op's process
    differs from the current one and the client isn't reusable
    (interpreter.clj:33-67)."""

    def __init__(self, node):
        self.node = node
        self.process = None
        self.client: Optional[jclient.Client] = None

    def invoke(self, test, op):
        if (self.process != op.get("process")
                and not jclient.is_reusable(self.client, test)):
            self.close(test)
            try:
                self.client = jclient.validate(test["client"]).open(
                    test, self.node)
                self.process = op.get("process")
            except Exception as e:  # noqa: BLE001
                self.client = None
                o = Op(op)
                o["type"] = "fail"
                o["error"] = ["no-client", str(e)]
                return o
        return self.client.invoke(test, op)

    def close(self, test):
        if self.client is not None:
            self.client.close(test)
            self.client = None


class NemesisWorker(Worker):
    """Routes ops to the test's nemesis (interpreter.clj:69-76)."""

    def invoke(self, test, op):
        return test["nemesis"].invoke(test, op)


class ClientNemesisWorker(Worker):
    """Spawns ClientWorkers for integer ids (node chosen by id mod
    #nodes) and a NemesisWorker for the nemesis id
    (interpreter.clj:80-97)."""

    def open(self, test, worker_id):
        if isinstance(worker_id, int):
            nodes = test.get("nodes") or [None]
            return ClientWorker(nodes[worker_id % len(nodes)])
        return NemesisWorker()


def client_nemesis_worker() -> ClientNemesisWorker:
    return ClientNemesisWorker()


class _WorkerHandle:
    def __init__(self, worker_id, inbox, thread):
        self.id = worker_id
        self.in_q = inbox
        self.thread = thread


def spawn_worker(test, out_q: "queue.SimpleQueue", worker: Worker, worker_id) -> _WorkerHandle:
    """Spawn a worker thread with an inbox queue; completions go to the
    shared out_q (interpreter.clj:99-164).

    The reference uses a 1-slot ArrayBlockingQueue per worker
    (interpreter.clj:110), but the bound is never load-bearing: the
    scheduler only dispatches to free threads, so an inbox holds at most
    one op at a time by construction. SimpleQueue (C-implemented,
    lock-light) roughly halves scheduler overhead on the hot path."""
    in_q: "queue.SimpleQueue" = queue.SimpleQueue()

    def run():
        w = worker.open(test, worker_id)
        try:
            while True:
                op = in_q.get()
                try:
                    t = op.get("type")
                    if t == "exit":
                        return
                    if t == "sleep":
                        _time.sleep(op["value"])
                        out_q.put(op)
                    elif t == "log":
                        print(op.get("value"))
                        out_q.put(op)
                    else:
                        out_q.put(w.invoke(test, op))
                except BaseException as e:  # noqa: BLE001
                    # Convert a crash into an indeterminate :info op
                    # (interpreter.clj:142-157).
                    o = Op(op)
                    o["type"] = "info"
                    o["error"] = f"indeterminate: {e}"
                    o["exception"] = traceback.format_exc()
                    out_q.put(o)
        finally:
            w.close(test)

    th = threading.Thread(target=run, name=f"jepsen worker {worker_id}",
                          daemon=True)
    th.start()
    return _WorkerHandle(worker_id, in_q, th)


def goes_in_history(op) -> bool:
    """:log and :sleep are executed but not journaled
    (interpreter.clj:172-179)."""
    return op.get("type") not in ("sleep", "log")


def run(test) -> History:
    """Evaluate all ops from test["generator"], dispatching to worker
    threads driving test["client"] / test["nemesis"]; returns the
    recorded history (interpreter.clj:181-292)."""
    ctx = Ctx.for_test(test)
    completions: "queue.SimpleQueue" = queue.SimpleQueue()
    workers = [spawn_worker(test, completions, client_nemesis_worker(), wid)
               for wid in ctx.all_threads()]
    inboxes = {w.id: w.in_q for w in workers}
    gen = validate(friendly_exceptions(test.get("generator")))

    outstanding = 0
    poll_timeout_us = 0
    history: list = []
    try:
        while True:
            op_done = _poll(completions, poll_timeout_us)
            if op_done is not None:
                # Completion-first path (interpreter.clj:215-241).
                thread = ctx.process_to_thread(op_done.get("process"))
                now = relative_time_nanos()
                op_done = Op(op_done)
                op_done["time"] = now
                ctx = ctx.with_time(now).free(thread)
                gen = gen_update(gen, test, ctx, op_done)
                if thread != NEMESIS and op_done.get("type") == "info":
                    ctx = ctx.with_worker(thread, ctx.next_process(thread))
                if goes_in_history(op_done):
                    history.append(op_done)
                outstanding -= 1
                poll_timeout_us = 0
                continue

            now = relative_time_nanos()
            ctx = ctx.with_time(now)
            res = gen_op(gen, test, ctx)
            if res is None:
                if outstanding > 0:
                    poll_timeout_us = MAX_PENDING_INTERVAL_US
                    continue
                for w in workers:
                    w.in_q.put({"type": "exit"})
                for w in workers:
                    w.thread.join()
                return History.wrap(history)

            op, gen2 = res
            if op is PENDING:
                # Keep the pre-op generator (interpreter.clj:264).
                poll_timeout_us = MAX_PENDING_INTERVAL_US
                continue

            if now < op["time"]:
                # Not time yet; wait on completions until then
                # (interpreter.clj:268-275).
                poll_timeout_us = (op["time"] - now) // 1000
                continue

            thread = ctx.process_to_thread(op.get("process"))
            # Hand the worker its own copy: Python clients may mutate the
            # op in place, which must not corrupt the journaled invocation
            # (immutable maps make this a non-issue in the reference).
            inboxes[thread].put(Op(op))
            ctx = Ctx(op["time"], ctx.free_threads, ctx.workers).busy(thread)
            gen = gen_update(gen2, test, ctx, op)
            if goes_in_history(op):
                history.append(op)
            outstanding += 1
            poll_timeout_us = 0
    except BaseException:
        # Abnormal exit: ask every worker to exit via its queue
        # (interpreter.clj:294-310). SimpleQueue is unbounded, so the
        # exit op always enqueues.
        for w in workers:
            w.in_q.put({"type": "exit"})
        raise


def _poll(q: "queue.SimpleQueue", timeout_us: int):
    try:
        if timeout_us <= 0:
            return q.get_nowait()
        return q.get(timeout=timeout_us / 1e6)
    except queue.Empty:
        return None
