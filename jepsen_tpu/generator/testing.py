"""Deterministic generator simulation — no threads, no wall clock
(reference: jepsen/src/jepsen/generator/test.clj).

`simulate` runs a generator against a completion function
`(ctx, invoke) -> completion op`, maintaining a sorted in-flight set and
the context's clock, exactly as generator/test.clj:49-106 does. The
completion policies `quick` / `perfect` / `perfect_info` / `imperfect`
mirror generator/test.clj:108-180. Randomness is pinned with
`fixed_rand(RAND_SEED)` so simulations are reproducible.
"""

from __future__ import annotations

from typing import Callable, Optional

from jepsen_tpu.history import History, Op
from jepsen_tpu.generator import (
    Ctx, PENDING, fixed_rand, gen_op, gen_update, validate, NEMESIS,
)

DEFAULT_TEST: dict = {}
RAND_SEED = 45100  # generator/test.clj:43-47
PERFECT_LATENCY = 10  # nanos; generator/test.clj:124-126


def default_context(n: int = 2) -> Ctx:
    """n worker threads + one nemesis (generator/test.clj:15-23)."""
    return Ctx.for_test({"concurrency": n})


def invocations(history) -> History:
    return History.wrap(o for o in history if o.get("type") == "invoke")


def simulate(gen, complete_fn: Callable, ctx: Optional[Ctx] = None,
             test: Optional[dict] = None, seed: int = RAND_SEED) -> History:
    """Simulate the series of ops from a generator given a completion
    function (generator/test.clj:49-106). Returns the full history
    (invocations interleaved with completions by time)."""
    ctx = ctx if ctx is not None else default_context()
    test = test if test is not None else DEFAULT_TEST

    with fixed_rand(seed):
        ops: list = []
        in_flight: list = []  # kept sorted by :time
        g = validate(gen)
        while True:
            res = gen_op(g, test, ctx)
            if res is None:
                ops.extend(in_flight)
                # :sleep/:log are executed but stay out of the history,
                # as in the interpreter (goes-in-history?)
                return History.wrap(
                    o for o in ops if o.get("type") not in ("sleep", "log"))
            invoke, g2 = res

            if (invoke is not PENDING
                    and (not in_flight
                         or invoke["time"] <= in_flight[0]["time"])):
                # Invocation happens before every in-flight completion.
                thread = ctx.process_to_thread(invoke["process"])
                ctx = ctx.with_time(max(ctx.time, invoke["time"])).busy(thread)
                g = gen_update(g2, test, ctx, invoke)
                if invoke.get("type") == "sleep":
                    # the interpreter's worker idles dt seconds
                    # (interpreter.py handling of :sleep); model that
                    # instead of handing sleeps to the completion policy
                    complete = Op(invoke)
                    complete["time"] = (invoke["time"]
                                        + int(invoke.get("value", 0) * 1e9))
                else:
                    complete = complete_fn(ctx, Op(invoke))
                in_flight.append(complete)
                in_flight.sort(key=lambda o: o["time"])
                ops.append(invoke)
            else:
                # Must complete something first (keeps original g on
                # PENDING, as the interpreter does, interpreter.clj:264).
                assert in_flight, "generator pending and nothing in flight"
                o = in_flight.pop(0)
                thread = ctx.process_to_thread(o["process"])
                ctx = ctx.with_time(max(ctx.time, o["time"])).free(thread)
                g = gen_update(g, test, ctx, o)
                if thread != NEMESIS and o.get("type") == "info":
                    ctx = ctx.with_worker(thread, ctx.next_process(thread))
                ops.append(o)


def quick_ops(gen, ctx: Optional[Ctx] = None) -> History:
    """Every op completes :ok instantly with zero latency
    (generator/test.clj:108-115)."""
    return simulate(gen, lambda c, inv: _with(inv, type="ok"), ctx)


def quick(gen, ctx: Optional[Ctx] = None) -> History:
    return invocations(quick_ops(gen, ctx))


def perfect_star(gen, ctx: Optional[Ctx] = None) -> History:
    """Every op succeeds in 10ns; full history
    (generator/test.clj:128-139)."""
    return simulate(gen,
                    lambda c, inv: _with(inv, type="ok",
                                         time=inv["time"] + PERFECT_LATENCY),
                    ctx)


def perfect(gen, ctx: Optional[Ctx] = None) -> History:
    return invocations(perfect_star(gen, ctx))


def perfect_info(gen, ctx: Optional[Ctx] = None) -> History:
    """Every op crashes :info in 10ns; invocations only
    (generator/test.clj:150-161)."""
    return invocations(
        simulate(gen,
                 lambda c, inv: _with(inv, type="info",
                                      time=inv["time"] + PERFECT_LATENCY),
                 ctx))


def imperfect(gen, ctx: Optional[Ctx] = None) -> History:
    """Threads rotate fail -> info -> ok -> fail...; 10ns each; full
    history (generator/test.clj:163-180)."""
    state: dict = {}
    rotation = {None: "fail", "fail": "info", "info": "ok", "ok": "fail"}

    def complete(c: Ctx, inv: Op) -> Op:
        t = c.process_to_thread(inv["process"])
        state[t] = rotation[state.get(t)]
        return _with(inv, type=state[t], time=inv["time"] + PERFECT_LATENCY)

    return simulate(gen, complete, ctx)


def _with(o: Op, **kw) -> Op:
    o = Op(o)
    o.update(kw)
    return o
