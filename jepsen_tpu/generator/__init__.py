"""Pure-functional generator DSL (reference: jepsen/src/jepsen/generator.clj).

A *generator* is an immutable value that produces operations for worker
threads. The protocol (generator.clj:382-390):

    gen_op(gen, test, ctx)            -> (op, gen') | (PENDING, gen') | None
    gen_update(gen, test, ctx, event) -> gen'

where `ctx` carries the simulated/real clock and the set of free worker
threads (generator.clj:453-464). The following Python values are
generators out of the box, mirroring the reference's protocol extensions
(generator.clj:545-590):

    None            the exhausted generator
    dict / Op       a one-shot op map: emits once, filled in from ctx
    callable        an infinite generator: called (with (test, ctx) if it
                    accepts two args, else no args) for a fresh op-ish
                    value each time; never updated
    list / tuple    a sequence of generators, run one after the other

Everything else is one of the combinator classes below. All combinators
are immutable: op/update return fresh instances, so generators can be
reused, checkpointed, and replayed deterministically.

Randomness goes through this module's `rand` (a `random.Random`), which
`fixed_rand(seed)` rebinds for reproducible tests — the analogue of the
reference's `with-fixed-rand-int` (generator/test.clj:30-47).
"""

from __future__ import annotations

import inspect
import random
from typing import Any, Callable, Dict, Iterable, Optional

from jepsen_tpu.history import Op
from jepsen_tpu.util import secs_to_nanos

NEMESIS = "nemesis"


class _Pending:
    __slots__ = ()

    def __repr__(self):
        return ":pending"


PENDING = _Pending()

# ------------------------------------------------------------------ rand

rand = random.Random()


class fixed_rand:
    """Context manager rebinding this module's RNG to a seeded stream —
    determinism for tests (generator/test.clj:30-47, seed 45100)."""

    def __init__(self, seed: int = 45100):
        self.seed = seed

    def __enter__(self):
        global rand
        self._saved = rand
        rand = random.Random(self.seed)
        return rand

    def __exit__(self, *exc):
        global rand
        rand = self._saved
        return False


# --------------------------------------------------------------- context


def _thread_key(t):
    # stable ordering over ints + the :nemesis keyword
    return (1, str(t)) if isinstance(t, str) else (0, t)


class Ctx:
    """Generator context: time (nanos), free threads, worker map
    (thread -> process it is currently executing). Immutable
    (generator.clj:453-464)."""

    __slots__ = ("time", "free_threads", "workers")

    def __init__(self, time: int, free_threads: tuple, workers: dict):
        self.time = time
        self.free_threads = free_threads  # sorted tuple, acts as a fair set
        self.workers = workers

    @classmethod
    def for_test(cls, test: dict) -> "Ctx":
        n = test.get("concurrency", 2)
        threads = tuple(sorted([NEMESIS, *range(n)], key=_thread_key))
        return cls(0, threads, {t: t for t in threads})

    # -- functional updates
    def with_time(self, t: int) -> "Ctx":
        return Ctx(t, self.free_threads, self.workers)

    def busy(self, thread) -> "Ctx":
        return Ctx(self.time,
                   tuple(t for t in self.free_threads if t != thread),
                   self.workers)

    def free(self, thread) -> "Ctx":
        if thread in self.free_threads:
            return self
        ft = tuple(sorted((*self.free_threads, thread), key=_thread_key))
        return Ctx(self.time, ft, self.workers)

    def with_worker(self, thread, process) -> "Ctx":
        w = dict(self.workers)
        w[thread] = process
        return Ctx(self.time, self.free_threads, w)

    def restrict(self, pred: Callable[[Any], bool]) -> "Ctx":
        """Context restricted to threads satisfying pred
        (on-threads-context, generator.clj:845-863)."""
        return Ctx(self.time,
                   tuple(t for t in self.free_threads if pred(t)),
                   {t: p for t, p in self.workers.items() if pred(t)})

    # -- queries (generator.clj:474-527)
    def all_threads(self) -> list:
        return list(self.workers)

    def all_processes(self) -> list:
        return list(self.workers.values())

    def free_processes(self) -> list:
        return [self.workers[t] for t in self.free_threads]

    def some_free_process(self):
        """A uniformly random free process — the fair scheduler
        (generator.clj:480-487)."""
        n = len(self.free_threads)
        if n == 0:
            return None
        return self.workers[self.free_threads[rand.randrange(n)]]

    def thread_to_process(self, thread):
        return self.workers.get(thread)

    def process_to_thread(self, process):
        for t, p in self.workers.items():
            if p == process:
                return t
        return None

    def next_process(self, thread):
        """Process id to assign a thread whose process crashed: current
        process + number of numeric processes in the worker map
        (generator.clj:519-527)."""
        if isinstance(thread, str):
            return thread
        return (self.workers[thread]
                + sum(1 for p in self.workers.values() if isinstance(p, int)))

    def __repr__(self):
        return (f"Ctx(time={self.time}, free={list(self.free_threads)}, "
                f"workers={self.workers})")


def context(test: dict) -> Ctx:
    return Ctx.for_test(test)


def rand_int_seq(seed: Optional[int] = None):
    """Reproducible stream of random ints (generator.clj:466-472)."""
    r = random.Random(seed if seed is not None else rand.randrange(2**31))
    while True:
        yield r.randrange(-(2**63), 2**63)


# ------------------------------------------------------------- protocol


def fill_in_op(o: dict, ctx: Ctx):
    """Fill :time, :process, :type from context; PENDING if no process is
    free (generator.clj:531-543)."""
    p = ctx.some_free_process()
    if p is None:
        return PENDING
    o = Op(o)
    if o.get("time") is None:
        o["time"] = ctx.time
    if o.get("process") is None:
        o["process"] = p
    if o.get("type") is None:
        o["type"] = "invoke"
    return o


class Generator:
    """Base class for combinator generators."""

    def op(self, test, ctx):  # -> (op|PENDING, gen') | None
        raise NotImplementedError

    def update(self, test, ctx, event):  # -> gen'
        return self


def _fn_wants_args(f) -> bool:
    try:
        sig = inspect.signature(f)
    except (ValueError, TypeError):
        return False
    params = [p for p in sig.parameters.values()
              if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    return len(params) >= 2


class _Fn(Generator):
    """Wrapper giving function generators seq-continuation semantics
    (generator.clj:556-563): each call produces a fresh op-ish value; the
    fn itself is the continuation."""

    __slots__ = ("f", "wants")

    def __init__(self, f, wants=None):
        self.f = f
        self.wants = _fn_wants_args(f) if wants is None else wants

    def op(self, test, ctx):
        x = self.f(test, ctx) if self.wants else self.f()
        if x is None:
            return None
        return gen_op(_Seq(x, (x, self), 0), test, ctx)

    def update(self, test, ctx, event):
        return self


class _Seq(Generator):
    """Sequence-of-generators with an O(1) cursor: `head` is the live
    state of items[idx]; the untouched tail is never copied
    (generator.clj:571-590 Seqable semantics; updates go to the first
    generator only)."""

    __slots__ = ("head", "items", "idx")

    def __init__(self, head, items, idx):
        self.head = head
        self.items = items  # tuple, never mutated
        self.idx = idx

    def op(self, test, ctx):
        head, idx = self.head, self.idx
        while True:
            res = gen_op(head, test, ctx)
            if res is not None:
                o, g2 = res
                if idx == len(self.items) - 1:
                    return o, g2  # last element: collapse to its state
                return o, _Seq(g2, self.items, idx)
            idx += 1
            if idx >= len(self.items):
                return None
            head = self.items[idx]

    def update(self, test, ctx, event):
        return _Seq(gen_update(self.head, test, ctx, event),
                    self.items, self.idx)


def gen_op(gen, test, ctx: Ctx):
    """Protocol dispatch for `op` (generator.clj:545-590)."""
    if gen is None:
        return None
    if isinstance(gen, Generator):
        return gen.op(test, ctx)
    if isinstance(gen, dict):
        o = fill_in_op(gen, ctx)
        return (o, gen) if o is PENDING else (o, None)
    if callable(gen):
        return _Fn(gen).op(test, ctx)
    if isinstance(gen, (list, tuple)):
        if not gen:
            return None
        items = tuple(gen)
        return _Seq(items[0], items, 0).op(test, ctx)
    raise TypeError(f"not a generator: {gen!r}")


def gen_update(gen, test, ctx: Ctx, event):
    """Protocol dispatch for `update` (generator.clj:545-590)."""
    if gen is None or isinstance(gen, dict) or callable(gen):
        return gen
    if isinstance(gen, Generator):
        return gen.update(test, ctx, event)
    if isinstance(gen, (list, tuple)):
        if not gen:
            return None
        items = tuple(gen)
        return _Seq(gen_update(items[0], test, ctx, event), items, 0)
    raise TypeError(f"not a generator: {gen!r}")


# ----------------------------------------------------------- validation


class InvalidOp(Exception):
    def __init__(self, problems, res, ctx):
        self.problems = problems
        self.res = res
        self.ctx = ctx
        super().__init__(
            "Generator produced an invalid (op, gen') tuple: "
            + "; ".join(problems) + f" -- {res!r}")


class Validate(Generator):
    """Checks well-formedness of emitted ops: type in
    {invoke, info, sleep, log}, numeric time, a free process
    (generator.clj:622-676)."""

    __slots__ = ("gen",)

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        if not (isinstance(res, tuple) and len(res) == 2):
            raise InvalidOp(["should return a tuple of two elements"], res, ctx)
        o, g2 = res
        if o is not PENDING:
            problems = []
            if not isinstance(o, dict):
                problems.append("should be either PENDING or a dict")
            else:
                if o.get("type") not in ("invoke", "info", "sleep", "log"):
                    problems.append(
                        ":type should be invoke, info, sleep, or log")
                if not isinstance(o.get("time"), (int, float)):
                    problems.append(":time should be a number")
                if o.get("process") is None:
                    problems.append("no :process")
                elif o.get("process") not in ctx.free_processes():
                    problems.append(f"process {o.get('process')!r} is not free")
            if problems:
                raise InvalidOp(problems, res, ctx)
        return o, Validate(g2)

    def update(self, test, ctx, event):
        return Validate(gen_update(self.gen, test, ctx, event))


def validate(gen):
    return Validate(gen)


class GeneratorThrew(Exception):
    def __init__(self, kind, ctx, event=None):
        self.kind = kind
        self.ctx = ctx
        self.event = event
        super().__init__(f"Generator threw during {kind}")


class FriendlyExceptions(Generator):
    """Wraps op/update exceptions with generator context
    (generator.clj:678-718)."""

    __slots__ = ("gen",)

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        try:
            res = gen_op(self.gen, test, ctx)
        except GeneratorThrew:
            raise
        except Exception as e:
            raise GeneratorThrew("op", ctx) from e
        if res is None:
            return None
        o, g2 = res
        return o, FriendlyExceptions(g2)

    def update(self, test, ctx, event):
        try:
            return FriendlyExceptions(gen_update(self.gen, test, ctx, event))
        except GeneratorThrew:
            raise
        except Exception as e:
            raise GeneratorThrew("update", ctx, event) from e


def friendly_exceptions(gen):
    return FriendlyExceptions(gen)


class Trace(Generator):
    """Logs every op/update (generator.clj:720-763)."""

    __slots__ = ("k", "gen", "log")

    def __init__(self, k, gen, log=None):
        self.k = k
        self.gen = gen
        self.log = log or (lambda *a: print(*a))

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        self.log(self.k, "op", ctx, res and res[0])
        if res is None:
            return None
        o, g2 = res
        return o, Trace(self.k, g2, self.log)

    def update(self, test, ctx, event):
        self.log(self.k, "update", ctx, event)
        return Trace(self.k, gen_update(self.gen, test, ctx, event), self.log)


def trace(k, gen, log=None):
    return Trace(k, gen, log)


# -------------------------------------------------------- map / filter


class Map(Generator):
    """Transforms ops with f; PENDING/None bypass (generator.clj:765-788)."""

    __slots__ = ("f", "gen")

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        return (o if o is PENDING else self.f(o)), Map(self.f, g2)

    def update(self, test, ctx, event):
        return Map(self.f, gen_update(self.gen, test, ctx, event))


def map(f, gen):  # noqa: A001 - mirrors the reference's name
    return Map(f, gen)


def f_map(fm: Dict, gen):
    """Rewrites :f through the map fm (generator.clj:790-796)."""
    def transform(o):
        o = Op(o)
        o["f"] = fm.get(o.get("f"), o.get("f"))
        return o
    return Map(transform, gen)


class Filter(Generator):
    """Passes only ops matching (f op); PENDING bypasses
    (generator.clj:799-818)."""

    __slots__ = ("f", "gen")

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        g = self.gen
        while True:
            res = gen_op(g, test, ctx)
            if res is None:
                return None
            o, g2 = res
            if o is PENDING or self.f(o):
                return o, Filter(self.f, g2)
            g = g2

    def update(self, test, ctx, event):
        return Filter(self.f, gen_update(self.gen, test, ctx, event))


def filter(f, gen):  # noqa: A001
    return Filter(f, gen)


class IgnoreUpdates(Generator):
    __slots__ = ("gen",)

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        return gen_op(self.gen, test, ctx)

    def update(self, test, ctx, event):
        return self


class OnUpdate(Generator):
    """Custom update handler: (f this test ctx event) -> gen'
    (generator.clj:828-843)."""

    __slots__ = ("f", "gen")

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        return o, OnUpdate(self.f, g2)

    def update(self, test, ctx, event):
        return self.f(self, test, ctx, event)


def on_update(f, gen):
    return OnUpdate(f, gen)


# ------------------------------------------------------- thread routing


class OnThreads(Generator):
    """Restricts the wrapped generator to threads satisfying f; updates
    routed only for matching threads (generator.clj:865-882)."""

    __slots__ = ("f", "gen")

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx.restrict(self.f))
        if res is None:
            return None
        o, g2 = res
        return o, OnThreads(self.f, g2)

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread(event.get("process"))
        if thread is not None and self.f(thread):
            return OnThreads(
                self.f, gen_update(self.gen, test, ctx.restrict(self.f), event))
        return self


def on_threads(f, gen):
    return OnThreads(f, gen)


on = on_threads  # backwards-compat alias (generator.clj:884)


def clients(client_gen, nemesis_gen=None):
    """Restrict to client threads; two-arity combines with a nemesis
    generator (generator.clj:1093-1103)."""
    cg = OnThreads(lambda t: t != NEMESIS, client_gen)
    if nemesis_gen is None:
        return cg
    return any(cg, nemesis(nemesis_gen))


def nemesis(nemesis_gen, client_gen=None):
    """Restrict to the nemesis thread (generator.clj:1105-1115)."""
    ng = OnThreads(lambda t: t == NEMESIS, nemesis_gen)
    if client_gen is None:
        return ng
    return any(ng, clients(client_gen))


# -------------------------------------------------------- soonest race


def soonest_op_map(m1: Optional[dict], m2: Optional[dict]) -> Optional[dict]:
    """Earlier of two candidate {op, ..., weight} maps; PENDING loses;
    time ties break randomly proportional to weights, and the winner's
    weight becomes the sum (generator.clj:886-928)."""
    if m1 is None:
        return m2
    if m2 is None:
        return m1
    o1, o2 = m1["op"], m2["op"]
    if o1 is PENDING:
        return m2
    if o2 is PENDING:
        return m1
    t1, t2 = o1.get("time"), o2.get("time")
    if t1 == t2:
        w1 = m1.get("weight", 1)
        w2 = m2.get("weight", 1)
        w = w1 + w2
        winner = m1 if rand.randrange(w) < w1 else m2
        winner = dict(winner)
        winner["weight"] = w
        return winner
    return m1 if t1 < t2 else m2


class Any(Generator):
    """Ops from whichever sub-generator is soonest; updates to all
    (generator.clj:930-945)."""

    __slots__ = ("gens",)

    def __init__(self, gens):
        self.gens = list(gens)

    def op(self, test, ctx):
        soonest = None
        for i, g in enumerate(self.gens):
            res = gen_op(g, test, ctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1], "i": i})
        if soonest is None:
            return None
        gens = list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return soonest["op"], Any(gens)

    def update(self, test, ctx, event):
        return Any([gen_update(g, test, ctx, event) for g in self.gens])


def any(*gens):  # noqa: A001
    if len(gens) == 0:
        return None
    if len(gens) == 1:
        return gens[0]
    return Any(gens)


class EachThread(Generator):
    """An independent copy of the generator per thread; each copy's
    context contains exactly its own thread (generator.clj:956-1007)."""

    __slots__ = ("fresh_gen", "gens")

    def __init__(self, fresh_gen, gens=None):
        self.fresh_gen = fresh_gen
        self.gens = gens or {}

    def _thread_ctx(self, ctx, thread, free=True):
        return Ctx(ctx.time, (thread,) if free else (),
                   {thread: ctx.workers[thread]})

    def op(self, test, ctx):
        soonest = None
        for thread in ctx.free_threads:
            g = self.gens.get(thread, self.fresh_gen)
            res = gen_op(g, test, self._thread_ctx(ctx, thread))
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1], "thread": thread})
        if soonest is not None:
            gens = dict(self.gens)
            gens[soonest["thread"]] = soonest["gen"]
            return soonest["op"], EachThread(self.fresh_gen, gens)
        if len(ctx.free_threads) != len(ctx.workers):
            return PENDING, self  # busy threads may still free up
        return None  # every thread exhausted

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread(event.get("process"))
        if thread is None:
            return self
        g = self.gens.get(thread, self.fresh_gen)
        tctx = Ctx(ctx.time,
                   tuple(t for t in ctx.free_threads if t == thread),
                   {thread: event.get("process")})
        gens = dict(self.gens)
        gens[thread] = gen_update(g, test, tctx, event)
        return EachThread(self.fresh_gen, gens)


def each_thread(gen):
    return EachThread(gen)


class Reserve(Generator):
    """Dedicated thread ranges per generator plus a default
    (generator.clj:1009-1089)."""

    __slots__ = ("ranges", "all_ranges", "gens")

    def __init__(self, ranges, all_ranges, gens):
        self.ranges = ranges          # list of frozenset of threads
        self.all_ranges = all_ranges  # union
        self.gens = gens              # len(ranges)+1; last = default

    def op(self, test, ctx):
        soonest = None
        for i, threads in enumerate(self.ranges):
            rctx = ctx.restrict(lambda t, ts=threads: t in ts)
            res = gen_op(self.gens[i], test, rctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest,
                    {"op": res[0], "gen": res[1], "weight": len(threads),
                     "i": i})
        dctx = ctx.restrict(lambda t: t not in self.all_ranges)
        res = gen_op(self.gens[-1], test, dctx)
        if res is not None:
            soonest = soonest_op_map(
                soonest,
                {"op": res[0], "gen": res[1], "weight": len(dctx.workers),
                 "i": len(self.ranges)})
        if soonest is None:
            return None
        gens = list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return soonest["op"], Reserve(self.ranges, self.all_ranges, gens)

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread(event.get("process"))
        i = len(self.ranges)
        for j, threads in enumerate(self.ranges):
            if thread in threads:
                i = j
                break
        gens = list(self.gens)
        gens[i] = gen_update(gens[i], test, ctx, event)
        return Reserve(self.ranges, self.all_ranges, gens)


def reserve(*args):
    """reserve(5, write_gen, 10, cas_gen, default_gen): first 5 threads
    run write_gen, next 10 cas_gen, rest the default
    (generator.clj:1056-1089)."""
    assert len(args) >= 1 and len(args) % 2 == 1, "need pairs + default"
    default = args[-1]
    pairs = [(args[i], args[i + 1]) for i in range(0, len(args) - 1, 2)]
    ranges, gens, n = [], [], 0
    for count, g in pairs:
        ranges.append(frozenset(range(n, n + count)))
        gens.append(g)
        n += count
    all_ranges = frozenset().union(*ranges) if ranges else frozenset()
    gens.append(default)
    return Reserve(ranges, all_ranges, gens)


# ----------------------------------------------------------- selection


class Mix(Generator):
    """Uniform random mixture; ignores updates (generator.clj:1124-1154)."""

    __slots__ = ("i", "gens")

    def __init__(self, i, gens):
        self.i = i
        self.gens = gens

    def op(self, test, ctx):
        gens = list(self.gens)
        i = self.i
        while gens:
            res = gen_op(gens[i], test, ctx)
            if res is not None:
                o, g2 = res
                gens[i] = g2
                return o, Mix(rand.randrange(len(gens)), gens)
            del gens[i]
            if not gens:
                return None
            i = rand.randrange(len(gens))
        return None

    def update(self, test, ctx, event):
        return self


def mix(gens: Iterable):
    gens = list(gens)
    if not gens:
        return None
    return Mix(rand.randrange(len(gens)), gens)


class Limit(Generator):
    """At most n ops (generator.clj:1156-1170)."""

    __slots__ = ("remaining", "gen")

    def __init__(self, remaining, gen):
        self.remaining = remaining
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining <= 0:
            return None
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        return o, Limit(self.remaining - (0 if o is PENDING else 1), g2)

    def update(self, test, ctx, event):
        return Limit(self.remaining, gen_update(self.gen, test, ctx, event))


def limit(n, gen):
    return Limit(n, gen)


def once(gen):
    return Limit(1, gen)


def log(msg):
    """One :log op; the worker prints it (generator.clj:1177-1181)."""
    return {"type": "log", "value": msg}


class Repeat(Generator):
    """Re-emits from the *unchanged* underlying generator forever or n
    times — the inverse of `once` (generator.clj:1183-1210)."""

    __slots__ = ("remaining", "gen")

    def __init__(self, remaining, gen):
        self.remaining = remaining  # -1 = infinite
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining == 0:
            return None
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        o, _ = res
        dec = 0 if o is PENDING else 1
        return o, Repeat(self.remaining - dec, self.gen)

    def update(self, test, ctx, event):
        return Repeat(self.remaining, gen_update(self.gen, test, ctx, event))


def repeat(n_or_gen, gen=None):
    if gen is None:
        return Repeat(-1, n_or_gen)
    assert n_or_gen >= 0
    return Repeat(n_or_gen, gen)


class Cycle(Generator):
    """Endlessly restarts a sequence of generators once exhausted — the
    analogue of handing the reference a lazy `(cycle [...])` seq (e.g.
    the causal test's sleep/start/sleep/stop nemesis loop,
    causal.clj:124-128)."""

    __slots__ = ("items", "cur")

    def __init__(self, items, cur=None):
        self.items = tuple(items)
        self.cur = cur

    def op(self, test, ctx):
        if not self.items:
            return None
        cur = self.cur if self.cur is not None else list(self.items)
        res = gen_op(cur, test, ctx)
        if res is None:
            res = gen_op(list(self.items), test, ctx)
            if res is None:
                return None  # every element empty: stop rather than spin
        o, g2 = res
        return o, Cycle(self.items, g2)

    def update(self, test, ctx, event):
        if self.cur is None:
            return self
        return Cycle(self.items, gen_update(self.cur, test, ctx, event))


def cycle_gen(items):
    return Cycle(items)


class ProcessLimit(Generator):
    """Emits ops while the union of observed worker processes stays ≤ n
    (generator.clj:1212-1237)."""

    __slots__ = ("n", "procs", "gen")

    def __init__(self, n, procs, gen):
        self.n = n
        self.procs = procs
        self.gen = gen

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return o, ProcessLimit(self.n, self.procs, g2)
        procs = self.procs | frozenset(ctx.all_processes())
        if len(procs) > self.n:
            return None
        return o, ProcessLimit(self.n, procs, g2)

    def update(self, test, ctx, event):
        return ProcessLimit(self.n, self.procs,
                            gen_update(self.gen, test, ctx, event))


def process_limit(n, gen):
    return ProcessLimit(n, frozenset(), gen)


class TimeLimit(Generator):
    """Emits ops for dt (nanos) past the first emitted op's time
    (generator.clj:1239-1263)."""

    __slots__ = ("limit", "cutoff", "gen")

    def __init__(self, limit, cutoff, gen):
        self.limit = limit
        self.cutoff = cutoff
        self.gen = gen

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return o, TimeLimit(self.limit, self.cutoff, g2)
        cutoff = self.cutoff if self.cutoff is not None else o["time"] + self.limit
        if o["time"] >= cutoff:
            return None
        return o, TimeLimit(self.limit, cutoff, g2)

    def update(self, test, ctx, event):
        return TimeLimit(self.limit, self.cutoff,
                         gen_update(self.gen, test, ctx, event))


def time_limit(dt_secs, gen):
    return TimeLimit(secs_to_nanos(dt_secs), None, gen)


# -------------------------------------------------------- time shaping


class Stagger(Generator):
    """Schedules ops at uniformly random intervals in [0, 2*dt)
    (generator.clj:1265-1305)."""

    __slots__ = ("dt", "next_time", "gen")

    def __init__(self, dt, next_time, gen):
        self.dt = dt  # nanos, already doubled
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return o, self
        nt = self.next_time if self.next_time is not None else ctx.time
        nt2 = nt + int(rand.random() * self.dt)
        if nt <= o["time"]:
            return o, Stagger(self.dt, nt2, g2)
        o = Op(o)
        o["time"] = nt
        return o, Stagger(self.dt, nt2, g2)

    def update(self, test, ctx, event):
        return Stagger(self.dt, self.next_time,
                       gen_update(self.gen, test, ctx, event))


def stagger(dt_secs, gen):
    return Stagger(secs_to_nanos(2 * dt_secs), None, gen)


class Delay(Generator):
    """Ops exactly dt apart, catching up if behind
    (generator.clj:1344-1370)."""

    __slots__ = ("dt", "next_time", "gen")

    def __init__(self, dt, next_time, gen):
        self.dt = dt
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return o, Delay(self.dt, self.next_time, g2)
        nt = self.next_time if self.next_time is not None else o["time"]
        o = Op(o)
        o["time"] = max(o["time"], nt)
        return o, Delay(self.dt, nt + self.dt, g2)

    def update(self, test, ctx, event):
        return Delay(self.dt, self.next_time,
                     gen_update(self.gen, test, ctx, event))


def delay(dt_secs, gen):
    return Delay(secs_to_nanos(dt_secs), None, gen)


def sleep(dt_secs):
    """One :sleep op; its worker idles dt seconds
    (generator.clj:1372-1376)."""
    return {"type": "sleep", "value": dt_secs}


# ------------------------------------------------------------ barriers


class Synchronize(Generator):
    """PENDING until every thread is free, then becomes the wrapped
    generator (generator.clj:1378-1398)."""

    __slots__ = ("gen",)

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        if len(ctx.free_threads) == len(ctx.workers):
            return gen_op(self.gen, test, ctx)
        return PENDING, self

    def update(self, test, ctx, event):
        return Synchronize(gen_update(self.gen, test, ctx, event))


def synchronize(gen):
    return Synchronize(gen)


def phases(*gens):
    """Run each generator to completion with a barrier between
    (generator.clj:1400-1405)."""
    return [Synchronize(g) for g in gens]


def then(a, b):
    """b, then (synchronize a) — argument order matches the reference's
    ->> pipelining (generator.clj:1407-1416)."""
    return [b, Synchronize(a)]


class UntilOk(Generator):
    """Yields ops until one completes :ok (generator.clj:1418-1436)."""

    __slots__ = ("gen", "done")

    def __init__(self, gen, done=False):
        self.gen = gen
        self.done = done

    def op(self, test, ctx):
        if self.done:
            return None
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        return o, UntilOk(g2, self.done)

    def update(self, test, ctx, event):
        if event.get("type") == "ok":
            return UntilOk(self.gen, True)
        return UntilOk(gen_update(self.gen, test, ctx, event), self.done)


def until_ok(gen):
    return UntilOk(gen)


class FlipFlop(Generator):
    """Alternates A, B, A, B...; stops when either is exhausted; ignores
    updates (generator.clj:1438-1452)."""

    __slots__ = ("gens", "i")

    def __init__(self, gens, i=0):
        self.gens = gens
        self.i = i

    def op(self, test, ctx):
        res = gen_op(self.gens[self.i], test, ctx)
        if res is None:
            return None
        o, g2 = res
        gens = list(self.gens)
        gens[self.i] = g2
        ni = self.i if o is PENDING else (self.i + 1) % len(gens)
        return o, FlipFlop(gens, ni)

    def update(self, test, ctx, event):
        return self


def flip_flop(a, b):
    return FlipFlop([a, b])


def concat(*gens):
    """Sequence generators one after another (generator.clj:775-780)."""
    return list(gens)
