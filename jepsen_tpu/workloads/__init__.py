"""Reusable workloads (reference: jepsen/src/jepsen/tests.clj and
jepsen/src/jepsen/tests/*.clj).

`noop_test` is the base test map; `atom_client` is the in-memory fake
database (an atomic register implementing read/write/cas,
tests.clj:27-67) that lets the full lifecycle run with no cluster."""

from __future__ import annotations

import threading
from typing import Dict, Optional

from jepsen_tpu import core as jcore
from jepsen_tpu.client import Client
from jepsen_tpu.history import Op


class AtomDB:
    """Shared in-memory register state (tests.clj:27-40 atom-db)."""

    def __init__(self, value=None):
        self.lock = threading.Lock()
        self.value = value


class AtomClient(Client):
    """read/write/cas against an AtomDB (tests.clj:42-67 atom-client).
    Linearizable by construction — a useful control for checker tests."""

    def __init__(self, db: Optional[AtomDB] = None):
        self.db = db or AtomDB()

    def open(self, test, node):
        return AtomClient(self.db)

    def invoke(self, test, op):
        o = Op(op)
        f = op.get("f")
        with self.db.lock:
            if f == "read":
                o["type"] = "ok"
                o["value"] = self.db.value
            elif f == "write":
                self.db.value = op.get("value")
                o["type"] = "ok"
            elif f == "cas":
                old, new = op.get("value")
                if self.db.value == old:
                    self.db.value = new
                    o["type"] = "ok"
                else:
                    o["type"] = "fail"
            else:
                raise ValueError(f"unknown f {f!r}")
        return o

    def is_reusable(self, test):
        return True


def atom_client(db: Optional[AtomDB] = None) -> AtomClient:
    return AtomClient(db)


def noop_test(overrides: Optional[Dict] = None) -> Dict:
    """The base test map (tests.clj:12-25)."""
    t = jcore.make_test({"name": "noop"})
    t.update(overrides or {})
    return t
