"""Cycle-detection workload wrappers (reference:
jepsen/src/jepsen/tests/cycle.clj, tests/cycle/append.clj,
tests/cycle/wr.clj).

`checker(analyzer)` lifts a graph analyzer into a Checker
(cycle.clj:9-16); `append` / `wr` bundle the elle list-append and
rw-register checkers with matching txn generators into partial tests
(append.clj:30-58)."""

from __future__ import annotations

from typing import Callable, Dict, Optional

from jepsen_tpu import elle
from jepsen_tpu.checker.core import Checker, FnChecker
from jepsen_tpu.elle import list_append, rw_register


def checker(analyzer: Callable) -> Checker:
    """A Checker from a history -> (graph, explainer, by_id) analyzer
    (cycle.clj:9-16)."""
    return FnChecker(lambda test, history, opts: elle.check(analyzer, history),
                     name="cycle")


class AppendChecker(Checker):
    """Full list-append checker (append.clj:11-22); default anomalies
    [G1 G2]."""

    def __init__(self, opts: Optional[Dict] = None):
        self.opts = {"anomalies": ["G1", "G2"], **(opts or {})}

    def check(self, test, history, opts=None):
        return list_append.check(self.opts, history)

    @property
    def checker_name(self):
        return "append"


class WrChecker(Checker):
    """Full rw-register checker (wr.clj:14-54)."""

    def __init__(self, opts: Optional[Dict] = None):
        self.opts = opts or {}

    def check(self, test, history, opts=None):
        return rw_register.check(self.opts, history)

    @property
    def checker_name(self):
        return "wr"


def append(opts: Optional[Dict] = None) -> Dict:
    """Partial test {generator, checker} for list-append histories
    (append.clj:30-58)."""
    return {"generator": list_append.gen(opts), "checker": AppendChecker(opts)}


def wr(opts: Optional[Dict] = None) -> Dict:
    """Partial test {generator, checker} for rw-register histories."""
    return {"generator": rw_register.gen(opts), "checker": WrChecker(opts)}
