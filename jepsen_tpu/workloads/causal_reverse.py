"""Causal-reverse workload: strict-serializability anomaly where T1 < T2
but T2 is visible without T1 (reference:
jepsen/src/jepsen/tests/causal_reverse.clj).

Concurrent blind writes of distinct values; reads return the set of
visible values. Replay the history tracking which writes completed
before each write's invocation; a read showing w_i but missing some
w_j < w_i is a violation (causal_reverse.clj:21-49)."""

from __future__ import annotations

import itertools
from typing import Dict, Optional

from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker.core import Checker, compose
from jepsen_tpu.checker.suite import stats


def graph(history) -> Dict:
    """value -> set of values whose writes completed before this write
    was invoked (the first-order write precedence graph,
    causal_reverse.clj:21-49)."""
    completed: set = set()
    expected: Dict = {}
    for op in history:
        if op.get("f") != "write":
            continue
        if op.is_invoke:
            expected[op.get("value")] = set(completed)
        elif op.is_ok:
            completed.add(op.get("value"))
    return expected


def errors(history, expected: Dict) -> list:
    """Ok reads whose visible set misses an expected predecessor
    (causal_reverse.clj:51-77)."""
    out = []
    for op in history:
        if not (op.is_ok and op.get("f") == "read"):
            continue
        seen = set(op.get("value") or ())
        our_expected: set = set()
        for v in seen:
            our_expected |= expected.get(v, set())
        missing = our_expected - seen
        if missing:
            e = {k: v for k, v in op.items() if k != "value"}
            e["missing"] = sorted(missing, key=repr)
            e["expected-count"] = len(our_expected)
            out.append(e)
    return out


class CausalReverseChecker(Checker):
    """Subsequent writes never appear without prior acknowledged writes
    (causal_reverse.clj:79-88)."""

    def check(self, test, history, opts=None):
        expected = graph(history)
        errs = errors(history, expected)
        return {"valid?": not errs, "errors": errs}

    @property
    def checker_name(self):
        return "causal-reverse"


def checker() -> CausalReverseChecker:
    return CausalReverseChecker()


def workload(opts: Optional[Dict] = None) -> Dict:
    """{checker, generator}: per-key mixed reads and unique-value writes
    (causal_reverse.clj:90-114)."""
    o = opts or {}
    n = len(o.get("nodes") or [1])
    per_key_limit = o.get("per-key-limit", 500)

    def fgen(_k):
        values = itertools.count()

        def write(_t=None, _c=None):
            return {"f": "write", "value": next(values)}

        def read(_t=None, _c=None):
            # a fn, not a dict: dict generators are one-shot, and mix
            # would drop reads after the first one
            return {"f": "read"}

        return gen.limit(per_key_limit,
                         gen.stagger(1 / 100, gen.mix([read, write])))

    return {
        "checker": compose({
            "stats": stats(),
            "sequential": independent.checker(checker(),
                                              batch_device=False),
        }),
        "generator": independent.concurrent_generator(
            n, itertools.count(), fgen),
    }
