"""Causal-register workload (reference: jepsen/src/jepsen/tests/causal.clj).

A per-key causal order of five ops (read-init, write 1, read, write 2,
read) issued by a single worker; each op carries a :link to the position
of the causally preceding op, and the register model rejects reads of
unwritten values, writes out of counter order, and broken links
(causal.clj:12-88 — its own mini-Model protocol, separate from
knossos models)."""

from __future__ import annotations

from typing import Dict, Optional

from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker.core import Checker


class Inconsistent:
    def __init__(self, msg: str):
        self.msg = msg

    def step(self, op):
        return self

    def __str__(self):
        return self.msg


def is_inconsistent(m) -> bool:
    return isinstance(m, Inconsistent)


class CausalRegister:
    """value/counter/last-pos state machine (causal.clj:36-88)."""

    def __init__(self, value=0, counter=0, last_pos=None):
        self.value = value
        self.counter = counter
        self.last_pos = last_pos

    def step(self, op):
        c = self.counter + 1
        v = op.get("value")
        pos = op.get("position")
        link = op.get("link")
        if link != "init" and link != self.last_pos:
            return Inconsistent(
                f"Cannot link {link} to last-seen position {self.last_pos}")
        f = op.get("f")
        if f == "write":
            if v == c:
                return CausalRegister(v, c, pos)
            return Inconsistent(
                f"expected value {c} attempting to write {v} instead")
        if f == "read-init":
            if self.counter == 0 and v not in (0, None):
                return Inconsistent(f"expected init value 0, read {v}")
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return Inconsistent(
                f"can't read {v} from register {self.value}")
        if f == "read":
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return Inconsistent(
                f"can't read {v} from register {self.value}")
        return Inconsistent(f"unknown f {f!r}")

    def __str__(self):
        return repr(self.value)


def causal_register() -> CausalRegister:
    return CausalRegister()


class CausalChecker(Checker):
    """Steps the model over ok completions in order (causal.clj:90-113)."""

    def __init__(self, model: Optional[CausalRegister] = None):
        self.model = model or causal_register()

    def check(self, test, history, opts=None):
        s = self.model
        for op in history:
            if not op.is_ok:
                continue
            s = s.step(op)
            if is_inconsistent(s):
                return {"valid?": False, "error": s.msg}
        return {"valid?": True, "model": str(s)}

    @property
    def checker_name(self):
        return "causal"


def check(model: Optional[CausalRegister] = None) -> CausalChecker:
    return CausalChecker(model)


# ------------------------------------------------------------ generators


def r(_t=None, _c=None):
    return {"f": "read"}


def ri(_t=None, _c=None):
    return {"f": "read-init"}


def cw1(_t=None, _c=None):
    return {"f": "write", "value": 1}


def cw2(_t=None, _c=None):
    return {"f": "write", "value": 2}


def workload(opts: Optional[Dict] = None) -> Dict:
    """Per-key causal order [ri cw1 r cw2 r], one worker per key,
    staggered, with a start/stop nemesis cycle (causal.clj:116-131)."""
    o = opts or {}
    import itertools

    def fgen(_k):
        # each step once: bare fns are infinite generators (the reference
        # relies on Clojure fns being one-shot inside seqs; ours aren't)
        return [gen.once(ri), gen.once(cw1), gen.once(r),
                gen.once(cw2), gen.once(r)]

    g = independent.concurrent_generator(1, itertools.count(), fgen)
    g = gen.stagger(1, g)
    nemesis_cycle = gen.cycle_gen(
        [gen.sleep(10), {"type": "info", "f": "start"},
         gen.sleep(10), {"type": "info", "f": "stop"}])
    g = gen.nemesis(nemesis_cycle, g)
    if o.get("time-limit"):
        g = gen.time_limit(o["time-limit"], g)
    return {
        "checker": independent.checker(check(causal_register()),
                                       batch_device=False),
        "generator": g,
    }
