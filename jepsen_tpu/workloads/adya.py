"""Adya G2 predicate anti-dependency workload (reference:
jepsen/src/jepsen/tests/adya.clj).

Per key, exactly two concurrent :insert transactions race: each reads
both tables by predicate and inserts into its own table only if both
reads were empty. Under serializability at most one can commit; two ok
inserts for a key is a G2 (predicate anti-dependency cycle) witness
(adya.clj:12-60)."""

from __future__ import annotations

import itertools
import threading
from typing import Dict, Optional

from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker.core import Checker


def g2_gen():
    """Pairs of inserts [key [a-id b-id]] with globally unique ids, two
    ops per key, two workers per key (adya.clj:50-60)."""
    ids = itertools.count(1)
    lock = threading.Lock()

    def next_id() -> int:
        with lock:
            return next(ids)

    def fgen(_k):
        return [
            gen.once(lambda _t=None, _c=None:
                     {"f": "insert", "value": [None, next_id()]}),
            gen.once(lambda _t=None, _c=None:
                     {"f": "insert", "value": [next_id(), None]}),
        ]

    return independent.concurrent_generator(2, itertools.count(), fgen)


class G2Checker(Checker):
    """At most one ok :insert per key (adya.clj:62-87). Works on the
    un-split history: values are [k [a b]] KV tuples."""

    def check(self, test, history, opts=None):
        keys: Dict = {}
        for op in history:
            if op.get("f") != "insert":
                continue
            v = op.get("value")
            k = v.key if isinstance(v, independent.KV) else (
                v[0] if isinstance(v, (list, tuple)) and len(v) == 2 else None)
            if k is None:
                continue
            if op.is_ok:
                keys[k] = keys.get(k, 0) + 1
            else:
                keys.setdefault(k, 0)
        insert_count = sum(1 for c in keys.values() if c > 0)
        illegal = {k: c for k, c in sorted(keys.items(), key=lambda kv:
                                           repr(kv[0])) if c > 1}
        return {
            "valid?": not illegal,
            "key-count": len(keys),
            "legal-count": insert_count - len(illegal),
            "illegal-count": len(illegal),
            "illegal": illegal,
        }

    @property
    def checker_name(self):
        return "g2"


def g2_checker() -> G2Checker:
    return G2Checker()


def workload(opts: Optional[Dict] = None) -> Dict:
    return {"checker": g2_checker(), "generator": g2_gen()}
