"""Long-fork anomaly workload (reference:
jepsen/src/jepsen/tests/long_fork.clj).

Parallel snapshot isolation permits — and SI forbids — concurrent writes
observed in conflicting orders:

    T1: (write x 1)        T3: (read x nil) (read y 1)
    T2: (write y 1)        T4: (read x 1)   (read y nil)

Each key is written once (value 1), so every group read is a vector of
nil/1 cells; two reads of the same group conflict when neither dominates
the other (long_fork.clj:160-200). Domination over nil/1 cells is a
pure bitmask comparison, so the pairwise fork search runs as numpy
matrix ops over the whole group at once rather than python pairs."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker

UNKNOWN = "unknown"


def group_for(n: int, k: int) -> List[int]:
    """The n keys of k's group, lower inclusive (long_fork.clj:97-104)."""
    lo = k - (k % n)
    return list(range(lo, lo + n))


def read_txn_for(n: int, k: int) -> List[list]:
    """A txn reading k's group in shuffled order (long_fork.clj:106-112)."""
    ks = group_for(n, k)
    gen.rand.shuffle(ks)
    return [["r", kk, None] for kk in ks]


class LongForkGenerator(gen.Generator):
    """Single inserts followed by group reads, mixed with reads of other
    in-flight groups (long_fork.clj:114-152). Workers alternate
    write-fresh-key / read-own-group; idle workers sometimes read another
    worker's active group."""

    def __init__(self, n: int, next_key: int = 0,
                 workers: Optional[Dict] = None):
        self.n = n
        self.next_key = next_key
        self.workers = workers or {}

    def update(self, test, ctx, event):
        return self

    def op(self, test, ctx):
        process = ctx.some_free_process()
        if process is None:
            return gen.PENDING, self
        worker = ctx.process_to_thread(process)
        k = self.workers.get(worker)
        if k is not None:
            op = gen.fill_in_op(
                {"process": process, "f": "read",
                 "value": read_txn_for(self.n, k)}, ctx)
            return op, LongForkGenerator(
                self.n, self.next_key, {**self.workers, worker: None})
        active = [v for v in self.workers.values() if v is not None]
        if active and gen.rand.random() < 0.5:
            k = active[gen.rand.randrange(len(active))]
            op = gen.fill_in_op(
                {"process": process, "f": "read",
                 "value": read_txn_for(self.n, k)}, ctx)
            return op, self
        op = gen.fill_in_op(
            {"process": process, "f": "write",
             "value": [["w", self.next_key, 1]]}, ctx)
        return op, LongForkGenerator(
            self.n, self.next_key + 1, {**self.workers,
                                        worker: self.next_key})


def generator(n: int = 2) -> LongForkGenerator:
    return LongForkGenerator(n)


# ---------------------------------------------------------------- check


class IllegalHistory(Exception):
    def __init__(self, info):
        super().__init__(info.get("msg", "illegal history"))
        self.info = info


def is_read_txn(txn) -> bool:
    return all(m[0] == "r" for m in (txn or []))


def is_write_txn(txn) -> bool:
    return bool(txn) and len(txn) == 1 and txn[0][0] == "w"


def read_op_value_map(op) -> Dict:
    return {k: v for _f, k, v in op.get("value") or []}


def read_compare(a: Dict, b: Dict):
    """-1 if a dominates, 0 equal, 1 if b dominates, None incomparable
    (long_fork.clj:160-200). Values move nil -> written exactly once."""
    if set(a) != set(b):
        raise IllegalHistory(
            {"type": "illegal-history", "reads": [a, b],
             "msg": "These reads did not query for the same keys, and "
                    "therefore cannot be compared."})
    res = 0
    for k, va in a.items():
        vb = b[k]
        if va == vb:
            continue
        if vb is None:
            if res > 0:
                return None
            res = -1
        elif va is None:
            if res < 0:
                return None
            res = 1
        else:
            raise IllegalHistory(
                {"type": "illegal-history", "key": k, "reads": [a, b],
                 "msg": "These two read states contain distinct values for "
                        "the same key; this checker assumes only one write "
                        "occurs per key."})
    return res


def find_forks(ops: List) -> List[list]:
    """Mutually incomparable read pairs within one group
    (long_fork.clj:211-218), via one vectorized domination matrix:
    with presence bitvectors P (1 = non-nil), a dominates b iff
    P_a >= P_b elementwise; a fork is a pair where neither dominates."""
    if len(ops) < 2:
        return []
    maps = [read_op_value_map(o) for o in ops]
    keys = sorted(maps[0])
    for m in maps[1:]:
        if set(m) != set(keys):
            read_compare(maps[0], m)  # raises with the exemplar pair
    # single-writer invariant: each key has at most one non-nil value.
    # Raise directly — read_compare may hit an incomparable key first
    # and return None instead of raising on the conflicting one.
    for k in keys:
        distinct = {m[k] for m in maps if m[k] is not None}
        if len(distinct) > 1:
            a = next(m for m in maps if m[k] in distinct)
            b = next(m for m in maps
                     if m[k] is not None and m[k] != a[k])
            raise IllegalHistory(
                {"type": "illegal-history", "key": k, "reads": [a, b],
                 "msg": "These two read states contain distinct values "
                        "for the same key; this checker assumes only one "
                        "write occurs per key."})
    p = np.array([[0 if m[k] is None else 1 for k in keys] for m in maps],
                 dtype=np.int8)
    ge = (p[:, None, :] >= p[None, :, :]).all(axis=2)
    incomparable = ~ge & ~ge.T
    forks = []
    ii, jj = np.nonzero(np.triu(incomparable, k=1))
    for i, j in zip(ii.tolist(), jj.tolist()):
        forks.append([dict(ops[i]), dict(ops[j])])
    return forks


def _groups(n: int, read_ops: List) -> List[List]:
    """Partition reads by the key set they observed; each must have
    exactly n keys (long_fork.clj:238-253)."""
    by_keys: Dict = {}
    for o in read_ops:
        ks = frozenset(m[1] for m in o.get("value") or [])
        by_keys.setdefault(ks, []).append(o)
    out = []
    for ks, ops in by_keys.items():
        if len(ks) != n:
            raise IllegalHistory(
                {"type": "illegal-history", "op": dict(ops[0]),
                 "msg": f"Every read in this history should have observed "
                        f"exactly {n} keys, but this read observed "
                        f"{len(ks)} instead: {sorted(ks)}"})
        out.append(ops)
    return out


class LongForkChecker(Checker):
    """No multi-writes per key; no mutually incomparable group reads
    (long_fork.clj:282-299)."""

    def __init__(self, n: int = 2):
        self.n = n

    def check(self, test, history, opts=None):
        reads = [o for o in history
                 if o.is_ok and is_read_txn(o.get("value"))]
        stats = {
            "reads-count": len(reads),
            "early-read-count": sum(
                1 for o in reads
                if not any(m[2] is not None for m in o["value"])),
            "late-read-count": sum(
                1 for o in reads
                if all(m[2] is not None for m in o["value"])),
        }
        # multiple writes to one key -> unknown (long_fork.clj:255-271)
        seen = set()
        for o in history:
            if o.is_invoke and is_write_txn(o.get("value")):
                k = o["value"][0][1]
                if k in seen:
                    return {**stats, "valid?": UNKNOWN,
                            "error": ["multiple-writes", k]}
                seen.add(k)
        try:
            forks = []
            for grp in _groups(self.n, reads):
                forks.extend(find_forks(grp))
        except IllegalHistory as e:
            return {**stats, "valid?": UNKNOWN, "error": e.info}
        if forks:
            return {**stats, "valid?": False, "forks": forks}
        return {**stats, "valid?": True}

    @property
    def checker_name(self):
        return "long-fork"


def workload(n: int = 2) -> Dict:
    """{checker, generator} (long_fork.clj:301-307)."""
    return {"checker": LongForkChecker(n), "generator": generator(n)}
