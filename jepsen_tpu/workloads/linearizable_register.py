"""Linearizable register workload (reference:
jepsen/src/jepsen/tests/linearizable_register.clj).

Per-key cas-register test: reads, writes, and CAS ops over independent
keys, checked with per-key linearizability. Knossos-era tractability
caps: 20 ops per key, 20 processes per key by default
(linearizable_register.clj:30-32,45-53) — the TPU engine raises the
practical ceiling far beyond that, but the caps remain configurable."""

from __future__ import annotations

import itertools
from typing import Dict, Optional

from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker import linearizable
from jepsen_tpu.checker.core import compose
from jepsen_tpu.checker.suite import stats
from jepsen_tpu.models import CASRegister


def r(_test=None, _ctx=None):
    return {"f": "read", "value": None}


def w(_test=None, _ctx=None):
    return {"f": "write", "value": gen.rand.randrange(5)}


def cas(_test=None, _ctx=None):
    return {"f": "cas",
            "value": [gen.rand.randrange(5), gen.rand.randrange(5)]}


def workload(opts: Optional[Dict] = None) -> Dict:
    """{generator, checker, model} (linearizable_register.clj:22-53).
    opts: concurrency-per-key (n), ops-per-key, process-limit,
    algorithm."""
    o = opts or {}
    per_key = o.get("ops-per-key", 20)
    n = o.get("concurrency-per-key", 2)
    process_limit = o.get("process-limit", 20)
    algorithm = o.get("algorithm", "competition")

    def fgen(k):
        g = gen.mix([r, w, cas])
        g = gen.limit(per_key, g)
        g = gen.process_limit(process_limit, g)
        return g

    keys = itertools.count()
    return {
        "generator": independent.concurrent_generator(n, keys, fgen),
        "checker": compose({
            "linear": independent.checker(
                linearizable(CASRegister(), algorithm=algorithm)),
            "stats": stats(),
        }),
        "model": CASRegister(),
    }
