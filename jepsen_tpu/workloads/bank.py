"""Bank workload: transfers between accounts conserve the total balance
(reference: jepsen/src/jepsen/tests/bank.clj).

Reads return a map account -> balance; every ok read must cover exactly
the known accounts, contain no nil balances, sum to :total-amount, and
(unless negative-balances?) stay non-negative (bank.clj:57-85). The
checker classifies errors by type with first/worst/last exemplars
(bank.clj:87-121). Balance totals are summed with numpy across all reads
at once rather than op-at-a-time."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker, compose
from jepsen_tpu.history import Op

DEFAULTS = {
    "max-transfer": 5,
    "total-amount": 100,
    "accounts": list(range(8)),
}


def read(_test=None, _ctx=None):
    return {"f": "read"}


def transfer(test, _ctx=None):
    accounts = (test or {}).get("accounts", DEFAULTS["accounts"])
    max_transfer = (test or {}).get("max-transfer", DEFAULTS["max-transfer"])
    return {"f": "transfer",
            "value": {"from": accounts[gen.rand.randrange(len(accounts))],
                      "to": accounts[gen.rand.randrange(len(accounts))],
                      "amount": 1 + gen.rand.randrange(max_transfer)}}


def diff_transfer():
    """Transfers only between distinct accounts (bank.clj:35-39)."""
    return gen.filter(
        lambda op: op["value"]["from"] != op["value"]["to"], transfer)


def generator():
    return gen.mix([diff_transfer(), read])


def err_badness(test, err: dict) -> float:
    """Bigger numbers = more egregious (bank.clj:46-54)."""
    t = err["type"]
    if t == "unexpected-key":
        return len(err["unexpected"])
    if t == "nil-balance":
        return len(err["nils"])
    if t == "wrong-total":
        total_amount = test.get("total-amount", DEFAULTS["total-amount"])
        return abs((err["total"] - total_amount) / total_amount)
    if t == "negative-value":
        return -sum(err["negative"])
    return 0.0


def check_op(accts: set, total, negative_balances: bool, op: Op):
    """Errors in a single read's balances (bank.clj:57-85)."""
    value = op.get("value") or {}
    ks = list(value.keys())
    balances = list(value.values())
    if not all(k in accts for k in ks):
        return {"type": "unexpected-key",
                "unexpected": [k for k in ks if k not in accts],
                "op": dict(op)}
    if any(b is None for b in balances):
        return {"type": "nil-balance",
                "nils": {k: v for k, v in value.items() if v is None},
                "op": dict(op)}
    if sum(balances) != total:
        return {"type": "wrong-total", "total": sum(balances),
                "op": dict(op)}
    if not negative_balances and any(b < 0 for b in balances):
        return {"type": "negative-value",
                "negative": [b for b in balances if b < 0],
                "op": dict(op)}
    return None


class BankChecker(Checker):
    """All ok reads sum to :total-amount (bank.clj:87-121)."""

    def __init__(self, opts: Optional[Dict] = None):
        self.opts = opts or {}

    def check(self, test, history, opts=None):
        accts = set(test.get("accounts", DEFAULTS["accounts"]))
        total = test.get("total-amount", DEFAULTS["total-amount"])
        negative_ok = bool(self.opts.get("negative-balances?"))
        reads = [o for o in history if o.is_ok and o.get("f") == "read"]

        # fast path: when every read covers exactly the account set with
        # numeric balances, the totals check is one vectorized sum
        errors: Dict[str, list] = {}
        candidates = reads
        if reads and all(
                isinstance(o.get("value"), dict)
                and set(o["value"]) == accts
                and all(isinstance(v, (int, float))
                        for v in o["value"].values())
                for o in reads):
            mat = np.array([[o["value"][k] for k in sorted(accts, key=repr)]
                            for o in reads])
            sums = mat.sum(axis=1)
            bad = sums != total
            if not negative_ok:
                bad = bad | (mat < 0).any(axis=1)
            candidates = [o for o, b in zip(reads, bad) if b]

        for o in candidates:
            err = check_op(accts, total, negative_ok, o)
            if err is not None:
                errors.setdefault(err["type"], []).append(err)

        first_error = None
        firsts = [v[0] for v in errors.values()]
        if firsts:
            first_error = min(
                firsts, key=lambda e: e["op"].get("index", 0))

        def summarize(t, errs):
            out = {"count": len(errs), "first": errs[0],
                   "worst": max(errs, key=lambda e: err_badness(test, e)),
                   "last": errs[-1]}
            if t == "wrong-total":
                out["lowest"] = min(errs, key=lambda e: e["total"])
                out["highest"] = max(errs, key=lambda e: e["total"])
            return out

        return {
            "valid?": not errors,
            "read-count": len(reads),
            "error-count": sum(len(v) for v in errors.values()),
            "first-error": first_error,
            "errors": {t: summarize(t, errs) for t, errs in errors.items()},
        }

    @property
    def checker_name(self):
        return "bank"


class BalancePlotter(Checker):
    """Per-node [time, total] balance series (bank.clj:139-177); the
    rendered plot arrives via jepsen_tpu.checker.perf once the test map
    carries a store."""

    def check(self, test, history, opts=None):
        reads = [o for o in history
                 if o.is_ok and o.get("f") == "read"
                 and isinstance(o.get("value"), dict)]
        if not reads:
            return {"valid?": True}
        nodes = test.get("nodes") or ["local"]
        series: Dict[str, list] = {}
        for o in reads:
            p = o.get("process")
            node = nodes[p % len(nodes)] if isinstance(p, int) else str(p)
            total = sum(v for v in o["value"].values() if v is not None)
            series.setdefault(node, []).append(
                [o.get("time", 0) / 1e9, total])
        try:
            from jepsen_tpu.checker import perf
            perf.points_plot(test, opts or {}, "bank.svg",
                             series, ylabel="Total of all accounts")
        except Exception:  # noqa: BLE001 - plotting must never fail a test
            pass
        return {"valid?": True, "series": series}

    @property
    def checker_name(self):
        return "plot"


def workload(opts: Optional[Dict] = None) -> Dict:
    """Partial test map with defaults (bank.clj:179-192)."""
    o = opts or {}
    return {
        **DEFAULTS,
        "checker": compose({"SI": BankChecker(o), "plot": BalancePlotter()}),
        "generator": generator(),
    }
