"""Node-side network helpers (reference: jepsen/src/jepsen/control/net.clj).

All functions assume an ambient control session (c.on_host)."""

from __future__ import annotations

import functools
import re

from jepsen_tpu import control as c


def reachable(node: str) -> bool:
    """Can the current node ping the given node? (control/net.clj:8-12)."""
    try:
        c.exec_("ping", "-w", 1, node)
        return True
    except Exception:  # noqa: BLE001
        return False


def local_ip() -> str:
    """The current node's IP address (control/net.clj:14-17)."""
    return c.exec_("hostname", "-I").split()[0]


def ip_uncached(host: str) -> str:
    """Resolve a hostname to an IP via getent (control/net.clj:19-35)."""
    res = c.exec_("getent", "ahosts", host)
    first_line = res.splitlines()[0] if res else ""
    addr = first_line.split()[0] if first_line.split() else ""
    if not addr:
        raise RuntimeError(f"blank getent ip for {host!r}: {res!r}")
    return addr


@functools.lru_cache(maxsize=None)
def ip(host: str) -> str:
    """Memoized hostname -> IP (control/net.clj:37-39)."""
    return ip_uncached(host)


def control_ip() -> str:
    """The control node's IP as seen from the current DB node, via the
    $SSH_CLIENT env var of the session (control/net.clj:41-52)."""
    with c._Binding(sudo=None):  # escape sudo: env doesn't cross subshells
        out = c.exec_("bash", "-c", "echo $SSH_CLIENT")
    m = re.match(r"^(.+?)\s", out + " ")
    if not m or not m.group(1):
        raise RuntimeError(f"can't find control ip in SSH_CLIENT {out!r}")
    return m.group(1)
