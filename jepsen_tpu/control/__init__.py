"""Remote control: drive commands on cluster nodes
(reference: jepsen/src/jepsen/control.clj).

The `Remote` protocol (control.clj:19-36) has five operations:
connect / disconnect / execute / upload / download. Transports:

    SshRemote     OpenSSH subprocess (the reference uses clj-ssh/JSch,
                  control.clj:330-357); gated on an `ssh` binary
    DockerRemote  docker exec / docker cp (control/docker.clj:75-90)
    K8sRemote     kubectl exec / cp (control/k8s.clj:79-111)
    LocalRemote   run on this host via subprocess — the single-machine
                  harness used by tests and the in-memory cluster
    DummyRemote   no-ops that log (control.clj:346-355, `--no-ssh`)

Ambient state rides a thread-local `Scope` (the reference's dynamic
vars *host*/*session*/*sudo*/*dir*, control.clj:38-50), so client code
reads as:

    with c.on_host(session, "n1"):
        c.exec("grep", "-q", "foo", "/etc/hosts")

Command construction mirrors the escaping DSL (control.clj:82-125):
arguments are escaped unless wrapped in `lit`; `exec` joins them into
one shell line, applies sudo/cd wrappers, runs, and raises
`RemoteError` on nonzero exit with captured out/err.
"""

from __future__ import annotations

import os
import shlex
import shutil
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from jepsen_tpu.util import real_pmap


class Lit:
    """A literal string, passed to the shell unescaped
    (control.clj:96-100)."""

    def __init__(self, s: str):
        self.s = s

    def __str__(self):
        return self.s


def lit(s: str) -> Lit:
    return Lit(s)


def escape(x) -> str:
    """Escape one argument (control.clj:102-125): Lit passes through;
    everything else is stringified and shell-quoted if needed."""
    if isinstance(x, Lit):
        return x.s
    if isinstance(x, (list, tuple)):
        return " ".join(escape(e) for e in x)
    s = str(x)
    if s == "":
        return "''"
    if all(c.isalnum() or c in "-_./=:,@+%^" for c in s):
        return s
    return shlex.quote(s)


def wrap_sudo(cmd: str, sudo: Optional[str]) -> str:
    """Wrap a command in sudo -u (control.clj:127-137)."""
    if not sudo:
        return cmd
    return f"sudo -S -u {escape(sudo)} bash -c {shlex.quote(cmd)}"


def wrap_cd(cmd: str, dir_: Optional[str]) -> str:
    if not dir_:
        return cmd
    return f"cd {escape(dir_)} && {cmd}"


class RemoteError(RuntimeError):
    def __init__(self, cmd, exit_code, out, err, host=None):
        self.cmd = cmd
        self.exit = exit_code
        self.out = out
        self.err = err
        self.host = host
        super().__init__(
            f"command failed on {host!r} (exit {exit_code}): {cmd}\n"
            f"stdout: {out}\nstderr: {err}")


@dataclass
class Result:
    cmd: str
    exit: int
    out: str
    err: str

    def throw_on_nonzero(self, host=None) -> "Result":
        if self.exit != 0:
            raise RemoteError(self.cmd, self.exit, self.out, self.err, host)
        return self


class Remote:
    """Transport protocol (control.clj:19-36)."""

    def connect(self, conn_spec: dict) -> "Remote":
        """Return a connected remote for the given spec
        ({host, port, username, ...})."""
        return self

    def disconnect(self) -> None:
        pass

    def execute(self, ctx: dict, cmd: str) -> Result:
        """Run one shell line; ctx may carry {sudo, dir}."""
        raise NotImplementedError

    def upload(self, local_paths, remote_path) -> None:
        raise NotImplementedError

    def download(self, remote_paths, local_path) -> None:
        raise NotImplementedError


# ------------------------------------------------------------- scoping


class Scope(threading.local):
    """The ambient control state (control.clj:38-50)."""

    def __init__(self):
        self.host: Optional[str] = None
        self.session: Optional[Remote] = None
        self.sudo: Optional[str] = None
        self.dir: Optional[str] = None
        self.trace: bool = False
        self.retries: int = 3


scope = Scope()


class _Binding:
    def __init__(self, **kw):
        self.kw = kw
        self.saved: dict = {}

    def __enter__(self):
        for k, v in self.kw.items():
            self.saved[k] = getattr(scope, k)
            setattr(scope, k, v)
        return scope

    def __exit__(self, *exc):
        for k, v in self.saved.items():
            setattr(scope, k, v)
        return False


def on_host(session: Remote, host: str):
    """Bind the ambient session/host (the reference's `on`/`with-session`)."""
    return _Binding(session=session, host=host)


def su(user: str = "root"):
    return _Binding(sudo=user)


def cd(dir_: str):
    return _Binding(dir=dir_)


def trace_on():
    return _Binding(trace=True)


def exec_(*args) -> str:
    """Run a command on the current session; returns trimmed stdout;
    raises RemoteError on nonzero exit (control.clj:196-215)."""
    assert scope.session is not None, "no session bound; use on_host(...)"
    cmd = " ".join(escape(a) for a in args)
    if scope.trace:
        print(f"[control] {scope.host}: {cmd}")
    ctx = {"sudo": scope.sudo, "dir": scope.dir}
    res = scope.session.execute(ctx, cmd)
    res.throw_on_nonzero(scope.host)
    return res.out.strip()


# Alias matching the reference's c/exec
exec = exec_  # noqa: A001


def upload(local_paths, remote_path):
    assert scope.session is not None
    return scope.session.upload(local_paths, remote_path)


def download(remote_paths, local_path):
    assert scope.session is not None
    return scope.session.download(remote_paths, local_path)


# ------------------------------------------------------------ remotes


def _run_local(argv_or_str, shell=False, stdin=None, timeout=600,
               env=None) -> Result:
    if env is not None:
        env = {**os.environ, **env}
    p = subprocess.run(
        argv_or_str, shell=shell, input=stdin, capture_output=True,
        text=True, timeout=timeout, env=env)
    cmd = argv_or_str if isinstance(argv_or_str, str) else " ".join(argv_or_str)
    return Result(cmd, p.returncode, p.stdout, p.stderr)


class LocalRemote(Remote):
    """Runs commands on this machine — the single-host harness. sudo/cd
    wrappers apply exactly as on a real node."""

    def __init__(self, host="localhost"):
        self.host = host

    def connect(self, conn_spec):
        return LocalRemote(conn_spec.get("host", "localhost"))

    def execute(self, ctx, cmd):
        full = wrap_cd(cmd, ctx.get("dir"))
        # sudo only if requested AND we aren't already that user
        sudo = ctx.get("sudo")
        if sudo and sudo != _current_user():
            full = wrap_sudo(full, sudo)
        return _run_local(["bash", "-c", full])

    def upload(self, local_paths, remote_path):
        for p in _coll(local_paths):
            shutil.copy(p, remote_path)

    def download(self, remote_paths, local_path):
        for p in _coll(remote_paths):
            dst = (os.path.join(local_path, os.path.basename(p))
                   if os.path.isdir(local_path) else local_path)
            shutil.copy(p, dst)


def _current_user() -> str:
    try:
        import getpass
        return getpass.getuser()
    except Exception:  # noqa: BLE001
        return ""


class DummyRemote(Remote):
    """Does nothing, records commands — the reference's
    {:dummy? true} / --no-ssh remote (control.clj:346-355). Lets the
    full test lifecycle run with no cluster."""

    def __init__(self):
        self.log: List[str] = []

    def connect(self, conn_spec):
        return self

    def execute(self, ctx, cmd):
        self.log.append(cmd)
        return Result(cmd, 0, "", "")

    def upload(self, local_paths, remote_path):
        self.log.append(f"upload {local_paths} -> {remote_path}")

    def download(self, remote_paths, local_path):
        self.log.append(f"download {remote_paths} -> {local_path}")


class SshRemote(Remote):
    """OpenSSH subprocess transport with retry on transient failures
    (control.clj:173-194,314-357). Requires `ssh`/`scp` binaries."""

    TRANSIENT = ("Connection reset", "Connection refused",
                 "Broken pipe", "timed out")

    def __init__(self, conn_spec: Optional[dict] = None):
        self.spec = conn_spec or {}

    def connect(self, conn_spec):
        if shutil.which("ssh") is None:
            raise RuntimeError("no `ssh` binary on PATH")
        return SshRemote(conn_spec)

    def _base(self, prog="ssh") -> List[str]:
        s = self.spec
        argv = [prog, "-o", "StrictHostKeyChecking=no",
                "-o", "UserKnownHostsFile=/dev/null", "-o", "LogLevel=ERROR"]
        if self._env() is not None:
            # password auth rides sshpass -e (password via SSHPASS env,
            # never on the argv where `ps` would expose it); key auth
            # never falls back to the password. Without sshpass,
            # BatchMode below fails fast instead of hanging on a prompt.
            argv = ["sshpass", "-e", *argv]
        else:
            argv += ["-o", "BatchMode=yes"]
        if s.get("port"):
            argv += (["-P", str(s["port"])] if prog == "scp"
                     else ["-p", str(s["port"])])
        if s.get("private-key-path"):
            argv += ["-i", s["private-key-path"]]
        return argv

    def _env(self):
        s = self.spec
        if (s.get("password") and not s.get("private-key-path")
                and shutil.which("sshpass")):
            return {"SSHPASS": s["password"]}
        return None

    def _dest(self) -> str:
        s = self.spec
        user = s.get("username", "root")
        return f"{user}@{s['host']}"

    def execute(self, ctx, cmd):
        full = wrap_sudo(wrap_cd(cmd, ctx.get("dir")), ctx.get("sudo"))
        last = None
        for attempt in range(3):
            res = _run_local(self._base() + [self._dest(), full],
                             env=self._env())
            last = res
            if res.exit == 255 and any(t in res.err for t in self.TRANSIENT):
                time.sleep(0.5 * (attempt + 1))
                continue
            return res
        return last

    def upload(self, local_paths, remote_path):
        argv = self._base("scp") + [*_coll(local_paths),
                                    f"{self._dest()}:{remote_path}"]
        _run_local(argv, env=self._env()).throw_on_nonzero(
            self.spec.get("host"))

    def download(self, remote_paths, local_path):
        argv = self._base("scp") + [f"{self._dest()}:{p}"
                                    for p in _coll(remote_paths)] + [local_path]
        _run_local(argv, env=self._env()).throw_on_nonzero(
            self.spec.get("host"))


class DockerRemote(Remote):
    """docker exec / docker cp (control/docker.clj:75-90)."""

    def __init__(self, container: Optional[str] = None):
        self.container = container

    def connect(self, conn_spec):
        if shutil.which("docker") is None:
            raise RuntimeError("no `docker` binary on PATH")
        return DockerRemote(conn_spec["host"])

    def execute(self, ctx, cmd):
        full = wrap_sudo(wrap_cd(cmd, ctx.get("dir")), ctx.get("sudo"))
        return _run_local(["docker", "exec", self.container,
                           "bash", "-c", full])

    def upload(self, local_paths, remote_path):
        for p in _coll(local_paths):
            _run_local(["docker", "cp", p,
                        f"{self.container}:{remote_path}"]
                       ).throw_on_nonzero(self.container)

    def download(self, remote_paths, local_path):
        for p in _coll(remote_paths):
            _run_local(["docker", "cp", f"{self.container}:{p}",
                        local_path]).throw_on_nonzero(self.container)


class K8sRemote(Remote):
    """kubectl exec / cp (control/k8s.clj:79-111)."""

    def __init__(self, pod: Optional[str] = None, namespace: str = "default",
                 container: Optional[str] = None):
        self.pod = pod
        self.namespace = namespace
        self.container = container

    def connect(self, conn_spec):
        if shutil.which("kubectl") is None:
            raise RuntimeError("no `kubectl` binary on PATH")
        return K8sRemote(conn_spec["host"],
                         conn_spec.get("namespace", "default"),
                         conn_spec.get("container"))

    def _kargs(self) -> List[str]:
        out = ["-n", self.namespace]
        if self.container:
            out += ["-c", self.container]
        return out

    def execute(self, ctx, cmd):
        full = wrap_sudo(wrap_cd(cmd, ctx.get("dir")), ctx.get("sudo"))
        return _run_local(["kubectl", "exec", *self._kargs(), self.pod,
                           "--", "bash", "-c", full])

    def upload(self, local_paths, remote_path):
        for p in _coll(local_paths):
            _run_local(["kubectl", "cp", *self._kargs()[:2], p,
                        f"{self.namespace}/{self.pod}:{remote_path}"]
                       ).throw_on_nonzero(self.pod)

    def download(self, remote_paths, local_path):
        for p in _coll(remote_paths):
            _run_local(["kubectl", "cp", *self._kargs()[:2],
                        f"{self.namespace}/{self.pod}:{p}", local_path]
                       ).throw_on_nonzero(self.pod)


# -------------------------------------------------- sessions & fan-out


def remote_for_test(test: dict) -> Remote:
    """Pick the transport from the test map: an explicit :remote, else
    dummy when ssh:{dummy: true} (cli.clj:76-77), else SSH."""
    if test.get("remote") is not None:
        return test["remote"]
    ssh = test.get("ssh") or {}
    if ssh.get("dummy"):
        return DummyRemote()
    return SshRemote()


def session(test: dict, node: str) -> Remote:
    base = remote_for_test(test)
    spec = dict(test.get("ssh") or {})
    spec["host"] = node
    return base.connect(spec)


class Sessions:
    """One connected session per node, opened in parallel
    (core.clj:349-359 with-ssh)."""

    def __init__(self, test: dict):
        self.test = test
        self.sessions: Dict[str, Remote] = {}

    def __enter__(self):
        nodes = self.test.get("nodes") or []
        opened = real_pmap(lambda n: (n, session(self.test, n)), nodes)
        self.sessions = dict(opened)
        self.test["sessions"] = self.sessions
        return self

    def __exit__(self, *exc):
        for s in self.sessions.values():
            try:
                s.disconnect()
            except Exception:  # noqa: BLE001
                pass
        self.sessions = {}
        self.test.pop("sessions", None)
        return False

    def on(self, node: str, args: Sequence) -> str:
        """Run one escaped command on one node (used by nemeses)."""
        with on_host(self.sessions[node], node):
            return exec_(*args)


def with_sessions(test: dict) -> Sessions:
    return Sessions(test)


def on_nodes(test: dict, f, nodes: Optional[Sequence] = None) -> Dict:
    """Evaluate (f test node) in parallel on each node with the node's
    session bound; returns {node: result} (control.clj:419-447)."""
    nodes = list(nodes if nodes is not None else test.get("nodes") or [])
    sessions = test.get("sessions") or {}

    def run(node):
        s = sessions.get(node)
        if s is None:
            s = session(test, node)
        with on_host(s, node):
            return node, f(test, node)

    return dict(real_pmap(run, nodes))


def _coll(x) -> List:
    return list(x) if isinstance(x, (list, tuple)) else [x]
