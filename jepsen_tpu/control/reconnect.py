"""Auto-reconnecting client wrappers (reference: jepsen/src/jepsen/reconnect.clj).

A Wrapper owns a connection plus open/close functions. `with_conn`
hands the current connection to a body under a read lock — many threads
may use the connection concurrently — while open/close/reopen take the
write lock. When a body raises, the wrapper reopens the connection
(only if it is still the same one that failed — another thread may have
already replaced it, reconnect.clj:104-116) and re-raises the original
error."""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Callable, Optional

log = logging.getLogger(__name__)


class RWLock:
    """Writer-preferring read/write lock (the ReentrantReadWriteLock of
    reconnect.clj:30, minus reentrancy, which the wrapper doesn't use)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    @contextlib.contextmanager
    def read(self):
        self.acquire_read()
        try:
            yield
        finally:
            self.release_read()

    @contextlib.contextmanager
    def write(self):
        self.acquire_write()
        try:
            yield
        finally:
            self.release_write()


class Wrapper:
    """(reconnect.clj:16-31). open: () -> conn; close: (conn) -> None."""

    def __init__(self, open: Callable, close: Callable,  # noqa: A002
                 name: Optional[str] = None, log_: bool = False):
        assert callable(open) and callable(close)
        self._open = open
        self._close = close
        self.name = name
        self.log = log_
        self.lock = RWLock()
        self._conn = None

    def conn(self):
        """Active connection, if any (reconnect.clj:49-52)."""
        return self._conn

    def open(self) -> "Wrapper":
        """Opens a connection; no-op if already open (reconnect.clj:54-66)."""
        with self.lock.write():
            if self._conn is None:
                c = self._open()
                if c is None:
                    raise RuntimeError(
                        f"Reconnect wrapper {self.name!r}'s open function "
                        f"returned None instead of a connection!")
                self._conn = c
        return self

    def close(self) -> "Wrapper":
        """(reconnect.clj:68-75)."""
        with self.lock.write():
            if self._conn is not None:
                self._close(self._conn)
                self._conn = None
        return self

    def reopen(self) -> "Wrapper":
        """Closes (tolerating a dead connection) and opens fresh
        (reconnect.clj:77-90)."""
        with self.lock.write():
            self.reopen_locked()
        return self

    @contextlib.contextmanager
    def with_conn(self):
        """Yields the current connection under the read lock; on any
        exception, reopens (if this conn is still current) and
        re-raises the *original* error (reconnect.clj:92-129)."""
        self.lock.acquire_read()
        c = self._conn
        try:
            yield c
        except Exception as e:
            self.lock.release_read()
            try:
                with self.lock.write():
                    if c is self._conn:
                        if self.log:
                            log.warning(
                                "Encountered error with conn %r; "
                                "reopening: %r", self.name, e)
                        try:
                            self.reopen_locked()
                        except Exception as e2:  # noqa: BLE001
                            if self.log:
                                log.warning("Error reopening %r: %r",
                                            self.name, e2)
            finally:
                self.lock.acquire_read()
            raise
        finally:
            self.lock.release_read()

    def reopen_locked(self):
        """reopen body for callers already holding the write lock."""
        if self._conn is not None:
            try:
                self._close(self._conn)
            except Exception:  # noqa: BLE001 - old conn may be dead
                pass
            self._conn = None
        c = self._open()
        if c is None:
            raise RuntimeError(
                f"Reconnect wrapper {self.name!r}'s open function "
                f"returned None instead of a connection!")
        self._conn = c


def wrapper(open: Callable, close: Callable,  # noqa: A002
            name: Optional[str] = None, log_: bool = False) -> Wrapper:
    return Wrapper(open, close, name=name, log_=log_)
