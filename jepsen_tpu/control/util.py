"""Node-management utilities (reference: jepsen/src/jepsen/control/util.clj):
file tests, archive installs with cached downloads, daemon lifecycle via
pidfiles, port waits, and grepkill. All run through the ambient control
session (jepsen_tpu.control)."""

from __future__ import annotations

import time
from typing import Optional, Sequence

from jepsen_tpu import control as c
from jepsen_tpu.control import RemoteError, lit

WGET_CACHE_DIR = "/tmp/jepsen/wget-cache"  # control/util.clj cache dir


def file_exists(path: str) -> bool:
    """(control/util.clj:13-20 exists?)"""
    try:
        c.exec_("stat", path)
        return True
    except RemoteError:
        return False


def ls(path: str = ".") -> list:
    try:
        return c.exec_("ls", "-1", path).splitlines()
    except RemoteError:
        return []


def ls_full(path: str) -> list:
    p = path if path.endswith("/") else path + "/"
    return [p + f for f in ls(p)]


def tmp_file(ext: str = "") -> str:
    return c.exec_("mktemp", f"--suffix={ext}")


def tmp_dir() -> str:
    return c.exec_("mktemp", "-d")


def wget(url: str, force: bool = False, cache: bool = True) -> str:
    """Download url to the current dir; with cache, keep a shared copy
    under WGET_CACHE_DIR keyed by url (control/util.clj:106-180)."""
    filename = url.rstrip("/").split("/")[-1]
    if cache:
        key = url.replace("/", "_")
        cached = f"{WGET_CACHE_DIR}/{key}"
        if force or not file_exists(cached):
            c.exec_("mkdir", "-p", WGET_CACHE_DIR)
            # Download to a temp name and mv into place atomically: a
            # failed `wget -O cached` leaves a partial/empty file that
            # would poison every future cached install.
            try:
                c.exec_("wget", "-O", cached + ".part", url)
            except RemoteError:
                c.exec_("rm", "-f", cached + ".part")
                raise
            c.exec_("mv", cached + ".part", cached)
        c.exec_("cp", cached, filename)
    else:
        if force:
            c.exec_("rm", "-f", filename)
        if not file_exists(filename):
            c.exec_("wget", url)
    return filename


def install_archive(url: str, dest: str, force: bool = False,
                    user: Optional[str] = None) -> str:
    """Download (or file:// copy) a tarball/zip and extract it to dest,
    flattening a single top-level directory (control/util.clj:182-247)."""
    c.exec_("rm", "-rf", dest) if force else None
    if file_exists(dest) and not force:
        return dest
    c.exec_("mkdir", "-p", dest)
    tmp = tmp_dir()
    try:
        if url.startswith("file://"):
            archive = url[len("file://"):]
        else:
            with c.cd(tmp):
                archive = tmp + "/" + wget(url)
        with c.cd(tmp):
            if archive.endswith(".zip"):
                c.exec_("unzip", "-o", archive, "-d", tmp)
            else:
                c.exec_("tar", "--no-same-owner", "--no-same-permissions",
                        "--extract", "--file", archive, "--directory", tmp,
                        "--exclude", archive.split("/")[-1])
            entries = [e for e in ls(tmp)
                       if tmp + "/" + e != archive
                       and e != archive.split("/")[-1]]
            if len(entries) == 1 and _is_dir(tmp + "/" + entries[0]):
                src = tmp + "/" + entries[0]
                c.exec_("sh", "-c",
                        lit(f"mv {c.escape(src)}/* {c.escape(dest)}/"))
            else:
                for e in entries:
                    c.exec_("mv", tmp + "/" + e, dest + "/")
        if user:
            c.exec_("chown", "-R", user, dest)
        return dest
    finally:
        c.exec_("rm", "-rf", tmp)


def _is_dir(path: str) -> bool:
    try:
        c.exec_("test", "-d", path)
        return True
    except RemoteError:
        return False


# ------------------------------------------------------------- daemons


def start_daemon(opts: dict, bin_: str, *args) -> bool:
    """Start bin as a daemon with a pidfile; returns False when already
    running (control/util.clj:282-328 start-daemon!). opts:
    {chdir, logfile, pidfile, env}."""
    pidfile = opts["pidfile"]
    logfile = opts.get("logfile", "/dev/null")
    chdir = opts.get("chdir", "/")
    if daemon_running(pidfile):
        return False
    env = " ".join(f"{k}={c.escape(v)}" for k, v in
                   (opts.get("env") or {}).items())
    argv = " ".join(c.escape(a) for a in args)
    # The background job must be a SIMPLE command (`nohup ... &`), not an
    # `&&` chain: bash backgrounds a whole chain in a subshell that keeps
    # the caller's stdout pipe open until the daemon exits, hanging any
    # transport that waits for EOF. `cd` runs as its own statement.
    cmd = (f"cd {c.escape(chdir)}; "
           f"{env + ' ' if env else ''}nohup {c.escape(bin_)} {argv} "
           f"< /dev/null >> {c.escape(logfile)} 2>&1 "
           f"& echo $! > {c.escape(pidfile)}")
    c.exec_("bash", "-c", lit(c.escape(cmd)))
    return True


def daemon_running(pidfile: str) -> bool:
    """Is the pidfile's process alive? (control/util.clj:330-339)"""
    try:
        pid = c.exec_("cat", pidfile)
    except RemoteError:
        return False
    if not pid.strip():
        return False
    try:
        c.exec_("ps", "-p", pid.strip())
        return True
    except RemoteError:
        return False


def stop_daemon(pidfile: str, signal: str = "TERM", timeout_s: float = 10):
    """Kill the pidfile's process and remove the pidfile
    (control/util.clj:341-348)."""
    try:
        pid = c.exec_("cat", pidfile).strip()
    except RemoteError:
        return
    if pid:
        try:
            c.exec_("kill", f"-{signal}", pid)
        except RemoteError:
            pass
        deadline = time.time() + timeout_s
        while time.time() < deadline and daemon_running(pidfile):
            time.sleep(0.1)
        if daemon_running(pidfile):
            try:
                c.exec_("kill", "-KILL", pid)
            except RemoteError:
                pass
    c.exec_("rm", "-f", pidfile)


def grepkill(pattern: str, signal: str = "KILL"):
    """Kill processes matching pattern (control/util.clj:258-280)."""
    try:
        c.exec_("pkill", f"-{signal}", "-f", pattern)
    except RemoteError as e:
        if e.exit != 1:  # 1 = no processes matched
            raise


def await_tcp_port(port: int, host: str = "localhost",
                   timeout_s: float = 60, interval_s: float = 0.5):
    """Block until the port accepts connections
    (control/util.clj:350-361)."""
    deadline = time.time() + timeout_s
    while True:
        try:
            c.exec_("bash", "-c",
                    lit(c.escape(f"exec 3<>/dev/tcp/{host}/{port}")))
            return
        except RemoteError:
            if time.time() > deadline:
                raise TimeoutError(f"port {host}:{port} never opened")
            time.sleep(interval_s)
