"""Helpers for exploring stored test runs interactively.

The reference ships `jepsen.repl` (jepsen/src/jepsen/repl.clj:1-14)
with a single `last-test` convenience for "mucking around with tests";
this is its analogue over our store layout, returning the loaded run
map (test map + history + results) rather than a lazy deref.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from jepsen_tpu import store as store_mod


def last_test(test_name: Optional[str] = None,
              base_dir: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """The most recently run test as a loaded run map
    (jepsen.repl/last-test, repl.clj:6-13). With `test_name`, the
    newest run of that test; otherwise the newest run of any test.
    Returns None when nothing has been stored yet."""
    if base_dir is None:
        # resolve at call time: store.BASE_DIR is runtime-configurable
        base_dir = store_mod.BASE_DIR
    run_dir = store_mod.latest(base_dir, test_name=test_name)
    return store_mod.load_run(run_dir) if run_dir else None
