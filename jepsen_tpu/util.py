"""Kitchen-sink utilities (reference: jepsen/src/jepsen/util.clj, 886 LoC).

Host-side analogues of the reference helpers the rest of the framework
leans on: parallel map with meaningful-exception selection, quorum math,
relative-time clock, retry/timeout control flow, latency pairing, nemesis
interval extraction, fixed points, and integer interval-set printing.
"""

from __future__ import annotations

import math
import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Optional, Sequence


# ----------------------------------------------------------- quorum math
def majority(n: int) -> int:
    """Smallest majority of n nodes (util.clj:80-84). majority(5) = 3."""
    return n // 2 + 1


def minority(n: int) -> int:
    return (n - 1) // 2


def minority_third(n: int) -> int:
    """Largest f such that 3f < n — BFT-style fault bound (util.clj:86-89)."""
    return max(0, int(math.ceil(n / 3)) - 1)


def random_nonempty_subset(coll: Sequence) -> list:
    """A random non-empty subset of coll (util.clj analogue used by the
    clock/combined nemeses, nemesis/time.clj:149-152). Uses the generator
    RNG so fixed_rand makes nemesis schedules deterministic."""
    from jepsen_tpu import generator as _gen  # lazy: util is a leaf module
    xs = list(coll)
    if not xs:
        return []
    k = _gen.rand.randint(1, len(xs))
    _gen.rand.shuffle(xs)
    return xs[:k]


# ------------------------------------------------------- parallel helpers
def real_pmap(f: Callable, coll: Sequence) -> list:
    """Thread-per-element map; raises the most *meaningful* exception if
    several fail (util.clj:61-73 — prefers a real error over e.g. the
    BrokenBarrier noise its siblings produce when one thread dies)."""
    coll = list(coll)
    if not coll:
        return []
    results: list = [None] * len(coll)
    errors: list = []

    def run(i, x):
        try:
            results[i] = f(x)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=run, args=(i, x), daemon=True)
               for i, x in enumerate(coll)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise _meaningful_exception(errors)
    return results


def _meaningful_exception(errors: list) -> BaseException:
    """Prefer non-interrupt-ish exceptions (util.clj:48-59 semantics)."""
    boring = (InterruptedError, BrokenPipeError, TimeoutError)
    for e in errors:
        if not isinstance(e, boring):
            return e
    return errors[0]


def bounded_pmap(f: Callable, coll: Iterable, bound: Optional[int] = None) -> list:
    """Parallel map with at most `bound` concurrent workers
    (util.clj bounded-pmap; used by jepsen.independent/checker,
    independent.clj:282-304)."""
    import os
    coll = list(coll)
    bound = bound or (os.cpu_count() or 4) + 2
    if not coll:
        return []
    with ThreadPoolExecutor(max_workers=min(bound, len(coll))) as pool:
        return list(pool.map(f, coll))


# -------------------------------------------------------------- time
_NANOS = 1_000_000_000

_local_clock_origin = None
_origin_lock = threading.Lock()


def relative_time_nanos() -> int:
    """Nanoseconds since the first call in this process — every op's :time
    is relative to test start (util.clj:324-342)."""
    global _local_clock_origin
    now = _time.monotonic_ns()
    if _local_clock_origin is None:
        with _origin_lock:
            if _local_clock_origin is None:
                _local_clock_origin = now
    return now - _local_clock_origin


def reset_relative_time():
    global _local_clock_origin
    with _origin_lock:
        _local_clock_origin = _time.monotonic_ns()


def nanos_to_secs(ns: float) -> float:
    return ns / _NANOS


def secs_to_nanos(s: float) -> int:
    return int(s * _NANOS)


def ms_to_nanos(ms: float) -> int:
    return int(ms * 1_000_000)


# ----------------------------------------------------------- control flow
class RetryFailed(Exception):
    pass


def with_retry(f: Callable[[], Any], retries: int = 3,
               backoff: float = 0.0,
               exceptions: tuple = (Exception,)) -> Any:
    """Retry f up to `retries` extra times (util.clj with-retry macro)."""
    attempt = 0
    while True:
        try:
            return f()
        except exceptions:
            attempt += 1
            if attempt > retries:
                raise
            if backoff:
                _time.sleep(backoff)


def timeout(seconds: float, timeout_val: Any, f: Callable[[], Any]) -> Any:
    """Run f with a deadline; return timeout_val if it doesn't finish
    (util.clj:365-380 `timeout` macro). The worker thread is abandoned on
    timeout (daemon), matching the reference's thread-interrupt best-effort."""
    result: list = []
    error: list = []

    def run():
        try:
            result.append(f())
        except BaseException as e:  # noqa: BLE001
            error.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(seconds)
    if t.is_alive():
        return timeout_val
    if error:
        raise error[0]
    return result[0]


def fixed_point(f: Callable[[Any], Any], x: Any, max_iters: int = 10_000) -> Any:
    """Iterate f until it stops changing (util.clj:880-886)."""
    for _ in range(max_iters):
        x2 = f(x)
        if x2 == x:
            return x
        x = x2
    raise RuntimeError("fixed_point: did not converge")


# -------------------------------------------------- history-derived stats
def history_to_latencies(history) -> list:
    """Attach :latency (completion time - invoke time, nanos) to each
    invocation; returns [(invoke_op, completion_op, latency_ns)]
    (util.clj:653-687)."""
    out = []
    open_by_process: dict = {}
    for o in history:
        p = o.get("process")
        if o.get("type") == "invoke":
            open_by_process[p] = o
        else:
            inv = open_by_process.pop(p, None)
            if inv is not None and inv.get("time") is not None and o.get("time") is not None:
                lat = o["time"] - inv["time"]
                inv["latency"] = lat
                out.append((inv, o, lat))
    return out


def nemesis_intervals(history, fs_start=("start",), fs_stop=("stop",)) -> list:
    """[(start_op, stop_op_or_None)] intervals of nemesis activity
    (util.clj:689-734). Pairs each nemesis start with the next stop."""
    out = []
    opened = []
    for o in history:
        if o.get("process") != "nemesis" or o.get("type") == "invoke":
            continue
        if o.get("f") in fs_start:
            opened.append(o)
        elif o.get("f") in fs_stop:
            while opened:
                out.append((opened.pop(0), o))
    for o in opened:
        out.append((o, None))
    return out


# --------------------------------------------------- interval set printing
def integer_interval_set_str(xs: Iterable[int]) -> str:
    """Compact print of an int set: #{1..3 5 7..9} (util.clj:582-607)."""
    xs = sorted(set(xs))
    if not xs:
        return "#{}"
    runs = []
    lo = hi = xs[0]
    for x in xs[1:]:
        if x == hi + 1:
            hi = x
        else:
            runs.append((lo, hi))
            lo = hi = x
    runs.append((lo, hi))
    parts = [str(lo) if lo == hi else f"{lo}..{hi}" for lo, hi in runs]
    return "#{" + " ".join(parts) + "}"


# ------------------------------------------------------------ misc
def coll(x) -> list:
    """Ensure a list (util.clj coll)."""
    if x is None:
        return []
    if isinstance(x, (list, tuple, set, frozenset)):
        return list(x)
    return [x]


def name_of(x) -> str:
    """Keyword-ish name of a value."""
    if hasattr(x, "name"):
        return x.name
    return str(x)


class LazyAtom:
    """Thread-safe lazily-initialised mutable box (util.clj:761-795)."""

    def __init__(self, init: Callable[[], Any]):
        self._init = init
        self._lock = threading.Lock()
        self._set = False
        self._value = None

    def deref(self):
        if not self._set:
            with self._lock:
                if not self._set:
                    self._value = self._init()
                    self._set = True
        return self._value

    def swap(self, f, *args):
        with self._lock:
            self.deref()
            self._value = f(self._value, *args)
            return self._value
