"""`jepsen-tpu lint` — tracing-safety & concurrency static analysis.

An AST-based linter enforcing the purity contract the paper's layering
implies (host canonicalisation vs. device frontier expansion) plus the
concurrency and env-flag hygiene the round-5 hardware window showed we
need enforced mechanically, the way Jepsen itself enforces history
invariants. Three rule families:

  purity       host effects / numpy / tracer branches inside traced code
  recompile    jit-cache defeats and undecided buffer donation
  concurrency  unlocked cross-thread writes; JEPSEN_TPU_* env reads
               outside the validated accessor (jepsen_tpu.envflags)

Pure `ast` work: no JAX import, no device init — safe and fast on
CPU-only CI even with a wedged PJRT runtime. Entry points:

    python -m jepsen_tpu.analysis --check      # CI gate, exit 0/1
    jepsen lint [paths...] [--json]            # CLI subcommand
    run_lint(paths=None, root=None)            # library API

Suppressions: `# jepsen-lint: disable=<rule>[,<rule>]` on the line (or
anywhere in the enclosing statement, or on the enclosing `def` line to
cover the body), `disable-file=<rule>` for a whole file, and
`# jepsen-lint: device` to mark a traced root the call graph cannot
see. Bare or unknown-rule suppressions are themselves findings. See
docs/linting.md.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from jepsen_tpu.analysis import concurrency, purity, recompile
from jepsen_tpu.analysis.core import (  # noqa: F401  (public API)
    RULES, Finding, SourceFile, default_targets, expand_targets,
)
from jepsen_tpu.analysis.report import (  # noqa: F401
    format_json, format_text, save_to_store, summarize,
)

_FAMILIES = (purity.check, recompile.check, concurrency.check)


def repo_root() -> str:
    """The repo checkout this package lives in."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def lint_file(path: str, root: Optional[str] = None) -> List[Finding]:
    """All findings for one file (suppressed ones included, marked)."""
    root = root or repo_root()
    sf = SourceFile(path, root)
    findings: List[Finding] = []
    for fam in _FAMILIES:
        findings.extend(fam(sf))
    findings = sf.apply_suppressions(findings)
    for line, msg in sf.suppressions.bad:
        findings.append(Finding("bad-suppression", sf.relpath, line, 0,
                                msg))
    # deterministic order regardless of reachability-set iteration
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def run_lint(paths: Optional[Sequence[str]] = None,
             root: Optional[str] = None,
             rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint `paths` (default: the repo's production tree — jepsen_tpu/,
    tools/, bench.py, __graft_entry__.py). `rules` filters to a subset
    of rule names."""
    root = root or repo_root()
    files = (expand_targets(paths, root) if paths
             else default_targets(root))
    findings: List[Finding] = []
    for path in files:
        findings.extend(lint_file(path, root))
    if rules:
        findings = [f for f in findings if f.rule in set(rules)]
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI body shared by `python -m jepsen_tpu.analysis` and the
    `jepsen lint` subcommand. Exit contract: 0 clean, 1 findings,
    2 usage error."""
    import argparse

    p = argparse.ArgumentParser(
        prog="jepsen-tpu lint",
        description="tracing-safety & concurrency static analysis")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the repo tree)")
    p.add_argument("--check", action="store_true",
                   help="CI gate mode: print active findings only")
    p.add_argument("--json", action="store_true",
                   help="emit the JSON report")
    p.add_argument("--rules", help="comma-separated rule subset")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--show-suppressed", action="store_true",
                   help="include suppressed findings in text output")
    p.add_argument("--save-store", action="store_true",
                   help="persist lint.json/lint.txt into a store/ run "
                        "dir (store.Store('lint'))")
    try:
        args = p.parse_args(list(argv) if argv is not None else None)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 2

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}: {desc}")
        return 0

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        findings = run_lint(args.paths or None, rules=rules)
    except (OSError, SyntaxError, ValueError) as e:
        # a missing/unreadable/undecodable/unparseable target is a
        # USAGE error (2), not "findings found" (1) — CI must not
        # misread a typo'd path as a lint verdict. ValueError covers
        # UnicodeDecodeError (non-UTF8 bytes) and ast's NUL-byte
        # rejection.
        import sys
        print(f"lint: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(format_json(findings))
    else:
        print(format_text(findings,
                          show_suppressed=args.show_suppressed
                          and not args.check))
    if args.save_store:
        import sys

        from jepsen_tpu import store as jstore
        d = save_to_store(findings, jstore.Store("lint"))
        # stderr: stdout is the (documented machine-parseable) report
        print(f"report saved under {d}", file=sys.stderr)
    return 0 if all(f.suppressed for f in findings) else 1
