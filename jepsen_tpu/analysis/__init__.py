"""`jepsen-tpu lint` — tracing-safety & concurrency static analysis.

An AST-based linter enforcing the purity contract the paper's layering
implies (host canonicalisation vs. device frontier expansion) plus the
concurrency and env-flag hygiene the round-5 hardware window showed we
need enforced mechanically, the way Jepsen itself enforces history
invariants. Three rule families:

  purity       host effects / numpy / tracer branches inside traced code
  recompile    jit-cache defeats and undecided buffer donation
  concurrency  unlocked cross-thread writes; lock-discipline pass
               (lock-order cycles, blocking ops under a held lock,
               guarded-field inference — see analysis/locks.py);
               JEPSEN_TPU_* env reads outside the validated accessor
               (jepsen_tpu.envflags)

plus repo-sweep-only gates: stale-suppression detection (a disable
comment whose rule no longer fires is itself a finding), the
cross-module lock-order pairs (service<->wal, fleet<->breaker), and
the doc-drift gates (envflags registry vs docs flag rows; minted obs
metric names vs docs/observability.md — see analysis/drift.py).

Pure `ast` work: no JAX import, no device init — safe and fast on
CPU-only CI even with a wedged PJRT runtime. Entry points:

    python -m jepsen_tpu.analysis --check      # CI gate, exit 0/1
    python -m jepsen_tpu.analysis --changed    # pre-commit fast mode
    jepsen lint [paths...] [--json]            # CLI subcommand
    run_lint(paths=None, root=None)            # library API

Suppressions: `# jepsen-lint: disable=<rule>[,<rule>]` on the line (or
anywhere in the enclosing statement, or on the enclosing `def` line to
cover the body), `disable-file=<rule>` for a whole file, and
`# jepsen-lint: device` to mark a traced root the call graph cannot
see. Bare or unknown-rule suppressions are themselves findings. See
docs/linting.md.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from jepsen_tpu.analysis import concurrency, purity, recompile
from jepsen_tpu.analysis.core import (  # noqa: F401  (public API)
    DEFAULT_DIRS, DEFAULT_TOP_FILES, RULES, Finding, SourceFile,
    default_targets, expand_targets,
)
from jepsen_tpu.analysis.report import (  # noqa: F401
    format_json, format_text, save_to_store, summarize,
)

_FAMILIES = (purity.check, recompile.check, concurrency.check)


def repo_root() -> str:
    """The repo checkout this package lives in."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def lint_file(path: str, root: Optional[str] = None) -> List[Finding]:
    """All findings for one file (suppressed ones included, marked)."""
    root = root or repo_root()
    sf = SourceFile(path, root)
    findings: List[Finding] = []
    for fam in _FAMILIES:
        findings.extend(fam(sf))
    findings = sf.apply_suppressions(findings)
    for line, msg in sf.suppressions.bad:
        findings.append(Finding("bad-suppression", sf.relpath, line, 0,
                                msg))
    # a directive that suppressed nothing is itself a finding — and
    # deliberately not suppressible: the inventory only ever shrinks
    findings.extend(sf.stale_suppression_findings())
    # deterministic order regardless of reachability-set iteration
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def run_lint(paths: Optional[Sequence[str]] = None,
             root: Optional[str] = None,
             rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint `paths` (default: the repo's production tree — jepsen_tpu/,
    tools/, bench.py, __graft_entry__.py). `rules` filters to a subset
    of rule names."""
    root = root or repo_root()
    files = (expand_targets(paths, root) if paths
             else default_targets(root))
    findings: List[Finding] = []
    for path in files:
        findings.extend(lint_file(path, root))
    if paths is None:
        # repo-sweep-only gates: cross-module lock-order pairs and
        # the doc-drift checks (an explicit-path lint of one file must
        # not fail on an unrelated doc)
        findings.extend(_pair_sweep(root, files))
        from jepsen_tpu.analysis import drift
        findings.extend(drift.check_repo(root, files))
    if rules:
        findings = [f for f in findings if f.rule in set(rules)]
    return findings


def _pair_sweep(root: str, files: Sequence[str]) -> List[Finding]:
    """Cross-module lock-order cycles over the known pairs."""
    from jepsen_tpu.analysis import locks
    present = {os.path.relpath(f, root).replace(os.sep, "/"): f
               for f in files}
    out: List[Finding] = []
    for rel_a, rel_b, hint_b, hint_a in locks.CROSS_MODULE_PAIRS:
        if rel_a not in present or rel_b not in present:
            continue
        sf_a = SourceFile(present[rel_a], root)
        sf_b = SourceFile(present[rel_b], root)
        for f in locks.pair_findings(sf_a, sf_b, hint_b, hint_a):
            sf = sf_a if f.path == sf_a.relpath else sf_b
            out.extend(sf.apply_suppressions([f]))
    return out


def changed_files(base: str = "HEAD",
                  root: Optional[str] = None) -> List[str]:
    """Lintable .py files changed vs `base` (plus untracked ones),
    restricted to the default sweep's tree — the `--changed` fast
    mode's work list. Raises on git failure (caller maps to exit 2)."""
    import subprocess
    root = root or repo_root()

    def git(*argv2: str) -> List[str]:
        res = subprocess.run(["git", *argv2], cwd=root,
                             capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(argv2)} failed: "
                f"{res.stderr.strip() or res.returncode}")
        return [ln.strip() for ln in res.stdout.splitlines()
                if ln.strip()]

    rels = set(git("diff", "--name-only", base))
    rels |= set(git("ls-files", "--others", "--exclude-standard"))
    out: List[str] = []
    for rel in sorted(rels):
        if not rel.endswith(".py"):
            continue
        top = rel.replace("\\", "/").split("/", 1)[0]
        if not (top in DEFAULT_DIRS or rel in DEFAULT_TOP_FILES):
            continue
        path = os.path.join(root, rel)
        if os.path.isfile(path):        # deleted files drop out
            out.append(path)
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI body shared by `python -m jepsen_tpu.analysis` and the
    `jepsen lint` subcommand. Exit contract: 0 clean, 1 findings,
    2 usage error."""
    import argparse

    p = argparse.ArgumentParser(
        prog="jepsen-tpu lint",
        description="tracing-safety & concurrency static analysis")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the repo tree)")
    p.add_argument("--check", action="store_true",
                   help="CI gate mode: print active findings only")
    p.add_argument("--changed", nargs="?", const="HEAD", metavar="BASE",
                   help="fast mode: lint only files changed vs BASE "
                        "(git diff --name-only, default HEAD) plus "
                        "untracked ones — the sub-second pre-commit "
                        "loop; the full sweep stays the CI gate")
    p.add_argument("--json", action="store_true",
                   help="emit the JSON report")
    p.add_argument("--rules", help="comma-separated rule subset")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--show-suppressed", action="store_true",
                   help="include suppressed findings in text output")
    p.add_argument("--save-store", action="store_true",
                   help="persist lint.json/lint.txt into a store/ run "
                        "dir (store.Store('lint'))")
    try:
        args = p.parse_args(list(argv) if argv is not None else None)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 2

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}: {desc}")
        return 0

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    lint_paths: Optional[Sequence[str]] = args.paths or None
    if args.changed is not None:
        import sys
        if args.paths:
            print("lint: --changed and explicit paths are mutually "
                  "exclusive", file=sys.stderr)
            return 2
        try:
            lint_paths = changed_files(args.changed)
        except Exception as e:
            print(f"lint: --changed: {e}", file=sys.stderr)
            return 2
        if not lint_paths:
            print("lint: no changed python files", file=sys.stderr)
            return 0
    try:
        findings = run_lint(lint_paths, rules=rules)
    except (OSError, SyntaxError, ValueError) as e:
        # a missing/unreadable/undecodable/unparseable target is a
        # USAGE error (2), not "findings found" (1) — CI must not
        # misread a typo'd path as a lint verdict. ValueError covers
        # UnicodeDecodeError (non-UTF8 bytes) and ast's NUL-byte
        # rejection.
        import sys
        print(f"lint: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(format_json(findings))
    else:
        print(format_text(findings,
                          show_suppressed=args.show_suppressed
                          and not args.check))
    if args.save_store:
        import sys

        from jepsen_tpu import store as jstore
        d = save_to_store(findings, jstore.Store("lint"))
        # stderr: stdout is the (documented machine-parseable) report
        print(f"report saved under {d}", file=sys.stderr)
    return 0 if all(f.suppressed for f in findings) else 1
