"""Report formatting for lint runs: human text, JSON, and store wiring.

The JSON shape is a stable contract (tests and CI parse it):

    {"findings": [{rule, path, line, col, message, suppressed}, ...],
     "counts": {"total": N, "suppressed": M, "active": N - M},
     "by_rule": {rule: active_count, ...},
     "clean": bool}

`save_to_store` drops lint.json + lint.txt into a jepsen store run
directory (store.Store), so a lint pass rides the same artifact
lifecycle as histories and checker results.
"""

from __future__ import annotations

import json
from typing import Dict, List

from jepsen_tpu.analysis.core import Finding


def summarize(findings: List[Finding]) -> Dict:
    active = [f for f in findings if not f.suppressed]
    by_rule: Dict[str, int] = {}
    for f in active:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        "findings": [f.to_dict() for f in findings],
        "counts": {"total": len(findings),
                   "suppressed": len(findings) - len(active),
                   "active": len(active)},
        "by_rule": dict(sorted(by_rule.items())),
        "clean": not active,
    }


def format_json(findings: List[Finding]) -> str:
    return json.dumps(summarize(findings), indent=2)


def format_text(findings: List[Finding], show_suppressed: bool = False) -> str:
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    lines = [f.format() for f in
             sorted(shown, key=lambda f: (f.path, f.line, f.col))]
    s = summarize(findings)
    c = s["counts"]
    lines.append(f"{c['active']} finding(s) "
                 f"({c['suppressed']} suppressed, "
                 f"{c['total']} total)")
    if s["by_rule"]:
        lines.append("by rule: " + ", ".join(
            f"{r}={n}" for r, n in s["by_rule"].items()))
    return "\n".join(lines)


def save_to_store(findings: List[Finding], store) -> str:
    """Write lint.json + lint.txt into a store.Store run dir; returns
    the run directory."""
    store.write_file(["lint.json"], format_json(findings) + "\n")
    store.write_file(["lint.txt"],
                     format_text(findings, show_suppressed=True) + "\n")
    return store.dir
