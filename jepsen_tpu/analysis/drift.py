"""Doc-drift gates: env-flag and metric-name cross-checks.

Docs rot silently: a flag lands in envflags.py and never reaches the
docs table, a metric is renamed and the observability page keeps the
old spelling. Both gates are pure text/AST work over the live tree —
no imports of the checked modules — and run only on the DEFAULT
repo-wide sweep (``run_lint(paths=None)``); explicit-path invocations
stay a plain lint so `jepsen lint some_file.py` never fails on an
unrelated doc.

hygiene-flag-doc-drift
    The envflags.py registration table (the ``JEPSEN_TPU_<NAME>
    env_<kind> <module>`` comment rows) is cross-checked against every
    ``JEPSEN_TPU_*`` mention in docs/performance.md, observability.md,
    streaming.md, and resilience.md — both directions. A registered
    flag no doc mentions anchors at its registry row; a documented
    flag the registry does not know anchors at the doc line.

hygiene-metric-doc-drift
    Metric names are collected statically: every
    ``counter/gauge/histogram("dotted.name")`` call resolving to the
    obs registry (f-strings become wildcard patterns; a
    ``labeled("base", ...)`` argument contributes its base name).
    The docs side parses the "Naming scheme" table rows of
    docs/observability.md whose kind column says counter/gauge/
    histogram, expanding the table's shorthands: leading-dot rows
    (`.key` continues the previous name's prefix), ``{a,b,c}``
    alternation, and ``<placeholder>`` wildcards. A minted name no doc
    row matches anchors at the mint; a documented row no mint matches
    anchors at the doc line.

Drift findings are deliberately NOT suppressible: the acceptance
contract is that drift gets FIXED in the same change, not waved off.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from jepsen_tpu.analysis.core import Finding, SourceFile

ENVFLAGS_REL = "jepsen_tpu/envflags.py"
FLAG_DOC_RELS = ("docs/performance.md", "docs/observability.md",
                 "docs/streaming.md", "docs/resilience.md")
OBS_DOC_REL = "docs/observability.md"

# a registry row: "#   JEPSEN_TPU_FOO  env_int  module — description"
_REGISTRY_ROW = re.compile(
    r"^#\s{1,3}(JEPSEN_TPU_[A-Z0-9_]+)\s+(env_\w+)")
_FLAG_MENTION = re.compile(r"JEPSEN_TPU_[A-Z0-9_]+")

_MINT_LEAVES = {"counter", "gauge", "histogram"}

# wildcard sentinel inside collected/expanded names (never a valid
# metric character)
WILD = "\x00"


def _read_lines(root: str, rel: str) -> List[str]:
    with open(os.path.join(root, rel), encoding="utf-8") as fh:
        return fh.read().splitlines()


# ------------------------------------------------------------- flags

def registered_flags(root: str,
                     envflags_rel: str = ENVFLAGS_REL
                     ) -> Dict[str, int]:
    """Flag name -> registry-table line number."""
    out: Dict[str, int] = {}
    for i, line in enumerate(_read_lines(root, envflags_rel), 1):
        m = _REGISTRY_ROW.match(line)
        if m:
            out.setdefault(m.group(1), i)
    return out


def documented_flags(root: str,
                     doc_rels: Sequence[str] = FLAG_DOC_RELS
                     ) -> Dict[str, Tuple[str, int]]:
    """Flag name -> first (doc relpath, line) mentioning it."""
    out: Dict[str, Tuple[str, int]] = {}
    for rel in doc_rels:
        if not os.path.isfile(os.path.join(root, rel)):
            continue
        for i, line in enumerate(_read_lines(root, rel), 1):
            for m in _FLAG_MENTION.finditer(line):
                out.setdefault(m.group(0), (rel, i))
    return out


def flag_findings(root: str,
                  envflags_rel: str = ENVFLAGS_REL,
                  doc_rels: Sequence[str] = FLAG_DOC_RELS
                  ) -> List[Finding]:
    reg = registered_flags(root, envflags_rel)
    doc = documented_flags(root, doc_rels)
    findings: List[Finding] = []
    for name in sorted(set(reg) - set(doc)):
        findings.append(Finding(
            "hygiene-flag-doc-drift", envflags_rel, reg[name], 0,
            f"`{name}` is registered here but documented in none of "
            f"{', '.join(doc_rels)} — add its doc row"))
    for name in sorted(set(doc) - set(reg)):
        rel, line = doc[name]
        findings.append(Finding(
            "hygiene-flag-doc-drift", rel, line, 0,
            f"`{name}` is documented here but not registered in "
            f"{envflags_rel} — fix the doc (or register the flag)"))
    return findings


# ------------------------------------------------------------ metrics

def _mint_name(sf: SourceFile, call: ast.Call) -> Optional[str]:
    """The (possibly wildcarded) metric name a mint call emits, or
    None if the call is not a registry mint / the name is dynamic."""
    dotted = sf.dotted(call.func) or ""
    leaf = dotted.split(".")[-1]
    if leaf not in _MINT_LEAVES:
        return None
    prefix = dotted[: -len(leaf)].rstrip(".")
    base = prefix.split(".")[-1]
    if not ("obs" in prefix or "metrics" in prefix
            or base in ("reg", "registry")):
        return None     # some other counter()-shaped callable
    if not call.args:
        return None
    return _name_expr(sf, call.args[0])


def _name_expr(sf: SourceFile, node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append(WILD)
        return "".join(parts)
    if isinstance(node, ast.Call):
        # labeled("base", k=v) emits under `base[...]` — the base name
        # is what the docs table documents
        dotted = sf.dotted(node.func) or ""
        if dotted.split(".")[-1] == "labeled" and node.args:
            return _name_expr(sf, node.args[0])
    return None


def minted_metrics(root: str, files: Sequence[str]
                   ) -> Dict[str, Tuple[str, int]]:
    """Metric name/pattern -> first (relpath, line) minting it."""
    out: Dict[str, Tuple[str, int]] = {}
    for path in files:
        if not path.endswith(".py"):
            continue
        sf = SourceFile(path, root)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _mint_name(sf, node)
            if name:
                out.setdefault(name, (sf.relpath, node.lineno))
    return out


_ROW = re.compile(r"^\s*\|(?P<name>[^|]*)\|(?P<kind>[^|]*)\|")
_TICKED = re.compile(r"`([^`]+)`")
_KINDED = re.compile(r"\b(counter|gauge|histogram)\b")
_BRACES = re.compile(r"\{([^{}]*)\}")


def _expand(fragment: str, prev_full: Optional[str]) -> List[str]:
    """One backticked doc fragment -> concrete name patterns.
    Handles `.suffix` shorthand (continue the previous name's prefix),
    `{a,b,c}` alternation, `<placeholder>` wildcards, and `name[...]`
    label rows (the base name is what gets minted)."""
    name = fragment.strip()
    if not name or " " in name:
        return []
    name = name.split("[", 1)[0]            # label row -> base name
    if name.startswith("."):
        if prev_full is None:
            return []
        name = prev_full.rsplit(".", 1)[0] + name
    name = re.sub(r"<[^<>]*>", WILD, name)
    out = [name]
    while True:
        expanded: List[str] = []
        changed = False
        for n in out:
            m = _BRACES.search(n)
            if m is None:
                expanded.append(n)
                continue
            changed = True
            for alt in m.group(1).split(","):
                expanded.append(n[:m.start()] + alt.strip()
                                + n[m.end():])
        out = expanded
        if not changed:
            return [n for n in out if n.strip(".")]


def documented_metrics(root: str, doc_rel: str = OBS_DOC_REL
                       ) -> Dict[str, int]:
    """Documented metric name/pattern -> doc line. Only the "Naming
    scheme" section's counter/gauge/histogram rows count; span rows
    are tracing, not metrics, and other tables (the stats-field
    glossary) merely talk ABOUT counters."""
    out: Dict[str, int] = {}
    prev_full: Optional[str] = None
    in_section = False
    for i, line in enumerate(_read_lines(root, doc_rel), 1):
        if line.startswith("## "):
            in_section = line.lower().startswith("## naming scheme")
            continue
        if not in_section:
            continue
        m = _ROW.match(line)
        if m is None:
            continue
        fragments = _TICKED.findall(m.group("name"))
        is_metric = bool(_KINDED.search(m.group("kind")))
        for frag in fragments:
            for name in _expand(frag, prev_full):
                if not name.startswith(WILD):
                    prev_full = name
                if is_metric:
                    out.setdefault(name, i)
    return out


def _pat(name: str) -> "re.Pattern[str]":
    return re.compile(
        ".+".join(re.escape(p) for p in name.split(WILD)) + "$")


def names_match(a: str, b: str) -> bool:
    """Wildcard-tolerant equality: `a` covers `b` or `b` covers `a`
    (either side may carry WILD segments)."""
    return bool(_pat(a).match(b.replace(WILD, "x"))
                or _pat(b).match(a.replace(WILD, "x")))


def metric_findings(root: str, files: Sequence[str],
                    doc_rel: str = OBS_DOC_REL) -> List[Finding]:
    if not os.path.isfile(os.path.join(root, doc_rel)):
        return []
    minted = minted_metrics(root, files)
    documented = documented_metrics(root, doc_rel)
    findings: List[Finding] = []
    for name in sorted(minted):
        if any(names_match(name, d) for d in documented):
            continue
        rel, line = minted[name]
        shown = name.replace(WILD, "<...>")
        findings.append(Finding(
            "hygiene-metric-doc-drift", rel, line, 0,
            f"metric `{shown}` is minted here but has no row in the "
            f"{doc_rel} naming-scheme table — document it"))
    for name in sorted(documented):
        if any(names_match(name, m) for m in minted):
            continue
        shown = name.replace(WILD, "<...>")
        findings.append(Finding(
            "hygiene-metric-doc-drift", doc_rel, documented[name], 0,
            f"metric `{shown}` is documented here but never minted "
            f"anywhere in the tree — fix the doc (or emit it)"))
    return findings


def check_repo(root: str, files: Sequence[str]) -> List[Finding]:
    """Both drift gates over the default sweep's file list."""
    return flag_findings(root) + metric_findings(root, files)
