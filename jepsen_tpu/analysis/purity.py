"""Device-purity rules: no host effects inside traced code.

The paper's layering puts canonicalisation on the host and frontier
expansion on the device; the boundary is `jax.jit` (and its relatives).
Anything that crosses it — wall clocks, RNGs, env vars, file IO, locks,
raw numpy — either runs once at trace time (a silent wrong-answer
hazard: the value is frozen into the compiled program) or breaks the
trace outright. These rules walk every function reachable from a
jit/vmap/pmap/shard_map/pallas/lax-control-flow entry point (plus
`# jepsen-lint: device` pragma'd dispatch-table steps) and flag:

  purity-host-call     time/random/os/threading/subprocess/socket use,
                       open()/input()/print()
  purity-numpy-call    np.* calls (legal on trace-time constants only —
                       suppress with the rule name where that is the
                       intent, e.g. static index-table construction)
  purity-tracer-branch Python `if`/`while`/bool()/int()/float() on a
                       jnp/lax expression — host sync or tracer error
  purity-obs-in-trace  obs.span()/timer()/metrics-registry use — the
                       telemetry side effect fires ONCE at trace time
                       (the span records the trace, the counter bumps
                       once), then never again for any execution of
                       the compiled program: a silently lying metric.
                       Instrument the host seam around the jit instead.
"""

from __future__ import annotations

import ast
from typing import List

from jepsen_tpu.analysis import core
from jepsen_tpu.analysis.core import Finding, SourceFile

# modules whose mere use inside a trace is a host effect
_BANNED_MODULES = {
    "time": "wall-clock/sleep",
    "random": "host RNG (use jax.random with an explicit key)",
    "os": "process state (env vars, fds)",
    "threading": "locks/threads",
    "subprocess": "process spawning",
    "socket": "network IO",
    "shutil": "file IO",
    "pathlib": "file IO",
}
_NUMPY_MODULES = {"numpy", "numpy.random"}
_BANNED_BUILTINS = {"open": "file IO", "input": "stdin",
                    "print": "host stdout (use jax.debug.print)"}
_JNP_MODULES = {"jax.numpy", "jax.lax", "jax.nn"}

# the telemetry package (jepsen_tpu.obs): spans and registry metrics
# are host-side effects — inside a trace they fire at trace time only.
# Matched by resolved module prefix, so `from jepsen_tpu import obs`,
# `import jepsen_tpu.obs as obs`, and `from jepsen_tpu.obs import
# span` all flag.
_OBS_PREFIX = "jepsen_tpu.obs"


def _base_module(dotted: str) -> str:
    return dotted.split(".")[0]


def _is_jnp_expr(sf: SourceFile, node: ast.AST) -> bool:
    """The expression contains a call/attribute rooted at jnp/lax."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            dotted = sf.dotted(sub)
            if dotted and (dotted.rsplit(".", 1)[0] in _JNP_MODULES
                           or _base_module(dotted) in ("jax",)
                           and ".numpy." in f".{dotted}."):
                return True
            if dotted and dotted.startswith(("jax.numpy.", "jax.lax.")):
                return True
    return False


def check(sf: SourceFile) -> List[Finding]:
    roots = core.trace_roots(sf)
    if not roots:
        return []
    reachable = core.reach(sf, roots)
    findings: List[Finding] = []
    seen_lines = set()

    def emit(rule: str, node: ast.AST, msg: str):
        # one finding per source position: `os.environ.get` must not
        # double-report as both `os.environ` and `os.environ.get`
        key = (rule, node.lineno, getattr(node, "col_offset", 0))
        if key in seen_lines:
            return
        seen_lines.add(key)
        findings.append(sf.finding(rule, node, msg))

    for fi in reachable:
        fname = fi.name
        for node in core.walk_own(fi.node):
            # host-module attribute use (call or bare reference)
            if isinstance(node, ast.Attribute):
                dotted = sf.dotted(node)
                if not dotted:
                    continue
                base = _base_module(dotted)
                full_mod = dotted.rsplit(".", 1)[0]
                if base in _BANNED_MODULES and full_mod != "jax":
                    emit("purity-host-call", node,
                         f"`{dotted}` ({_BANNED_MODULES[base]}) inside "
                         f"traced function `{fname}` — move it to the "
                         f"host side of the jit boundary")
                elif base in _NUMPY_MODULES or full_mod in _NUMPY_MODULES:
                    emit("purity-numpy-call", node,
                         f"`{dotted}` inside traced function `{fname}` "
                         f"— numpy only sees trace-time constants here; "
                         f"use jnp for anything derived from inputs")
                elif dotted == _OBS_PREFIX \
                        or dotted.startswith(_OBS_PREFIX + "."):
                    emit("purity-obs-in-trace", node,
                         f"`{dotted}` inside traced function `{fname}` "
                         f"— spans/metrics fire at trace time, not run "
                         f"time; instrument the host seam around the "
                         f"jit instead")
            # banned builtins
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in _BANNED_BUILTINS \
                    and node.func.id not in fi.locals:
                emit("purity-host-call", node,
                     f"`{node.func.id}()` "
                     f"({_BANNED_BUILTINS[node.func.id]}) inside traced "
                     f"function `{fname}`")
            # obs primitives imported bare (`from jepsen_tpu.obs
            # import span`): the Attribute branch can't see these —
            # resolve the call name through the import aliases
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id not in fi.locals \
                    and (sf.dotted(node.func) or "").startswith(
                        _OBS_PREFIX + "."):
                emit("purity-obs-in-trace", node,
                     f"`{node.func.id}()` "
                     f"(= {sf.dotted(node.func)}) inside traced "
                     f"function `{fname}` — spans/metrics fire at "
                     f"trace time, not run time")
            # Python-level branch on a traced value
            elif isinstance(node, (ast.If, ast.While)):
                if _is_jnp_expr(sf, node.test):
                    emit("purity-tracer-branch", node,
                         f"Python `{'if' if isinstance(node, ast.If) else 'while'}` "
                         f"on a jnp/lax expression inside traced "
                         f"function `{fname}` — use lax.cond/"
                         f"lax.while_loop or jnp.where")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in ("bool", "int", "float") \
                    and node.args and _is_jnp_expr(sf, node.args[0]):
                emit("purity-tracer-branch", node,
                     f"`{node.func.id}()` cast of a jnp/lax expression "
                     f"inside traced function `{fname}` — forces a "
                     f"host sync (concretization error under jit)")
    return findings
