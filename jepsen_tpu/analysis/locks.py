"""Lock-discipline rules: deadlock order, blocking-under-lock, and
guarded-field races over the serve/fleet/resilience threading stack.

The review-hardening logs of PRs 8, 11, 12, and 13 caught the same
defect classes by hand every round: flight-dump file I/O moved outside
the serve condition (PR 8), `freeze_key` races fixed by serializing
under the condition (PR 11), fence writes ordered before copies
(PR 12), WAL-stage stamps taken under the cond (PR 13). This module
machine-checks those shapes.

The model
---------
Lock *identities* are discovered statically:

  * ``self._x = threading.Lock()/RLock()/Condition()/Semaphore()``
    inside class ``C``  ->  lock ``C._x`` (kind remembered: a
    Condition's own ``wait()`` releases it, so waiting on the
    condition you hold is NOT blocking);
  * ``name = threading.Lock()`` at module scope  ->  ``<mod>.name``;
  * ``self._locks.setdefault(k, threading.Lock())`` or
    ``self._locks[k] = threading.Lock()``  ->  the per-key lock
    *family* ``C._locks[*]`` (one identity for the whole dict — the
    per-key instances are interchangeable for ordering purposes);
  * a local bound from a family (``slock = self._stem_locks
    .setdefault(...)``) aliases to the family's identity.

The *held set* is tracked through ``with`` statements (multi-item,
left to right), explicit ``acquire()``/``release()`` pairs in straight
-line code, and ONE level of direct same-class ``self.method()``
inlining (recursion cut at depth 1 — the documented interprocedural
bound; deeper call chains need their own audit). A helper that is
self-called anywhere is judged in its callers' lock contexts, so
``_rotate_locked``-style helpers are seen under the locks their
callers actually hold.

Rules
-----
concurrency-lock-order
    Acquiring lock B while holding lock A adds edge A->B to the
    static lock-order graph. A cycle is a potential deadlock the
    moment both paths run concurrently. Checked per module, and (via
    ``pair_findings``) across the known cross-module pairs
    (service<->wal, fleet<->breaker), where a call made under a held
    lock to a partner-module method is charged with every lock that
    method acquires (receiver names are matched against the pair's
    hint regex so ``list.append`` never aliases ``DeltaWAL.append``).

concurrency-blocking-under-lock
    A blocking operation inside a held-lock region: file I/O
    (``open``/``.write``/``.flush``/``os.fsync``/``os.replace``/
    ``shutil.*``), sockets/HTTP, ``subprocess``, ``time.sleep``, a
    ``wait()`` on a condition/event you do NOT hold, a supervised
    ``dispatch(...)`` (a device program under a host lock), and
    ``obs.flight_dump`` (the PR-8 shape). Audited sites — the WAL
    fsync under the per-key handoff lock is the canonical one —
    carry a rule-named suppression WITH the reason.

concurrency-unguarded-field
    Guarded-field inference: a ``self.x`` whose (non-``__init__``)
    writes hold one specific lock at >=90% of the write sites is
    inferred guarded by it; the remaining write sites flag. A 100%-
    consistent field is silent; a field with no dominant lock is
    undecidable and also silent (the thread-root race rule still
    covers the closure/global cases).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from jepsen_tpu.analysis.core import Finding, FuncInfo, SourceFile

# threading constructors that mint a lock identity
_LOCK_CTORS = {"Lock": "lock", "RLock": "lock", "Condition": "condition",
               "Semaphore": "lock", "BoundedSemaphore": "lock"}

# os-level / shutil-level calls that hit the filesystem
_FS_CALLS = {"os.fsync", "os.replace", "os.rename", "os.remove",
             "os.unlink", "os.makedirs", "os.rmdir", "os.link",
             "os.symlink", "os.truncate"}

# dotted leaves that mean "socket / HTTP round trip"
_NET_LEAVES = {"urlopen", "create_connection", "sendall", "recv",
               "connect", "getresponse"}

_SUBPROCESS_LEAVES = {"run", "Popen", "call", "check_call",
                      "check_output"}

# attribute leaves that write a file handle
_HANDLE_WRITE_LEAVES = {"write", "flush"}

# the fraction of write sites that must hold one lock before the
# field is inferred guarded by it
GUARD_THRESHOLD = 0.9

# cross-module lock-order pairs: (file A, file B, regex a receiver in
# A must match to count as a call INTO B, and vice versa)
CROSS_MODULE_PAIRS = (
    ("jepsen_tpu/serve/service.py", "jepsen_tpu/serve/wal.py",
     r"wal", r"service|_svc"),
    ("jepsen_tpu/serve/fleet.py", "jepsen_tpu/resilience/breaker.py",
     r"breaker|_br\b", r"fleet|replica"),
)


@dataclasses.dataclass
class _Write:
    cls: str
    attr: str
    node: ast.AST
    held: frozenset
    inlined: bool          # observed via a caller's inline scan
    func: str
    rmw: bool


@dataclasses.dataclass
class _Block:
    node: ast.AST
    held: Tuple[str, ...]
    what: str
    func: str
    via: Optional[str]


@dataclasses.dataclass
class _ExtCall:
    leaf: str
    recv_src: str
    held: Tuple[str, ...]
    node: ast.AST
    func: str


class ModuleLockFacts:
    """Everything the lock pass learned about one file."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.modname = os.path.splitext(
            os.path.basename(sf.relpath))[0]
        self.class_locks: Dict[Tuple[str, str], str] = {}   # (C,attr)->kind
        self.families: Set[Tuple[str, str]] = set()
        self.module_locks: Dict[str, str] = {}
        self.acquired: Set[str] = set()     # every acquisition event
        self.edges: Dict[Tuple[str, str], ast.AST] = {}
        self.blocks: List[_Block] = []
        self.writes: List[_Write] = []
        self.ext_calls: List[_ExtCall] = []
        # method name -> union of lock ids its body acquires (the
        # summary the cross-module pass charges callers with)
        self.method_locks: Dict[str, Set[str]] = {}
        self._kinds: Dict[str, str] = {}


def _is_lock_ctor(sf: SourceFile, node: ast.AST) -> Optional[str]:
    """'threading.Lock()' (or a from-import of it) -> its kind."""
    if not isinstance(node, ast.Call):
        return None
    dotted = sf.dotted(node.func) or ""
    leaf = dotted.split(".")[-1]
    if leaf not in _LOCK_CTORS:
        return None
    if "threading" in dotted or dotted == leaf:
        return _LOCK_CTORS[leaf]
    return None


def _class_of(sf: SourceFile, node: ast.AST) -> Optional[str]:
    """Name of the innermost enclosing class, through any nesting
    (nested worker defs inside a method still see self's class)."""
    cur = sf.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        cur = sf.parents.get(cur)
    return None


def collect_facts(sf: SourceFile) -> ModuleLockFacts:
    facts = ModuleLockFacts(sf)
    kinds: Dict[str, str] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            kind = _is_lock_ctor(sf, node.value) \
                if node.value is not None else None
            ann = getattr(node, "annotation", None)
            for t in targets:
                if kind and isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    cls = _class_of(sf, node)
                    if cls:
                        facts.class_locks[(cls, t.attr)] = kind
                        kinds[f"{cls}.{t.attr}"] = kind
                elif kind and isinstance(t, ast.Name) \
                        and sf.func_of(node) is None:
                    facts.module_locks[t.id] = kind
                    kinds[f"{facts.modname}.{t.id}"] = kind
                elif kind and isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Attribute) \
                        and isinstance(t.value.value, ast.Name) \
                        and t.value.value.id == "self":
                    cls = _class_of(sf, node)
                    if cls:
                        facts.families.add((cls, t.value.attr))
                        kinds[f"{cls}.{t.value.attr}[*]"] = "lock"
                elif ann is not None and isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    # self._stem_locks: Dict[str, threading.Lock] = {}
                    try:
                        ann_src = ast.unparse(ann)
                    except Exception:  # pragma: no cover
                        ann_src = ""
                    if any(c in ann_src for c in _LOCK_CTORS):
                        cls = _class_of(sf, node)
                        if cls:
                            facts.families.add((cls, t.attr))
                            kinds[f"{cls}.{t.attr}[*]"] = "lock"
        elif isinstance(node, ast.Call):
            # self._locks.setdefault(k, threading.Lock())
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "setdefault" \
                    and len(node.args) >= 2 \
                    and _is_lock_ctor(sf, node.args[1]) \
                    and isinstance(f.value, ast.Attribute) \
                    and isinstance(f.value.value, ast.Name) \
                    and f.value.value.id == "self":
                cls = _class_of(sf, node)
                if cls:
                    facts.families.add((cls, f.value.attr))
                    kinds[f"{cls}.{f.value.attr}[*]"] = "lock"
    facts._kinds = kinds
    return facts


class _FuncScanner:
    """Held-set tracking through one function body (plus one level of
    same-class self.method() inlining)."""

    def __init__(self, facts: ModuleLockFacts,
                 methods: Dict[Tuple[str, str], FuncInfo]):
        self.facts = facts
        self.sf = facts.sf
        self.methods = methods

    # ---------------------------------------------------- identities
    def lock_id(self, expr: ast.AST, cls: Optional[str],
                aliases: Dict[str, str]) -> Optional[str]:
        facts = self.facts
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls:
            if (cls, expr.attr) in facts.class_locks:
                return f"{cls}.{expr.attr}"
            if (cls, expr.attr) in facts.families:
                return f"{cls}.{expr.attr}[*]"
        elif isinstance(expr, ast.Subscript):
            inner = self.lock_id(expr.value, cls, aliases)
            if inner and inner.endswith("[*]"):
                return inner
        elif isinstance(expr, ast.Name):
            if expr.id in aliases:
                return aliases[expr.id]
            if expr.id in facts.module_locks:
                return f"{facts.modname}.{expr.id}"
        elif isinstance(expr, ast.Call):
            # slock = self._stem_locks.setdefault(stem, Lock()) — the
            # call itself evaluates to a family member
            f = expr.func
            if isinstance(f, ast.Attribute) and f.attr in (
                    "setdefault", "get"):
                inner = self.lock_id(f.value, cls, aliases)
                if inner and inner.endswith("[*]"):
                    return inner
        return None

    def _alias_from(self, value: ast.AST, cls: Optional[str],
                    aliases: Dict[str, str]) -> Optional[str]:
        lid = self.lock_id(value, cls, aliases)
        if lid is not None:
            return lid
        return None

    # -------------------------------------------------------- driver
    def scan(self, fi: FuncInfo, held: Tuple[str, ...],
             depth: int, via: Optional[str]):
        cls = _class_of(self.sf, fi.node)
        aliases: Dict[str, str] = {}
        body = (fi.node.body if isinstance(fi.node.body, list)
                else [fi.node.body])
        self._scan_stmts(body, list(held), fi, cls, aliases, depth, via)

    def _scan_stmts(self, stmts, held: List[str], fi: FuncInfo,
                    cls: Optional[str], aliases: Dict[str, str],
                    depth: int, via: Optional[str]):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue            # runs later, not here
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = list(held)
                for item in stmt.items:
                    self._scan_expr(item.context_expr, inner, fi, cls,
                                    aliases, depth, via)
                    lid = self.lock_id(item.context_expr, cls, aliases)
                    if lid is not None:
                        self._acquire(lid, inner, item.context_expr)
                        inner = inner + [lid]
                    if item.optional_vars is not None and lid is not None \
                            and isinstance(item.optional_vars, ast.Name):
                        aliases[item.optional_vars.id] = lid
                self._scan_stmts(stmt.body, inner, fi, cls, aliases,
                                 depth, via)
                continue
            # straight-line acquire()/release() on a known lock
            if isinstance(stmt, ast.Expr) \
                    and isinstance(stmt.value, ast.Call) \
                    and isinstance(stmt.value.func, ast.Attribute):
                f = stmt.value.func
                lid = self.lock_id(f.value, cls, aliases)
                if lid is not None and f.attr == "acquire":
                    self._acquire(lid, held, stmt.value)
                    held.append(lid)
                    continue
                if lid is not None and f.attr == "release":
                    if lid in held:
                        held.remove(lid)
                    continue
            if isinstance(stmt, ast.Assign) \
                    and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                alias = self._alias_from(stmt.value, cls, aliases)
                if alias is not None:
                    aliases[stmt.targets[0].id] = alias
            # expressions anywhere in the statement
            for expr in self._stmt_exprs(stmt):
                self._scan_expr(expr, held, fi, cls, aliases, depth, via)
            # attribute writes
            wtargets: List[ast.AST] = []
            rmw = False
            if isinstance(stmt, ast.Assign):
                wtargets = stmt.targets
                rmw = self._self_referencing(stmt)
            elif isinstance(stmt, ast.AugAssign):
                wtargets = [stmt.target]
                rmw = True
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                wtargets = [stmt.target]
            for t in wtargets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" and cls \
                        and fi.name != "__init__":
                    self.facts.writes.append(_Write(
                        cls, t.attr, t, frozenset(held),
                        inlined=depth > 0, func=fi.name, rmw=rmw))
            # compound statements: recurse into their bodies with the
            # same held set (control flow does not release locks)
            for sub in self._stmt_bodies(stmt):
                self._scan_stmts(sub, held, fi, cls, aliases, depth, via)

    @staticmethod
    def _self_referencing(stmt: ast.Assign) -> bool:
        """self.x = f(self.x): a read-modify-write in assignment form."""
        reads = {(n.value.id, n.attr) for n in ast.walk(stmt.value)
                 if isinstance(n, ast.Attribute)
                 and isinstance(n.value, ast.Name)
                 and isinstance(n.ctx, ast.Load)}
        for t in stmt.targets:
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and (t.value.id, t.attr) in reads:
                return True
        return False

    @staticmethod
    def _stmt_bodies(stmt: ast.stmt) -> Iterable[list]:
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if isinstance(sub, list) and sub \
                    and isinstance(sub[0], ast.stmt):
                yield sub
        for h in getattr(stmt, "handlers", []) or []:
            yield h.body

    @staticmethod
    def _stmt_exprs(stmt: ast.stmt) -> Iterable[ast.AST]:
        """The statement's own expressions (not nested statement
        bodies, not nested defs/lambdas)."""
        nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        for field, value in ast.iter_fields(stmt):
            if field in ("body", "orelse", "finalbody", "handlers"):
                continue
            vals = value if isinstance(value, list) else [value]
            for v in vals:
                if isinstance(v, ast.expr) and not isinstance(v, nested):
                    yield v

    # ---------------------------------------------------- observers
    def _acquire(self, lid: str, held: List[str], node: ast.AST):
        self.facts.acquired.add(lid)
        for h in held:
            if h != lid and (h, lid) not in self.facts.edges:
                self.facts.edges[(h, lid)] = node

    def _scan_expr(self, expr: ast.AST, held: List[str], fi: FuncInfo,
                   cls: Optional[str], aliases: Dict[str, str],
                   depth: int, via: Optional[str]):
        nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, nested):
                continue
            stack.extend(c for c in ast.iter_child_nodes(node)
                         if not isinstance(c, nested))
            if not isinstance(node, ast.Call):
                continue
            self._check_call(node, held, fi, cls, aliases, depth, via)

    def _check_call(self, call: ast.Call, held: List[str],
                    fi: FuncInfo, cls: Optional[str],
                    aliases: Dict[str, str], depth: int,
                    via: Optional[str]):
        facts = self.facts
        dotted = self.sf.dotted(call.func) or ""
        leaf = dotted.split(".")[-1] if dotted else (
            call.func.attr if isinstance(call.func, ast.Attribute)
            else "")
        # one-level interprocedural: a direct self.method() call runs
        # the callee's body under the caller's held set (depth 1 cut)
        if isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name) \
                and call.func.value.id == "self" and cls \
                and depth == 0:
            callee = self.methods.get((cls, call.func.attr))
            if callee is not None and callee.node is not fi.node:
                self.scan(callee, tuple(held), 1,
                          via=f"{cls}.{fi.name}")
        if not held:
            return
        # a held-lock call that might enter a partner module (the
        # cross-module pass filters by receiver hint)
        if isinstance(call.func, ast.Attribute) \
                and not (isinstance(call.func.value, ast.Name)
                         and call.func.value.id == "self"):
            try:
                recv = ast.unparse(call.func.value)
            except Exception:  # pragma: no cover
                recv = ""
            facts.ext_calls.append(_ExtCall(
                call.func.attr, recv, tuple(held), call, fi.name))
        what = self._blocking_kind(call, dotted, leaf, held, cls,
                                   aliases)
        if what is not None:
            facts.blocks.append(_Block(call, tuple(held), what,
                                       fi.name, via))

    def _blocking_kind(self, call: ast.Call, dotted: str, leaf: str,
                       held: List[str], cls: Optional[str],
                       aliases: Dict[str, str]) -> Optional[str]:
        if dotted == "open":
            return "file I/O (`open`)"
        if dotted in _FS_CALLS or dotted.startswith("shutil."):
            return f"file I/O (`{dotted}`)"
        if dotted == "time.sleep":
            return "`time.sleep`"
        if dotted.startswith("subprocess.") \
                and leaf in _SUBPROCESS_LEAVES:
            return f"subprocess (`{dotted}`)"
        if leaf in _NET_LEAVES or dotted.startswith("urllib.") \
                or dotted.startswith("socket."):
            return f"network round trip (`{dotted or leaf}`)"
        if leaf == "flight_dump":
            return "`obs.flight_dump` (flight-recorder file dump)"
        if leaf == "dispatch" and not dotted.startswith("self."):
            return "supervised device dispatch"
        if isinstance(call.func, ast.Attribute) \
                and leaf in _HANDLE_WRITE_LEAVES:
            # a write/flush on something that is itself a lock is the
            # lock API, not file I/O
            if self.lock_id(call.func.value, cls, aliases) is None:
                return f"file-handle `.{leaf}()`"
            return None
        if isinstance(call.func, ast.Attribute) \
                and leaf in ("wait", "wait_for"):
            lid = self.lock_id(call.func.value, cls, aliases)
            if lid is not None and lid in held:
                return None     # waiting on the condition you hold
                                # releases it — the sanctioned idiom
            return ("a `wait()` on a condition/event you do NOT "
                    "hold (it cannot release your locks)")
        return None


def _methods_map(sf: SourceFile) -> Dict[Tuple[str, str], FuncInfo]:
    out: Dict[Tuple[str, str], FuncInfo] = {}
    for f in sf.functions:
        if isinstance(f.node, ast.Lambda):
            continue
        cls = _class_of(sf, f.node)
        if cls and f.is_method:
            out.setdefault((cls, f.name), f)
    return out


def analyze(sf: SourceFile) -> ModuleLockFacts:
    """Run the held-set scan over every function of the file."""
    facts = collect_facts(sf)
    if not (facts.class_locks or facts.module_locks or facts.families):
        return facts
    methods = _methods_map(sf)
    scanner = _FuncScanner(facts, methods)
    for fi in sf.functions:
        if isinstance(fi.node, ast.Lambda):
            continue
        scanner.scan(fi, (), 0, None)
    # per-method acquisition summary for the cross-module pass: which
    # locks does calling this method (from outside) take?
    for (cls, name), fi in methods.items():
        probe = ModuleLockFacts(sf)
        probe.class_locks = facts.class_locks
        probe.families = facts.families
        probe.module_locks = facts.module_locks
        probe._kinds = facts._kinds
        _FuncScanner(probe, methods).scan(fi, (), 0, None)
        facts.method_locks.setdefault(name, set()).update(probe.acquired)
    return facts


# ----------------------------------------------------------- findings

def _cycle_findings(sf: SourceFile,
                    edges: Dict[Tuple[str, str], ast.AST]
                    ) -> List[Finding]:
    """SCCs of the lock-order graph with more than one node are
    potential deadlocks; one finding per cycle, anchored at the
    lexicographically-first edge site."""
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str):
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    findings: List[Finding] = []
    for comp in sccs:
        if len(comp) < 2:
            continue
        cset = set(comp)
        cyc_edges = sorted((a, b) for (a, b) in edges
                           if a in cset and b in cset)
        anchor = min((edges[e] for e in cyc_edges),
                     key=lambda n: (getattr(n, "lineno", 0),
                                    getattr(n, "col_offset", 0)))
        order = " -> ".join(sorted(cset)) + f" -> {sorted(cset)[0]}"
        findings.append(sf.finding(
            "concurrency-lock-order", anchor,
            f"lock-order cycle {order}: these locks are acquired in "
            f"opposite orders on different paths — a potential "
            f"deadlock once both run concurrently"))
    return findings


def _blocking_findings(sf: SourceFile,
                       facts: ModuleLockFacts) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[int] = set()
    for b in facts.blocks:
        if id(b.node) in seen:
            continue
        seen.add(id(b.node))
        where = (f"`{b.func}` (inlined from `{b.via}`)" if b.via
                 else f"`{b.func}`")
        held = ", ".join(f"`{h}`" for h in b.held)
        findings.append(sf.finding(
            "concurrency-blocking-under-lock", b.node,
            f"{b.what} in {where} while holding {held} — every "
            f"thread needing that lock stalls behind it; move it "
            f"outside the lock or suppress with the audit reason"))
    return findings


def _unguarded_findings(sf: SourceFile,
                        facts: ModuleLockFacts) -> List[Finding]:
    lockish_attrs = {(c, a) for (c, a) in facts.class_locks} \
        | facts.families
    by_site: Dict[int, List[_Write]] = {}
    for w in facts.writes:
        if (w.cls, w.attr) in lockish_attrs:
            continue
        by_site.setdefault(id(w.node), []).append(w)
    # per write SITE: the lock view of its realistic contexts — a
    # self-called helper is judged under its callers' locks
    sites: Dict[Tuple[str, str], List[Tuple[_Write, frozenset]]] = {}
    for recs in by_site.values():
        inlined = [w for w in recs if w.inlined]
        use = inlined if inlined else recs
        held: frozenset = frozenset()
        for w in use:
            held = held | w.held
        w0 = recs[0]
        sites.setdefault((w0.cls, w0.attr), []).append((w0, held))
    findings: List[Finding] = []
    for (cls, attr), recs in sorted(sites.items()):
        total = len(recs)
        if total < 2:
            continue
        counts: Dict[str, int] = {}
        for _w, held in recs:
            for lock in held:
                counts[lock] = counts.get(lock, 0) + 1
        if not counts:
            continue
        guard, n = max(counts.items(), key=lambda kv: (kv[1], kv[0]))
        if n == total or n / total < GUARD_THRESHOLD:
            continue
        for w, held in recs:
            if guard in held:
                continue
            kind = "read-modify-write" if w.rmw else "write"
            findings.append(sf.finding(
                "concurrency-unguarded-field", w.node,
                f"`self.{attr}` is guarded by `{guard}` "
                f"({n}/{total} write sites hold it) but this {kind} "
                f"in `{w.func}` does not — it races every guarded "
                f"writer"))
    return findings


def check(sf: SourceFile) -> List[Finding]:
    facts = analyze(sf)
    if not (facts.class_locks or facts.module_locks or facts.families):
        return []
    return (_cycle_findings(sf, facts.edges)
            + _blocking_findings(sf, facts)
            + _unguarded_findings(sf, facts))


# ------------------------------------------------- cross-module pairs

def pair_findings(sf_a: SourceFile, sf_b: SourceFile,
                  hint_b_in_a: str, hint_a_in_b: str) -> List[Finding]:
    """Lock-order cycles that only close ACROSS two modules: a call
    made under a held lock in one file, whose receiver matches the
    pair's hint regex and whose method name the partner defines, is
    charged with every lock that partner method acquires."""
    fa, fb = analyze(sf_a), analyze(sf_b)
    edges: Dict[Tuple[str, str], ast.AST] = {}
    own = set()
    for (e, n) in list(fa.edges.items()) + list(fb.edges.items()):
        edges.setdefault(e[0:2], n)
        own.add(e[0:2])
    sites: Dict[Tuple[str, str], Tuple[SourceFile, ast.AST]] = {}

    def cross(src: ModuleLockFacts, dst: ModuleLockFacts,
              src_sf: SourceFile, hint: str):
        rx = re.compile(hint, re.IGNORECASE)
        for c in src.ext_calls:
            if not rx.search(c.recv_src):
                continue
            dst_locks = dst.method_locks.get(c.leaf) or set()
            for h in c.held:
                for lid in dst_locks:
                    if h == lid:
                        continue
                    if (h, lid) not in edges:
                        edges[(h, lid)] = c.node
                        sites[(h, lid)] = (src_sf, c.node)

    cross(fa, fb, sf_a, hint_b_in_a)
    cross(fb, fa, sf_b, hint_a_in_b)
    cross_edges = set(edges) - own
    if not cross_edges:
        return []
    # cycles must involve at least one cross edge (pure in-module
    # cycles are already reported by the per-file pass)
    findings: List[Finding] = []
    for f in _cycle_findings(sf_a, edges):
        # re-anchor at a cross edge participating in the cycle, in
        # whichever file it lives
        hit = None
        for e in sorted(cross_edges):
            if e[0] in f.message and e[1] in f.message:
                hit = e
                break
        if hit is None:
            continue
        src_sf, node = sites[hit]
        findings.append(src_sf.finding(
            "concurrency-lock-order", node,
            f.message + f" (cycle closes across "
            f"{sf_a.relpath} <-> {sf_b.relpath})"))
    return findings
