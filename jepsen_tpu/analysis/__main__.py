"""`python -m jepsen_tpu.analysis --check` — the CI gate entry point.

Exit contract (mirrors cli.py's validity codes at the two ends that
matter for CI): 0 = clean (every finding suppressed with a rule name),
1 = active findings, 2 = usage error. Pure-AST, CPU-only, no JAX
device init — safe to run first in the tier-1 flow.
"""

import sys

from jepsen_tpu import analysis

if __name__ == "__main__":
    sys.exit(analysis.main())
