"""Recompilation / shape-hazard rules.

XLA compiles one program per (function object, static-arg values,
input shapes). Three ways the tree can silently defeat that cache:

  recompile-closure-capture      `jax.jit(...)` evaluated inside a
                                 function body — each call builds a new
                                 wrapper object, so nothing ever hits
                                 the cache (and closure-captured Python
                                 scalars bake into the trace)
  recompile-nonliteral-static-args  static_argnames/static_argnums
                                 computed at runtime (dict order, list
                                 comprehensions) — cache keys stop
                                 being deterministic across processes
  recompile-donate-argnums       the big frontier-buffer entry points
                                 (parallel/engine|dense|bitdense|
                                 sharded) jitted without an explicit
                                 donation decision; donating the
                                 multi-MB reachable-set/frontier
                                 buffers halves HBM pressure, NOT
                                 donating must be a recorded choice
                                 (suppress with the reason)
"""

from __future__ import annotations

import ast
from typing import List

from jepsen_tpu.analysis import core
from jepsen_tpu.analysis.core import Finding, SourceFile

# files whose jits move frontier-scale buffers: donation must be decided
DONATE_FILES = {
    "jepsen_tpu/parallel/engine.py",
    "jepsen_tpu/parallel/dense.py",
    "jepsen_tpu/parallel/bitdense.py",
    "jepsen_tpu/parallel/sharded.py",
}

_STATIC_KWS = ("static_argnames", "static_argnums")
_DONATE_KWS = ("donate_argnums", "donate_argnames")


def _is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return all(_is_literal(e) for e in node.elts)
    return False


def _jit_calls(sf: SourceFile):
    """All (call_node, keywords, decorated_def) jax.jit applications:
    direct calls, partial(jax.jit, ...) calls, and decorator forms."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            if core.is_jax_jit(sf, node.func):
                yield node, node.keywords, None
            elif core.is_jax_jit(sf, node):
                # functools.partial(jax.jit, ...) — keywords ride the
                # partial call itself
                yield node, node.keywords, None
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and core.is_jax_jit(sf, dec):
                    yield dec, dec.keywords, node
                elif core.is_jax_jit(sf, dec):
                    yield dec, [], node


def check(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    seen = set()
    for call, keywords, decorated in _jit_calls(sf):
        key = (call.lineno, call.col_offset)
        if key in seen:
            continue
        seen.add(key)

        # jit created inside a function body (not a decorator): the
        # wrapper — and its compile cache — dies with the call frame
        if decorated is None and isinstance(call, ast.Call) \
                and sf.func_of(call) is not None:
            owner = sf.func_of(call)
            findings.append(sf.finding(
                "recompile-closure-capture", call,
                f"jax.jit evaluated inside `{owner.name}` — a fresh "
                f"wrapper per call never reuses the compile cache; "
                f"hoist to module level (or memoize the wrapper once)"))

        for kw in keywords:
            if kw.arg in _STATIC_KWS and not _is_literal(kw.value):
                findings.append(sf.finding(
                    "recompile-nonliteral-static-args", kw.value,
                    f"{kw.arg} is computed at runtime "
                    f"(`{ast.unparse(kw.value)}`) — static-arg cache "
                    f"keys must be literal and order-stable"))

        if sf.relpath in DONATE_FILES:
            kws = {kw.arg for kw in keywords}
            if not kws.intersection(_DONATE_KWS):
                findings.append(sf.finding(
                    "recompile-donate-argnums", call,
                    "jit of a frontier-buffer entry point with no "
                    "donate_argnums/donate_argnames — donate the big "
                    "buffers or suppress with the reason donation is "
                    "unsafe here"))
    return findings
