"""Linter infrastructure: findings, suppressions, and per-file AST facts.

The analysis pass is pure ``ast`` work — no imports of the linted
modules, no JAX, no device runtime — so it runs on CPU-only CI in
milliseconds and cannot hang on a wedged PJRT backend (the exact
failure mode that motivates several of its rules).

Suppression syntax (see docs/linting.md):

    x = np.arange(8)          # jepsen-lint: disable=purity-numpy-call
    def _plan(C):             # jepsen-lint: disable=purity-numpy-call
        ...                   # (a def-line comment covers the body)
    # jepsen-lint: disable-file=concurrency-unlocked-shared-write
    def step(...):            # jepsen-lint: device
        ...                   # (marks a traced root the call-graph
                              #  cannot see, e.g. dict-dispatched steps)

Every ``disable`` must carry at least one known rule name; a bare or
unknown-rule suppression is itself reported (rule ``bad-suppression``)
so the repo-clean gate keeps the suppression inventory auditable.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# one entry per rule: name -> one-line description (docs + --list-rules)
RULES: Dict[str, str] = {
    "purity-host-call":
        "host-side effect (time/random/os/threading/IO/print) inside "
        "code reachable from a jit/vmap/pmap/shard_map/pallas trace",
    "purity-numpy-call":
        "numpy call inside traced code — legal only on trace-time "
        "constants; on tracers it silently falls back to host or dies",
    "purity-tracer-branch":
        "Python-level branch (if/while/bool cast) on a jnp/lax value "
        "inside traced code — forces a host sync or a tracer error",
    "purity-obs-in-trace":
        "obs.span()/timer()/metrics-registry call inside traced code — "
        "the side effect fires once at trace time, not per execution, "
        "so the span/counter silently lies",
    "recompile-closure-capture":
        "jax.jit created inside a function body — every call builds a "
        "fresh wrapper, so the compile cache never hits",
    "recompile-nonliteral-static-args":
        "static_argnames/static_argnums computed at runtime (e.g. from "
        "dict order) — cache keys become nondeterministic",
    "recompile-donate-argnums":
        "jit of a frontier-buffer entry point without donate_argnums/"
        "donate_argnames — decide donation explicitly (or suppress "
        "with the reason it is unsafe)",
    "concurrency-unlocked-shared-write":
        "attribute/global write to an object shared across threads "
        "with no lock in scope",
    "concurrency-lock-order":
        "lock-order cycle: two locks acquired in opposite orders on "
        "different paths — a potential deadlock the moment both paths "
        "run concurrently",
    "concurrency-blocking-under-lock":
        "blocking operation (file I/O / socket / subprocess / sleep / "
        "foreign Condition.wait / supervised dispatch / flight dump) "
        "inside a held-lock region — every other thread needing that "
        "lock stalls behind the I/O; audited sites carry a named "
        "suppression with the reason",
    "concurrency-unguarded-field":
        "write to a self.<field> outside the lock that guards it "
        "(inferred: >=90% of the field's writes hold one specific "
        "lock) — the unguarded write races every guarded one",
    "concurrency-unsupervised-dispatch":
        "direct call to a device-dispatch entry point outside the "
        "resilience.supervisor seam — faults, watchdog, and breaker "
        "cannot see it (wrap in supervisor.dispatch(site, thunk))",
    "env-flag-accessor":
        "JEPSEN_TPU_* environment variable read outside "
        "jepsen_tpu.envflags — all flag reads go through the validated "
        "accessor",
    "bad-suppression":
        "jepsen-lint suppression without a (known) rule name",
    "lint-stale-suppression":
        "a disable comment whose rule no longer fires on the code it "
        "covers — dead suppressions must be dropped so the inventory "
        "only ever shrinks",
    "hygiene-flag-doc-drift":
        "the envflags.py registration table and the docs' flag rows "
        "disagree: a registered JEPSEN_TPU_* flag is undocumented, or "
        "a documented flag is unregistered",
    "hygiene-metric-doc-drift":
        "the statically-minted obs metric names and the "
        "docs/observability.md naming-scheme rows disagree: a minted "
        "name is undocumented, or a documented metric is never "
        "emitted",
}

# the one module allowed to touch JEPSEN_TPU_* env vars directly
ENV_ACCESSOR_RELPATH = os.path.join("jepsen_tpu", "envflags.py")

_SUPPRESS_RE = re.compile(
    r"#\s*jepsen-lint:\s*(?P<verb>disable-file|disable|device)"
    r"(?:\s*=\s*(?P<rules>[A-Za-z0-9_\-,\s]+?))?\s*(?:#|$)")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-relative, '/'-separated
    line: int
    col: int
    message: str
    suppressed: bool = False

    def anchor(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        tag = " [suppressed]" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule}: "
                f"{self.message}{tag}")


class Suppressions:
    """Parsed ``# jepsen-lint:`` comments of one file."""

    def __init__(self):
        self.file_rules: Set[str] = set()
        self.line_rules: Dict[int, Set[str]] = {}
        self.device_lines: Set[int] = set()
        self.bad: List[Tuple[int, str]] = []
        # where each directive physically lives (directive-comment
        # line -> target line it covers), so stale reporting anchors
        # at the COMMENT the reader would delete
        self.directive_lines: Dict[Tuple[int, str], int] = {}
        self.file_directive_lines: Dict[str, int] = {}
        # filled by SourceFile.apply_suppressions: which (target line,
        # rule) / file-level rules actually suppressed a finding —
        # everything else is a stale directive
        self.used_line: Set[Tuple[int, str]] = set()
        self.used_file: Set[str] = set()

    @classmethod
    def parse(cls, text: str) -> "Suppressions":
        sup = cls()
        lines = text.splitlines()

        def next_code_line(i: int) -> int:
            """First line after i that carries code — blank and
            comment-only lines between a directive and its statement
            must not void the suppression."""
            j = i + 1
            while j <= len(lines):
                body = lines[j - 1].split("#", 1)[0].strip()
                if body:
                    return j
                j += 1
            return i + 1

        # real COMMENT tokens only: docstrings/strings that merely
        # mention the marker (this package documents itself) never parse
        # as directives
        import io
        import tokenize
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(text).readline))
        except (tokenize.TokenError, IndentationError):
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT \
                    or "jepsen-lint" not in tok.string:
                continue
            i = tok.start[0]
            m = _SUPPRESS_RE.search(tok.string)
            if m is None:
                sup.bad.append((i, "unparseable jepsen-lint comment "
                                   "(expected disable=<rule>[,<rule>], "
                                   "disable-file=<rule>, or device)"))
                continue
            verb = m.group("verb")
            # a comment-only line targets the next CODE line (so long
            # statements can carry the suppression just above them,
            # with explanatory comments in between)
            own_line = tok.line.split("#", 1)[0].strip() == ""
            target = next_code_line(i) if own_line else i
            if verb == "device":
                sup.device_lines.add(target)
                continue
            names = [r.strip() for r in (m.group("rules") or "").split(",")
                     if r.strip()]
            if not names:
                sup.bad.append((i, f"'{verb}' without a rule name — every "
                                   f"suppression must name its rule"))
                continue
            unknown = [r for r in names if r not in RULES]
            if unknown:
                sup.bad.append((i, f"unknown rule(s) {unknown} in "
                                   f"'{verb}' (known: "
                                   f"{sorted(RULES)})"))
            known = [r for r in names if r in RULES]
            if verb == "disable-file":
                sup.file_rules.update(known)
                for r in known:
                    sup.file_directive_lines.setdefault(r, i)
            else:
                sup.line_rules.setdefault(target, set()).update(known)
                for r in known:
                    sup.directive_lines.setdefault((target, r), i)
        return sup


class SourceFile:
    """One parsed file plus the derived facts every rule family needs:
    parent links, import aliases, function table, statement spans, and
    suppressions."""

    def __init__(self, path: str, root: str):
        self.path = path
        self.relpath = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as fh:
            self.text = fh.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=path)
        self.suppressions = Suppressions.parse(self.text)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.aliases = _import_aliases(self.tree)
        self.functions = _collect_functions(self.tree)
        self._by_node = {f.node: f for f in self.functions}

    # ------------------------------------------------------ helpers
    def func_of(self, node: ast.AST) -> Optional["FuncInfo"]:
        """The innermost function whose body contains `node`."""
        cur = self.parents.get(node)
        while cur is not None:
            if cur in self._by_node:
                return self._by_node[cur]
            cur = self.parents.get(cur)
        return None

    def stmt_span(self, node: ast.AST) -> Tuple[int, int]:
        """Line span of the statement enclosing `node` (so one
        suppression comment covers a multi-line statement)."""
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parents.get(cur)
        if cur is None:
            cur = node
        return cur.lineno, getattr(cur, "end_lineno", cur.lineno)

    def dotted(self, node: ast.AST) -> Optional[str]:
        """'jax.jit'-style dotted name with the leading alias resolved
        through this file's imports ('_os.environ' -> 'os.environ')."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        parts.append(self.aliases.get(cur.id, cur.id))
        return ".".join(reversed(parts))

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule, self.relpath, node.lineno,
                       getattr(node, "col_offset", 0), message)

    def apply_suppressions(self, findings: Iterable[Finding]) -> List[Finding]:
        """Mark each finding suppressed if a matching comment covers its
        line, its enclosing statement, its enclosing def line, or the
        whole file."""
        sup = self.suppressions
        # "def-line" coverage includes decorator lines: an own-line
        # comment above `@jax.jit` targets the decorator, and it must
        # mean the function, not silently nothing
        def_spans = [(func_head_lines(f.node),
                      getattr(f.node, "end_lineno", f.node.lineno))
                     for f in self.functions
                     if not isinstance(f.node, ast.Lambda)]
        out = []
        for fd in findings:
            covering: List[int] = []
            # exact line + any line of the enclosing statement span
            span = self._span_at(fd.line)
            covering.extend(range(span[0], span[1] + 1))
            # a def-line (or decorator-line) comment covers the body
            for heads, hi in def_spans:
                if min(heads) <= fd.line <= hi:
                    covering.extend(heads)
            for ln in covering:
                if fd.rule in sup.line_rules.get(ln, set()):
                    fd.suppressed = True
                    sup.used_line.add((ln, fd.rule))
            if fd.rule in sup.file_rules:
                fd.suppressed = True
                sup.used_file.add(fd.rule)
            out.append(fd)
        return out

    def stale_suppression_findings(self) -> List[Finding]:
        """Directives that suppressed NOTHING — call strictly after
        apply_suppressions has run over every family's findings. Each
        stale directive anchors at its comment line (the thing to
        delete), so the suppression inventory can only shrink."""
        sup = self.suppressions
        out: List[Finding] = []
        for (target, rule), cline in sorted(sup.directive_lines.items()):
            if (target, rule) in sup.used_line:
                continue
            out.append(Finding(
                "lint-stale-suppression", self.relpath, cline, 0,
                f"suppression for `{rule}` no longer matches any "
                f"finding on line {target} — delete the dead "
                f"directive"))
        for rule, cline in sorted(sup.file_directive_lines.items()):
            if rule in sup.used_file:
                continue
            out.append(Finding(
                "lint-stale-suppression", self.relpath, cline, 0,
                f"file-level suppression for `{rule}` no longer "
                f"matches any finding in this file — delete the dead "
                f"directive"))
        return out

    def _span_at(self, line: int) -> Tuple[int, int]:
        best: Optional[Tuple[int, int]] = None
        for node in ast.walk(self.tree):
            if isinstance(node, ast.stmt) \
                    and not isinstance(node, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.ClassDef)):
                lo, hi = node.lineno, getattr(node, "end_lineno",
                                              node.lineno)
                if lo <= line <= hi and (
                        best is None
                        or (hi - lo) < (best[1] - best[0])):
                    best = (lo, hi)
        return best if best is not None else (line, line)


class FuncInfo:
    """A def/lambda with its lexical scope facts."""

    def __init__(self, node, name: str, parent: Optional["FuncInfo"],
                 is_method: bool = False):
        self.node = node
        self.name = name
        self.parent = parent
        self.is_method = is_method      # class attr, not a module name
        self.children: Dict[str, "FuncInfo"] = {}
        self.nested: List["FuncInfo"] = []
        self.refs: Set[str] = set()     # Name loads in the body
        self.locals: Set[str] = set()   # params + assigned names

    def free_refs(self) -> Set[str]:
        """Names referenced but not bound locally — the only ones that
        can resolve to functions in enclosing/module scope."""
        return self.refs - self.locals

    def resolve(self, name: str,
                module_funcs: Dict[str, "FuncInfo"]) -> Optional["FuncInfo"]:
        scope: Optional[FuncInfo] = self
        while scope is not None:
            if name in scope.children:
                return scope.children[name]
            scope = scope.parent
        return module_funcs.get(name)


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _collect_functions(tree: ast.Module) -> List[FuncInfo]:
    out: List[FuncInfo] = []

    def visit(node: ast.AST, scope: Optional[FuncInfo], in_class: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                name = getattr(child, "name", "<lambda>")
                fi = FuncInfo(child, name, scope, is_method=in_class)
                out.append(fi)
                if scope is not None and not in_class:
                    scope.children[name] = fi
                if scope is not None:
                    scope.nested.append(fi)
                _fill_scope_facts(fi)
                visit(child, fi, False)
            elif isinstance(child, ast.ClassDef):
                # methods live in the class namespace, not the enclosing
                # scope: they must not shadow plain names in resolution
                visit(child, scope, True)
            else:
                visit(child, scope, in_class)

    visit(tree, None, False)
    return out


def _fill_scope_facts(fi: FuncInfo):
    node = fi.node
    args = node.args
    for a in (list(args.posonlyargs) + list(args.args)
              + list(args.kwonlyargs)
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        fi.locals.add(a.arg)
    for sub in _walk_own(node):
        if isinstance(sub, ast.Name):
            if isinstance(sub.ctx, ast.Load):
                fi.refs.add(sub.id)
            else:
                fi.locals.add(sub.id)
        elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi.locals.add(sub.name)
        elif isinstance(sub, ast.Global):
            # a declared global is not a local — writes hit module state
            fi.locals.difference_update(sub.names)


def _walk_own(func_node) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested defs/lambdas
    (their facts are collected on their own FuncInfo)."""
    body = (func_node.body if isinstance(func_node.body, list)
            else [func_node.body])
    nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    stack: List[ast.AST] = [n for n in body if not isinstance(n, nested)]
    while stack:
        node = stack.pop()
        yield node
        stack.extend(child for child in ast.iter_child_nodes(node)
                     if not isinstance(child, nested))


def walk_own(func_node) -> Iterable[ast.AST]:
    """Public alias of the own-body walker for the rule families."""
    return _walk_own(func_node)


def func_head_lines(node) -> List[int]:
    """The lines that 'mean this function' for comment targeting: the
    def line plus every decorator line (an own-line comment above a
    decorated def lands on the first decorator)."""
    return [d.lineno for d in getattr(node, "decorator_list", [])] \
        + [node.lineno]


def module_functions(sf: SourceFile) -> Dict[str, FuncInfo]:
    return {f.name: f for f in sf.functions if f.parent is None
            and not f.is_method and not isinstance(f.node, ast.Lambda)}


# ------------------------------------------------------------ traced roots

# entry points whose callable arguments run under a trace
_TRACE_ENTRIES = {"jit", "vmap", "pmap", "shard_map", "pallas_call",
                  "scan", "while_loop", "fori_loop", "cond", "switch",
                  "custom_jvp", "custom_vjp", "checkpoint", "remat"}


def is_trace_entry(sf: SourceFile, call: ast.Call) -> bool:
    dotted = sf.dotted(call.func)
    if dotted is None:
        return False
    return dotted.split(".")[-1] in _TRACE_ENTRIES


def is_jax_jit(sf: SourceFile, node: ast.AST) -> bool:
    """`node` is an expression producing jax.jit (directly or via
    functools.partial(jax.jit, ...))."""
    if isinstance(node, ast.Call):
        dotted = sf.dotted(node.func)
        if dotted and dotted.split(".")[-1] == "partial" and node.args:
            return is_jax_jit(sf, node.args[0])
        return False
    dotted = sf.dotted(node)
    return bool(dotted) and dotted.split(".")[-1] == "jit" \
        and ("jax" in dotted or dotted == "jit")


def trace_roots(sf: SourceFile) -> List[FuncInfo]:
    """Functions whose bodies run under a JAX trace: jit/vmap/pmap/
    shard_map/pallas_call/lax-control-flow targets, decorated defs, and
    `# jepsen-lint: device` pragma'd defs (for dispatch tables the call
    graph cannot see)."""
    mod_funcs = module_functions(sf)
    roots: List[FuncInfo] = []
    by_node = {f.node: f for f in sf.functions}

    def add_target(node: ast.AST, scope: Optional[FuncInfo]):
        if isinstance(node, ast.Lambda):
            fi = by_node.get(node)
            if fi is not None:
                roots.append(fi)
        elif isinstance(node, ast.Name):
            base = scope if scope is not None else None
            fi = (base.resolve(node.id, mod_funcs) if base is not None
                  else mod_funcs.get(node.id))
            if fi is not None:
                roots.append(fi)
        elif isinstance(node, ast.Call):
            # partial(f, ...) — recurse into its arguments
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                add_target(a, scope)

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and is_trace_entry(sf, node):
            scope = sf.func_of(node)
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                add_target(a, scope)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                if is_jax_jit(sf, d) or is_jax_jit(sf, dec) or (
                        sf.dotted(d) or "").split(".")[-1] in _TRACE_ENTRIES:
                    fi = by_node.get(node)
                    if fi is not None:
                        roots.append(fi)
            if any(ln in sf.suppressions.device_lines
                   for ln in func_head_lines(node)):
                fi = by_node.get(node)
                if fi is not None:
                    roots.append(fi)
    return roots


def reach(sf: SourceFile, roots: Sequence[FuncInfo]) -> Set[FuncInfo]:
    """Transitive closure over name references and lexical nesting:
    anything a traced function references (or defines inline) is traced
    with it."""
    mod_funcs = module_functions(sf)
    seen: Set[FuncInfo] = set()
    stack = list(roots)
    while stack:
        fi = stack.pop()
        if fi in seen:
            continue
        seen.add(fi)
        stack.extend(fi.nested)
        for name in fi.free_refs():
            target = fi.resolve(name, mod_funcs)
            if target is not None and target is not fi:
                stack.append(target)
    return seen


# ------------------------------------------------------------ file walking

DEFAULT_TOP_FILES = ("bench.py", "__graft_entry__.py")
DEFAULT_DIRS = ("jepsen_tpu", "tools")
SKIP_PARTS = {"__pycache__", ".git", "node_modules", "store",
              "bench_results"}


def default_targets(root: str) -> List[str]:
    out: List[str] = []
    for fname in DEFAULT_TOP_FILES:
        p = os.path.join(root, fname)
        if os.path.isfile(p):
            out.append(p)
    for d in DEFAULT_DIRS:
        base = os.path.join(root, d)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(x for x in dirnames
                                 if x not in SKIP_PARTS)
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return out


def expand_targets(paths: Sequence[str], root: str) -> List[str]:
    out: List[str] = []
    for p in paths:
        p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(x for x in dirnames
                                     if x not in SKIP_PARTS)
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames)
                           if f.endswith(".py"))
        else:
            out.append(p)
    return out
