"""Concurrency rules: the threaded interpreter and control transports.

concurrency-unlocked-shared-write
    A lightweight race detector over thread-run code. Roots are
    functions handed to `threading.Thread(target=...)`, Timer targets,
    and `executor.submit(...)` callables, plus everything they
    reference (same reachability machinery as the purity pass). Inside
    those, an attribute write whose base object is *not local* to the
    writing function (a closed-over or global object — i.e. state
    another thread can also see) is flagged unless the write sits
    inside a `with <something lock-ish>` block. Writes to locals and
    subscript stores are out of scope (per-index list writes under the
    GIL are the project's accepted fan-in idiom, see util.real_pmap).

env-flag-accessor
    Every read of a JEPSEN_TPU_* environment variable must go through
    jepsen_tpu.envflags (the validated accessor). A raw
    os.environ/os.getenv read reintroduces the round-5 failure mode:
    a malformed value silently flipping a measured default.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from jepsen_tpu.analysis import core
from jepsen_tpu.analysis.core import Finding, FuncInfo, SourceFile

_LOCKISH = re.compile(r"lock|cond|sem|mutex|barrier", re.IGNORECASE)

_ENV_PREFIX = "JEPSEN_TPU_"
_ENV_READ_CALLS = {"os.environ.get", "os.getenv", "os.environ.pop",
                   "os.environ.setdefault"}


# ------------------------------------------------------- thread roots

def _thread_roots(sf: SourceFile) -> List[FuncInfo]:
    mod_funcs = core.module_functions(sf)
    by_node = {f.node: f for f in sf.functions}
    roots: List[FuncInfo] = []

    def add(node: Optional[ast.AST], scope: Optional[FuncInfo]):
        if isinstance(node, ast.Lambda):
            fi = by_node.get(node)
            if fi is not None:
                roots.append(fi)
        elif isinstance(node, ast.Name):
            fi = (scope.resolve(node.id, mod_funcs) if scope is not None
                  else mod_funcs.get(node.id))
            if fi is not None:
                roots.append(fi)
        elif isinstance(node, ast.Attribute):
            # bound method handed to the thread (target=self._poll,
            # target=worker.run): resolve by attribute name against
            # this file's methods — an over-approximation on name
            # collisions, which is the right direction for a race
            # detector
            roots.extend(f for f in sf.functions
                         if f.is_method and f.name == node.attr)

    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = sf.dotted(node.func) or ""
        leaf = dotted.split(".")[-1]
        scope = sf.func_of(node)
        if leaf in ("Thread", "Timer"):
            for kw in node.keywords:
                if kw.arg in ("target", "function"):
                    add(kw.value, scope)
        elif leaf == "submit" and node.args:
            add(node.args[0], scope)
    return roots


def _under_lock(sf: SourceFile, node: ast.AST) -> bool:
    """Some ancestor `with` statement's context expression looks like a
    lock (RLock/Condition/read()/write() wrappers included)."""
    cur = sf.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                try:
                    src = ast.unparse(item.context_expr)
                except Exception:  # pragma: no cover - unparse is total
                    src = ""
                if _LOCKISH.search(src):
                    return True
        cur = sf.parents.get(cur)
    return False


def _race_findings(sf: SourceFile) -> List[Finding]:
    roots = _thread_roots(sf)
    if not roots:
        return []
    reachable = core.reach(sf, roots)
    findings: List[Finding] = []
    for fi in reachable:
        global_names: Set[str] = set()
        for node in core.walk_own(fi.node):
            if isinstance(node, ast.Global):
                global_names.update(node.names)
        for node in core.walk_own(fi.node):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name):
                    base = t.value.id
                    if base in fi.locals or base == "self":
                        continue
                    if not _under_lock(sf, node):
                        findings.append(sf.finding(
                            "concurrency-unlocked-shared-write", t,
                            f"`{base}.{t.attr}` written in thread-run "
                            f"function `{fi.name}` on a shared "
                            f"(closed-over/global) object with no lock "
                            f"in scope"))
                elif isinstance(t, ast.Name) and t.id in global_names:
                    if not _under_lock(sf, node):
                        findings.append(sf.finding(
                            "concurrency-unlocked-shared-write", t,
                            f"global `{t.id}` written in thread-run "
                            f"function `{fi.name}` with no lock in "
                            f"scope"))
    return findings


# ---------------------------------------------------- env-flag hygiene

def _env_findings(sf: SourceFile) -> List[Finding]:
    if sf.relpath == core.ENV_ACCESSOR_RELPATH.replace("\\", "/"):
        return []
    findings: List[Finding] = []

    def is_prefixed(node: ast.AST) -> bool:
        return isinstance(node, ast.Constant) \
            and isinstance(node.value, str) \
            and node.value.startswith(_ENV_PREFIX)

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            dotted = sf.dotted(node.func) or ""
            if dotted in _ENV_READ_CALLS and node.args \
                    and is_prefixed(node.args[0]):
                findings.append(sf.finding(
                    "env-flag-accessor", node,
                    f"raw `{dotted}({node.args[0].value!r})` — read "
                    f"JEPSEN_TPU_* flags through jepsen_tpu.envflags "
                    f"(env_bool/env_choice) so malformed values fail "
                    f"loudly instead of flipping defaults"))
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            dotted = sf.dotted(node.value) or ""
            if dotted == "os.environ" and is_prefixed(node.slice):
                findings.append(sf.finding(
                    "env-flag-accessor", node,
                    f"raw `os.environ[{node.slice.value!r}]` — read "
                    f"JEPSEN_TPU_* flags through jepsen_tpu.envflags"))
    return findings


def check(sf: SourceFile) -> List[Finding]:
    return _race_findings(sf) + _env_findings(sf)
