"""Concurrency rules: the threaded interpreter and control transports.

concurrency-unlocked-shared-write
    A lightweight race detector over thread-run code. Roots are
    functions handed to `threading.Thread(target=...)`, Timer targets,
    and `executor.submit(...)` callables, plus everything they
    reference (same reachability machinery as the purity pass). Inside
    those, an attribute write whose base object is *not local* to the
    writing function (a closed-over or global object — i.e. state
    another thread can also see) is flagged unless the write sits
    inside a `with <something lock-ish>` block. Writes to locals and
    subscript stores are out of scope (per-index list writes under the
    GIL are the project's accepted fan-in idiom, see util.real_pmap).

env-flag-accessor
    Every read of a JEPSEN_TPU_* environment variable must go through
    jepsen_tpu.envflags (the validated accessor). A raw
    os.environ/os.getenv read reintroduces the round-5 failure mode:
    a malformed value silently flipping a measured default.

concurrency-lock-order / concurrency-blocking-under-lock /
concurrency-unguarded-field
    The lock-discipline pass (jepsen_tpu.analysis.locks) runs as part
    of this family: static lock-order-cycle detection, blocking
    operations inside held-lock regions, and guarded-field inference
    over `threading.Lock/RLock/Condition` attributes. See locks.py
    for the held-set model and the interprocedural bound.

concurrency-unsupervised-dispatch
    Every call to a device-dispatch entry point (the jitted
    _check_device*/_check_bitdense*/_check_sharded* functions) must
    run inside a thunk handed to resilience.supervisor.dispatch — the
    seam where fault injection, the watchdog, and the circuit breaker
    live. Roots are callables passed to a `dispatch(...)` call (same
    resolution as the thread-root detector); an entry-point call NOT
    reachable from such a root is a dispatch the resilience layer
    cannot see: it would hang forever on the r05 wedge signature and
    its failures would never trip the breaker. The usual
    `# jepsen-lint: disable=` escape applies (e.g. deliberate
    benchmarking of the bare program).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from jepsen_tpu.analysis import core
from jepsen_tpu.analysis.core import Finding, FuncInfo, SourceFile

_LOCKISH = re.compile(r"lock|cond|sem|mutex|barrier", re.IGNORECASE)

_ENV_PREFIX = "JEPSEN_TPU_"
_ENV_READ_CALLS = {"os.environ.get", "os.getenv", "os.environ.pop",
                   "os.environ.setdefault"}


# ------------------------------------------------------- thread roots

def _thread_roots(sf: SourceFile) -> List[FuncInfo]:
    mod_funcs = core.module_functions(sf)
    by_node = {f.node: f for f in sf.functions}
    roots: List[FuncInfo] = []

    def add(node: Optional[ast.AST], scope: Optional[FuncInfo]):
        if isinstance(node, ast.Lambda):
            fi = by_node.get(node)
            if fi is not None:
                roots.append(fi)
        elif isinstance(node, ast.Name):
            fi = (scope.resolve(node.id, mod_funcs) if scope is not None
                  else mod_funcs.get(node.id))
            if fi is not None:
                roots.append(fi)
        elif isinstance(node, ast.Attribute):
            # bound method handed to the thread (target=self._poll,
            # target=worker.run): resolve by attribute name against
            # this file's methods — an over-approximation on name
            # collisions, which is the right direction for a race
            # detector
            roots.extend(f for f in sf.functions
                         if f.is_method and f.name == node.attr)

    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = sf.dotted(node.func) or ""
        leaf = dotted.split(".")[-1]
        scope = sf.func_of(node)
        if leaf in ("Thread", "Timer"):
            for kw in node.keywords:
                if kw.arg in ("target", "function"):
                    add(kw.value, scope)
        elif leaf == "submit" and node.args:
            add(node.args[0], scope)
    return roots


def _under_lock(sf: SourceFile, node: ast.AST) -> bool:
    """Some ancestor `with` statement's context expression looks like a
    lock (RLock/Condition/read()/write() wrappers included)."""
    cur = sf.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                try:
                    src = ast.unparse(item.context_expr)
                except Exception:  # pragma: no cover - unparse is total
                    src = ""
                if _LOCKISH.search(src):
                    return True
        cur = sf.parents.get(cur)
    return False


def _race_findings(sf: SourceFile) -> List[Finding]:
    roots = _thread_roots(sf)
    if not roots:
        return []
    reachable = core.reach(sf, roots)
    findings: List[Finding] = []
    for fi in reachable:
        global_names: Set[str] = set()
        for node in core.walk_own(fi.node):
            if isinstance(node, ast.Global):
                global_names.update(node.names)
        for node in core.walk_own(fi.node):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name):
                    base = t.value.id
                    if base in fi.locals or base == "self":
                        continue
                    if not _under_lock(sf, node):
                        findings.append(sf.finding(
                            "concurrency-unlocked-shared-write", t,
                            f"`{base}.{t.attr}` written in thread-run "
                            f"function `{fi.name}` on a shared "
                            f"(closed-over/global) object with no lock "
                            f"in scope"))
                elif isinstance(t, ast.Name) and t.id in global_names:
                    if not _under_lock(sf, node):
                        findings.append(sf.finding(
                            "concurrency-unlocked-shared-write", t,
                            f"global `{t.id}` written in thread-run "
                            f"function `{fi.name}` with no lock in "
                            f"scope"))
    return findings


# ------------------------------------------- supervised-dispatch seam

# the jitted device-dispatch entry points (engine / bitdense / sharded)
# whose every call must sit inside a supervisor.dispatch thunk
_DISPATCH_ENTRIES = {
    "_check_device", "_check_device_batch", "_check_device_resumable",
    "_check_device_batch_resumable",
    "_check_bitdense", "_check_bitdense_batch",
    "_check_sharded", "_check_sharded2d", "_check_sharded_resume",
}


def _supervised_roots(sf: SourceFile) -> List[FuncInfo]:
    """Callables passed (positionally or by keyword) to a call whose
    dotted name ends in `dispatch` — the supervisor seam's thunks.
    Same resolution machinery as the thread-root detector above."""
    mod_funcs = core.module_functions(sf)
    by_node = {f.node: f for f in sf.functions}
    roots: List[FuncInfo] = []

    def add(node: ast.AST, scope: Optional[FuncInfo]):
        if isinstance(node, ast.Lambda):
            fi = by_node.get(node)
            if fi is not None:
                roots.append(fi)
        elif isinstance(node, ast.Name):
            fi = (scope.resolve(node.id, mod_funcs) if scope is not None
                  else mod_funcs.get(node.id))
            if fi is not None:
                roots.append(fi)
        elif isinstance(node, ast.Attribute):
            roots.extend(f for f in sf.functions
                         if f.is_method and f.name == node.attr)

    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = sf.dotted(node.func) or ""
        if dotted.split(".")[-1] != "dispatch":
            continue
        scope = sf.func_of(node)
        for arg in node.args:
            add(arg, scope)
        for kw in node.keywords:
            if kw.arg == "thunk":
                add(kw.value, scope)
    return roots


def _dispatch_findings(sf: SourceFile) -> List[Finding]:
    calls = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            dotted = sf.dotted(node.func) or ""
            if dotted.split(".")[-1] in _DISPATCH_ENTRIES:
                calls.append((node, dotted))
    if not calls:
        return []
    reachable = core.reach(sf, _supervised_roots(sf))
    findings: List[Finding] = []
    for node, dotted in calls:
        fi = sf.func_of(node)
        if fi is not None and fi in reachable:
            continue
        findings.append(sf.finding(
            "concurrency-unsupervised-dispatch", node,
            f"`{dotted}(...)` dispatched outside the "
            f"resilience.supervisor seam — wrap it in a thunk passed "
            f"to supervisor.dispatch(site, ...) so the watchdog, "
            f"fault injection, and circuit breaker can see it"))
    return findings


# ---------------------------------------------------- env-flag hygiene

def _env_findings(sf: SourceFile) -> List[Finding]:
    if sf.relpath == core.ENV_ACCESSOR_RELPATH.replace("\\", "/"):
        return []
    findings: List[Finding] = []

    def is_prefixed(node: ast.AST) -> bool:
        return isinstance(node, ast.Constant) \
            and isinstance(node.value, str) \
            and node.value.startswith(_ENV_PREFIX)

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            dotted = sf.dotted(node.func) or ""
            if dotted in _ENV_READ_CALLS and node.args \
                    and is_prefixed(node.args[0]):
                findings.append(sf.finding(
                    "env-flag-accessor", node,
                    f"raw `{dotted}({node.args[0].value!r})` — read "
                    f"JEPSEN_TPU_* flags through jepsen_tpu.envflags "
                    f"(env_bool/env_choice) so malformed values fail "
                    f"loudly instead of flipping defaults"))
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            dotted = sf.dotted(node.value) or ""
            if dotted == "os.environ" and is_prefixed(node.slice):
                findings.append(sf.finding(
                    "env-flag-accessor", node,
                    f"raw `os.environ[{node.slice.value!r}]` — read "
                    f"JEPSEN_TPU_* flags through jepsen_tpu.envflags"))
    return findings


def check(sf: SourceFile) -> List[Finding]:
    from jepsen_tpu.analysis import locks
    return (_race_findings(sf) + _dispatch_findings(sf)
            + _env_findings(sf) + locks.check(sf))
