"""Client protocol (reference: jepsen/src/jepsen/client.clj).

A client applies operations to the system under test. Lifecycle
(client.clj:9-27): `open` a network connection, `setup` initial state
once, `invoke` many ops, `teardown`, `close`. One client instance per
process; a crashed (:info) process abandons its client and a fresh one
is opened for the replacement process (interpreter semantics).
"""

from __future__ import annotations

from typing import Any, Optional

from jepsen_tpu.history import Op


class Client:
    def open(self, test, node) -> "Client":
        """Return a client bound to the given node. Called before any
        invocations; must return a fresh (or this) client."""
        return self

    def setup(self, test) -> None:
        """One-time database setup."""

    def invoke(self, test, op: Op) -> Op:
        """Apply op to the system; return the completion op with :type
        ok/fail/info. Exceptions become :info (indeterminate)."""
        raise NotImplementedError

    def teardown(self, test) -> None:
        """One-time cleanup."""

    def close(self, test) -> None:
        """Release the connection."""

    def is_reusable(self, test) -> bool:
        """May this client be reused across processes? (client.clj:29-44
        Reusable protocol; default false)."""
        return False


class Validate(Client):
    """Wraps a client, checking completion invariants: :type in
    {ok, fail, info}, same :process and :f as the invocation
    (client.clj:64-114)."""

    def __init__(self, client: Client):
        self.client = client

    def open(self, test, node):
        return Validate(self.client.open(test, node))

    def setup(self, test):
        self.client.setup(test)

    def invoke(self, test, op):
        res = self.client.invoke(test, op)
        problems = []
        if not isinstance(res, dict):
            problems.append(f"should be a dict, was {res!r}")
        else:
            if res.get("type") not in ("ok", "fail", "info"):
                problems.append(
                    f":type should be ok, fail, or info, was {res.get('type')!r}")
            if res.get("process") != op.get("process"):
                problems.append(
                    f"should have the same :process as the invocation "
                    f"({op.get('process')!r}), was {res.get('process')!r}")
            if res.get("f") != op.get("f"):
                problems.append(
                    f"should have the same :f as the invocation "
                    f"({op.get('f')!r}), was {res.get('f')!r}")
        if problems:
            raise RuntimeError(
                "Client returned an invalid completion for " + repr(op)
                + ": " + "; ".join(problems))
        return res

    def teardown(self, test):
        self.client.teardown(test)

    def close(self, test):
        self.client.close(test)

    def is_reusable(self, test):
        return self.client.is_reusable(test)


def validate(client: Client) -> Validate:
    return Validate(client)


def is_reusable(client: Optional[Client], test) -> bool:
    return client is not None and client.is_reusable(test)


class Noop(Client):
    """Does nothing; every op is :ok (client.clj:46-53)."""

    def invoke(self, test, op):
        o = Op(op)
        o["type"] = "ok"
        return o

    def is_reusable(self, test):
        return True


def noop() -> Noop:
    return Noop()


def closable(c: Any) -> bool:
    return hasattr(c, "close")
