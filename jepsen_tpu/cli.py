"""Command-line runner (reference: jepsen/src/jepsen/cli.clj).

Subcommands mirror `jepsen.cli/single-test-cmd` + serve
(cli.clj:343-419, 324-341):

    test      build a test from flags and run it
    analyze   re-check the latest (or given) stored history
    serve     browse stored results over HTTP

Exit-code contract (cli.clj:120-130): 0 = valid, 1 = invalid,
2 = unknown validity, 254 = bad arguments, 255 = crash.

A suite supplies `run_cli(test_fn)` where (test_fn options) -> test map;
options include the parsed flags below. The `--concurrency` flag accepts
the reference's "3n" syntax — a multiple of the node count
(cli.clj:55-102 parse-concurrency).
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback
from typing import Callable, Dict, Optional

from jepsen_tpu import core as jcore
from jepsen_tpu import store as jstore
from jepsen_tpu.history import History

EXIT_VALID = 0
EXIT_INVALID = 1
EXIT_UNKNOWN = 2
EXIT_BAD_ARGS = 254
EXIT_CRASH = 255


def parse_concurrency(s: str, n_nodes: int) -> int:
    """'10' -> 10; '3n' -> 3 * node count (cli.clj:132-150)."""
    s = str(s).strip()
    if s.endswith("n"):
        return int(s[:-1] or 1) * max(1, n_nodes)
    return int(s)


def parse_nodes(args) -> list:
    if args.node:
        return list(args.node)
    if args.nodes_file:
        with open(args.nodes_file) as fh:
            return [ln.strip() for ln in fh if ln.strip()]
    return ["n1", "n2", "n3", "n4", "n5"]  # cli.clj default node set


def base_parser(prog: str = "jepsen") -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=prog)
    sub = p.add_subparsers(dest="command")

    def common(sp):
        sp.add_argument("--node", action="append",
                        help="node name (repeatable)")
        sp.add_argument("--nodes-file", help="file with one node per line")
        sp.add_argument("--username", default="root")
        sp.add_argument("--password", default="root")
        sp.add_argument("--private-key-path")
        sp.add_argument("--ssh-port", type=int, default=22)
        sp.add_argument("--no-ssh", action="store_true",
                        help="use the dummy remote (no cluster needed)")
        sp.add_argument("--concurrency", default="1n",
                        help="worker count; '3n' = 3 per node")
        sp.add_argument("--time-limit", type=float, default=60,
                        help="seconds of main workload")
        sp.add_argument("--test-count", type=int, default=1)
        sp.add_argument("--workload", default=None)
        sp.add_argument("--nemesis", default=None)

    t = sub.add_parser("test", help="run a test")
    common(t)
    a = sub.add_parser("analyze", help="re-check a stored history")
    common(a)
    a.add_argument("--run-dir", help="store/<name>/<timestamp> to re-check")
    s = sub.add_parser(
        "serve",
        help="serve stored results over HTTP; with --checker, run the "
             "streaming checker service instead")
    s.add_argument("--port", type=int, default=8080)
    s.add_argument("--host", default="0.0.0.0")
    s.add_argument("--checker", action="store_true",
                   help="run the streaming checker service: JSONL "
                        "delta requests on stdin, verdict responses "
                        "on stdout (docs/streaming.md)")
    s.add_argument("--model", default="cas-register",
                   choices=sorted(SERVE_MODELS),
                   help="model family for --checker")
    s.add_argument("--wal-dir", default=None,
                   help="delta WAL + checkpoint-store directory for "
                        "--checker (default: JEPSEN_TPU_SERVE_WAL)")
    s.add_argument("--dedupe", default=None,
                   choices=("sort", "hash"),
                   help="frontier dedupe strategy for --checker "
                        "(default: JEPSEN_TPU_DEDUPE)")
    s.add_argument("--ops-port", type=int, default=None,
                   help="with --checker: serve /metrics (Prometheus "
                        "text), /healthz, and /status on this port "
                        "(0 = OS-assigned; default: "
                        "JEPSEN_TPU_OPS_PORT, unset = no ops "
                        "endpoint — docs/observability.md)")
    s.add_argument("--repl-dir", default=None,
                   help="with --checker: WAL segment replication "
                        "target — the successor replica's repl/ "
                        "mirror (e.g. a shared mount); required when "
                        "JEPSEN_TPU_SERVE_REPL is async/sync, "
                        "rejected when it is off (docs/streaming.md "
                        "'Fleet self-healing')")
    s.add_argument("--ingress-port", type=int, default=None,
                   help="with --checker: accept streamed-JSONL delta "
                        "requests over HTTP on this port "
                        "(POST /v1/deltas, GET /v1/result, "
                        "POST /v1/finalize; per-tenant bearer-token "
                        "auth when JEPSEN_TPU_TENANTS is set; 0 = "
                        "OS-assigned; default: "
                        "JEPSEN_TPU_INGRESS_PORT, unset = stdio "
                        "only — docs/streaming.md)")
    # listed for --help discoverability only: run_cli dispatches `lint`
    # to jepsen_tpu.analysis.main BEFORE parsing (its own parser is the
    # single source of truth for lint flags and the 0/1/2 contract;
    # argparse.REMAINDER cannot forward a leading optional)
    li = sub.add_parser(
        "lint", add_help=False,
        help="tracing-safety & concurrency static analysis "
             "(jepsen_tpu.analysis); exit 0 clean / 1 findings / "
             "2 usage error")
    # listed for --help discoverability only, like lint: run_cli
    # dispatches `probe` BEFORE parsing (jepsen_tpu.probe owns its
    # flags and the 0/1/2 healthy/wedged/no-backend exit contract)
    pr = sub.add_parser(
        "probe", add_help=False,
        help="bounded device-runtime health check (subprocess "
             "jax.devices() with timeout + retry); exit 0 healthy / "
             "1 wedged / 2 no-backend")
    # listed for --help discoverability only, like lint/probe: run_cli
    # dispatches `status` BEFORE parsing (jepsen_tpu.obs.httpd owns its
    # flags and the 0/1/2 ready/degraded/unreachable exit contract)
    st = sub.add_parser(
        "status", add_help=False,
        help="fetch /status + /healthz from a running `jepsen serve "
             "--checker --ops-port N` and print the operator summary; "
             "exit 0 ready / 1 degraded / 2 unreachable")
    # listed for --help discoverability only, like lint/probe/status:
    # run_cli dispatches `report` BEFORE parsing (obs.search_report
    # owns its flags; exit 0 written / 1 no stats / 254 usage)
    rp = sub.add_parser(
        "report", add_help=False,
        help="render a stored run's telemetry reports; --search "
             "renders the JEPSEN_TPU_SEARCH_STATS per-key table "
             "(worst keys by load factor / escalations / pad waste); "
             "--slow renders the slow-delta forensics table "
             "(JEPSEN_TPU_SLOW_DELTA_SECS stage breakdowns); --plan "
             "renders the strategy-advisor table (JEPSEN_TPU_LEDGER "
             "decision records joined with perf_ab bench evidence)")
    # listed for --help discoverability only, like lint/probe/status:
    # run_cli dispatches `trace` BEFORE parsing (obs.trace_merge owns
    # its flags and the 0/1/2 merged/invalid/unreachable contract)
    tr = sub.add_parser(
        "trace", add_help=False,
        help="merge a fleet's per-replica trace exports (live /trace "
             "endpoints, run dirs, flight dumps) into one Perfetto "
             "file — one process track per replica, wall-clock "
             "aligned; --validate schema-checks exports")
    ta = sub.add_parser(
        "test-all", help="run a whole suite of tests in one go")
    common(ta)
    ta.add_argument("--workloads",
                    help="comma-separated workload sweep (default: the "
                         "single --workload)")
    ta.add_argument("--nemeses",
                    help="comma-separated nemesis sweep (default: the "
                         "single --nemesis)")
    p._jepsen_subparsers = {"test": t, "analyze": a, "serve": s,
                            "lint": li, "probe": pr, "status": st,
                            "report": rp, "trace": tr,
                            "test-all": ta}
    return p


def options_from_args(args) -> Dict:
    nodes = parse_nodes(args)
    ssh = {
        "username": args.username,
        "password": args.password,
        "port": args.ssh_port,
        "private-key-path": args.private_key_path,
        "dummy": bool(args.no_ssh),
    }
    return {
        "nodes": nodes,
        "ssh": ssh,
        "concurrency": parse_concurrency(args.concurrency, len(nodes)),
        "time-limit": args.time_limit,
        "test-count": args.test_count,
        "workload": args.workload,
        "nemesis": args.nemesis,
        # suite-specific flags as plain data (serializable, no Namespace)
        "args": dict(vars(args)),
        "explicit-nodes": bool(args.node or args.nodes_file),
    }


def validity_exit_code(results: Dict) -> int:
    v = (results or {}).get("valid?")
    if v is True:
        return EXIT_VALID
    if v is False:
        return EXIT_INVALID
    return EXIT_UNKNOWN


def run_test_cmd(test_fn: Callable[[Dict], Dict], args) -> int:
    opts = options_from_args(args)
    for _ in range(opts["test-count"]):  # cli.clj:375-386 loop
        test = test_fn(opts)
        completed = jcore.run(test)
        code = validity_exit_code(completed.get("results"))
        print(json.dumps({"valid?": completed["results"].get("valid?"),
                          "store": completed["store"].dir}, default=str))
        if code != EXIT_VALID:
            # exit on first non-valid run, as the reference does
            return code
    return EXIT_VALID


def run_analyze_cmd(test_fn: Callable[[Dict], Dict], args) -> int:
    """Reload the latest stored run and re-check it against a freshly
    built test map (cli.clj:388-419)."""
    run_dir = args.run_dir or jstore.latest()
    if run_dir is None:
        print("no stored runs to analyze", file=sys.stderr)
        return EXIT_BAD_ARGS
    stored = jstore.load_run(run_dir)
    history = stored.get("history")
    if history is None:
        print(f"no history.npz/history.edn under {run_dir}", file=sys.stderr)
        return EXIT_BAD_ARGS
    opts = options_from_args(args)
    test = test_fn(opts)
    # merge stored test config under the fresh test map (cli.clj:396-400)
    for k, v in (stored.get("test") or {}).items():
        test.setdefault(k, v)
    results = jcore.analyze(test, History.wrap(history))
    print(json.dumps({"valid?": results.get("valid?"), "run": run_dir},
                     default=str))
    return validity_exit_code(results)


def _sweep_tests(args, opts):
    """The default tests-fn for test-all: the cross product of
    --workloads x --nemeses, each repeated --test-count times."""
    workloads = [w.strip() for w in (args.workloads or "").split(",")
                 if w.strip()] or [opts.get("workload")]
    nemeses = [n.strip() for n in (args.nemeses or "").split(",")
               if n.strip()] or [opts.get("nemesis")]
    for w in workloads:
        for n in nemeses:
            for _ in range(max(1, opts.get("test-count") or 1)):
                o = dict(opts)
                o["workload"] = w
                o["nemesis"] = n
                yield f"{w or 'default'}:{n or 'none'}", o


def run_test_all_cmd(test_fn: Callable[[Dict], Dict], args,
                     tests_fn: Optional[Callable] = None) -> int:
    """Run a suite of tests, collate outcomes, print a summary, and exit
    255 if any crashed / 2 if any unknown / 1 if any invalid / 0 if all
    passed (cli.clj:421-503 test-all-cmd + test-all-exit!).

    tests_fn(opts) may yield (name, options) pairs to override the
    default --workloads x --nemeses sweep."""
    opts = options_from_args(args)
    pairs = (tests_fn(opts) if tests_fn is not None
             else _sweep_tests(args, opts))
    outcomes: Dict = {}  # True | False | "unknown" | "crashed" -> [runs]
    for name, o in pairs:
        try:
            completed = jcore.run(test_fn(o))
            v = completed["results"].get("valid?")
            key = v if v in (True, False) else "unknown"
            run_ref = str(getattr(completed.get("store"), "dir", name))
            outcomes.setdefault(key, []).append(run_ref)
            print(json.dumps({"test": name, "valid?": v,
                              "store": run_ref}, default=str))
        except KeyboardInterrupt:
            raise
        except Exception:  # noqa: BLE001 — one crash must not end the sweep
            traceback.print_exc()
            outcomes.setdefault("crashed", []).append(name)
    for title, key in (("Successful tests", True),
                       ("Indeterminate tests", "unknown"),
                       ("Crashed tests", "crashed"),
                       ("Failed tests", False)):
        if outcomes.get(key):
            print(f"\n# {title}\n")
            for r in outcomes[key]:
                print(r)
    print()
    print(len(outcomes.get(True, [])), "successes")
    print(len(outcomes.get("unknown", [])), "unknown")
    print(len(outcomes.get("crashed", [])), "crashed")
    print(len(outcomes.get(False, [])), "failures")
    if outcomes.get("crashed"):
        return EXIT_CRASH
    if outcomes.get("unknown"):
        return EXIT_UNKNOWN
    if outcomes.get(False):
        return EXIT_INVALID
    return EXIT_VALID


# model families the streaming checker service can be started with
# (jepsen serve --checker --model <name>); values are jepsen_tpu.models
# class names, instantiated with their defaults
SERVE_MODELS = {
    "register": "Register",
    "cas-register": "CASRegister",
    "mutex": "Mutex",
    "gset": "GSet",
    "fifo": "FIFOQueue",
    "uqueue": "UnorderedQueue",
}


def run_serve_cmd(args) -> int:
    if getattr(args, "checker", False):
        # the streaming checker service (docs/streaming.md): deltas in,
        # verdicts out, over the JSONL stdio transport — jax imports
        # stay inside this branch so the results browser keeps working
        # against a wedged device runtime
        from jepsen_tpu import models as model_ns
        from jepsen_tpu.obs import httpd as ops_httpd
        from jepsen_tpu.serve import CheckerService, default_wal_dir
        from jepsen_tpu.serve.stdio import run_stdio
        model = getattr(model_ns, SERVE_MODELS[args.model])()
        # WAL segment replication (docs/streaming.md "Fleet
        # self-healing"): --repl-dir names the successor's repl/
        # mirror; the mode comes from JEPSEN_TPU_SERVE_REPL. The
        # service itself rejects a mode with no target; reject the
        # inverse here too — a --repl-dir under mode "off" would be
        # an operator believing replication is on when it is not.
        from jepsen_tpu.serve import fleet as fleet_mod
        wal_dir = args.wal_dir or default_wal_dir()
        repl_mode = fleet_mod.resolve_repl_mode()
        replicator = None
        if repl_mode != "off" and not getattr(args, "repl_dir", None):
            # the service would raise the same complaint — answer it
            # here as a usage error, not a traceback
            print(f"jepsen serve: JEPSEN_TPU_SERVE_REPL={repl_mode!r}"
                  f" but no --repl-dir names the successor's mirror "
                  f"— add --repl-dir PATH or unset the flag "
                  f"(docs/streaming.md 'Fleet self-healing')",
                  file=sys.stderr)
            return 2
        if getattr(args, "repl_dir", None):
            if repl_mode == "off":
                print("jepsen serve: --repl-dir given but "
                      "JEPSEN_TPU_SERVE_REPL is off/unset — set the "
                      "mode (async|sync) or drop the flag",
                      file=sys.stderr)
                return 2
            if not wal_dir:
                print("jepsen serve: --repl-dir needs a WAL-backed "
                      "service (--wal-dir / JEPSEN_TPU_SERVE_WAL)",
                      file=sys.stderr)
                return 2
            from jepsen_tpu.serve.wal import DeltaWAL
            replicator = fleet_mod.SegmentReplicator(
                DeltaWAL(wal_dir),
                fleet_mod.constant_dst(args.repl_dir),
                mode=repl_mode)
        svc = CheckerService(model, wal_dir=wal_dir,
                             dedupe=args.dedupe,
                             replicator=replicator)
        # the live ops surface (docs/observability.md "Ops endpoint"):
        # off unless --ops-port / JEPSEN_TPU_OPS_PORT names a port, so
        # a bare serve is byte-identical to the pre-ops service. The
        # continuous chip watch rides JEPSEN_TPU_PROBE_INTERVAL
        # independently — its gauges also feed flight-recorder dumps.
        from jepsen_tpu import probe as probe_mod
        watch = probe_mod.start_watch_from_env()
        port = ops_httpd.resolve_ops_port(
            getattr(args, "ops_port", None))
        ops = None
        if port is not None:

            def _health():
                doc = svc.health()
                if watch is not None:
                    p = watch.status()
                    doc["checks"]["probe"] = p
                    doc["ok"] = doc["ok"] and p["ok"]
                return doc

            ops = ops_httpd.start_ops_server(
                port, host=args.host, health_fn=_health,
                status_fn=svc.status, refresh_fn=svc.refresh_gauges,
                # POST /adopt: the fleet supervisor's live handoff
                # trigger (WAL-backed services only — adopt_keys
                # raises without one)
                adopt_fn=(svc.adopt_keys if wal_dir else None))
            print(f"ops endpoint: http://{args.host}:{ops.port} "
                  f"(/metrics /healthz /status — `jepsen status "
                  f"--port {ops.port}`)", file=sys.stderr)
        # the HTTP delta ingress (docs/streaming.md "HTTP ingress"):
        # off unless --ingress-port / JEPSEN_TPU_INGRESS_PORT names a
        # port; stdio keeps running either way — both transports feed
        # the same admission layer (tenancy, quotas, backpressure)
        from jepsen_tpu.serve import ingress as ingress_mod
        iport = ingress_mod.resolve_ingress_port(
            getattr(args, "ingress_port", None))
        ing = None
        if iport is not None:
            ing = ingress_mod.start_ingress(svc, iport,
                                            host=args.host)
            print(f"delta ingress: http://{args.host}:{ing.port} "
                  f"(POST /v1/deltas — streamed JSONL)",
                  file=sys.stderr)
        try:
            return run_stdio(svc)
        finally:
            if ing is not None:
                ing.close()
            if ops is not None:
                ops.close()
            if watch is not None:
                watch.stop()
    from jepsen_tpu import web
    web.serve(host=args.host, port=args.port)
    return EXIT_VALID




def run_cli(test_fn: Optional[Callable[[Dict], Dict]] = None,
            argv: Optional[list] = None, prog: str = "jepsen",
            extend_parser: Optional[Callable] = None,
            tests_fn: Optional[Callable] = None) -> int:
    """Main dispatcher (cli.clj:246-322). test_fn builds a test map from
    parsed options; defaults to the noop test. extend_parser(parser)
    may add suite-specific flags (parser._jepsen_subparsers maps
    subcommand names to their subparsers). tests_fn(opts), if given,
    yields (name, options) pairs for the test-all sweep
    (cli.clj:478-503's :tests-fn)."""
    if test_fn is None:
        test_fn = lambda opts: jcore.make_test(  # noqa: E731
            {"nodes": opts["nodes"], "ssh": opts["ssh"],
             "concurrency": opts["concurrency"]})
    raw = list(argv) if argv is not None else sys.argv[1:]
    if raw[:1] == ["lint"]:
        # forwarded BEFORE the main parser: argparse.REMAINDER drops a
        # leading optional (`lint --check` errors), and the analysis
        # package's own parser is the single source of truth for lint
        # flags, help, and the 0/1/2 exit contract
        from jepsen_tpu import analysis
        return analysis.main(raw[1:])
    if raw[:1] == ["probe"]:
        # same pre-parse forwarding as lint: jepsen_tpu.probe owns its
        # flags and the 0/1/2 healthy/wedged/no-backend contract (the
        # r05 runbook's automation hook — see docs/observability.md)
        from jepsen_tpu import probe
        return probe.main(raw[1:])
    if raw[:1] == ["status"]:
        # same pre-parse forwarding: the ops-endpoint client owns its
        # flags and the 0/1/2 ready/degraded/unreachable contract, and
        # importing it never touches jax — `jepsen status` must answer
        # against a wedged runtime
        from jepsen_tpu.obs import httpd as ops_httpd
        return ops_httpd.status_main(raw[1:])
    if raw[:1] == ["report"]:
        # same pre-parse forwarding: the telemetry reports own their
        # flags (`--search` / `--slow`, `--run-dir`), read stored
        # artifacts only, and never touch jax
        from jepsen_tpu.obs import search_report
        return search_report.report_main(raw[1:])
    if raw[:1] == ["trace"]:
        # same pre-parse forwarding: the fleet trace merge owns its
        # flags, talks only to ops endpoints / trace files, and never
        # touches jax — it must run from a coordinator while the
        # fleet's device runtimes are busy or wedged
        from jepsen_tpu.obs import trace_merge
        return trace_merge.trace_main(raw[1:])
    parser = base_parser(prog)
    if extend_parser is not None:
        extend_parser(parser)
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return EXIT_BAD_ARGS if e.code not in (0, None) else 0
    if args.command is None:
        parser.print_help()
        return EXIT_BAD_ARGS
    try:
        if args.command == "test":
            return run_test_cmd(test_fn, args)
        if args.command == "test-all":
            return run_test_all_cmd(test_fn, args, tests_fn=tests_fn)
        if args.command == "analyze":
            return run_analyze_cmd(test_fn, args)
        if args.command == "serve":
            return run_serve_cmd(args)
        return EXIT_BAD_ARGS
    except KeyboardInterrupt:
        raise
    except Exception:  # noqa: BLE001
        traceback.print_exc()
        return EXIT_CRASH


def main(argv: Optional[list] = None) -> int:
    return run_cli(None, argv)


if __name__ == "__main__":
    sys.exit(main())
