"""Synthetic history generators — test + benchmark corpora.

The reference benchmarks knossos on register histories ("knossos
benchmark corpus: etcd/cockroach register histories", BASELINE.json
configs; knossos.history generators). We generate equivalent corpora in
process: concurrent cas-register histories that are *valid by
construction* (every effect applied at a legal linearization point), with
optional crashes and failures, plus adversarial corruption for invalid
cases. Deterministic under a seed.
"""

from __future__ import annotations

import random
from typing import Optional

from jepsen_tpu.history import History, Op


def rand_register_history(
    n_ops: int = 100,
    n_processes: int = 5,
    n_values: int = 5,
    cas: bool = True,
    crash_p: float = 0.05,
    fail_p: float = 0.05,
    busy: float = 0.5,
    seed: int = 45100,
) -> History:
    """A random, linearizable-by-construction cas-register history.

    Simulation: a true register value evolves; each op's effect is applied
    at its completion instant (a legal linearization point inside its
    [invoke, complete] window). Crashed ops (:info) either applied at
    crash time or never — both legal. Failed ops never applied.
    Concurrency comes from interleaving invocations and completions of
    different processes. Default seed 45100 is the reference's test seed
    (jepsen/src/jepsen/generator/test.clj:30-47).
    """
    rng = random.Random(seed)
    h = History()
    value = None            # true register state
    pending: dict = {}      # process -> op dict
    free = list(range(n_processes))
    next_process = n_processes  # crashed processes are replaced with fresh ids
    started = 0
    t = 0

    def emit(typ, process, f, val, **kw):
        nonlocal t
        t += rng.randint(1, 1000)
        o = Op(type=typ, process=process, f=f, value=val, time=t, **kw)
        h.append(o)
        return o

    while started < n_ops or pending:
        # `busy` biases toward opening new calls before completing pending
        # ones: higher busy -> more concurrency -> wider search windows
        can_start = started < n_ops and free
        if can_start and (not pending or rng.random() < busy):
            p = free.pop(rng.randrange(len(free)))
            r = rng.random()
            if cas and r < 0.3:
                f, v = "cas", [rng.randrange(n_values), rng.randrange(n_values)]
            elif r < 0.6:
                f, v = "write", rng.randrange(n_values)
            else:
                f, v = "read", None
            emit("invoke", p, f, v)
            pending[p] = {"f": f, "value": v}
            started += 1
        else:
            p = rng.choice(list(pending))
            op_info = pending.pop(p)
            f, v = op_info["f"], op_info["value"]
            roll = rng.random()
            if roll < crash_p:
                # crashed: maybe applied, maybe not; process id retired
                if rng.random() < 0.5:
                    value = _apply(value, f, v)[0]
                emit("info", p, f, v, error="indeterminate")
                free.append(next_process)
                next_process += 1
            elif roll < crash_p + fail_p and f != "read":
                emit("fail", p, f, v)
                free.append(p)
            else:
                value, result, ok = _apply_and_result(value, f, v)
                if ok:
                    emit("ok", p, f, result)
                else:
                    emit("fail", p, f, v)
                free.append(p)
    return h.index()


def _apply(value, f, v):
    if f == "write":
        return v, True
    if f == "cas":
        old, new = v
        if value == old:
            return new, True
        return value, False
    return value, True


def _apply_and_result(value, f, v):
    if f == "read":
        return value, value, True
    new_value, ok = _apply(value, f, v)
    return (new_value, v, True) if ok else (value, v, False)


def corrupt_history(h: History, seed: int = 0,
                    n_corruptions: int = 1) -> History:
    """Flip ok-read values to likely-inconsistent ones — adversarial
    invalid(ish) histories; pair with a checker oracle, don't assume."""
    rng = random.Random(seed)
    out = History.wrap(Op(dict(o)) for o in h)
    reads = [i for i, o in enumerate(out)
             if o.get("type") == "ok" and o.get("f") == "read"
             and o.get("value") is not None]
    for i in rng.sample(reads, min(n_corruptions, len(reads))):
        out[i]["value"] = (out[i]["value"] or 0) + 1000
    return out.index()
