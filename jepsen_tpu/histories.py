"""Synthetic history generators — test + benchmark corpora.

The reference benchmarks knossos on register histories ("knossos
benchmark corpus: etcd/cockroach register histories", BASELINE.json
configs; knossos.history generators). We generate equivalent corpora in
process: concurrent cas-register histories that are *valid by
construction* (every effect applied at a legal linearization point), with
optional crashes and failures, plus adversarial corruption for invalid
cases. Deterministic under a seed.
"""

from __future__ import annotations

import random
from typing import Optional

from jepsen_tpu.history import History, Op


def _simulate(
    n_ops: int,
    n_processes: int,
    busy: float,
    crash_p: float,
    seed: int,
    choose_op,      # (rng) -> (f, invoke_value)
    complete_op,    # (rng, f, v) -> (type, completion_value); applies effect
    crash_op,       # (rng, f, v) -> None; maybe-applies effect (both legal)
) -> History:
    """The shared concurrent-simulation driver behind every generator:
    a true model state evolves; each op's effect is applied at its
    completion instant (a legal linearization point inside its
    [invoke, complete] window), so histories are valid by construction.
    Crashed (:info) ops are applied-or-not by `crash_op` and their
    process id is retired for a fresh one; `busy` biases toward opening
    new calls before completing pending ones (higher -> more
    concurrency -> wider search windows).

    **The n_ops contract** (pinned by tests/test_generator.py):
    ``n_ops`` counts INVOCATIONS — operation attempts — exactly like
    the reference's generators count :invoke entries. Every invocation
    also emits exactly one completion row (``ok``/``fail``/``info``),
    so ``len(history) == 2 * n_ops``, with the two rows of one call
    interleaved arbitrarily far apart. Do NOT slice a generated
    history by ``n_ops`` expecting "the whole thing" — that truncates
    mid-stream, leaves calls dangling open, and reads like a phantom
    parity bug when two differently-sliced views are compared. Slice
    by ``len(ops)`` (or not at all); a prefix slice is still a VALID
    history (open calls are legal), just not the full one."""
    rng = random.Random(seed)
    h = History()
    pending: dict = {}      # process -> (f, invoke value)
    free = list(range(n_processes))
    next_process = n_processes
    started = 0
    t = 0

    def emit(typ, process, f, val, **kw):
        nonlocal t
        t += rng.randint(1, 1000)
        h.append(Op(type=typ, process=process, f=f, value=val, time=t, **kw))

    while started < n_ops or pending:
        can_start = started < n_ops and free
        if can_start and (not pending or rng.random() < busy):
            p = free.pop(rng.randrange(len(free)))
            f, v = choose_op(rng)
            emit("invoke", p, f, v)
            pending[p] = (f, v)
            started += 1
        else:
            p = rng.choice(list(pending))
            f, v = pending.pop(p)
            if rng.random() < crash_p:
                crash_op(rng, f, v)
                emit("info", p, f, v, error="indeterminate")
                free.append(next_process)
                next_process += 1
            else:
                typ, out = complete_op(rng, f, v)
                emit(typ, p, f, out)
                free.append(p)
    return h.index()


def rand_register_history(
    n_ops: int = 100,
    n_processes: int = 5,
    n_values: int = 5,
    cas: bool = True,
    crash_p: float = 0.05,
    fail_p: float = 0.05,
    busy: float = 0.5,
    seed: int = 45100,
) -> History:
    """A random, linearizable-by-construction cas-register history
    (see `_simulate` for the driver semantics — NOTE ``n_ops`` counts
    invocations, so the history has ``2 * n_ops`` rows). Failed ops
    never apply. Default seed 45100 is the reference's test seed
    (jepsen/src/jepsen/generator/test.clj:30-47).
    """
    state = {"value": None}

    def choose(rng):
        r = rng.random()
        if cas and r < 0.3:
            return "cas", [rng.randrange(n_values), rng.randrange(n_values)]
        if r < 0.6:
            return "write", rng.randrange(n_values)
        return "read", None

    def complete(rng, f, v):
        if f != "read" and rng.random() < fail_p:
            return "fail", v
        value = state["value"]
        if f == "read":
            return "ok", value
        new_value, ok = _apply(value, f, v)
        state["value"] = new_value
        return ("ok", v) if ok else ("fail", v)

    def crash(rng, f, v):
        if rng.random() < 0.5:
            state["value"] = _apply(state["value"], f, v)[0]

    return _simulate(n_ops, n_processes, busy, crash_p, seed,
                     choose, complete, crash)


def _apply(value, f, v):
    if f == "write":
        return v, True
    if f == "cas":
        old, new = v
        if value == old:
            return new, True
        return value, False
    return value, True


def rand_gset_history(
    n_ops: int = 100,
    n_processes: int = 5,
    n_elements: int = 8,
    read_p: float = 0.4,
    crash_p: float = 0.05,
    busy: float = 0.5,
    seed: int = 45100,
) -> History:
    """A random, linearizable-by-construction grow-only-set history:
    adds of distinct elements and full-set reads (see `_simulate` —
    ``n_ops`` counts invocations; the history has ``2 * n_ops``
    rows)."""
    true_set: set = set()
    counter = iter(range(n_elements))

    def choose(rng):
        if rng.random() >= read_p:
            v = next(counter, None)
            if v is not None:
                return "add", v
        return "read", None

    def complete(rng, f, v):
        if f == "add":
            true_set.add(v)
            return "ok", v
        return "ok", sorted(true_set)

    def crash(rng, f, v):
        if f == "add" and rng.random() < 0.5:
            true_set.add(v)

    return _simulate(n_ops, n_processes, busy, crash_p, seed,
                     choose, complete, crash)


def rand_queue_history(
    n_ops: int = 100,
    n_processes: int = 5,
    n_values: int = 3,
    deq_p: float = 0.45,
    crash_p: float = 0.05,
    busy: float = 0.5,
    seed: int = 45100,
) -> History:
    """A random, linearizable-by-construction unordered-queue history:
    enqueues of a small value domain and dequeues returning any pending
    element (see `_simulate` — ``n_ops`` counts invocations; the
    history has ``2 * n_ops`` rows). Dequeues finding the queue empty
    complete as :fail (dropped by the checkers, like a client-side
    retryable empty-queue error)."""
    from collections import Counter
    q: Counter = Counter()

    def pop_random(rng):
        x = rng.choice(list(q.elements()))
        q[x] -= 1
        return x

    def choose(rng):
        if rng.random() < deq_p:
            return "dequeue", None
        return "enqueue", rng.randrange(n_values)

    def complete(rng, f, v):
        if f == "enqueue":
            q[v] += 1
            return "ok", v
        if sum(q.values()) == 0:
            return "fail", None
        return "ok", pop_random(rng)

    def crash(rng, f, v):
        # crashed: enqueues maybe applied; dequeues maybe popped
        if f == "enqueue" and rng.random() < 0.5:
            q[v] += 1
        elif (f == "dequeue" and sum(q.values()) > 0
              and rng.random() < 0.5):
            pop_random(rng)

    return _simulate(n_ops, n_processes, busy, crash_p, seed,
                     choose, complete, crash)


def rand_fifo_history(
    n_ops: int = 100,
    n_processes: int = 5,
    n_values: int = 3,
    deq_p: float = 0.45,
    crash_p: float = 0.05,
    busy: float = 0.5,
    seed: int = 45100,
) -> History:
    """A random, linearizable-by-construction strict-FIFO history (see
    `_simulate` — ``n_ops`` counts invocations; the history has
    ``2 * n_ops`` rows): dequeues return the true head; empty-queue dequeues
    complete as :fail (dropped by the checkers). Dequeue-biased once
    the queue runs deep, so the packed device tier's depth bound stays
    inside its 31-bit budget."""
    from collections import deque
    q: deque = deque()

    def choose(rng):
        if len(q) >= 3 or rng.random() < deq_p:
            return "dequeue", None
        return "enqueue", rng.randrange(n_values)

    def complete(rng, f, v):
        if f == "enqueue":
            q.append(v)
            return "ok", v
        if not q:
            return "fail", None
        return "ok", q.popleft()

    def crash(rng, f, v):
        if f == "enqueue" and rng.random() < 0.5:
            q.append(v)
        elif f == "dequeue" and q and rng.random() < 0.5:
            q.popleft()

    return _simulate(n_ops, n_processes, busy, crash_p, seed,
                     choose, complete, crash)


def adversarial_register_history(
    n_ops: int = 1000,
    k_crashed: int = 12,
    n_values: int = 5,
    seed: int = 45100,
) -> History:
    """The knossos-killer shape: k concurrent crashed writes of distinct
    values opened at the start and never completed, followed by a
    sequential write/read tail. Every crashed write stays open forever
    (knossos completes :info ops at history end — SURVEY.md §2.10), so
    the search must carry ~2^k linearized-subset configurations through
    EVERY later event: the host's per-config frontier walk grinds at
    ~2^k work per return, while the bit-packed device engine's cost is
    independent of the live frontier (the whole mask space is a static
    [S, 2^C/32] tensor). Valid by construction: reads return the last
    completed write (crashed writes "not yet" linearized — always
    legal). Host cost scales 2^k; device cost does not."""
    rng = random.Random(seed)
    h = History()
    t = 0

    def emit(typ, process, f, val, **kw):
        nonlocal t
        t += 1
        h.append(Op(type=typ, process=process, f=f, value=val, time=t, **kw))

    for i in range(k_crashed):
        emit("invoke", 500 + i, "write", 1000 + i)
    state = None
    for j in range(max(0, n_ops - k_crashed)):
        if j % 2 == 0:
            v = rng.randrange(n_values)
            emit("invoke", 0, "write", v)
            emit("ok", 0, "write", v)
            state = v
        else:
            emit("invoke", 0, "read", None)
            emit("ok", 0, "read", state)
    return h.index()


def with_impossible_read(h: History, value=999,
                         process: int = 90) -> History:
    """Append a read observing `value` — pick one no write/enqueue ever
    produced and the result is invalid with the failure at the very
    end. The canonical invalid suffix for engine differential tests."""
    ops = [dict(o) for o in h]
    n = len(ops)
    t = (ops[-1]["time"] + 1) if ops else 0
    ops += [{"index": n, "time": t, "process": process,
             "type": "invoke", "f": "read", "value": None},
            {"index": n + 1, "time": t + 1, "process": process,
             "type": "ok", "f": "read", "value": value}]
    return History.wrap(ops).index()


def corrupt_history(h: History, seed: int = 0,
                    n_corruptions: int = 1) -> History:
    """Flip ok completion values to likely-inconsistent ones —
    adversarial invalid(ish) histories; pair with a checker oracle,
    don't assume. Reads claim unobservable values (scalar bump, or a
    never-added element for collection-valued reads); dequeues claim a
    never-enqueued value, so queue families get invalid coverage too."""
    rng = random.Random(seed)
    out = History.wrap(Op(dict(o)) for o in h)
    targets = [i for i, o in enumerate(out)
               if o.get("type") == "ok" and o.get("f") in ("read", "dequeue")
               and o.get("value") is not None]
    for i in rng.sample(targets, min(n_corruptions, len(targets))):
        v = out[i]["value"]
        if isinstance(v, list):
            # set-style read (gset observes a collection): claim an
            # element that was never added
            out[i]["value"] = v + [1000 + i]
        elif isinstance(v, (set, frozenset)):
            out[i]["value"] = set(v) | {1000 + i}
        else:
            out[i]["value"] = (v or 0) + 1000
    return out.index()
